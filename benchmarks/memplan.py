"""Scale dress-rehearsal: memory/sharding audit of the big driver configs.

No TPU compute — ``jax.eval_shape`` + sharding math on a VIRTUAL v5p-64
mesh (64 CPU devices), verifying that every sharding spec actually divides
every parameter and that the per-chip HBM budget closes.  Emits the tables
MEMPLAN.md records.

Configs (SURVEY.md driver configs #2-#4):
  A: Llama-3-8B,  ZeRO-3 (+Infinity posture), dp=64, S=8192
  B: Llama-3-70B, 3D: pp=4 x tp=8 x dp=2,     S=8192
  C: Mixtral-8x7B, EP: ep=8 x dp=8,           S=4096

Usage: python benchmarks/memplan.py [--dryrun]   (--dryrun additionally
trains one GPT-2-125M ZeRO-3 step on an 8-device CPU mesh.)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=64"

if "--dryrun-only" in sys.argv:  # subprocess entry: 8 devices, not 64
    os.environ["XLA_FLAGS"] = os.environ["XLA_FLAGS"].replace(
        "device_count=64", "device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

V5P_HBM = 95e9  # bytes per chip
GB = 1e9


def shard_bytes(abstract, shardings, itemsize=None, pp=1, n_layers=None):
    """Per-device bytes of a pytree under NamedShardings; raises if any spec
    does not divide its array (exactly the bug this audit exists to catch).

    ``pp``: pipeline stages — [L, ...]-stacked block leaves (leading dim ==
    n_layers) live on one stage each, so their bytes divide by pp (the
    pipe engine partitions blocks outside the ZeRO plan)."""
    total = 0
    for leaf, sh in zip(jax.tree_util.tree_leaves(abstract),
                        jax.tree_util.tree_leaves(
                            shardings, is_leaf=lambda x: hasattr(x, "spec"))):
        shape = sh.shard_shape(leaf.shape)  # raises on non-divisible
        n = int(np.prod(shape))
        if pp > 1 and n_layers and leaf.shape and leaf.shape[0] == n_layers:
            n //= pp
        total += n * (itemsize or leaf.dtype.itemsize)
    return total


def audit(name, model_cfg_build, topo, zero_stage, micro_bs, seq,
          persistence=32768, act_factor=2, notes=()):
    from deepspeed_tpu.runtime.zero.sharding import ZeroShardingPlan

    cfg, model = model_cfg_build()
    topo_str = "x".join(f"{a}{n}" for a, n in topo.axis_sizes.items() if n > 1)
    abstract = jax.eval_shape(model.init_fn, jax.random.PRNGKey(0))
    tp_specs = model.tp_rules(abstract) if model.tp_rules else None
    plan = ZeroShardingPlan(zero_stage, topo.mesh,
                            param_persistence_threshold=persistence)
    p_shard = plan.param_shardings(abstract, tp_specs)
    g_shard = plan.grad_shardings(abstract, tp_specs)
    tp_tree = plan._resolve_tp(abstract, tp_specs)
    o_shard = jax.tree_util.tree_map(
        lambda p, tp: plan._named(plan.opt_spec(tuple(p.shape), tp)),
        abstract, tp_tree)

    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(abstract))
    pp = topo.axis_sizes.get("pp", 1)
    L = cfg.num_layers
    # engine memory model: fp32 master params + adam m/v fp32 (opt layout),
    # grads fp32 (grad layout), bf16 compute copy materialized per use
    master = shard_bytes(abstract, p_shard, 4, pp=pp, n_layers=L)
    opt = 2 * shard_bytes(abstract, o_shard, 4, pp=pp, n_layers=L)
    grads = shard_bytes(abstract, g_shard, 4, pp=pp, n_layers=L)
    # activation estimate per microbatch (selective remat: ~act_factor
    # bf16 copies of [B, S, d] per layer + attention workspace)
    d = cfg.hidden_size
    L = cfg.num_layers
    sp = topo.axis_sizes.get("sp", 1)
    acts = act_factor * L * micro_bs * (seq // max(sp, 1)) * d * 2
    pp = topo.axis_sizes.get("pp", 1)
    acts = acts // pp
    total = master + opt + grads + acts
    print(f"\n== {name} ({topo_str}, zero{zero_stage}, "
          f"bs/chip={micro_bs}, S={seq}) ==")
    print(f"params {n_params/1e9:.2f}B | per-chip: master {master/GB:.2f} GB"
          f" + adam {opt/GB:.2f} + grads {grads/GB:.2f}"
          f" + acts~{acts/GB:.2f} = {total/GB:.2f} GB"
          f" ({100*total/V5P_HBM:.0f}% of v5p HBM)")
    for nline in notes:
        print("   " + nline)
    assert total < V5P_HBM, f"{name} does not fit v5p HBM"
    return dict(name=name, params=n_params, per_chip_bytes=total)


def main():
    from deepspeed_tpu.parallel.topology import MeshTopology

    # ---- A: Llama-3-8B ZeRO-3 (+Infinity posture), dp=64 ----------------
    def build_8b():
        from deepspeed_tpu.models import llama

        cfg = llama.LlamaConfig.llama3_8b()
        return cfg, llama.build(cfg)

    topo_a = MeshTopology(dp=64)
    n_chips = 64
    audit("Llama-3-8B ZeRO-3", build_8b, topo_a, zero_stage=3,
          micro_bs=1, seq=8192, act_factor=2, notes=[
              "ZeRO-3 comm/step: 2x all-gather of bf16 params (fwd+bwd, "
              "16.1 GB over ICI, pipelined per scan step) + reduce-scatter "
              "of f32 grads (32.1 GB/64 chips = 0.5 GB/chip)",
              "Infinity tier: optimizer state (m+v+master, 96.5 GB global) "
              "can move to host DRAM via offload_optimizer; param tier "
              "streams blocks (zero/param_stream.py)"])

    # ---- B: Llama-3-70B 3D pp4 x tp8 x dp2 -------------------------------
    def build_70b():
        from deepspeed_tpu.models import llama

        cfg = llama.LlamaConfig.llama3_70b()
        return cfg, llama.build(cfg)

    topo_b = MeshTopology(pp=4, tp=8, dp=2)
    audit("Llama-3-70B 3D", build_70b, topo_b, zero_stage=1,
          micro_bs=1, seq=8192, act_factor=2, notes=[
              "block leaves divide by pp=4 (pipe/engine partitions the "
              "[L, ...] stacks per stage, outside the ZeRO plan)",
              "tp comm/step/layer: 4 all-reduces of [B, S/sp, d] bf16 "
              "(0.13 GB each at bs=1) over the innermost-axis ICI",
              "dp comm/step: grad all-reduce of the per-stage tp shard "
              "(~8.8 GB f32 at pp4 x tp8)"])

    # ---- C: Mixtral-8x7B EP ep8 x dp8 ------------------------------------
    def build_mixtral():
        from deepspeed_tpu.models import mixtral

        cfg = mixtral.MixtralConfig()
        return cfg, mixtral.build(cfg)

    topo_c = MeshTopology(ep=8, dp=8)
    audit("Mixtral-8x7B EP", build_mixtral, topo_c, zero_stage=2,
          micro_bs=1, seq=4096, act_factor=2, notes=[
              "experts shard over ep (8 experts -> 1/chip); zero-2 "
              "shards opt+grads over (dp, ep) = all 64 chips",
              "ep comm/step/layer: 2 all-to-alls of the routed token "
              "activations (top-2 of [B, S, d] bf16)"])

    # ---- D: OPT-13B auto-TP serving (driver config #5) -------------------
    serving_audit_opt13b()

    if "--dryrun" in sys.argv:
        # fresh process with an 8-device platform (this one holds 64)
        import subprocess

        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--dryrun-only"])
        sys.exit(r.returncode)


def serving_audit_opt13b(hbm_gb=(16, 95), batch=8, max_tokens=2048):
    """Serving-side MEMPLAN: OPT-13B under auto-TP at tp=4/8 — bf16 weights
    per chip via the REAL inferred TP specs (module_inject/auto_tp.py, the
    path init_inference uses) + static KV-cache bytes vs HBM.  Reference
    scale anchor: benchmarks/inference/gpt-bench.py runs the same
    multi-billion sizes on GPUs."""
    from deepspeed_tpu.models import opt as opt_model
    from deepspeed_tpu.module_inject.auto_tp import infer_tp_specs
    from deepspeed_tpu.parallel.topology import MeshTopology

    cfg = opt_model.OPTConfig.opt_13b()
    model = opt_model.build(cfg)
    abstract = jax.eval_shape(model.init_fn, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(abstract))
    hd = cfg.hidden_size // cfg.num_heads
    for tp in (4, 8):
        topo = MeshTopology(tp=tp)
        specs = infer_tp_specs(abstract)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(topo.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        weights = shard_bytes(abstract, shardings, 2)   # bf16 serving copy
        # static KV cache (inference/engine.py workspace): k+v per layer,
        # heads sharded over tp, sized by the token budget
        kv = 2 * cfg.num_layers * batch * (cfg.num_heads // tp) * \
            max_tokens * hd * 2
        total = weights + kv
        fits = " / ".join(
            f"{100 * total / (g * GB):.0f}% of {g}GB"
            for g in hbm_gb)
        print(f"\n== OPT-13B auto-TP serving tp={tp} (bs={batch}, "
              f"budget {max_tokens} tok) ==")
        print(f"params {n_params/1e9:.2f}B | per-chip: weights "
              f"{weights/GB:.2f} GB + kv-cache {kv/GB:.2f} GB = "
              f"{total/GB:.2f} GB ({fits} HBM)")
        assert total < max(hbm_gb) * GB


def dryrun_125m():
    """One REAL ZeRO-3 train step of GPT-2 125M (124M params) on an
    8-device CPU mesh — the >=100M-param execution check."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.gpt2_125m()
    cfg.max_seq_len = 128  # tiny sequence: the check is the 124M-param
    cfg.remat = True       # sharded execution, not throughput
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
    })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 129)).astype(np.int32)}
    _, m = engine.train_batch(batch)
    loss = float(m["loss"])
    n = sum(x.size for x in jax.tree_util.tree_leaves(engine.state["params"]))
    print(f"\n== dryrun: GPT-2 125M zero3 on 8-dev CPU mesh ==")
    print(f"params {n/1e6:.1f}M, one train step OK, loss={loss:.3f}")
    assert np.isfinite(loss)


def dryrun_355m_streamed():
    """One REAL ZeRO-3 + param-STREAMING train step at GPT-2-medium scale
    (355M params) — the streamed ZeRO-Infinity path exercised above 124M
    (round-3 verdict: it had only ever executed at 124M, and only
    unstreamed).  Blocks live host-side; the device sees one layer at a
    time (zero/param_stream.py), optimizer steps on the host CPU-Adam."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=50257, max_seq_len=128, num_layers=24,
                          num_heads=16, hidden_size=1024)  # GPT-2 medium
    cfg.remat = True
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "offload_param": {"device": "cpu"},
                              "offload_optimizer": {"device": "cpu"}},
    })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 129)).astype(np.int32)}
    _, m = engine.train_batch(batch)
    loss = float(m["loss"])
    n_host = sum(x.size for x in engine._param_store.master)
    n_res = sum(x.size for x in
                jax.tree_util.tree_leaves(engine.state["params"]))
    print(f"\n== dryrun: GPT-2-medium 355M zero3 + param streaming ==")
    print(f"params {(n_host + n_res)/1e6:.1f}M ({n_host/1e6:.1f}M "
          f"host-streamed blocks), one train step OK, loss={loss:.3f}")
    assert np.isfinite(loss)
    assert (n_host + n_res) >= 350e6


if __name__ == "__main__":
    if "--dryrun-only" in sys.argv:
        dryrun_125m()
        dryrun_355m_streamed()
    else:
        main()
