"""W8A8 kernel bandwidth probe: achieved GB/s per OPT matmul shape.

bs=1 decode is HBM-bound on the int8 weight read, so the kernel's achieved
bandwidth IS the serving headroom question (PROFILE.md round-4: OPT-6.7B
decodes at ~2x the int8 read floor — this probe locates the gap shape by
shape).  For each decode matmul shape it times:

  - the w8a8 Pallas kernel (`quantized_matmul.w8a8_matmul`)
  - a pure int8 read floor on the same buffer (sum-reduce, XLA)
  - the bf16 dense dot (2 bytes/param yardstick)

Usage: python benchmarks/w8a8_microbench.py [--d 4096] [--ffn 16384]
       [--b 1] [--trials 30] [--step-mb 4]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=4096)
    ap.add_argument("--ffn", type=int, default=16384)
    ap.add_argument("--vocab", type=int, default=50272)
    ap.add_argument("--b", type=int, default=1)
    ap.add_argument("--trials", type=int, default=30)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--k-group", type=int, default=128)
    ap.add_argument("--unroll", action="store_true",
                    help="python-unrolled layer loop instead of lax.scan")
    ap.add_argument("--skip-shapes", action="store_true",
                    help="only run the layer-stack probe")
    ap.add_argument("--step-mb", type=float, default=None)
    args = ap.parse_args()
    if args.step_mb is not None:
        os.environ["DS_QMM_STEP_MB"] = str(args.step_mb)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops import quantization as quant
    from deepspeed_tpu.ops import quantized_matmul as qmm

    d, ffn = args.d, args.ffn
    shapes = [("qkv", d, 3 * d), ("attn_out", d, d),
              ("fc1", d, ffn), ("fc2", ffn, d),
              ("lm_head", d, args.vocab)]
    rng = np.random.default_rng(0)

    # block_until_ready is a no-op through the axon tunnel (PROFILE.md) and
    # a value-fetch sync pays the tunnel RTT, which swamps microsecond
    # kernels.  So: run the op R times inside one jit (data-dependent chain
    # so XLA cannot CSE the repeats) and take the (R_hi - R_lo) slope —
    # dispatch + RTT cancel.
    def timeit(op, x, *a, n=args.trials):
        def repeat(r):
            def f(x, *a):
                def body(i, x):
                    y = op(x, *a)
                    # fold a runtime scalar of the output back into x at a
                    # numerically-negligible magnitude: XLA cannot fold it
                    # (value unknown) so iterations stay serialized and the
                    # op cannot be hoisted out of the loop
                    s = jnp.sum(y[:1, :1].astype(jnp.float32))
                    return x + (s * 1e-30).astype(x.dtype)
                return jax.lax.fori_loop(0, r, body, x)
            return jax.jit(f)

        def sync(out):
            jax.device_get(jnp.sum(out[:1, :1].astype(jnp.float32)))

        # estimate op time with a coarse window, then size the repeat count
        # so each window carries ~50ms of device work (tunnel RTT jitter is
        # ms-scale; the r_hi - r_lo slope cancels the mean RTT)
        f_est = repeat(256)
        sync(f_est(x, *a))
        t0 = time.perf_counter(); sync(f_est(x, *a))
        est = max((time.perf_counter() - t0) / 256, 1e-7)
        r_lo = max(8, int(0.05 / est))
        r_hi = 2 * r_lo
        f_lo, f_hi = repeat(r_lo), repeat(r_hi)
        sync(f_lo(x, *a)); sync(f_hi(x, *a))
        ts = []
        for _ in range(n):
            t0 = time.perf_counter(); sync(f_lo(x, *a))
            t1 = time.perf_counter(); sync(f_hi(x, *a))
            t2 = time.perf_counter()
            ts.append(((t2 - t1) - (t1 - t0)) / (r_hi - r_lo))
        return float(np.median(ts))

    print(f"# b={args.b} trials={args.trials} "
          f"step_mb={os.environ.get('DS_QMM_STEP_MB', '4(default)')}")
    if args.skip_shapes:
        shapes_run = []
    else:
        shapes_run = shapes
    print(f"{'shape':>9} {'KxN':>14} {'int8MB':>7} "
          f"{'w8a8 us':>9} {'GB/s':>6} {'read us':>9} {'GB/s':>6} "
          f"{'bf16 us':>9} {'GB/s':>6}")
    tot_w8a8 = tot_floor = 0.0
    for name, k, n in shapes_run:
        w = rng.standard_normal((k, n)).astype(np.float32) * 0.02
        rec = quant.quantize_k_grouped(jnp.asarray(w), k_group=args.k_group)
        x = jnp.asarray(rng.standard_normal((args.b, k)), jnp.bfloat16)
        wb = jnp.asarray(w, jnp.bfloat16)
        mb = k * n / 2**20

        t_w8 = timeit(lambda x, qk, ks: qmm.w8a8_matmul(
            x, {"qk": qk, "kscale": ks}), x, rec["qk"], rec["kscale"])

        def read_floor(x, qk):
            # perturb qk with a runtime-valued (but actually-zero) int8 from
            # the loop carry so the reduce cannot be hoisted out of the
            # timing loop as loop-invariant
            t8 = jnp.clip(x[:1, :1].astype(jnp.float32) * 1e-30,
                          0, 1).astype(jnp.int8)
            return jnp.max(jnp.abs((qk + t8).astype(jnp.int32))) \
                .reshape(1, 1).astype(jnp.float32)

        t_rd = timeit(read_floor, x, rec["qk"])

        t_bf = timeit(lambda x, w: jax.lax.dot(x, w), x, wb)

        gbs = lambda t, bytes_: bytes_ / t / 1e9
        print(f"{name:>9} {k:>6}x{n:<7} {mb:>7.1f} "
              f"{t_w8*1e6:>9.0f} {gbs(t_w8, k*n):>6.0f} "
              f"{t_rd*1e6:>9.0f} {gbs(t_rd, k*n):>6.0f} "
              f"{t_bf*1e6:>9.0f} {gbs(t_bf, 2*k*n):>6.0f}")
        if name != "lm_head":
            tot_w8a8 += t_w8
            tot_floor += t_rd
    print(f"# per-layer matmul total (no head): w8a8 {tot_w8a8*1e3:.3f} ms, "
          f"read floor {tot_floor*1e3:.3f} ms "
          f"(ratio {tot_w8a8/max(tot_floor,1e-12):.2f}x)")

    # ---- layer-stack probe: scan over n_layers of the four matmuls -------
    # One dispatch covers n_layers x 4 matmuls (~the whole decode weight
    # read), so tunnel RTT jitter is amortized away without any dependency
    # tricks — this is the trustworthy per-layer number.
    n_layers = args.layers
    ws = {}
    for i, (name, k, n) in enumerate(shapes[:4]):
        # weights born on-device: the tunnel host->device link is ~0.06
        # GiB/s, shipping GBs of host randoms would take minutes.  Chunk
        # the generate+quantize in groups of <=8 layers so the in-jit f32
        # transient stays ~2GB (a 32-layer fc leaf is 8.6GB f32, and 2x
        # that in one jit thrashes 16GB HBM); one dispatch per chunk keeps
        # the ~100ms-RTT dispatch count low
        chunk = min(8, n_layers)

        @functools.partial(jax.jit, static_argnames=("size",))
        def make(key, size, k=k, n=n):
            w = jax.random.normal(key, (size, k, n), jnp.float32) * 0.02
            return quant.quantize_k_grouped(w, k_group=args.k_group)
        parts = []
        for j in range(0, n_layers, chunk):
            # the last chunk is sized to the remainder so --layers values
            # that are not multiples of 8 never allocate extra layers
            p = make(jax.random.fold_in(jax.random.PRNGKey(i), j),
                     size=min(chunk, n_layers - j))
            # serialize: queued async chunks would co-allocate their ~2GB
            # f32 generator transients and OOM the 16GB chip at 32 layers
            jax.device_get(jnp.sum(p["qk"][0, 0, :8].astype(jnp.int32)))
            parts.append(p)
        ws[name] = {
            kk: jnp.concatenate([p[kk] for p in parts], axis=0)
            for kk in parts[0]}
        del parts
        jax.device_get(jnp.sum(ws[name]["qk"][0, 0, :8].astype(jnp.int32)))

    x0 = jnp.asarray(rng.standard_normal((args.b, d)), jnp.bfloat16)

    def stack_step(x, layer):
        qkv = qmm.w8a8_matmul(x, layer["qkv"])
        h = qmm.w8a8_matmul(qkv[:, :d], layer["attn_out"])
        f = qmm.w8a8_matmul(h, layer["fc1"])
        o = qmm.w8a8_matmul(jax.nn.gelu(f), layer["fc2"])
        return (x + o.astype(x.dtype)) * 0.5, None

    layers = {name: {"qk": ws[name]["qk"], "kscale": ws[name]["kscale"]}
              for name in ws}

    def build(n_sub):
        # run only the first n_sub layers of the same stacked weights, so
        # the lo/hi variants share buffers; the (hi - lo) time slope
        # cancels the per-dispatch tunnel RTT (~100ms here)
        sub = jax.tree_util.tree_map(lambda a: a[:n_sub], layers)
        if args.unroll:
            @jax.jit
            def stack(x, sub=sub, n=n_sub):
                for i in range(n):
                    layer = jax.tree_util.tree_map(lambda a: a[i], sub)
                    x, _ = stack_step(x, layer)
                return x
        else:
            @jax.jit
            def stack(x, sub=sub):
                y, _ = jax.lax.scan(stack_step, x, sub)
                return y
        return stack

    def sync_arr(y):
        jax.device_get(jnp.sum(y.astype(jnp.float32)))

    if n_layers < 2:
        raise SystemExit("--layers must be >= 2 for the slope probe")
    n_lo = max(1, n_layers // 8)
    f_lo, f_hi = build(n_lo), build(n_layers)
    sync_arr(f_lo(x0)); sync_arr(f_hi(x0))
    slopes, his = [], []
    for _ in range(args.trials):
        t0 = time.perf_counter(); sync_arr(f_lo(x0))
        t1 = time.perf_counter(); sync_arr(f_hi(x0))
        t2 = time.perf_counter()
        slopes.append(((t2 - t1) - (t1 - t0)) / (n_layers - n_lo))
        his.append(t2 - t1)
    per_layer = float(np.median(slopes))
    layer_bytes = sum(k * n for _, k, n in shapes[:4])
    print(f"# w8a8 stack slope ({n_lo}->{n_layers} layers): "
          f"{per_layer*1e6:.0f} us/layer = "
          f"{layer_bytes/per_layer/1e9:.0f} GB/s on the int8 weights "
          f"(full dispatch {float(np.median(his))*1e3:.1f} ms incl. RTT)")


if __name__ == "__main__":
    main()
