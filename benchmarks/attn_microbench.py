"""Attention micro-benchmark: Pallas flash kernel vs XLA einsum attention.

Sweeps block sizes at training shapes, fwd+bwd, and prints ms/iter + attention
TFLOPs for each variant.  The analog of the reference's kernel-vs-eager checks
under ``tests/perf`` (e.g. ``tests/perf/adam_test.py``) but for the attention
kernel that dominates the training step.

Usage: python benchmarks/attn_microbench.py [B H S D]
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, iters=20):
    """Time ``fn`` by scanning it ``iters`` times *inside one jit call*.

    Per-call dispatch overhead on remote/tunneled backends (~10ms) would
    otherwise swamp sub-ms kernels.  Each iteration's q input depends on the
    previous output so the compiler cannot hoist the body out of the loop.
    A host fetch of the final scalar forces completion (``block_until_ready``
    can return at enqueue time on tunneled backends).
    """
    q0 = args[0]

    @jax.jit
    def runner(*a):
        def body(carry, _):
            out = fn(carry, *a[1:])
            lead = jax.tree_util.tree_leaves(out)[0]
            nxt = (carry + 0.001 * lead.reshape(carry.shape).astype(
                carry.dtype))
            return nxt, None
        final, _ = jax.lax.scan(body, q0, None, length=iters)
        return jnp.sum(final.astype(jnp.float32))

    jax.device_get(runner(*args))  # warmup/compile
    t0 = time.perf_counter()
    jax.device_get(runner(*args))
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main():
    from deepspeed_tpu.ops import flash_attention as fa

    b, h, s, d = (int(x) for x in sys.argv[1:5]) if len(sys.argv) > 4 else \
        (32, 12, 1024, 64)
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)

    # causal attention flops (fwd): 2 matmuls * b*h*s*s*d * 0.5 (causal)
    fwd_flops = 2 * 2 * b * h * s * s * d * 0.5
    fb_flops = fwd_flops * 3.5  # bwd ~2.5x fwd for flash (recompute + 4 mm)

    def loss_of(attn_fn):
        def f(q, k, v):
            return (attn_fn(q, k, v) * v).sum(dtype=jnp.float32)
        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    variants = {"xla_einsum": functools.partial(fa.mha_reference, causal=True)}
    for bq, bk in [(128, 128), (256, 256), (256, 512), (512, 256), (512, 512),
                   (1024, 512), (256, 1024)]:
        if bq > s or bk > s:
            continue
        variants[f"flash_{bq}x{bk}"] = functools.partial(
            fa.flash_attention, causal=True, block_q=bq, block_k=bk)

    print(f"shape B={b} H={h} S={s} D={d} bf16, fwd+bwd")
    for name, attn in variants.items():
        # fwd only
        fwd = jax.jit(attn)
        ms_f = timeit(lambda *a: fwd(*a), q, k, v)
        # fwd+bwd
        g = loss_of(attn)
        ms_fb = timeit(lambda *a: g(*a)[0], q, k, v)
        print(f"{name:18s} fwd {ms_f:7.3f} ms ({fwd_flops/ms_f/1e9:6.1f} TF/s)"
              f"   fwd+bwd {ms_fb:7.3f} ms ({fb_flops/ms_fb/1e9:6.1f} TF/s)")


if __name__ == "__main__":
    main()
