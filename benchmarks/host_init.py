"""Shared bench-side host initialization helpers."""

from __future__ import annotations

import numpy as np


def host_init_bf16(model, seed: int = 0):
    """Leaf-by-leaf random bf16 host tree (no f32 jit tree — OPT-30B f32
    is 120GB; this peaks at the 58GB bf16 tree).  Weight VALUES are
    random: for serving-throughput measurement only."""
    import jax
    import jax.numpy as jnp

    abstract = jax.eval_shape(model.init_fn, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    bf16 = np.dtype(jnp.bfloat16)

    def mk(x):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return np.zeros(x.shape, x.dtype)
        out = np.empty(x.shape, bf16)
        flat = out.reshape(-1)
        step = 1 << 24
        for i in range(0, flat.size, step):
            n = min(step, flat.size - i)
            flat[i:i + n] = (0.02 * rng.standard_normal(
                n, dtype=np.float32)).astype(bf16)
        return out

    return jax.tree_util.tree_map(mk, abstract)
