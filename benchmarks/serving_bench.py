"""Serving benchmark: paged chunked-prefill scheduler vs the bucketed
slot-pool baseline vs sequential ``generate``.

Drives the same trace through three paths and reports aggregate generated
tokens/sec plus compile counts and the paged engine's ``stats()``:

 - **serving** (the headline): ``inference/serving.py`` with the block-paged
   KV pool, chunked prefill and prefix caching — exactly 2 compiled
   programs (1 prefill + 1 decode) for any trace, and shared prompt
   prefixes prefill for free after their first occurrence.
 - **serving_bucketed**: the PR 1-style fallback on the same engine —
   bucket-ladder prefill over the paged pool, no prefix reuse,
   O(#buckets)+1 compiled programs.  ``speedup_vs_bucketed`` is the paged/
   chunked win isolated from the continuous-batching win.
 - **sequential**: one-shot ``InferenceEngine.generate``, one request at a
   time, one compiled program per exact request shape.
 - **serving_speculative** (``--speculative K``): the chunked engine with
   speculative decoding — the n-gram prompt-lookup proposer drafts K
   tokens per slot per iteration and one K+1-token paged verify pass
   scores them (<= 3 compiled programs; 2 in n-gram mode).  Outputs stay
   token-exact with plain greedy decode; ``speedup_spec_vs_chunked`` is
   the draft–verify win over the single-token decode loop.
 - **serving_tp** (``--tp N``): the same chunked trace on a tensor-
   parallel engine — weights Megatron-sharded and the paged KV pool
   sharded over the KV-head dim (``inference/serving.py`` tp section), so
   each chip stores ``HKV/N`` heads.  Reports per-chip KV pool bytes
   (the headline: ~N× smaller than the replicated layout) and asserts
   token parity vs sequential.  Includes a speculative pass when
   ``--speculative`` is also given.  Needs >= N devices — on CPU set
   ``XLA_FLAGS=--xla_force_host_platform_device_count=8``; CPU-sim tok/s
   under tp is emulation overhead, not a hardware prediction.
 - **serving_quant** (``--quantize kv8[,w8a8[,w8a8+kv8]]``): quantized
   serving lanes on the same trace — int8 KV pool with per-block scales
   (``kv8``), K-grouped int8 weights on the s8 decode kernels
   (``w8a8``), or both.  Each lane reports tok/s, the quant-adjusted
   per-chip pool bytes, ``servable_blocks_per_chip_vs_bf16`` (bf16 pool
   bytes / quant pool bytes — the memory headline; ~1.9x for ``kv8``),
   and the measured token match rate vs full-precision sequential
   (bounded-divergence contract, ``tests/unit/quant_divergence.py`` —
   quantized lanes are NOT exact-parity lanes).  With ``--tp N`` a
   ``kv8`` lane also runs on the tp engine (the tp × kv8 combo: per-chip
   pool bytes divide by BOTH factors).  CPU-sim tok/s measures XLA-CPU
   op mixes, not HBM bandwidth — the on-chip bandwidth argument is
   PROFILE.md's (+32-34% w8a8 decode; int8 KV halves decode's dominant
   traffic term).

Methodology (PROFILE.md "continuous-batching serving" entry): the default
trace draws ARBITRARY prompt lengths in [32, 512] and completion budgets in
[16, 64] — real mixed traffic, where the sequential path jit-compiles one
program per exact request shape while the serving loop compiles O(1).
``--prefix-len N`` instead prepends a shared N-token system prompt to every
request (tails in [16, 64]) — the prefix-heavy trace where the prefix cache
collapses per-request prefill to the unique tail.  The headline is
aggregate generated tokens/sec over the whole trace, compiles included on
both sides; a second pass over the same trace reports the compile- and
prefix-warm steady state.  ``--grid`` snaps the default trace to a small
shape grid that fits the sequential LRU and reports a compile-warm
sequential pass too.  Greedy decoding; the bench asserts all serving
outputs are token-identical to sequential before reporting numbers.

``--decode-heavy`` draws short prompts and long completion budgets — the
decode-bound traffic speculative decoding targets (BENCH_r05 lane:
``--decode-heavy --speculative 4``).

``--pool-frac F`` adds the BENCH_r09 tiered-KV lane: the device pool is
deliberately sized at fraction F of the trace's working set (ROADMAP's
~25% scenario — block pressure guaranteed), and the same trace runs on
two engines differing ONLY in the host tier: the **evict/preempt
baseline** (cold blocks discarded, preemption recomputes whole
prefixes) vs the **tiered engine** (``host_blocks`` sized to the
working set: eviction/preemption demote to host DRAM, admission
promotes back with the double-buffered prefetch).  Reports
``speedup_tiered_vs_preemption`` (cold + warm), the swap counters,
prefetch-wait p50/p95 from the metrics registry, and both engines'
resume-recompute token counts; token parity vs sequential is asserted
for BOTH engines (zero parity loss is the tiering contract).  Best on
the prefix-heavy trace (``--prefix-len``) where the evicted prefix is
exactly what the next request needs.

``--telemetry-bench`` adds the BENCH_r08 overhead lane: the same chunked
trace on two fresh twin engines — telemetry-off (``trace_capacity=0``:
the event ring disabled; the metrics registry behind ``stats()`` is
always on) vs fully-enabled (default ring) — comparing interleaved
best-of-3 compile-warm passes.  The contract is ≤2% aggregate tok/s
overhead, recorded as ``within_2pct`` (a breach warns without failing
the run — wall-clock ratios on shared boxes carry ~±5% noise; the
committed 64-request BENCH_r08.json is the pinned artifact); the
lane also schema-validates the enabled engine's exported Chrome trace
(``telemetry/trace.py validate_chrome_trace``: monotonic ``ts``, paired/
complete events, pid/tid, per-request spans) and records the summary.
``--trace-out PATH`` writes that trace for Perfetto.  ``--emit-metrics
PATH`` dumps the headline serving engine's Prometheus text exposition to
``PATH`` and the JSON registry snapshot to ``PATH.json`` alongside the
bench JSON (tier-1 CI uploads these as a workflow artifact).

``--quant-suite`` runs the BENCH_r07 protocol: the mixed, prefix-heavy,
and decode-heavy traces each with the quantized lanes, plus the tp × kv8
combo, merged into one JSON.  Recommended at ``--dtype bf16`` (the
production serving dtype the memory/throughput headlines are quoted
against); bf16 runs gate the unquantized baseline on per-request
agreement instead of bit parity (see ``main`` — bf16 near-tie argmax
flips between equally valid compute shapes), fp32 runs keep the exact
gate.

``--replicas N`` runs the BENCH_r10 multi-replica router protocol
instead of the single-engine lanes: ``deepspeed_tpu/serving/``'s
``ReplicaRouter`` over 1 → 2 → 4 engine replicas (capped at N, weights
shared so every scale is token-identical) on the returning-session
trace.  Scaling is WEAK — n replicas serve n× the traffic (requests×n
over sessions×n), per-replica load constant: the DP capacity claim.
CPU-sim methodology: one process TIME-SLICES the replicas on the host
CPU — each replica stands in for an independent accelerator — so the
scaling headline is **aggregate busy-time throughput** (each replica's
generated tokens over its own ``step()`` wall time, summed over 3
interleaved warm rounds: the DP scaling signal), reported next to raw
wall clock (flat on a single core by construction; with >= N cores and
``threaded`` workers the wall numbers converge toward the busy
aggregate).  The protocol also runs affinity-vs-round-robin twin
fleets (prefix hit rate under pool pressure) and a drained-replica
migration: every migrated session's chain is KV-pulled from the
drained replica's host tier and resumed on the survivor with zero
prefix recompute, vs a ``kv_pull=False`` twin that re-prefills whole
prompts (TTFT-shaped continuations — migration changes the prefill
side).  Every lane is parity-gated; each replica's compile count is
checked against its unchanged sentry budget.

``--replicas N --slo`` runs the BENCH_r12 **fleet observability**
protocol instead: SLO-classed traffic (realtime/interactive/standard/
batch round-robin) on an N-replica router with the whole observability
layer enabled — the federated fleet registry scraped from the LIVE
``/metrics`` endpoint while the step loop runs (parse + snapshot
agreement asserted), a drain-forced cross-replica KV pull whose
``s``/``f`` flow events are validated in the ONE merged Chrome trace,
per-class SLO attainment (``router.slo_report()``), the FLOPs/MFU
profiler (cost_analysis vs analytic agreement ≤10% asserted on at least
one family; ``--peak-flops`` is a *nominal* CPU-sim MFU denominator),
and the PR 8 ≤2% overhead contract re-verified fleet-wide with twin
fleets (everything on vs trace rings off).  With ``--replicas`` (either
protocol), ``--emit-metrics`` writes the **federated fleet** Prometheus
text + JSON snapshot — router + every replica registry with ``replica=``
labels — not one engine's registry.

``--chaos`` runs the BENCH_r14 **fault-tolerance** protocol (PR 15,
docs/reliability.md): seeded ``FaultPlan``s (``serving/faults.py``)
against the returning-sessions trace — (1) a crash lane killing one of
two tiered replicas mid-decode, gated on token-EXACT parity vs the
fault-free twin fleet, zero hung handles, and unchanged compile
budgets, with recovery latency read off the ``replica_fail`` →
``rehome`` timeline gap (add ``--quantize kv8`` for the kv8 crash
twin: bit-exact vs unfaulted kv8, bounded match vs fp32 sequential);
(2) a flaky-transport lane where a drain-forced migration must land
its pulls through the transient-fault retry/backoff machinery; (3) a
corruption lane flipping bits in EVERY host-tier arena entry after a
full drain — 100% must be caught by checksum (promote exit gates +
the final patrol scrub) and recovered via recompute, corrupt KV never
served; (4) an ``--overload``x batch burst against bounded admission —
``realtime``/``interactive`` submit-to-first-token p95 must hold
within 1.5x of the unloaded baseline while batch absorbs every
``RequestRejected``.

``--host-loop`` runs the BENCH_r15 **fused multi-step decode** protocol
(PR 16, docs/inference.md): the K=1 per-token host loop vs the fused
``decode_steps=K`` engine (one on-device ``lax.while_loop`` program, one
host fence per K-token window) on the BENCH_r09 returning-sessions
trace.  Gated on EXACT token parity (fp32) between the twins, a kv8
twin pair that is bit-exact between K=1-kv8 and fused-kv8, and the
headline: host scheduler decode iterations per generated token down
``>= --host-loop-min-reduction`` (default 4x; the committed artifact
runs K=8).  Fused tok/s >= the K=1 baseline and the trace-ring-off
telemetry twin's <=2% overhead contract are recorded and warn on
breach (wall-clock on shared boxes is noise-prone; the committed
BENCH_r15.json pins passing measurements).

``--sampling`` runs the BENCH_r18 **on-device sampling** protocol
(PR 20, docs/inference.md "Sampled decoding"): per-slot temperature/
top-k/top-p/seed ride as fixed-shape ``[slots]`` device operands of the
SAME compiled programs (greedy is the temperature-0 row — zero extra
programs, zero recompiles across greedy/sampled/constrained mixes), and
every gate is DETERMINISTIC because the counter-based PRNG keys are
pure functions of (request seed, tokens emitted).  Lanes: fresh-twin
stream determinism, temp-0 bit parity vs a ``sampling=False`` engine
and sequential ``generate``, ``decode_steps=K`` fused decode token-
EXACT vs K=1 (``grid_keys`` ≡ per-step ``slot_keys``) with the host-
iteration-reduction floor, speculative **rejection sampling** (n-gram
+ 1-layer draft model) gated on twin determinism, the 2-/3-program
compile budget, the deterministic tokens-per-host-decode-iteration
ratio >= ``--sampling-min-spec-speedup`` x plain sampling, and a
statistical-parity TV gate (rejection sampling is distribution-exact
for ANY proposer, so spec-sampled token histograms must sit inside the
self-calibrated reseeded-plain null band), plus the mixed greedy +
sampled + constrained-JSON trace on a ``logit_masks=True`` engine —
still 2 programs, sentry strict, every constrained completion valid
JSON.  CPU-sim wall tok/s is recorded, never gated.

``--long-context`` runs the BENCH_r17 **long-context serving** protocol
(PR 19, docs/inference.md "Long-context serving"): the sp=1 chunked
engine vs the ``sp=N`` Ulysses sequence-parallel prefill twin on
``--long-prompt-len``-token prompts (EXACT token parity and the
unchanged 2-program compile budget exit-fatal; the prefill wall-clock
speedup recorded and warned only — CPU-sim shard_map emulates the sp
mesh on one host), the ``resident_window_blocks=W`` decode lane with
the device pool sized under 25% of the served context (window slides,
host-tier demotion, full token budgets, and the unamended compile
budget all exit-fatal; full-window bit-identity against the plain
engine pins the exactness floor), and a 131072-token-declared windowed
engine probing the compile budget at 128k scale.

Usage:
  python benchmarks/serving_bench.py [--requests 64] [--slots 8]
      [--prefix-len 256] [--grid] [--decode-heavy] [--speculative K]
      [--tp N] [--quantize kv8,w8a8+kv8 | --quant-suite]
      [--replicas N] [--slo] [--chaos] [--host-loop] [--long-context]
      [--sampling] [--hidden 128] [--seed 0] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROMPT_RANGE = (32, 512)
NEW_TOKEN_RANGE = (16, 64)
#: --decode-heavy: short prompts, long completions — decode steps dominate
#: wall-clock (the BENCH_r04 147-decode-vs-55-prefill regime, amplified)
DECODE_HEAVY_PROMPT_RANGE = (16, 48)
DECODE_HEAVY_NEW_RANGE = (96, 160)
#: --prefix-len mode: unique tail length / completion budget ranges —
#: long shared context, short unique tail and output (the classification /
#: extraction-style traffic prefix caching exists for)
TAIL_RANGE = (16, 64)
PREFIX_NEW_RANGE = (8, 32)
# --grid shape grids: |prompts| * |budgets| stays under the engine's
# 32-entry LRU so a second sequential pass is compile-free (see module doc)
PROMPT_GRID = (32, 64, 96, 128, 192, 256, 384, 512)
NEW_TOKEN_GRID = (16, 32, 64)


def build_trace(n_requests: int, vocab: int, seed: int, grid: bool,
                prefix_len: int = 0, decode_heavy: bool = False,
                sessions: int = 0):
    """``sessions > 0`` (with ``prefix_len``) draws S distinct session
    prefixes and deals requests round-robin across them — the multi-turn
    chat shape: request i returns to session ``i % S`` with a fresh tail,
    AFTER the other sessions' traffic has pushed that session's blocks
    out of a pressure-sized pool.  This is the trace the tiered-KV lane
    runs: every return is a full re-prefill for the evict/preempt
    baseline and a host-tier promotion for the tiered engine."""
    from deepspeed_tpu.inference.serving import Request

    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, vocab, prefix_len) \
        if prefix_len and not sessions else None
    if sessions and prefix_len:
        prefixes = [rng.integers(0, vocab, prefix_len)
                    for _ in range(sessions)]
    reqs = []
    for i in range(n_requests):
        if sessions and prefix_len:
            tail = rng.integers(0, vocab,
                                int(rng.integers(TAIL_RANGE[0],
                                                 TAIL_RANGE[1] + 1)))
            prompt = np.concatenate([prefixes[i % sessions], tail])
            mnew = int(rng.integers(PREFIX_NEW_RANGE[0],
                                    PREFIX_NEW_RANGE[1] + 1))
            reqs.append(Request(uid=i, max_new_tokens=mnew, prompt=prompt))
            continue
        if decode_heavy:
            prompt = rng.integers(
                0, vocab, int(rng.integers(DECODE_HEAVY_PROMPT_RANGE[0],
                                           DECODE_HEAVY_PROMPT_RANGE[1] + 1)))
            mnew = int(rng.integers(DECODE_HEAVY_NEW_RANGE[0],
                                    DECODE_HEAVY_NEW_RANGE[1] + 1))
        elif prefix_len:
            tail = rng.integers(0, vocab,
                                int(rng.integers(TAIL_RANGE[0],
                                                 TAIL_RANGE[1] + 1)))
            prompt = np.concatenate([prefix, tail])
            mnew = int(rng.integers(PREFIX_NEW_RANGE[0],
                                    PREFIX_NEW_RANGE[1] + 1))
        elif grid:
            prompt = rng.integers(0, vocab, int(rng.choice(PROMPT_GRID)))
            mnew = int(rng.choice(NEW_TOKEN_GRID))
        else:
            prompt = rng.integers(0, vocab,
                                  int(rng.integers(PROMPT_RANGE[0],
                                                   PROMPT_RANGE[1] + 1)))
            mnew = int(rng.integers(NEW_TOKEN_RANGE[0],
                                    NEW_TOKEN_RANGE[1] + 1))
        reqs.append(Request(uid=i, max_new_tokens=mnew, prompt=prompt))
    return reqs


def run_sequential(engine, reqs):
    outs = {}
    t0 = time.perf_counter()
    for r in reqs:
        outs[r.uid] = engine.generate(r.prompt[None, :],
                                      max_new_tokens=r.max_new_tokens)[0]
    return outs, time.perf_counter() - t0


def run_bench(requests: int = 64, slots: int = 8, prefill_batch: int = 4,
              layers: int = 2, hidden: int = 128, heads: int = 4,
              vocab: int = 2048, seed: int = 0, dtype: str = "fp32",
              grid: bool = False, prefix_len: int = 0,
              block_size: int = 32, prefill_chunk: int = 128,
              speculative: int = 0, decode_heavy: bool = False,
              tp: int = 1, quantize: tuple = (),
              pool_frac: float = 0.0, swap_batch: int = 8,
              sessions: int = 0,
              telemetry_bench: bool = False, trace_out: str = None,
              emit_metrics: str = None):
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models import gpt2

    if decode_heavy:
        max_total = max(DECODE_HEAVY_PROMPT_RANGE) + max(DECODE_HEAVY_NEW_RANGE)
    elif prefix_len:
        max_total = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    else:
        max_total = max(PROMPT_GRID) + max(NEW_TOKEN_GRID)
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg), config={"dtype": dtype,
                                 "tensor_parallel": {"tp_size": 1}})
    reqs = build_trace(requests, vocab, seed, grid, prefix_len, decode_heavy,
                       sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)

    # --- sequential pass 1: per-shape compiles included — this IS the
    # sequential path's steady state on arbitrary request shapes
    seq_outs, seq_cold = run_sequential(engine, reqs)
    n_shapes = len({(len(r.prompt), r.max_new_tokens) for r in reqs})
    seq_warm = None
    if grid and not prefix_len:
        # grid mode: every shape program survived the LRU, pass 2 is
        # compile-free — the batching win isolated from the compile win
        assert n_shapes <= 32, "shape grid exceeds the LRU"
        _, seq_warm = run_sequential(engine, reqs)

    # --- bucketed fallback (PR 1-style slot-pool semantics on the paged
    # pool): bucket-ladder prefill, no prefix reuse
    buckets = tuple(b for b in PROMPT_GRID if b < max_total) + (max_total,)
    srv_b = ServingEngine(engine, slots=slots, max_seq_len=max_total,
                          prompt_buckets=buckets, prefill_batch=prefill_batch,
                          block_size=block_size)
    t0 = time.perf_counter()
    bkt_outs = srv_b.serve(reqs)
    bkt_cold = time.perf_counter() - t0
    bkt_stats_cold = srv_b.stats()
    # second pass on the same engine: compile-warm (no prefix cache in
    # bucketed mode, so there is nothing else to warm)
    t0 = time.perf_counter()
    bkt_outs2 = srv_b.serve(reqs)
    bkt_warm = time.perf_counter() - t0

    # --- paged chunked prefill + prefix cache: cold (compiles included),
    # then a second pass on the same engine — compile-warm AND prefix-warm
    # (the steady state under shared-prefix traffic)
    srv = ServingEngine(engine, slots=slots, max_seq_len=max_total,
                        prefill_batch=prefill_batch, block_size=block_size,
                        prefill_chunk=prefill_chunk)
    t0 = time.perf_counter()
    srv_outs = srv.serve(reqs)
    srv_cold = time.perf_counter() - t0
    stats_cold = srv.stats()               # pass-1 numbers (counters are
    t0 = time.perf_counter()               # cumulative across serve calls)
    srv_outs2 = srv.serve(reqs)
    srv_warm = time.perf_counter() - t0

    # --- speculative draft–verify on the same chunked engine config:
    # n-gram proposer drafts K per slot, one K+1 verify pass scores them
    spec_res = None
    if speculative:
        srv_s = ServingEngine(engine, slots=slots, max_seq_len=max_total,
                              prefill_batch=prefill_batch,
                              block_size=block_size,
                              prefill_chunk=prefill_chunk,
                              spec_tokens=speculative)
        t0 = time.perf_counter()
        spec_outs = srv_s.serve(reqs)
        spec_cold = time.perf_counter() - t0
        spec_stats_cold = srv_s.stats()
        t0 = time.perf_counter()
        spec_outs2 = srv_s.serve(reqs)
        spec_warm = time.perf_counter() - t0
        spec_res = {
            "tok_s": gen_tokens / spec_cold,
            "wall_s": spec_cold,
            "tok_s_warm": gen_tokens / spec_warm,
            "wall_warm_s": spec_warm,
            "compiled_programs": srv_s.compile_count,
            "spec_tokens": speculative,
            "acceptance_rate": spec_stats_cold["acceptance_rate"],
            "stats": spec_stats_cold,
            "stats_after_warm_pass": srv_s.stats(),
        }

    # --- tensor-parallel lane (--tp N): same chunked trace, weights
    # Megatron-sharded and the paged KV pool head-sharded over the tp mesh
    # axis.  The headline is per-chip KV pool bytes (~N× below the
    # replicated layout); CPU-sim tok/s under tp measures emulation
    # overhead, not hardware.  Token parity vs sequential is asserted.
    tp_res = None
    tp_outs = {}
    if tp > 1:
        import jax

        ndev = len(jax.devices())
        if ndev % tp:
            raise SystemExit(
                f"--tp {tp} does not divide the {ndev} visible devices — on "
                "CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8")
        deepspeed_tpu.comm.reset_topology()
        engine_tp = deepspeed_tpu.init_inference(
            gpt2.build(cfg), config={"dtype": dtype,
                                     "tensor_parallel": {"tp_size": tp}})
        srv_tp = ServingEngine(engine_tp, slots=slots, max_seq_len=max_total,
                               prefill_batch=prefill_batch,
                               block_size=block_size,
                               prefill_chunk=prefill_chunk)
        t0 = time.perf_counter()
        tp_outs = srv_tp.serve(reqs)
        tp_cold = time.perf_counter() - t0
        tp_stats = srv_tp.stats()
        t0 = time.perf_counter()
        tp_outs2 = srv_tp.serve(reqs)
        tp_warm = time.perf_counter() - t0
        tp_res = {
            "tp": tp,
            "tok_s": gen_tokens / tp_cold,
            "wall_s": tp_cold,
            "tok_s_warm": gen_tokens / tp_warm,
            "wall_warm_s": tp_warm,
            "compiled_programs": srv_tp.compile_count,
            "kv_sharded": tp_stats["kv_sharded"],
            "kv_pool_shape": tp_stats["kv_pool_shape"],
            "kv_pool_bytes": tp_stats["kv_pool_bytes"],
            "kv_pool_bytes_per_chip": tp_stats["kv_pool_bytes_per_chip"],
            "stats": tp_stats,
        }
        if speculative:
            srv_tp_s = ServingEngine(engine_tp, slots=slots,
                                     max_seq_len=max_total,
                                     prefill_batch=prefill_batch,
                                     block_size=block_size,
                                     prefill_chunk=prefill_chunk,
                                     spec_tokens=speculative)
            t0 = time.perf_counter()
            tp_spec_outs = srv_tp_s.serve(reqs)
            tp_spec_cold = time.perf_counter() - t0
            tp_res["speculative"] = {
                "tok_s": gen_tokens / tp_spec_cold,
                "wall_s": tp_spec_cold,
                "compiled_programs": srv_tp_s.compile_count,
                "acceptance_rate": srv_tp_s.stats()["acceptance_rate"],
                "kv_pool_bytes_per_chip":
                    srv_tp_s.stats()["kv_pool_bytes_per_chip"],
            }
            tp_outs = {u: (tp_outs[u], tp_spec_outs[u]) for u in tp_outs}
        else:
            tp_outs = {u: (tp_outs[u],) for u in tp_outs}
        tp_outs = {u: list(v) + [tp_outs2[u]] for u, v in tp_outs.items()}

    # --- quantized lanes (--quantize): int8 KV pool / w8a8 weights on the
    # same trace and engine config.  Bounded divergence replaces exact
    # parity here: the token match rate vs full-precision sequential is
    # measured and recorded (quantized greedy is a different — equally
    # valid — greedy model, so a near-tie argmax flip cascades).
    quant_res = {}
    if quantize:
        tu = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", "unit")
        if tu not in sys.path:     # idempotent: --quant-suite re-enters
            sys.path.insert(0, tu)
        from quant_divergence import token_match_rate

        for mode in quantize:
            eng_q = engine
            if "w8a8" in mode:
                deepspeed_tpu.comm.reset_topology()
                eng_q = deepspeed_tpu.init_inference(
                    gpt2.build(cfg),
                    config={"dtype": dtype,
                            "quant": {"enabled": True, "type": "w8a8"},
                            "tensor_parallel": {"tp_size": 1}})
            srv_q = ServingEngine(eng_q, slots=slots, max_seq_len=max_total,
                                  prefill_batch=prefill_batch,
                                  block_size=block_size,
                                  prefill_chunk=prefill_chunk,
                                  quantize=mode)
            t0 = time.perf_counter()
            q_outs = srv_q.serve(reqs)
            q_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            srv_q.serve(reqs)
            q_warm = time.perf_counter() - t0
            qst = srv_q.stats()
            # bf16 yardstick for the memory headline: the pool's payload
            # element count at 2 bytes (identical to a bf16 pool's actual
            # bytes; the parity baseline above runs fp32, which would
            # flatter the ratio by 2x)
            bf16_bytes = 2 * 2 * int(np.prod(qst["kv_pool_shape"]))
            quant_res[mode] = {
                "tok_s": gen_tokens / q_cold,
                "wall_s": q_cold,
                "tok_s_warm": gen_tokens / q_warm,
                "wall_warm_s": q_warm,
                "compiled_programs": srv_q.compile_count,
                "kv_dtype": qst["kv_dtype"],
                "weight_quant": qst["weight_quant"],
                "kv_pool_bytes": qst["kv_pool_bytes"],
                "kv_scale_bytes": qst["kv_scale_bytes"],
                "kv_pool_bytes_per_chip": qst["kv_pool_bytes_per_chip"],
                "servable_blocks_per_chip_vs_bf16":
                    bf16_bytes / qst["kv_pool_bytes"]
                    if qst["kv_dtype"] == "int8" else 1.0,
                "token_match_rate_vs_sequential":
                    token_match_rate(seq_outs, q_outs),
                "tok_s_vs_serving": (gen_tokens / q_cold) /
                    (gen_tokens / srv_cold),
                "tok_s_warm_vs_serving": srv_warm / q_warm,
            }
        if tp > 1 and any("kv8" in m for m in quant_res):
            # tp x kv8 combo: the per-chip pool divides by BOTH factors
            srv_tpq = ServingEngine(engine_tp, slots=slots,
                                    max_seq_len=max_total,
                                    prefill_batch=prefill_batch,
                                    block_size=block_size,
                                    prefill_chunk=prefill_chunk,
                                    quantize="kv8")
            t0 = time.perf_counter()
            tpq_outs = srv_tpq.serve(reqs)
            tpq_cold = time.perf_counter() - t0
            tpq_st = srv_tpq.stats()
            bf16_rep_per_chip = 2 * 2 * int(np.prod(tpq_st["kv_pool_shape"]))
            quant_res["kv8+tp"] = {
                "tp": tp,
                "tok_s": gen_tokens / tpq_cold,
                "wall_s": tpq_cold,
                "kv_sharded": tpq_st["kv_sharded"],
                "kv_pool_bytes_per_chip":
                    tpq_st["kv_pool_bytes_per_chip"],
                "servable_blocks_per_chip_vs_bf16_replicated":
                    bf16_rep_per_chip / tpq_st["kv_pool_bytes_per_chip"],
                "token_match_rate_vs_sequential":
                    token_match_rate(seq_outs, tpq_outs),
                "compiled_programs": srv_tpq.compile_count,
            }

    # --- tiered-KV lane (--pool-frac F): a device pool sized at F of the
    # trace working set (guaranteed block pressure), evict/preempt
    # baseline vs the host-DRAM tier with prefetch.  Zero parity loss is
    # the contract — both engines must match sequential exactly.
    tiered_res = None
    tiered_outs = {}
    if pool_frac:
        from deepspeed_tpu.inference.paged import chain_keys
        from deepspeed_tpu.ops.paged_kv import blocks_for

        # working set = UNIQUE cacheable content blocks (shared session
        # prefixes count once — the same dedup the prefix trie does) plus
        # each request's private tail/generation blocks
        uniq = set()
        private = 0
        for r in reqs:
            nfull = len(r.prompt) // block_size
            uniq.update(chain_keys(r.prompt, nfull, block_size))
            private += blocks_for(len(r.prompt) + r.max_new_tokens,
                                  block_size) - nfull
        ws_blocks = len(uniq) + private
        nbper = blocks_for(max_total, block_size)
        small = max(1 + nbper + 1, int(round(ws_blocks * pool_frac)) + 1)
        small_kw = dict(slots=slots, max_seq_len=max_total,
                        prefill_batch=prefill_batch, block_size=block_size,
                        prefill_chunk=prefill_chunk, num_blocks=small)
        srv_small = ServingEngine(engine, **small_kw)
        t0 = time.perf_counter()
        small_outs = srv_small.serve(reqs)
        small_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        small_outs2 = srv_small.serve(reqs)
        small_warm = time.perf_counter() - t0
        small_stats = srv_small.stats()

        srv_t = ServingEngine(engine, host_blocks=ws_blocks + nbper,
                              swap_batch=swap_batch, **small_kw)
        t0 = time.perf_counter()
        t_outs = srv_t.serve(reqs)
        t_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        t_outs2 = srv_t.serve(reqs)
        t_warm = time.perf_counter() - t0
        t_stats = srv_t.stats()
        tiered_outs = {u: (t_outs[u], t_outs2[u], small_outs[u],
                           small_outs2[u]) for u in t_outs}
        tiered_res = {
            "pool_frac": pool_frac,
            "working_set_blocks": ws_blocks,
            "device_pool_blocks": small,
            "host_blocks": ws_blocks + nbper,
            "host_pool_bytes": t_stats["host_pool_bytes"],
            "swap_batch": swap_batch,
            "tiered": {
                "tok_s": gen_tokens / t_cold,
                "wall_s": t_cold,
                "tok_s_warm": gen_tokens / t_warm,
                "wall_warm_s": t_warm,
                "compiled_programs": srv_t.compile_count,
                "swap_out": t_stats["swap_out"],
                "swap_in": t_stats["swap_in"],
                "swap_bytes": t_stats["swap_bytes"],
                "prefetch_misses": t_stats["prefetch_misses"],
                "prefetch_wait_p50_s": t_stats["prefetch_wait_p50_s"],
                "prefetch_wait_p95_s": t_stats["prefetch_wait_p95_s"],
                "preempted": t_stats["evicted"],
                "resume_recompute_tokens":
                    t_stats["resume_recompute_tokens"],
                "prefix_cache_hit_rate": t_stats["prefix_cache_hit_rate"],
            },
            "preemption_baseline": {
                "tok_s": gen_tokens / small_cold,
                "wall_s": small_cold,
                "tok_s_warm": gen_tokens / small_warm,
                "wall_warm_s": small_warm,
                "compiled_programs": srv_small.compile_count,
                "preempted": small_stats["evicted"],
                "resume_recompute_tokens":
                    small_stats["resume_recompute_tokens"],
                "prefix_cache_hit_rate":
                    small_stats["prefix_cache_hit_rate"],
            },
            "speedup_tiered_vs_preemption": small_cold / t_cold,
            "speedup_tiered_vs_preemption_warm": small_warm / t_warm,
        }

    # --- telemetry overhead lane (--telemetry-bench): twin engines, same
    # config, differing ONLY in the trace-event ring (off vs default) —
    # interleaved best-of-3 compile-warm passes bound the wall-clock
    # noise on a shared box.  The registry behind stats() is always on in
    # both (it replaced the loose counter attributes 1:1), so this
    # isolates the cost of the event stream the ≤2% contract covers.
    telemetry_res = None
    if telemetry_bench:
        from deepspeed_tpu.telemetry import validate_chrome_trace

        def _mk(cap):
            return ServingEngine(engine, slots=slots, max_seq_len=max_total,
                                 prefill_batch=prefill_batch,
                                 block_size=block_size,
                                 prefill_chunk=prefill_chunk,
                                 trace_capacity=cap)

        srv_off, srv_on = _mk(0), _mk(16384)
        srv_off.serve(reqs)                 # compile + prefix-warm pass
        srv_on.serve(reqs)
        # interleaved best-of-3 pairs: machine drift (cache state, GC,
        # neighbors on a shared box) hits both engines alike instead of
        # biasing whichever ran last
        off_warm = on_warm = float("inf")
        on_outs = None
        for _ in range(3):
            t0 = time.perf_counter()
            srv_off.serve(reqs)
            off_warm = min(off_warm, time.perf_counter() - t0)
            t0 = time.perf_counter()
            on_outs = srv_on.serve(reqs)
            on_warm = min(on_warm, time.perf_counter() - t0)
        doc = srv_on.timeline.to_chrome()
        trace_summary = validate_chrome_trace(doc)   # raises if malformed
        if trace_out:
            srv_on.dump_trace(trace_out)
        on_stats = srv_on.stats()
        telemetry_res = {
            "tok_s_warm_off": gen_tokens / off_warm,
            "tok_s_warm_on": gen_tokens / on_warm,
            "wall_warm_off_s": off_warm,
            "wall_warm_on_s": on_warm,
            "overhead_pct": (on_warm / off_warm - 1.0) * 100.0,
            "within_2pct": on_warm <= off_warm * 1.02,
            "token_parity": all(np.array_equal(srv_outs[r.uid],
                                               on_outs[r.uid])
                                for r in reqs),
            "trace_valid": True,            # validate_chrome_trace passed
            "trace_summary": trace_summary,
            "trace_events_recorded": on_stats["trace_events"],
            "trace_events_dropped": on_stats["trace_events_dropped"],
            "trace_out": trace_out,
        }

    # --- metrics artifact (--emit-metrics): the headline serving engine's
    # Prometheus text + JSON registry snapshot, next to the bench JSON
    metrics_files = None
    if emit_metrics:
        with open(emit_metrics, "w") as f:
            f.write(srv.metrics.prometheus_text())
        snap_path = emit_metrics + ".json"
        with open(snap_path, "w") as f:
            f.write(srv.metrics.snapshot_json())
        metrics_files = {"prometheus": emit_metrics, "snapshot": snap_path}

    mismatches = [r.uid for r in reqs
                  if not (np.array_equal(seq_outs[r.uid], srv_outs[r.uid])
                          and np.array_equal(seq_outs[r.uid],
                                             srv_outs2[r.uid])
                          and np.array_equal(seq_outs[r.uid],
                                             bkt_outs[r.uid])
                          and np.array_equal(seq_outs[r.uid],
                                             bkt_outs2[r.uid])
                          and all(np.array_equal(seq_outs[r.uid], o)
                                  for o in tp_outs.get(r.uid, ()))
                          and all(np.array_equal(seq_outs[r.uid], o)
                                  for o in tiered_outs.get(r.uid, ()))
                          and (speculative == 0 or
                               (np.array_equal(seq_outs[r.uid],
                                               spec_outs[r.uid])
                                and np.array_equal(seq_outs[r.uid],
                                                   spec_outs2[r.uid]))))]
    result = {
        "trace": (f"decode-heavy prompts {DECODE_HEAVY_PROMPT_RANGE}, "
                  f"new {DECODE_HEAVY_NEW_RANGE}") if decode_heavy else
                 (f"{sessions} sessions x {prefix_len}-token prefixes "
                  f"(round-robin returns), tails {TAIL_RANGE}, new "
                  f"{PREFIX_NEW_RANGE}") if sessions and prefix_len else
                 (f"shared {prefix_len}-token prefix, tails {TAIL_RANGE}, "
                  f"new {PREFIX_NEW_RANGE}") if prefix_len else
                 ("shape-grid" if grid else
                  f"arbitrary prompts {PROMPT_RANGE}, new {NEW_TOKEN_RANGE}"),
        "requests": requests,
        "prefix_len": prefix_len,
        "request_shapes": n_shapes,
        "generated_tokens": gen_tokens,
        "sequential": {
            "tok_s": gen_tokens / seq_cold,
            "wall_s": seq_cold,
            "tok_s_warm": gen_tokens / seq_warm if seq_warm else None,
            "wall_warm_s": seq_warm,
            # resident programs only — the engine LRU caps at 32, so on the
            # arbitrary-shape trace true compile count is >= request_shapes
            "compiled_programs": len(engine._generate_fns),
        },
        "serving": {
            "tok_s": gen_tokens / srv_cold,
            "wall_s": srv_cold,
            "tok_s_warm": gen_tokens / srv_warm,
            "wall_warm_s": srv_warm,
            "compiled_programs": srv.compile_count,
            "slots": slots, "prefill_batch": prefill_batch,
            "stats": stats_cold,
            "stats_after_warm_pass": srv.stats(),
        },
        "serving_bucketed": {
            "tok_s": gen_tokens / bkt_cold,
            "wall_s": bkt_cold,
            "tok_s_warm": gen_tokens / bkt_warm,
            "wall_warm_s": bkt_warm,
            "compiled_programs": srv_b.compile_count,
            "stats": bkt_stats_cold,
        },
        "speedup": seq_cold / srv_cold,
        "speedup_warm": (seq_warm / srv_warm) if seq_warm else None,
        # the paged/chunked/prefix win over the PR 1-style bucketed slot
        # pool: compiles included, and the compile-warm steady state
        "speedup_vs_bucketed": bkt_cold / srv_cold,
        "speedup_vs_bucketed_warm": bkt_warm / srv_warm,
        "serving_speculative": spec_res,
        # the draft–verify win over single-token decode, same engine config
        "speedup_spec_vs_chunked": (srv_cold / spec_res["wall_s"])
        if spec_res else None,
        "speedup_spec_vs_chunked_warm": (srv_warm / spec_res["wall_warm_s"])
        if spec_res else None,
        "serving_tp": tp_res,
        "serving_quant": quant_res or None,
        # tiered-KV vs evict/preempt baseline on a pressure-sized pool
        # (the BENCH_r09 lane, module docstring)
        "serving_tiered": tiered_res,
        # telemetry-on vs telemetry-off twin engines + trace-schema check
        # (the BENCH_r08 ≤2% overhead contract, module docstring)
        "serving_telemetry": telemetry_res,
        "metrics_files": metrics_files,
        # the memory headline: per-chip KV pool bytes, replicated vs
        # head-sharded — sharding shrinks the per-chip share by ~tp
        "kv_bytes_per_chip_replicated":
            stats_cold["kv_pool_bytes_per_chip"],
        "kv_bytes_per_chip_tp": tp_res["kv_pool_bytes_per_chip"]
        if tp_res else None,
        "kv_per_chip_shrink": (stats_cold["kv_pool_bytes_per_chip"] /
                               tp_res["kv_pool_bytes_per_chip"])
        if tp_res else None,
        "token_parity": not mismatches and
        (telemetry_res is None or telemetry_res["token_parity"]),
        "mismatched_uids": mismatches,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }
    return result


_PROM_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})?)\s+'
    r'([+-]?(?:[0-9.eE+-]+|[Ii]nf|NaN))$')


def parse_prometheus_text(text: str):
    """Minimal Prometheus text-format parser: returns ``{sample_line_key:
    value}`` and raises ``ValueError`` on the first malformed line — the
    live-scrape acceptance check ("parses as Prometheus text")."""
    out = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        m = _PROM_LINE.match(ln)
        if m is None:
            raise ValueError(f"malformed Prometheus sample line: {ln!r}")
        out[m.group(1)] = float(m.group(2))
    return out


def run_fleet_observability_bench(replicas: int = 2, requests: int = 64,
                                  slots: int = 8, prefill_batch: int = 4,
                                  layers: int = 2, hidden: int = 128,
                                  heads: int = 4, vocab: int = 2048,
                                  seed: int = 0, dtype: str = "fp32",
                                  block_size: int = 32,
                                  prefill_chunk: int = 128,
                                  prefix_len: int = 192,
                                  sessions: int = 9, swap_batch: int = 8,
                                  peak_flops: float = 1e12,
                                  emit_metrics: str = None,
                                  trace_out: str = None):
    """The BENCH_r12 fleet observability protocol (``--replicas N
    --slo``): an SLO-classed returning-session trace on an N-replica
    router with the whole observability layer enabled — metrics
    federation scraped from the LIVE ``/metrics`` endpoint while the
    step loop runs, per-class SLO attainment, ONE merged Chrome trace
    with router→replica and kv-pull flow events validated, the
    cost_analysis/analytic FLOPs agreement + MFU/busy breakdown, and
    the PR 8 ≤2% overhead contract re-verified fleet-wide (twin fleets:
    everything on vs trace rings off).  ``peak_flops`` is a *nominal*
    MFU denominator on CPU-sim (the gauge mechanics, not a hardware
    claim).  Parity-gated vs sequential; per-replica compile budgets
    asserted unchanged."""
    import threading
    import urllib.request

    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.ops.paged_kv import blocks_for
    from deepspeed_tpu.serving import ReplicaRouter
    from deepspeed_tpu.telemetry import validate_chrome_trace

    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    spec = gpt2.build(cfg)
    max_total = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    nbper = blocks_for(max_total, block_size)
    state = {"params": None}

    def mk_engine():
        eng = deepspeed_tpu.init_inference(
            spec, config={"dtype": dtype,
                          "tensor_parallel": {"tp_size": 1}},
            params=state["params"])
        if state["params"] is None:
            state["params"] = eng.params
        return eng

    hb = sessions * (prefix_len // block_size + 2) + 2 * nbper

    def fleet(trace_capacity=16384, router_trace_capacity=8192):
        srvs = [ServingEngine(mk_engine(), slots=slots,
                              max_seq_len=max_total,
                              prefill_batch=prefill_batch,
                              block_size=block_size,
                              prefill_chunk=prefill_chunk,
                              host_blocks=hb, swap_batch=swap_batch,
                              trace_capacity=trace_capacity)
                for _ in range(replicas)]
        return ReplicaRouter(srvs, policy="affinity", kv_pull=True,
                             trace_capacity=router_trace_capacity)

    reqs = build_trace(requests, vocab, seed, False, prefix_len, False,
                       sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)
    classes = ("realtime", "interactive", "standard", "batch")
    seq_engine = mk_engine()
    seq_outs, seq_wall = run_sequential(seq_engine, reqs)
    mismatched = []

    def gate(tag, outs, keys=None):
        for r in reqs if keys is None else keys:
            if not np.array_equal(seq_outs[r.uid], outs[r.uid]):
                mismatched.append((tag, r.uid))

    # --- phase 1: SLO-classed traffic with a LIVE scrape mid-loop -------
    router = fleet()
    server = router.start_metrics_server(port=0)
    url = f"http://127.0.0.1:{server.port}"
    handles = [router.submit(r, slo_class=classes[i % len(classes)])
               for i, r in enumerate(reqs)]

    live = {"scrapes": 0, "error": None}

    def drive():
        while router.step():
            pass

    t = threading.Thread(target=drive)
    t0 = time.perf_counter()
    t.start()
    # the acceptance check: the endpoint answers (and parses) WHILE the
    # scheduler steps — a scrape is a lock-bracketed registry walk, so
    # it interleaves with the loop rather than waiting it out
    while t.is_alive():
        try:
            text = urllib.request.urlopen(url + "/metrics",
                                          timeout=5).read().decode()
            parse_prometheus_text(text)
            live["scrapes"] += 1
        except Exception as e:       # noqa: BLE001 — recorded, gated below
            live["error"] = repr(e)
        t.join(timeout=0.05)
    t.join()
    wall_cold = time.perf_counter() - t0
    gate("slo-trace", {h.uid: h.result(timeout=0) for h in handles})

    # --- phase 2: drain -> cross-replica KV pulls (flow-event source) ---
    loads = [len(rep._prefix._entries) if rep._prefix else 0
             for rep in router.replicas]
    rid0 = int(np.argmax([router.replicas[r]._alloc.blocks_in_use
                          for r in range(replicas)]))
    router.drain(rid0)
    rng = np.random.default_rng(seed + 1)
    conts = [Request(uid=f"cont{i}",
                     prompt=np.concatenate(
                         [reqs[i % sessions].prompt[:prefix_len],
                          rng.integers(0, vocab, 6 + i % 3)]),
                     max_new_tokens=4) for i in range(sessions)]
    seq_conts = {c.uid: seq_engine.generate(
        c.prompt[None, :], max_new_tokens=c.max_new_tokens)[0]
        for c in conts}
    cont_outs = router.serve(conts)
    for c in conts:
        if not np.array_equal(seq_conts[c.uid], cont_outs[c.uid]):
            mismatched.append(("cont", c.uid))
    router.readmit(rid0)

    # --- phase 3: quiesced scrape agrees with the federated snapshot ----
    text = urllib.request.urlopen(url + "/metrics",
                                  timeout=5).read().decode()
    samples = parse_prometheus_text(text)
    fed_snap = router.fleet_registry().snapshot()
    spot = {}
    agree = True
    for name in ("serving_requests_finished_total",
                 "serving_generated_tokens_total",
                 "serving_kv_pulls_total",
                 "serving_routed_affinity_total"):
        fam = fed_snap.get(name, {"series": []})
        for s in fam["series"]:
            labels = ",".join(f'{k}="{v}"'
                              for k, v in sorted(s["labels"].items()))
            key = f"{name}{{{labels}}}" if labels else name
            scraped = samples.get(key)
            spot[key] = [scraped, s["value"]]
            agree &= scraped == s["value"]
    rstats = router.stats()

    # --- phase 4: merged multi-replica trace + flow-event validation ----
    merged = router.merged_trace()
    trace_summary = validate_chrome_trace(merged)   # raises if malformed
    flows = [e for e in merged["traceEvents"] if e["ph"] in ("s", "f")]
    route_flows = sum(1 for e in flows
                      if e["name"] == "route" and e["ph"] == "f")
    pull_flows = [e for e in flows if e["name"] == "kv_pull"]
    pull_cross_lane = any(
        s["pid"] != f["pid"]
        for s in pull_flows if s["ph"] == "s"
        for f in pull_flows if f["ph"] == "f" and f["id"] == s["id"])
    if trace_out:
        router.dump_merged_trace(trace_out)

    # --- phase 5: FLOPs/MFU (cost_analysis vs analytic agreement) -------
    rid_live = min(r for r in range(len(router.replicas)) if r != rid0)
    frep = router.replicas[rid_live].flops_report(peak_flops=peak_flops)
    # agreement is only meaningful where cost_analysis actually reported
    # — an analytic-fallback family has flops_per_call == flops_analytic
    # by construction (rel err 0 would gate vacuously)
    rel_errs = {
        f: abs(p["flops_per_call"] - p["flops_analytic"])
        / max(p["flops_analytic"], 1.0)
        for f, p in frep["programs"].items()
        if p["flops_cost_analysis"] is not None}
    flops_ok = bool(rel_errs) and min(rel_errs.values()) <= 0.10

    slo_report = router.slo_report()
    budgets_ok = all(p["compile_count"] <= p["compile_budget"]
                     for p in rstats["per_replica"])
    if emit_metrics:
        with open(emit_metrics, "w") as f:
            f.write(router.fleet_metrics_text())
        with open(emit_metrics + ".json", "w") as f:
            json.dump(router.fleet_snapshot(), f, indent=2)
    router.stop()

    # --- phase 6: the ≤2% overhead contract, fleet-wide -----------------
    # twin fleets differing ONLY in the observability layer: everything
    # on (trace rings + live server + SLO + FLOPs profiler built) vs
    # rings off / no server.  Interleaved best-of-3 warm passes (the
    # PR 8 methodology) bound box noise; the registry + SLO accounting
    # are always on in both — they replaced plain attributes 1:1.
    f_off = fleet(trace_capacity=0, router_trace_capacity=0)
    f_on = fleet()
    f_on.start_metrics_server(port=0)
    on_url = f"http://127.0.0.1:{f_on.metrics_server.port}"

    def serve_classed(rt, trace):
        hs = [rt.submit(r, slo_class=classes[i % len(classes)])
              for i, r in enumerate(trace)]
        while rt.step():
            pass
        return {h.uid: h.result(timeout=0) for h in hs}

    gate("twin-off-warmup", serve_classed(f_off, reqs))
    gate("twin-on-warmup", serve_classed(f_on, reqs))
    f_on.replicas[0].flops_report(peak_flops=peak_flops)
    off_warm = on_warm = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        serve_classed(f_off, reqs)
        off_warm = min(off_warm, time.perf_counter() - t0)
        t0 = time.perf_counter()
        on_outs = serve_classed(f_on, reqs)
        on_warm = min(on_warm, time.perf_counter() - t0)
    gate("twin-on", on_outs)
    urllib.request.urlopen(on_url + "/metrics", timeout=5).read()
    f_on.replicas[0].flops_report(peak_flops=peak_flops)
    f_on.stop()

    return {
        "protocol": "fleet observability (PR 12): SLO-classed traffic "
                    "on an N-replica router with federation + live "
                    "/metrics scrape + merged distributed trace + "
                    "FLOPs/MFU profiler, ≤2% twin-fleet overhead "
                    "contract, parity-gated vs sequential",
        "replicas": replicas,
        "requests": requests,
        "generated_tokens": gen_tokens,
        "trace": f"{sessions} sessions x {prefix_len}-token prefixes, "
                 f"slo classes {classes} round-robin",
        "sequential": {"tok_s": gen_tokens / seq_wall,
                       "wall_s": seq_wall},
        "fleet_tok_s_cold": gen_tokens / wall_cold,
        "slo": slo_report,
        "federation": {
            "live_scrapes_during_step_loop": live["scrapes"],
            "live_scrape_error": live["error"],
            "scrape_parses": True,          # parse_prometheus_text passed
            "scrape_agrees_with_snapshot": agree,
            "spot_checks": spot,
            "metrics_endpoint": url,
        },
        "merged_trace": {
            "summary": trace_summary,
            "route_flow_ends": route_flows,
            "kv_pull_flow_events": len(pull_flows),
            "kv_pull_crosses_replica_lanes": pull_cross_lane,
            "kv_pulls": rstats["kv_pulls"],
            "drains": rstats["drains"],
            "sources": merged["otherData"]["sources"],
            "trace_out": trace_out,
        },
        "flops": {
            "programs": frep["programs"],
            "per_family_rel_err": rel_errs,
            "agreement_within_10pct": flops_ok,
            "model_flops_total": frep["model_flops_total"],
            "flops_per_generated_token":
                frep["flops_per_generated_token"],
            "peak_flops_nominal": peak_flops,
            "mfu": frep["mfu"],
            "busy_fractions": frep["busy_fractions"],
        },
        "overhead": {
            "tok_s_warm_off": gen_tokens / off_warm,
            "tok_s_warm_on": gen_tokens / on_warm,
            "wall_warm_off_s": off_warm,
            "wall_warm_on_s": on_warm,
            "overhead_pct": (on_warm / off_warm - 1.0) * 100.0,
            "within_2pct": on_warm <= off_warm * 1.02,
        },
        "compile_budgets_ok": budgets_ok,
        "per_replica_compiles": [[p["compile_count"], p["compile_budget"]]
                                 for p in rstats["per_replica"]],
        "prefix_entry_loads_at_drain": loads,
        "token_parity": not mismatched,
        "mismatched": mismatched,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }


def run_replica_bench(replicas: int = 4, requests: int = 64,
                      slots: int = 8, prefill_batch: int = 4,
                      layers: int = 2, hidden: int = 128, heads: int = 4,
                      vocab: int = 2048, seed: int = 0,
                      dtype: str = "fp32", block_size: int = 32,
                      prefill_chunk: int = 128, prefix_len: int = 192,
                      sessions: int = 9, swap_batch: int = 8,
                      emit_metrics: str = None):
    # sessions defaults ODD on purpose: a session count divisible by the
    # replica count strides round-robin routing into perfect session
    # co-location (request i of session i%S lands on replica i%R — same
    # replica whenever R | S), which would flatter the baseline
    """The BENCH_r10 multi-replica router protocol (module docstring
    ``--replicas``): scaling over 1→2→4 replicas, affinity vs
    round-robin, and the drained-replica KV-pull migration."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.ops.paged_kv import blocks_for
    from deepspeed_tpu.serving import ReplicaRouter

    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    spec = gpt2.build(cfg)
    max_total = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    nbper = blocks_for(max_total, block_size)
    state = {"params": None}

    def mk_engine():
        eng = deepspeed_tpu.init_inference(
            spec, config={"dtype": dtype,
                          "tensor_parallel": {"tp_size": 1}},
            params=state["params"])
        if state["params"] is None:
            state["params"] = eng.params     # every replica shares weights
        return eng

    def fleet(n, policy="affinity", host_blocks=0, kv_pull=True,
              num_blocks=None):
        extra = {"host_blocks": host_blocks, "swap_batch": swap_batch} \
            if host_blocks else {}
        if num_blocks is not None:
            extra["num_blocks"] = num_blocks
        srvs = [ServingEngine(mk_engine(), slots=slots,
                              max_seq_len=max_total,
                              prefill_batch=prefill_batch,
                              block_size=block_size,
                              prefill_chunk=prefill_chunk, **extra)
                for _ in range(n)]
        return ReplicaRouter(srvs, policy=policy, kv_pull=kv_pull)

    reqs = build_trace(requests, vocab, seed, False, prefix_len, False,
                       sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)
    seq_engine = mk_engine()
    seq_outs, seq_wall = run_sequential(seq_engine, reqs)
    mismatched = []

    # working set in blocks (unique shared prefixes + private tails) —
    # sizes the scaling pools (no pressure: isolates pure DP scaling
    # from the aggregate-HBM capacity win) and the pressure lanes below
    from deepspeed_tpu.inference.paged import chain_keys
    uniq = set()
    private = 0
    for r in reqs:
        nfull = len(r.prompt) // block_size
        uniq.update(chain_keys(r.prompt, nfull, block_size))
        private += blocks_for(len(r.prompt) + r.max_new_tokens,
                              block_size) - nfull
    ws_blocks = len(uniq) + private
    big = 1 + ws_blocks + slots * nbper
    small = max(1 + nbper + 1, int(round(ws_blocks * 0.35)) + 1)

    def gate(tag, outs):
        for r in reqs:
            if not np.array_equal(seq_outs[r.uid], outs[r.uid]):
                mismatched.append((tag, r.uid))

    # --- scaling 1 -> 2 -> 4, WEAK: n replicas serve n x the traffic
    # (requests*n over sessions*n — the DP capacity claim: add a replica,
    # serve another replica's worth of users) with per-replica load held
    # constant.  Every scale gets a pool that holds its replica share of
    # the working set, so the ratio measures replica scaling, not
    # eviction luck.  Warm passes are INTERLEAVED 3-round across the
    # scales (the telemetry lane's trick) and busy/token deltas sum over
    # all rounds: wall-clock drift on a shared box hits every scale
    # alike instead of biasing whichever lane ran last.  Parity: the
    # base trace gates vs sequential; the bigger weak traces gate vs a
    # fresh single-replica fleet serving the identical trace (engine vs
    # sequential parity is the n=1 gate + every other serving test).
    scales = [n for n in (1, 2, 4) if n <= replicas]
    traces = {1: reqs}
    refs = {1: seq_outs}
    for n in scales:
        if n == 1:
            continue
        tr = build_trace(requests * n, vocab, seed, False, prefix_len,
                         False, sessions * n)
        traces[n] = tr
        refs[n] = fleet(1, num_blocks=n * big).serve(tr)
    fleets = {}
    scaling = {}
    for n in scales:
        router = fleet(n, num_blocks=big)
        t0 = time.perf_counter()
        outs = router.serve(traces[n])      # compile + prefix-warm pass
        cold = time.perf_counter() - t0
        for r in traces[n]:
            if not np.array_equal(refs[n][r.uid], outs[r.uid]):
                mismatched.append((f"scale{n}-cold", r.uid))
        fleets[n] = router
        gen_n = sum(r.max_new_tokens for r in traces[n])
        scaling[str(n)] = {"replicas": n,
                           "requests": len(traces[n]),
                           "generated_tokens": gen_n,
                           "wall_cold_s": cold,
                           "tok_s_wall_cold": gen_n / cold}
    acc = {n: [0.0, [0.0] * n, [0.0] * n] for n in scales}  # wall, busy, gen
    for _ in range(3):
        for n in scales:
            router = fleets[n]
            busy0 = router.busy_seconds
            gen0 = [p["generated_tokens"]
                    for p in router.stats()["per_replica"]]
            t0 = time.perf_counter()
            outs2 = router.serve(traces[n])
            warm = time.perf_counter() - t0
            for r in traces[n]:
                if not np.array_equal(refs[n][r.uid], outs2[r.uid]):
                    mismatched.append((f"scale{n}-warm", r.uid))
            busy1 = router.busy_seconds
            gen1 = [p["generated_tokens"]
                    for p in router.stats()["per_replica"]]
            acc[n][0] += warm
            acc[n][1] = [a + (b1 - b0) for a, b0, b1 in
                         zip(acc[n][1], busy0, busy1)]
            acc[n][2] = [a + (g1 - g0) for a, g0, g1 in
                         zip(acc[n][2], gen0, gen1)]
    for n in scales:
        wall3, busy, gens = acc[n]
        st = fleets[n].stats()
        gen_n = scaling[str(n)]["generated_tokens"]
        scaling[str(n)].update({
            "wall_warm_s": wall3 / 3,
            "tok_s_wall_warm": gen_n / (wall3 / 3),
            "busy_warm_s": busy,
            "aggregate_tok_s_busy": sum(
                g / max(b, 1e-9) for g, b in zip(gens, busy) if g > 0),
            "routed_affinity": st["routed_affinity"],
            "routed_balance": st["routed_balance"],
            "prefix_cache_hit_rate": st["prefix_cache_hit_rate"],
            "compile_budgets_ok": all(
                p["compile_count"] <= p["compile_budget"]
                for p in st["per_replica"]),
            "per_replica_compiles": [
                [p["compile_count"], p["compile_budget"]]
                for p in st["per_replica"]],
        })
    fleets.clear()                          # free the pools
    ratios = {}
    for a, b in ((1, 2), (2, 4)):
        if str(a) in scaling and str(b) in scaling:
            ratios[f"{a}to{b}"] = (scaling[str(b)]["aggregate_tok_s_busy"]
                                   / scaling[str(a)]["aggregate_tok_s_busy"])

    # --- affinity vs round-robin twin fleets at 2 replicas on a
    # PRESSURE-SIZED device pool (the tiered-lane working-set math):
    # affinity halves each replica's session working set, round-robin
    # makes every replica carry all of it — the hit-rate gap IS the
    # routing policy's value under real block pressure
    aff_vs_rr = None
    if replicas >= 2:
        r_aff = fleet(2, num_blocks=small)
        gate("aff-cold", r_aff.serve(reqs))
        aff_cold = r_aff.stats()["prefix_cache_hit_rate"]
        gate("aff-warm", r_aff.serve(reqs))
        r_rr = fleet(2, policy="round_robin", num_blocks=small)
        gate("rr-cold", r_rr.serve(reqs))
        rr_cold = r_rr.stats()["prefix_cache_hit_rate"]
        gate("rr-warm", r_rr.serve(reqs))
        sa, sr = r_aff.stats(), r_rr.stats()
        aff_vs_rr = {
            "device_pool_blocks": small,
            "working_set_blocks": ws_blocks,
            "affinity_hit_rate_cold": aff_cold,
            "round_robin_hit_rate_cold": rr_cold,
            "affinity_hit_rate": sa["prefix_cache_hit_rate"],
            "round_robin_hit_rate": sr["prefix_cache_hit_rate"],
            "affinity_routed": [sa["routed_affinity"],
                                sa["routed_balance"]],
            "hit_rate_advantage": (sa["prefix_cache_hit_rate"]
                                   - sr["prefix_cache_hit_rate"]),
        }

    # --- drained-replica migration: sessions co-locate under affinity,
    # the owning replica drains (chains demote to ITS host tier), and a
    # continuation of its session resumes on the cold replica via the
    # cross-replica KV pull — vs a kv_pull=False twin that re-prefills
    # the whole prompt.  Zero prefix recompute means the cold replica
    # prefills only the mandatory sub-block tail.
    migration = None
    if replicas >= 2:
        hb = sessions * (prefix_len // block_size + 2) + 2 * nbper
        # request i belongs to session i % sessions (build_trace), so the
        # first `sessions` requests carry each session's shared prefix
        prefixes = [reqs[j].prompt[:prefix_len] for j in range(sessions)]

        def prep_migration(kv_pull):
            # pressure-sized device pool: the trace itself exercises the
            # demote/promote swap programs on BOTH replicas, so the timed
            # migration below is compile-free on every side
            router = fleet(2, host_blocks=hb, kv_pull=kv_pull,
                           num_blocks=small)
            gate(f"mig-pull{kv_pull}-trace", router.serve(reqs))
            gate(f"mig-pull{kv_pull}-warm", router.serve(reqs))
            # each session's home replica, then drain the busier home and
            # continue EVERY migrated session on the survivor — the
            # pull-vs-recompute gap scales with the migrated population
            # instead of drowning in single-request timing noise
            homes = []
            for p in prefixes:
                probe = [router.replicas[r].affinity_probe(
                    np.concatenate([p, [0]])) for r in range(2)]
                homes.append(int(np.argmax(
                    [q["device_blocks"] + q["host_blocks"]
                     for q in probe])))
            rid0 = int(np.argmax([homes.count(r) for r in range(2)]))
            migrated = [j for j, h in enumerate(homes) if h == rid0]
            # short completion budgets on purpose: migration changes the
            # PREFILL side (pull vs recompute the prefix), so the timed
            # window is TTFT-shaped — a long decode tail would be the
            # same on both sides and bury the difference
            rng = np.random.default_rng(seed + 1)
            conts = [Request(uid=f"mig{j}-{k}",
                             prompt=np.concatenate(
                                 [prefixes[j],
                                  rng.integers(0, vocab, 9 + k)]),
                             max_new_tokens=4)
                     for j in migrated for k in range(2)]
            seq_cont = {c.uid: seq_engine.generate(
                c.prompt[None, :], max_new_tokens=c.max_new_tokens)[0]
                for c in conts}
            router.drain(rid0)
            return router, router.replicas[1 - rid0], conts, seq_cont

        def timed_migration(prep, tag):
            router, tgt, conts, seq_cont = prep
            # dispatch warmup outside the window: one session-free short
            # request (sub-block prompt: no trie/host interaction) so the
            # first timed iteration doesn't pay cold host caches for
            # whatever ran since this fleet's prep
            wrng = np.random.default_rng(seed + 2)
            router.serve([Request(uid=f"warm-{tag}",
                                  prompt=wrng.integers(0, vocab, 8),
                                  max_new_tokens=2)])
            pt0, ht0 = tgt.prompt_tokens, tgt.prefix_hit_tokens
            t0 = time.perf_counter()
            outs = router.serve(conts)
            wall = time.perf_counter() - t0
            for c in conts:
                if not np.array_equal(seq_cont[c.uid], outs[c.uid]):
                    mismatched.append((tag, c.uid))
            recompute = (tgt.prompt_tokens - pt0) - \
                (tgt.prefix_hit_tokens - ht0)
            min_tail = sum(
                len(c.prompt)
                - ((len(c.prompt) - 1) // block_size) * block_size
                for c in conts)
            return wall, recompute, min_tail, conts

        # prepare BOTH fleets first, then run the two timed windows
        # back-to-back — wall drift on a shared box cannot favor one
        prep_pull = prep_migration(True)
        prep_re = prep_migration(False)
        wall_pull, rec_pull, min_tail, conts = timed_migration(
            prep_pull, "mig-pull")
        wall_re, rec_re, _, _ = timed_migration(prep_re, "mig-recompute")
        r_pull = prep_pull[0]
        sp = r_pull.stats()
        migration = {
            "migrated_sessions": len(conts) // 2,
            "continuations": len(conts),
            "host_blocks": hb,
            "kv_pulls": sp["kv_pulls"],
            "kv_pull_blocks": sp["kv_pull_blocks"],
            "kv_pull_bytes": sp["kv_pull_bytes"],
            "drains": sp["drains"],
            "wall_pull_s": wall_pull,
            "wall_recompute_s": wall_re,
            "speedup_pull_vs_recompute": wall_re / wall_pull,
            "recompute_tokens_pull": int(rec_pull),
            "recompute_tokens_baseline": int(rec_re),
            "mandatory_tail_tokens": int(min_tail),
            "zero_prefix_recompute": bool(rec_pull <= min_tail),
        }

    # --- federated fleet metrics artifact (--emit-metrics): with
    # --replicas the snapshot is the FLEET view — router + every replica
    # registry federated with replica= labels (telemetry/aggregate.py) —
    # not one engine's registry.  Emitted from the migration fleet (its
    # counters carry the kv-pull/drain story), else the affinity fleet.
    metrics_files = None
    emit_router = None
    if replicas >= 2:
        emit_router = r_pull if migration is not None else r_aff
    if emit_metrics and emit_router is not None:
        with open(emit_metrics, "w") as f:
            f.write(emit_router.fleet_metrics_text())
        snap_path = emit_metrics + ".json"
        with open(snap_path, "w") as f:
            json.dump(emit_router.fleet_snapshot(), f, indent=2)
        metrics_files = {"prometheus": emit_metrics,
                         "snapshot": snap_path, "federated": True}

    return {
        "protocol": "multi-replica DP router (PR 11): busy-time scaling "
                    "over 1->2->4 replicas, affinity-vs-round-robin hit "
                    "rate, drained-replica KV-pull migration — all "
                    "parity-gated vs sequential generate",
        "methodology": "WEAK scaling: n replicas serve n x the traffic "
                       "(requests*n over sessions*n) with per-replica "
                       "load constant; a single process time-slices the "
                       "replicas on the host CPU (each replica = one "
                       "simulated accelerator), so aggregate_tok_s_busy "
                       "— each replica's tokens over its own step() "
                       "wall time, summed over 3 interleaved warm "
                       "rounds — is the DP scaling signal; wall-clock "
                       "tok/s is flat on a 1-core box by construction",
        "trace": f"{sessions} sessions x {prefix_len}-token prefixes "
                 f"(round-robin returns), tails {TAIL_RANGE}, new "
                 f"{PREFIX_NEW_RANGE}",
        "requests": requests,
        "generated_tokens": gen_tokens,
        "sequential": {"tok_s": gen_tokens / seq_wall, "wall_s": seq_wall},
        "scaling": scaling,
        "scaling_ratio_busy": ratios,
        "affinity_vs_round_robin": aff_vs_rr,
        "migration": migration,
        "metrics_files": metrics_files,
        "token_parity": not mismatched,
        "mismatched": mismatched,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }


def run_chaos_bench(requests: int = 64, slots: int = 8,
                    prefill_batch: int = 4, layers: int = 2,
                    hidden: int = 128, heads: int = 4, vocab: int = 2048,
                    seed: int = 0, dtype: str = "fp32",
                    block_size: int = 32, prefill_chunk: int = 128,
                    prefix_len: int = 192, sessions: int = 16,
                    swap_batch: int = 8, overload: int = 4,
                    quantize: tuple = ()):
    """The BENCH_r14 chaos protocol (PR 15, module docstring
    ``--chaos``): seeded fault plans against the 16-session returning
    trace, every recovery gate measured.

     - **crash lane**: a seeded FaultPlan kills one of two tiered
       replicas mid-decode; every in-flight + pending request must
       complete on the survivor with tokens EXACTLY matching the
       fault-free twin fleet (fp32), zero hung handles, budgets intact.
       Recovery latency = the timeline gap from ``replica_fail`` to the
       last ``rehome``.  A ``kv8`` lane repeats the kill vs an
       unfaulted kv8 twin (bit-exact) and records the bounded token
       match vs full-precision sequential.  A **sampled** twin (PR 20)
       repeats the kill with odd-uid requests sampling at temperature
       0.8 — the counter-based PRNG streams must replay token-EXACTLY
       on the survivor (keys are pure functions of (request seed,
       tokens emitted), never of replica/slot state).
     - **flaky-transport lane**: transient TransportErrors on the pull
       path; a drain-forced migration must still land its pulls through
       the retry/backoff machinery with exact parity.
     - **corruption lane**: bit flips in every host-tier arena entry
       after a full drain; 100% must be detected by checksum at the
       promote gate and recovered via recompute — corrupt KV is never
       served (exact parity).
     - **overload/shed lane**: an ``overload``x burst of batch traffic
       in front of the protected classes with bounded admission;
       ``realtime``/``interactive`` submit-to-first-token p95 must stay
       within 1.5x of the unloaded baseline while batch absorbs every
       rejection (bench-side stamps — engine TTFT excludes queue wait,
       and queue wait is exactly what shedding bounds).
     - **flight-recorder lane** (ISSUE 18): the crash lane re-run with
       an :class:`IncidentRecorder` armed — the dumped bundle must pass
       the structural audit and ``replay_bundle`` must reproduce the
       trigger at the recorded scheduler iteration with token-exact
       pre-crash streams; recorder-on tokens must be identical to the
       recorder-off twin (<=2% wall overhead recorded, warn-only).
     - **stall-watchdog lane**: traffic submitted, stepping withheld —
       the :class:`StallWatchdog` must detect no-progress within its
       deadline and dump a ``watchdog_stall`` bundle carrying every
       thread's stack; the parked traffic then serves out cleanly.
    """
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.ops.paged_kv import blocks_for
    from deepspeed_tpu.serving import (FaultInjector, FaultPlan,
                                       ReplicaRouter, RequestRejected)

    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    spec = gpt2.build(cfg)
    max_total = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    nbper = blocks_for(max_total, block_size)
    state = {"params": None}

    def mk_engine():
        eng = deepspeed_tpu.init_inference(
            spec, config={"dtype": dtype,
                          "tensor_parallel": {"tp_size": 1}},
            params=state["params"])
        if state["params"] is None:
            state["params"] = eng.params
        return eng

    def mk_srv(**extra):
        kw = dict(slots=slots, max_seq_len=max_total,
                  prefill_batch=prefill_batch, block_size=block_size,
                  prefill_chunk=prefill_chunk, host_blocks=max(
                      32, sessions * (prefix_len // block_size + 2)),
                  swap_batch=swap_batch, debug_checks=True)
        kw.update(extra)
        return ServingEngine(mk_engine(), **kw)

    def fleet(n=2, **router_kw):
        return ReplicaRouter([mk_srv() for _ in range(n)],
                             debug_checks=True, **router_kw)

    reqs = build_trace(requests, vocab, seed, False, prefix_len, False,
                       sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)
    seq_engine = mk_engine()
    seq_outs, seq_wall = run_sequential(seq_engine, reqs)
    mismatched = []

    def gate(tag, ref, outs, uids=None):
        for uid in (uids if uids is not None else [r.uid for r in reqs]):
            if not np.array_equal(ref[uid], outs[uid]):
                mismatched.append((tag, uid))

    def drive_handles(router, handles):
        while router.step():
            pass
        return {h.uid: (h.result(timeout=0) if h.status == "finished"
                        else None) for h in handles}

    def recovery_window_s(router):
        """Timeline gap replica_fail -> last rehome (microsecond stamps
        on the router ring) — the crash-to-recovered latency."""
        evs = router.timeline.events()
        t_fail = [e["ts"] for e in evs if e["name"] == "replica_fail"]
        t_home = [e["ts"] for e in evs if e["name"] == "rehome"]
        if not t_fail or not t_home:
            return None
        return (max(t_home) - min(t_fail)) / 1e6

    # ---------------------------------------------------------- crash lane
    crash_step = 6                 # mid-decode for this trace shape
    crash_plan = FaultPlan(seed=seed,
                           crashes=[{"replica": 1,
                                     "at_step": crash_step}])
    free = fleet()
    outs_free = free.serve(reqs)
    gate("crash-faultfree", seq_outs, outs_free)
    chaos = fleet()
    inj = chaos.arm_faults(crash_plan)
    handles = [chaos.submit(r) for r in reqs]
    t0 = time.perf_counter()
    outs_chaos = drive_handles(chaos, handles)
    chaos_wall = time.perf_counter() - t0
    gate("crash-chaos", outs_free, outs_chaos)
    st = chaos.stats()
    crash = {
        "plan": crash_plan.to_json(),
        "crashes_fired": inj.report()["crashes_fired"],
        "hung_handles": sum(1 for h in handles if not h.done),
        "unfinished": sum(1 for h in handles
                          if h.status != "finished"),
        "requests_rehomed": st["requests_rehomed"],
        "requests_failed": st["requests_failed"],
        "replica_failures": st["replica_failures"],
        "kv_pulls": st["kv_pulls"],
        "recovery_latency_s": recovery_window_s(chaos),
        "wall_s": chaos_wall,
        "tok_s_wall": gen_tokens / chaos_wall,
        "compile_budgets_ok": all(
            p["compile_count"] <= p["compile_budget"]
            for p in st["per_replica"]),
        "survivor_prefix_hit_rate":
            st["per_replica"][0]["prefix_cache_hit_rate"],
        "parity_exact_vs_faultfree": not any(
            t == "crash-chaos" for t, _ in mismatched),
    }

    # kv8 crash twin (bounded divergence vs fp32 sequential, bit-exact
    # vs the unfaulted kv8 fleet)
    crash_kv8 = None
    if quantize and "kv8" in quantize:
        tu = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", "unit")
        if tu not in sys.path:
            sys.path.insert(0, tu)
        from quant_divergence import token_match_rate

        def kv8_fleet():
            return ReplicaRouter([mk_srv(quantize="kv8")
                                  for _ in range(2)], debug_checks=True)

        ref_q = kv8_fleet().serve(reqs)
        chaos_q = kv8_fleet()
        chaos_q.arm_faults(FaultPlan(
            seed=seed, crashes=[{"replica": 1, "at_step": crash_step}]))
        hq = [chaos_q.submit(r) for r in reqs]
        outs_q = drive_handles(chaos_q, hq)
        gate("crash-kv8-vs-twin", ref_q, outs_q)
        crash_kv8 = {
            "bit_exact_vs_unfaulted_kv8": not any(
                t == "crash-kv8-vs-twin" for t, _ in mismatched),
            "token_match_rate_vs_sequential":
                token_match_rate(seq_outs, outs_q),
            "requests_rehomed":
                chaos_q.stats()["requests_rehomed"],
        }

    # ---------------------------------------------- sampled crash lane
    # PR 20: the crash lane repeated with odd-uid requests SAMPLING
    # (temperature 0.8, per-request seeds).  Re-homing must replay the
    # streams token-EXACTLY on the survivor: the counter-based PRNG key
    # is a pure function of (request seed, tokens emitted), never of
    # the replica/slot that drew it, so a rebuilt slot resumes the
    # stream mid-request with no drift.
    srng = np.random.default_rng([seed, 1009])
    sreqs = [Request(uid=r.uid, prompt=r.prompt,
                     max_new_tokens=r.max_new_tokens,
                     temperature=0.8, top_k=20, top_p=0.95,
                     seed=int(srng.integers(1, 2 ** 31 - 1)))
             if r.uid % 2 else
             Request(uid=r.uid, prompt=r.prompt,
                     max_new_tokens=r.max_new_tokens)
             for r in reqs]
    free_s = fleet()
    outs_free_s = free_s.serve(sreqs)
    chaos_s = fleet()
    inj_s = chaos_s.arm_faults(FaultPlan(
        seed=seed, crashes=[{"replica": 1, "at_step": crash_step}]))
    handles_s = [chaos_s.submit(r) for r in sreqs]
    outs_chaos_s = drive_handles(chaos_s, handles_s)
    gate("crash-sampled", outs_free_s, outs_chaos_s)
    st_s = chaos_s.stats()
    crash_sampled = {
        "sampled_requests": sum(1 for r in sreqs if r.sampled),
        "crashes_fired": inj_s.report()["crashes_fired"],
        "hung_handles": sum(1 for h in handles_s if not h.done),
        "requests_rehomed": st_s["requests_rehomed"],
        "replica_failures": st_s["replica_failures"],
        "compile_budgets_ok": all(
            p["compile_count"] <= p["compile_budget"]
            for p in st_s["per_replica"]),
        "parity_exact_vs_faultfree": not any(
            t == "crash-sampled" for t, _ in mismatched),
    }

    # ------------------------------------------------- flaky transport lane
    flaky_plan = FaultPlan(
        seed=seed + 1,
        transport={"ops": ["export", "import"], "transient_rate": 1.0,
                   "max_faults": 2},
        stalls=[{"replica": 0, "at_step": 3, "stall_s": 0.002}])
    flk = fleet(pull_retries=5)
    inj_f = flk.arm_faults(flaky_plan)
    gate("flaky-trace", seq_outs, flk.serve(reqs))
    # drain the busiest session home => forced cross-replica pulls
    # through the flaky transport
    prefixes = [reqs[j].prompt[:prefix_len] for j in range(sessions)]

    def _home(p):
        probes = [flk.replicas[r].affinity_probe(
            np.concatenate([p, [0]])) for r in range(2)]
        return int(np.argmax([q["device_blocks"] + q["host_blocks"]
                              for q in probes]))

    homes = [_home(p) for p in prefixes]
    rid0 = int(np.argmax([homes.count(r) for r in range(2)]))
    migrated = [j for j, h in enumerate(homes) if h == rid0]
    flk.drain(rid0)
    rng = np.random.default_rng(seed + 2)
    conts = [Request(uid=f"mig{j}", prompt=np.concatenate(
        [prefixes[j], rng.integers(0, vocab, 9)]), max_new_tokens=4)
        for j in migrated]
    seq_cont = {c.uid: seq_engine.generate(
        c.prompt[None, :], max_new_tokens=4)[0] for c in conts}
    outs_mig = flk.serve(conts)
    gate("flaky-migration", seq_cont, outs_mig,
         uids=[c.uid for c in conts])
    stf = flk.stats()
    flaky = {
        "plan": flaky_plan.to_json(),
        "transport_faults_injected": inj_f.report()["transport_faults"],
        "stalls_fired": inj_f.report()["stalls_fired"],
        "kv_pull_retries": stf["kv_pull_retries"],
        "kv_pulls": stf["kv_pulls"],
        "kv_pull_blocks": stf["kv_pull_blocks"],
        "migrated_sessions": len(migrated),
        "pulls_landed_through_retries": stf["kv_pulls"] >= 1
        and stf["kv_pull_retries"] >= 1,
    }

    # ------------------------------------------------------ corruption lane
    # arena sized with 3x headroom: during the post-corruption re-serve
    # nothing is LRU-evicted, so EVERY injected corruption is still
    # accountable at the end — caught at a promote exit gate during
    # traffic, or by the final patrol scrub (entries shadowed behind an
    # earlier corrupt block in their chain are never probed by traffic;
    # the scrub is the background-scrubber primitive that finds them)
    srv_c = mk_srv(host_blocks=3 * max(
        64, sessions * (prefix_len // block_size + 4)))
    outs_c = srv_c.serve(reqs)
    gate("corrupt-pre", seq_outs, outs_c)
    srv_c.drain()                  # host tier becomes the only copy
    n_host = len(srv_c._host)
    corrupt_plan = FaultPlan(
        seed=seed + 3,
        corruption=[{"replica": 0, "at_step": 1, "entries": n_host,
                     "bits": 3}])
    inj_c = FaultInjector(corrupt_plan)
    srv_c.arm_faults(inj_c.bind(0))
    re_reqs = [Request(uid=f"re{r.uid}", prompt=r.prompt,
                       max_new_tokens=r.max_new_tokens) for r in reqs]
    outs_c2 = srv_c.serve(re_reqs)
    srv_c.arm_faults(None)
    gate("corrupt-post", {f"re{r.uid}": seq_outs[r.uid] for r in reqs},
         outs_c2, uids=[r.uid for r in re_reqs])
    detected_gate = int(srv_c._c_checksum_fail.value)
    scrubbed = srv_c.scrub_host_tier()
    detected = int(srv_c._c_checksum_fail.value)
    corruption = {
        "plan": corrupt_plan.to_json(),
        "host_entries_corrupted": inj_c.corrupted_entries,
        "detected_at_exit_gates": detected_gate,
        "detected_by_patrol_scrub": scrubbed,
        "checksum_failures_detected": detected,
        "detected_100pct": detected == inj_c.corrupted_entries
        and inj_c.corrupted_entries > 0,
        "recovered_via_recompute_parity": not any(
            t == "corrupt-post" for t, _ in mismatched),
        "swap_in_after_corruption": srv_c.stats()["swap_in"],
    }

    # -------------------------------------------------- overload/shed lane
    classes = ("realtime", "interactive")

    def measure_ttft(router, entries, warm_reqs=None):
        """Submit everything up front (batch first — the adversarial
        order), then step-poll: per-uid submit->first-token wall time,
        bench-side (INCLUDES queue wait, unlike the engine's
        slot-admission TTFT)."""
        if warm_reqs:                       # compile outside the window
            router.serve(warm_reqs)
        handles, t_submit, t_first, shed = {}, {}, {}, []
        for req, cls in entries:
            t_submit[req.uid] = time.perf_counter()
            try:
                handles[req.uid] = router.submit(req, slo_class=cls)
            except RequestRejected as e:
                shed.append((e.uid, e.slo_class))
        live = True
        while live:
            live = router.step()
            now = time.perf_counter()
            for uid, h in handles.items():
                if uid not in t_first and h.tokens():
                    t_first[uid] = now
        per_class = {}
        for (req, cls) in entries:
            if req.uid in t_first:
                per_class.setdefault(cls, []).append(
                    t_first[req.uid] - t_submit[req.uid])
        return handles, per_class, shed

    def p95(xs):
        return float(np.percentile(xs, 95)) if xs else None

    n_prot = max(4, requests // 4)
    rng = np.random.default_rng(seed + 4)
    prot_entries = [
        (Request(uid=f"p{i}", prompt=np.concatenate(
            [prefixes[i % sessions],
             rng.integers(0, vocab, 12)]), max_new_tokens=6),
         classes[i % 2]) for i in range(n_prot)]
    batch_entries = [
        (Request(uid=f"b{i}", prompt=np.concatenate(
            [prefixes[i % sessions],
             rng.integers(0, vocab, 12)]), max_new_tokens=6), "batch")
        for i in range(n_prot * (overload - 1))]
    warm = [Request(uid=f"w{i}", prompt=np.concatenate(
        [prefixes[i % sessions], rng.integers(0, vocab, 10)]),
        max_new_tokens=3) for i in range(4)]

    base_fleet = fleet()               # unloaded, shedding off
    _, base_cls, base_shed = measure_ttft(
        base_fleet, [(r, c) for r, c in prot_entries], warm_reqs=warm)
    shed_fleet = fleet(max_queue_depth=max(2, slots))
    over_entries = batch_entries + \
        [(Request(uid=r.uid + "o", prompt=r.prompt,
                  max_new_tokens=r.max_new_tokens), c)
         for r, c in prot_entries]
    over_handles, over_cls, over_shed = measure_ttft(
        shed_fleet, over_entries, warm_reqs=warm)
    base_p95 = p95(base_cls.get("realtime", [])
                   + base_cls.get("interactive", []))
    over_p95 = p95(over_cls.get("realtime", [])
                   + over_cls.get("interactive", []))
    shed_by_class = {}
    for _, cls in over_shed:
        key = cls if cls is not None else "standard"
        shed_by_class[key] = shed_by_class.get(key, 0) + 1
    overload_shed = {
        "overload_factor": overload,
        "protected_requests": n_prot,
        "batch_requests_offered": len(batch_entries),
        "max_queue_depth": max(2, slots),
        "unloaded_protected_ttft_p95_s": base_p95,
        "overloaded_protected_ttft_p95_s": over_p95,
        "protected_p95_ratio": (over_p95 / base_p95
                                if base_p95 and over_p95 else None),
        "protected_within_1p5x": bool(
            base_p95 and over_p95 and over_p95 <= 1.5 * base_p95),
        "shed_by_class": shed_by_class,
        "protected_shed": sum(v for k, v in shed_by_class.items()
                              if k != "batch"),
        "batch_absorbed_all_rejections": bool(shed_by_class) and all(
            k == "batch" for k in shed_by_class),
        "unloaded_sheds": len(base_shed),
        "protected_finished": sum(
            1 for uid, h in over_handles.items()
            if not uid.startswith("b") and h.status == "finished"),
    }

    # ------------------------------------------- flight-recorder lane
    # (ISSUE 18, docs/observability.md "Incident response"): the crash
    # lane re-run with the black-box recorder armed.  Gates: the
    # recorder must not perturb the schedule (token identity vs the
    # recorder-off chaos twin above), the dumped bundle must pass the
    # structural audit, and an in-process ``replay_bundle()`` must
    # re-execute it to the SAME trigger at the SAME scheduler iteration
    # with token-exact pre-crash streams.  The <=2% recorder-overhead
    # contract is recorded and warned on breach (wall-clock-noise-prone
    # on shared runners, like every wall-clock contract in this bench).
    import tempfile

    from deepspeed_tpu.analysis.invariants import audit_incident_bundle
    from deepspeed_tpu.telemetry.incident import (IncidentRecorder,
                                                  StallWatchdog,
                                                  gpt2_model_meta,
                                                  is_bundle,
                                                  replay_bundle)

    inc_dir = tempfile.mkdtemp(prefix="graft_incidents_")
    rec = IncidentRecorder(inc_dir, vocab=vocab,
                           model_meta=gpt2_model_meta(cfg, dtype=dtype))
    inc_fleet = fleet()
    rec.attach(inc_fleet)
    inc_fleet.arm_faults(FaultPlan(
        seed=seed, crashes=[{"replica": 1, "at_step": crash_step}]))
    h_inc = [inc_fleet.submit(r) for r in reqs]
    t0 = time.perf_counter()
    outs_inc = drive_handles(inc_fleet, h_inc)
    inc_wall = time.perf_counter() - t0
    rec.detach()
    gate("incident-recorder-on", outs_chaos, outs_inc)
    bundles = sorted(d for d in os.listdir(inc_dir)
                     if is_bundle(os.path.join(inc_dir, d)))
    bundle_audit_ok, replay_report = False, None
    if bundles:
        bpath = os.path.join(inc_dir, bundles[0])
        try:
            audit_incident_bundle(bpath)
            bundle_audit_ok = True
        except Exception as e:
            print(f"WARNING: incident bundle fails audit: {e}",
                  file=sys.stderr)
        replay_report = replay_bundle(bpath)

    # stall-watchdog lane: traffic submitted, stepping withheld — the
    # "fleet merely STOPPED" failure mode membership probes can't see.
    # The watchdog must detect no-progress within its deadline and dump
    # a watchdog_stall bundle carrying every thread's stack; afterwards
    # the parked traffic is served out so nothing leaks from the lane.
    stall_dir = tempfile.mkdtemp(prefix="graft_incidents_stall_")
    rec_s = IncidentRecorder(stall_dir, vocab=vocab,
                             model_meta=gpt2_model_meta(cfg, dtype=dtype))
    stall_fleet = fleet()
    rec_s.attach(stall_fleet)
    stall_handles = [stall_fleet.submit(r) for r in reqs[:4]]
    wd = StallWatchdog(stall_fleet, deadline_s=0.05, poll_s=0.01,
                       recorder=rec_s).start()
    t_w = time.perf_counter()
    while wd.stalls == 0 and time.perf_counter() - t_w < 10.0:
        time.sleep(0.01)
    wd.stop()
    while stall_fleet.step():
        pass
    rec_s.detach()
    stall_bundles = [d for d in os.listdir(stall_dir)
                     if is_bundle(os.path.join(stall_dir, d))]
    stall_has_stacks = False
    for d in stall_bundles:
        tpath = os.path.join(stall_dir, d, "threads.txt")
        if d.split("-")[-1] == "watchdog_stall" and \
                os.path.isfile(tpath) and os.path.getsize(tpath) > 0:
            stall_has_stacks = True
    wd_counter = int(stall_fleet.metrics.counter(
        "serving_watchdog_stalls_total", "").value)
    incident = {
        "bundle_dir": inc_dir,
        "bundles": bundles,
        "bundle_audit_ok": bundle_audit_ok,
        "replay_reproduced": bool(replay_report
                                  and replay_report["reproduced"]),
        "replay_trigger": replay_report["trigger"]
        if replay_report else None,
        "replay_mismatches": replay_report["mismatches"]
        if replay_report else ["no bundle dumped"],
        "recorder_token_identity": not any(
            t == "incident-recorder-on" for t, _ in mismatched),
        "recorder_wall_s": inc_wall,
        "recorder_off_wall_s": chaos_wall,
        "recorder_overhead_frac": inc_wall / chaos_wall - 1.0,
        "recorder_overhead_within_2pct":
            inc_wall <= 1.02 * chaos_wall,
        "watchdog_stalls_detected": wd.stalls,
        "watchdog_counter": wd_counter,
        "watchdog_bundles": stall_bundles,
        "watchdog_stall_has_thread_stacks": stall_has_stacks,
        "watchdog_parked_served_out": all(
            h.status == "finished" for h in stall_handles),
    }

    return {
        "protocol": "fault-tolerant serving fleet (PR 15, BENCH_r14): "
                    "seeded crash-at-iteration / flaky-transport / "
                    "host-corruption / overload-shedding lanes on the "
                    "returning-sessions trace, every lane parity- or "
                    "counter-gated (docs/reliability.md)",
        "trace": f"{sessions} sessions x {prefix_len}-token prefixes, "
                 f"tails {TAIL_RANGE}, new {PREFIX_NEW_RANGE}",
        "requests": requests,
        "generated_tokens": gen_tokens,
        "sequential": {"tok_s": gen_tokens / seq_wall,
                       "wall_s": seq_wall},
        "crash": crash,
        "crash_sampled": crash_sampled,
        "crash_kv8": crash_kv8,
        "flaky_transport": flaky,
        "corruption": corruption,
        "overload_shed": overload_shed,
        "incident": incident,
        "token_parity": not mismatched,
        "mismatched": mismatched,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }


def run_disaggregated_bench(requests: int = 48, slots: int = 8,
                            prefill_batch: int = 4, layers: int = 2,
                            hidden: int = 128, heads: int = 4,
                            vocab: int = 2048, seed: int = 0,
                            dtype: str = "fp32", block_size: int = 32,
                            prefill_chunk: int = 128,
                            prefix_len: int = 192, sessions: int = 12,
                            swap_batch: int = 8, victims: int = 6,
                            victim_new: int = 48,
                            burst_prompts: int = 6,
                            burst_prompt_len: int = 576):
    """The BENCH_r16 disaggregated-serving protocol (ISSUE 17,
    ``--disaggregated``): prefill/decode worker split + NVMe third KV
    tier, every lane parity- or counter-gated.

     - **structure lane** (deterministic stepping): a 1 prefill + 1
       decode fleet serves the returning-sessions trace with tokens
       EXACTLY matching the colocated 2x``role="both"`` twin and the
       sequential reference.  Every admission hands off
       (``handoffs == requests``), and the decode worker never re-runs
       prompt prefill: its recompute is bounded by the sub-block tail
       (``resume_recompute_tokens <= admitted * block_size``).
     - **interference lane** (threaded, wall-clock): decode-heavy
       victim streams measured quiet, then again with a long-prompt
       burst landing mid-decode.  Bench-side token-arrival stamps give
       victim TPOT p95 per fleet; the disaggregated fleet's
       burst/quiet ratio should stay ~flat (<= 1.15x) while the
       colocated twin absorbs the prefill stall in its decode gaps.
       Wall-clock ratios are recorded and warn-only in CI (CPU-sim
       noise); token parity in both runs is a hard gate.
     - **nvme lane** (deterministic stepping): a pressured host arena
       over a tmpdir spill file; serving the trace must spill
       (``nvme_spills > 0``), session resumes must promote back through
       the staged path (``nvme_loads > 0``) with zero prefix recompute
       (recompute delta bounded by the sub-block tails) and exact
       parity, zero checksum rejects, and the tier-labeled swap
       metrics + ``nvme_spill``/``nvme_load`` timeline events present.
     - **bit-identity lane**: ``role="both"`` + ``nvme_blocks=0`` vs
       the plain PR 16 engine — same tokens, same swap counters, same
       compile budget (the feature is free when off).
    """
    import tempfile

    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.ops.paged_kv import blocks_for
    from deepspeed_tpu.serving import ReplicaRouter

    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    spec = gpt2.build(cfg)
    max_total = max(prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE),
                    burst_prompt_len + 8)
    state = {"params": None}

    def mk_engine():
        eng = deepspeed_tpu.init_inference(
            spec, config={"dtype": dtype,
                          "tensor_parallel": {"tp_size": 1}},
            params=state["params"])
        if state["params"] is None:
            state["params"] = eng.params
        return eng

    host_blocks = max(32, sessions * (prefix_len // block_size + 2))

    def mk_srv(**extra):
        kw = dict(slots=slots, max_seq_len=max_total,
                  prefill_batch=prefill_batch, block_size=block_size,
                  prefill_chunk=prefill_chunk, host_blocks=host_blocks,
                  swap_batch=swap_batch, debug_checks=True)
        kw.update(extra)
        return ServingEngine(mk_engine(), **kw)

    def disagg_fleet(**router_kw):
        return ReplicaRouter([mk_srv(role="prefill"),
                              mk_srv(role="decode")],
                             kv_pull=True, debug_checks=True,
                             **router_kw)

    def colo_fleet(**router_kw):
        return ReplicaRouter([mk_srv(role="both"), mk_srv(role="both")],
                             debug_checks=True, **router_kw)

    reqs = build_trace(requests, vocab, seed, False, prefix_len, False,
                       sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)
    seq_engine = mk_engine()
    seq_outs, seq_wall = run_sequential(seq_engine, reqs)
    mismatched = []

    def gate(tag, ref, outs, uids=None):
        for uid in (uids if uids is not None else [r.uid for r in reqs]):
            if not np.array_equal(ref[uid], outs[uid]):
                mismatched.append((tag, uid))

    def p95(xs):
        return float(np.percentile(xs, 95)) if xs else None

    # ------------------------------------------------------ structure lane
    colo = colo_fleet()
    t0 = time.perf_counter()
    outs_colo = colo.serve(reqs)
    colo_wall = time.perf_counter() - t0
    gate("structure-colocated", seq_outs, outs_colo)
    dis = disagg_fleet()
    t0 = time.perf_counter()
    outs_dis = dis.serve(reqs)
    dis_wall = time.perf_counter() - t0
    gate("structure-disaggregated", seq_outs, outs_dis)
    std = dis.stats()
    pre = next(p for p in std["per_replica"] if p["role"] == "prefill")
    dec = next(p for p in std["per_replica"] if p["role"] == "decode")
    pre_eng = dis.replicas[pre["replica"]].stats()
    dec_eng = dis.replicas[dec["replica"]].stats()
    ev_names = [e["name"] for e in dis.timeline.events()]
    structure = {
        "requests": requests,
        "handoffs": std["handoffs"],
        "every_admission_handed_off": std["handoffs"] == len(reqs),
        "prefill_worker": {
            "prompt_tokens": pre_eng["prompt_tokens"],
            "prefill_calls": pre_eng["prefill_calls"],
            "handoffs": pre_eng["handoffs"],
        },
        "decode_worker": {
            "admitted": dec_eng["admitted"],
            "prompt_tokens": dec_eng["prompt_tokens"],
            "prefix_hit_tokens": dec_eng["prefix_hit_tokens"],
            "resume_recompute_tokens": dec_eng["resume_recompute_tokens"],
        },
        # the decode worker never re-runs prompt prefill: after the
        # chain pull only the sub-block tail past the last committed
        # block boundary is recomputed at admission
        "decode_recompute_bounded": (
            dec_eng["resume_recompute_tokens"]
            <= dec_eng["admitted"] * block_size),
        "decode_rode_the_pulled_chain": dec_eng["prefix_hit_tokens"] > 0,
        "handoff_events_on_timeline": "handoff" in ev_names,
        "kv_pulls": std["kv_pulls"],
        "kv_pull_blocks": std["kv_pull_blocks"],
        "colocated_wall_s": colo_wall,
        "disaggregated_wall_s": dis_wall,
        "parity_exact": not any(t.startswith("structure")
                                for t, _ in mismatched),
    }

    # --------------------------------------------------- interference lane
    # victims fit the decode worker's slots so the measurement isolates
    # PREFILL interference (the thing disaggregation removes), not slot
    # contention; burst admissions are pure prefill (max_new_tokens=1:
    # the first token is emitted during prefill, so they finish on the
    # prefill worker and never take a decode slot)
    victims = min(victims, slots)
    rng = np.random.default_rng(seed + 1)
    victim_reqs = [Request(uid=f"v{i}",
                           prompt=rng.integers(0, vocab, 16),
                           max_new_tokens=victim_new)
                   for i in range(victims)]
    burst_reqs = [Request(uid=f"g{i}",
                          prompt=rng.integers(0, vocab,
                                              burst_prompt_len),
                          max_new_tokens=1)
                  for i in range(burst_prompts)]
    warm = [Request(uid=f"w{i}", prompt=rng.integers(0, vocab, 16),
                    max_new_tokens=3) for i in range(2)] + \
           [Request(uid="wg", prompt=rng.integers(0, vocab,
                                                  burst_prompt_len),
                    max_new_tokens=1)]
    seq_victim = {r.uid: seq_engine.generate(
        r.prompt[None, :], max_new_tokens=r.max_new_tokens)[0]
        for r in victim_reqs}
    seq_burst = {r.uid: seq_engine.generate(
        r.prompt[None, :], max_new_tokens=r.max_new_tokens)[0]
        for r in burst_reqs}

    def run_stepped(mk_fleet, tag, with_burst):
        """Step-driven interference run on the per-replica VIRTUAL
        clock: single-threaded stepping serializes the fleet, so each
        replica's accumulated busy time is exactly the time ITS engine
        spent executing — what wall TPOT is on real per-chip hardware,
        and the only uncontaminated basis on a shared-core CPU sim
        (thread overlap there just time-slices one core).  Every victim
        token is stamped with its owning replica's busy clock; TPOT =
        consecutive same-replica stamps' deltas.  The burst fires once
        every victim is >= 2 tokens into its stream, so the long-prompt
        prefills land mid-decode; in the colocated fleet they ride the
        victims' own engines (the busy clock between victim tokens
        swallows whole prefill chunks), in the disaggregated fleet the
        decode worker's clock never runs a prefill program."""
        router = mk_fleet()
        router.serve(warm)                  # compile outside the window
        handles = {r.uid: router.submit(r) for r in victim_reqs}
        arrivals = {r.uid: [] for r in victim_reqs}  # (rid, busy, fired)
        burst_handles = {}
        b_submit, b_first = {}, {}
        fired = False
        dec_rids = sorted(router._decode_capable)
        dec_prefill_at_fire = None

        def _dec_prefill_calls():
            return sum(router.replicas[r].stats()["prefill_calls"]
                       for r in dec_rids)

        while router.step():
            # the burst phase ends when the last burst admission
            # completes — the window where prefill interference is live
            in_burst = fired and not all(
                h.done for h in burst_handles.values())
            for uid, h in handles.items():
                n = len(h.tokens())
                while len(arrivals[uid]) < n:
                    rid = router._handles[uid][1]
                    arrivals[uid].append(
                        (rid, router._busy_s[rid], in_burst))
            for uid, h in burst_handles.items():
                if uid not in b_first and h.tokens():
                    rid = router._handles[uid][1]
                    b_first[uid] = (rid, router._busy_s[rid])
            if with_burst and not fired and all(
                    len(a) >= 2 for a in arrivals.values()):
                fired = True
                dec_prefill_at_fire = _dec_prefill_calls()
                for r in burst_reqs:
                    h = router.submit(r)
                    rid = router._handles[r.uid][1]
                    burst_handles[r.uid] = h
                    b_submit[r.uid] = (rid, router._busy_s[rid])
        outs = {uid: h.result(timeout=0)
                for uid, h in {**handles, **burst_handles}.items()}
        gate(tag, {**seq_victim, **seq_burst}, outs, uids=list(outs))
        # victim TPOT = same-replica busy deltas between consecutive
        # tokens, steady-state window only (post-fire for the burst
        # run; tokens 2+ for the quiet run)
        gaps = []
        for uid, ts in arrivals.items():
            for (r0, t0, f0), (r1, t1, f1) in zip(ts[2:], ts[3:]):
                if r0 == r1 and ((f0 and f1) if with_burst else True):
                    gaps.append(t1 - t0)
        ttft = [b_first[uid][1] - b_submit[uid][1]
                for uid in burst_handles
                if uid in b_first
                and b_first[uid][0] == b_submit[uid][0]]
        dec_prefill_during_burst = (
            _dec_prefill_calls() - dec_prefill_at_fire
            if dec_prefill_at_fire is not None else None)
        return {"tpot_p95_s": p95(gaps), "n_gaps": len(gaps),
                "burst_ttft_p95_s": p95(ttft),
                "decode_prefill_calls_during_burst":
                    dec_prefill_during_burst}

    interference = {}
    for name, mk in (("colocated", colo_fleet),
                     ("disaggregated", disagg_fleet)):
        quiet = run_stepped(mk, f"quiet-{name}", with_burst=False)
        burst = run_stepped(mk, f"burst-{name}", with_burst=True)
        ratio = (burst["tpot_p95_s"] / quiet["tpot_p95_s"]
                 if quiet["tpot_p95_s"] and burst["tpot_p95_s"]
                 else None)
        interference[name] = {
            "victim_tpot_quiet_p95_s": quiet["tpot_p95_s"],
            "victim_tpot_burst_p95_s": burst["tpot_p95_s"],
            "tpot_burst_over_quiet": ratio,
            "burst_ttft_p95_s": burst["burst_ttft_p95_s"],
            "decode_prefill_calls_during_burst":
                burst["decode_prefill_calls_during_burst"],
        }
    dis_ratio = interference["disaggregated"]["tpot_burst_over_quiet"]
    colo_ratio = interference["colocated"]["tpot_burst_over_quiet"]
    interference["basis"] = (
        "per-replica busy (virtual) seconds, single-threaded stepping "
        "— equals wall TPOT on per-chip hardware")
    interference["victims"] = victims
    interference["burst_prompts"] = burst_prompts
    interference["burst_prompt_len"] = burst_prompt_len
    # the deterministic half of the flatness claim: during the burst
    # window the disaggregated decode worker executes ZERO prefill
    # programs while the colocated twin's victim engines run every
    # burst prompt's chunks between victim tokens
    interference["decode_isolated_from_prefill"] = (
        interference["disaggregated"]
        ["decode_prefill_calls_during_burst"] == 0
        and interference["colocated"]
        ["decode_prefill_calls_during_burst"] > 0)
    interference["tpot_flat_within_1p15"] = bool(
        dis_ratio is not None and dis_ratio <= 1.15)
    interference["colocated_degrades_more"] = bool(
        dis_ratio is not None and colo_ratio is not None
        and colo_ratio > dis_ratio)
    interference["ttft_no_worse_1p1"] = bool(
        interference["disaggregated"]["burst_ttft_p95_s"] is not None
        and interference["colocated"]["burst_ttft_p95_s"] is not None
        and interference["disaggregated"]["burst_ttft_p95_s"]
        <= 1.1 * interference["colocated"]["burst_ttft_p95_s"])
    interference["parity_exact"] = not any(
        t.startswith(("quiet-", "burst-")) for t, _ in mismatched)

    # ----------------------------------------------------------- nvme lane
    # pressured three-tier ladder: a device pool barely over one
    # sequence forces constant demotion, a half-watermark host arena a
    # fraction of the session working set forces LRU spill past it —
    # so resumes MUST promote back out of the spill file
    bp = blocks_for(prefix_len, block_size)
    trace_max = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    nvme_host = max(2 * swap_batch, sessions * bp // 3)
    with tempfile.TemporaryDirectory() as tmp:
        srv_n = ServingEngine(
            mk_engine(), slots=slots, max_seq_len=trace_max,
            prefill_batch=prefill_batch, block_size=block_size,
            prefill_chunk=prefill_chunk,
            num_blocks=1 + blocks_for(trace_max, block_size) + bp,
            host_blocks=nvme_host, swap_batch=swap_batch,
            debug_checks=True,
            nvme_blocks=sessions * (bp + 2),
            nvme_high_watermark=0.5,
            nvme_path=os.path.join(tmp, "kv.spill"))
        outs_n = srv_n.serve(reqs)
        gate("nvme-trace", seq_outs, outs_n)
        st_mid = srv_n.stats()
        rng = np.random.default_rng(seed + 2)
        conts = [Request(uid=f"n{j}", prompt=np.concatenate(
            [reqs[j].prompt[:prefix_len], rng.integers(0, vocab, 9)]),
            max_new_tokens=4) for j in range(sessions)]
        seq_cont = {c.uid: seq_engine.generate(
            c.prompt[None, :], max_new_tokens=4)[0] for c in conts}
        outs_cont = srv_n.serve(conts)
        gate("nvme-resume", seq_cont, outs_cont,
             uids=[c.uid for c in conts])
        st_n = srv_n.stats()
        recompute_delta = (st_n["resume_recompute_tokens"]
                           - st_mid["resume_recompute_tokens"])
        hit_delta = (st_n["prefix_hit_tokens"]
                     - st_mid["prefix_hit_tokens"])
        # zero PREFIX recompute: per resume only the 9 appended tokens
        # + the sub-block tail of the prefix may re-prefill
        recompute_bound = sessions * (9 + block_size)
        prom = srv_n.metrics.prometheus_text()
        names_n = [e["name"] for e in srv_n.timeline.events()]
        nvme = {
            "host_blocks": nvme_host,
            "nvme_blocks": sessions * (bp + 2),
            "nvme_spills": st_n["nvme_spills"],
            "nvme_loads": st_n["nvme_loads"],
            "nvme_blocks_in_use": st_n["nvme_blocks_in_use"],
            "checksum_rejects": srv_n._host.nvme_checksum_rejects,
            "spilled_under_pressure": st_mid["nvme_spills"] > 0,
            "resumed_from_nvme": (st_n["nvme_loads"]
                                  - st_mid["nvme_loads"]) > 0,
            "resume_recompute_tokens_delta": recompute_delta,
            "resume_prefix_hit_tokens_delta": hit_delta,
            "zero_prefix_recompute": recompute_delta <= recompute_bound,
            "tier_labeled_metrics": (
                'serving_kv_swaps_total{direction="out",tier="nvme"}'
                in prom
                and 'tier="host"' in prom
                and "serving_nvme_blocks_in_use" in prom),
            "timeline_events": ("nvme_spill" in names_n
                                and "nvme_load" in names_n),
            "parity_exact": not any(t.startswith("nvme")
                                    for t, _ in mismatched),
        }

    # --------------------------------------------------- bit-identity lane
    plain = mk_srv()
    outs_plain = plain.serve(reqs)
    twin = mk_srv(role="both", nvme_blocks=0)
    outs_twin = twin.serve(reqs)
    gate("bitident-plain", seq_outs, outs_plain)
    gate("bitident-twin", outs_plain, outs_twin)
    sp, stw = plain.stats(), twin.stats()
    bit_identity = {
        "tokens_identical": not any(t == "bitident-twin"
                                    for t, _ in mismatched),
        "swap_counters_identical": all(
            sp[k] == stw[k] for k in ("swap_out", "swap_in",
                                      "swap_bytes")),
        "schedule_identical": all(
            sp[k] == stw[k] for k in ("iterations", "generated_tokens",
                                      "prefix_hit_tokens")),
        "compile_budget_identical":
            sp["compile_budget"] == stw["compile_budget"],
        "nvme_stats_zero": (stw["nvme_spills"] == 0
                            and stw["nvme_loads"] == 0
                            and stw["nvme_blocks"] == 0),
    }

    return {
        "protocol": "disaggregated prefill/decode + NVMe third tier "
                    "(ISSUE 17, BENCH_r16): structure / interference / "
                    "nvme / bit-identity lanes on the returning-"
                    "sessions trace (docs/inference.md)",
        "trace": f"{sessions} sessions x {prefix_len}-token prefixes, "
                 f"tails {TAIL_RANGE}, new {PREFIX_NEW_RANGE}",
        "requests": requests,
        "generated_tokens": gen_tokens,
        "sequential": {"tok_s": gen_tokens / seq_wall,
                       "wall_s": seq_wall},
        "structure": structure,
        "interference": interference,
        "nvme": nvme,
        "bit_identity": bit_identity,
        "token_parity": not mismatched,
        "mismatched": mismatched,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }


def run_autotune_bench(requests: int = 64, sessions: int = 16,
                       prefix_len: int = 256, pool_frac: float = 0.25,
                       slots: int = 8, layers: int = 2, hidden: int = 128,
                       heads: int = 4, vocab: int = 2048, seed: int = 0,
                       dtype: str = "fp32",
                       results_dir: str = "autotuning_results_serving",
                       max_trials: int = None, min_budget: int = None,
                       eta: int = 2, min_speedup: float = 1.0,
                       resume: bool = False):
    """BENCH_r13 protocol (ROADMAP item 5): closed-loop serving autotune
    on the BENCH_r09 returning-sessions trace.

    The workload is ``sessions`` distinct ``prefix_len``-token session
    prefixes dealt round-robin over ``requests`` requests, with the
    device pool pressure-sized at ``pool_frac`` of the unique working
    set — the hand-picked default config (pressured pool, no host tier,
    no speculation) is candidate 0 AND the parity reference for every
    trial.  ``autotuning/runner.py tune_serving`` searches the knob
    space under the byte-equal memory ceiling with successive halving;
    every trial is parity-gated and runs ``debug_checks=True`` so the
    recompile sentry enforces each candidate's compile budget at trace
    time.  The bench gates on the measured winner >= ``min_speedup`` x
    the measured default and on ``best_config.json`` round-tripping
    through ``init_serving(**config)``."""
    import deepspeed_tpu
    from deepspeed_tpu.autotuning import ModelGeom, sessions_trace, \
        tune_serving
    from deepspeed_tpu.autotuning.space import workload_space
    from deepspeed_tpu.models import gpt2

    trace = sessions_trace(requests, vocab=vocab, seed=seed,
                           sessions=sessions, prefix_len=prefix_len,
                           tail_range=TAIL_RANGE,
                           new_range=PREFIX_NEW_RANGE)
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg), config={"dtype": dtype,
                                 "tensor_parallel": {"tp_size": 1}})
    # the searched knobs: block geometry vs pool depth under ONE byte
    # ceiling, chunk window, n-gram speculation, and the host tier (the
    # BENCH_r09 escape hatch from pool-pressure preemption).  The
    # spec_tokens=24 point is deliberately past the verify kernel's
    # window: the constraint layer must prune it BEFORE any trial runs
    # (pruned_by_constraint in the artifact), not crash a trial
    space = workload_space(
        ModelGeom.from_engine(engine), trace, pool_frac=pool_frac,
        base={"slots": slots},
        domains={"block_size": (32, 64),
                 "prefill_chunk": (128, 256),
                 "spec_tokens": (0, 4, 24),
                 "host_blocks": (0, "ws")})
    summary = tune_serving(engine, trace, space=space, eta=eta,
                           min_budget=min_budget, max_trials=max_trials,
                           results_dir=results_dir, resume=resume)

    # best_config.json must round-trip: build an engine straight from the
    # artifact and replay a short slice through it
    with open(os.path.join(results_dir, "best_config.json")) as f:
        best = json.load(f)
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(cfg), config={"dtype": dtype}, **best)
    probe = trace.slice(min(4, len(trace)))
    handles = probe.submit_all(srv)
    while srv.step():
        pass
    outs = {h.uid: h.result(timeout=0) for h in handles}
    roundtrip_ok = all(outs[u] is not None for u in outs) and \
        srv.resolved_config()["block_size"] == best["block_size"] and \
        srv.resolved_config()["num_blocks"] == best["num_blocks"] and \
        srv.resolved_config()["host_blocks"] == best["host_blocks"]

    speedup = summary["speedup"] or 0.0
    res = {
        "protocol": "closed-loop serving autotune (BENCH_r13): "
                    "successive-halving search over the serving knob "
                    "space on the BENCH_r09 returning-sessions trace, "
                    "every trial parity-gated with sentry-enforced "
                    "compile budgets; winner re-run at full budget vs "
                    "the hand-picked default",
        "trace": {"requests": requests, "sessions": sessions,
                  "prefix_len": prefix_len, "pool_frac": pool_frac,
                  "working_set_tokens": trace.working_set_tokens(),
                  "max_total_len": trace.max_total_len()},
        "model": {"layers": layers, "hidden": hidden, "heads": heads,
                  "vocab": vocab, "dtype": dtype},
        "search": {
            "candidates": summary["candidates"],
            "admissible": summary["admissible"],
            "pruned_by_constraint": summary["pruned_by_constraint"],
            "trials_executed": summary["trials_executed"],
            "trials_total": summary["trials_total"],
            "budget_spent_requests": summary["budget_spent_requests"],
            "rungs": summary["rungs"],
            "exhausted": summary["exhausted"],
            "mem_ceiling_bytes": space.mem_ceiling_bytes,
        },
        "default": {
            "config": space.default_config(),
            "measured_tok_s": summary["default"]["measured_tok_s"],
        },
        "winner": {
            "config": summary["best_config"],
            "predicted_tok_s": summary["winner"]["predicted_tok_s"],
            "measured_tok_s": summary["winner"]["measured_tok_s"],
            "token_match": summary["winner"]["record"].get("token_match"),
            "compiled_programs":
                summary["winner"]["record"].get("compiled_programs"),
            "prefix_cache_hit_rate":
                summary["winner"]["record"].get("prefix_cache_hit_rate"),
        },
        "speedup": speedup,
        "gates": {
            "min_speedup": min_speedup,
            "winner_ge_min_speedup": speedup >= min_speedup,
            "best_config_roundtrip": bool(roundtrip_ok),
            "all_trials_parity_gated": True,
            "sentry_strict_in_trials": True,
        },
        "artifacts": {
            "results_dir": results_dir,
            "best_config": os.path.join(results_dir, "best_config.json"),
            "exps": os.path.join(results_dir, "exps.json"),
            "report": os.path.join(results_dir, "report.md"),
        },
    }
    return res


def run_host_loop_bench(requests: int = 64, slots: int = 8,
                        prefill_batch: int = 4, layers: int = 2,
                        hidden: int = 128, heads: int = 4,
                        vocab: int = 2048, seed: int = 0,
                        dtype: str = "fp32", block_size: int = 32,
                        prefill_chunk: int = 128, prefix_len: int = 256,
                        sessions: int = 16, decode_steps: int = 8,
                        min_iter_reduction: float = 4.0):
    """The BENCH_r15 fused multi-step decode protocol (PR 16, module
    docstring ``--host-loop``): K=1 per-token host loop vs the fused
    ``decode_steps=K`` twin on the BENCH_r09 returning-sessions trace.

    The headline counter pair: in K=1 mode every decode iteration is a
    Python scheduler iteration (``decode_steps`` counts them); the fused
    engine runs the same iterations inside ONE ``lax.while_loop``
    program and touches the host once per K-token window
    (``host_fence_waits``).  Both twins must be token-EXACT (fp32) and
    the kv8 twin pair bit-exact between themselves.  The twins' TOTAL
    batched-iteration counts are recorded but not compared: at K>1
    decode windows overlap prefill chunks differently, so the batching
    schedule — never any request's token stream — may differ."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models import gpt2

    reqs = build_trace(requests, vocab, seed, False,
                       prefix_len=prefix_len, sessions=sessions)
    gen_tokens = sum(r.max_new_tokens for r in reqs)
    max_total = prefix_len + max(TAIL_RANGE) + max(PREFIX_NEW_RANGE)
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": dtype, "tensor_parallel": {"tp_size": 1}})

    def lane(K, quantize=None, trace_capacity=16384):
        srv = ServingEngine(engine, slots=slots, max_seq_len=max_total,
                            prefill_batch=prefill_batch,
                            block_size=block_size,
                            prefill_chunk=prefill_chunk,
                            decode_steps=K, quantize=quantize,
                            trace_capacity=trace_capacity)
        t0 = time.perf_counter()
        outs = srv.serve(reqs)
        cold = time.perf_counter() - t0
        st_cold = srv.stats()
        t0 = time.perf_counter()
        outs2 = srv.serve(reqs)
        warm = time.perf_counter() - t0
        st = srv.stats()
        # host-side scheduler decode iterations: one per decode program
        # dispatch at K=1, one per fence at K>1
        host_iters = st_cold["host_fence_waits"] if K > 1 \
            else st_cold["decode_steps"]
        return {
            "decode_steps_knob": K,
            "tok_s": gen_tokens / cold,
            "wall_s": cold,
            "tok_s_warm": gen_tokens / warm,
            "wall_warm_s": warm,
            "compiled_programs": srv.compile_count,
            "device_decode_iterations": st_cold["decode_steps"],
            "fused_iterations": st_cold["fused_iterations"],
            "host_decode_iterations": host_iters,
            "host_iters_per_token": host_iters / max(gen_tokens, 1),
            "generated_tokens": st_cold["generated_tokens"],
            "busy_fractions": srv.flops_report()["busy_fractions"],
            "stats": st_cold,
        }, outs, outs2

    base, base_outs, base_outs2 = lane(1)
    fused, fused_outs, fused_outs2 = lane(decode_steps)
    parity = all(np.array_equal(base_outs[r.uid], fused_outs[r.uid])
                 and np.array_equal(base_outs[r.uid], fused_outs2[r.uid])
                 and np.array_equal(base_outs[r.uid], base_outs2[r.uid])
                 for r in reqs)

    # kv8 twins: quantized greedy differs from fp32 (documented), but the
    # fused program must be BIT-exact against the K=1 kv8 twin — same
    # codes, same scales, same argmax
    kv8_base, kv8_base_outs, _ = lane(1, quantize="kv8")
    kv8_fused, kv8_fused_outs, _ = lane(decode_steps, quantize="kv8")
    kv8_exact = all(np.array_equal(kv8_base_outs[r.uid],
                                   kv8_fused_outs[r.uid]) for r in reqs)

    # telemetry twin: the fused engine with the trace ring off — the
    # BENCH_r08 <=2% contract must survive the new fence counters
    ring_off, off_outs, _ = lane(decode_steps, trace_capacity=0)
    overhead_pct = (fused["wall_warm_s"] / ring_off["wall_warm_s"]
                    - 1.0) * 100.0
    ring_parity = all(np.array_equal(base_outs[r.uid], off_outs[r.uid])
                      for r in reqs)

    iter_reduction = base["host_decode_iterations"] / \
        max(fused["host_decode_iterations"], 1)
    res = {
        "protocol": "fused multi-step on-device decode (PR 16, "
                    "BENCH_r15): K=1 per-token host loop vs one "
                    "lax.while_loop program fusing K decode iterations "
                    "with per-slot eos/budget exits on-device and one "
                    "host fence per window; exact-parity + kv8 "
                    "bit-exact twins on the returning-sessions trace",
        "trace": f"{sessions} sessions x {prefix_len}-token prefixes "
                 f"(round-robin returns), tails {TAIL_RANGE}, new "
                 f"{PREFIX_NEW_RANGE}",
        "requests": requests,
        "generated_tokens": gen_tokens,
        "decode_steps": decode_steps,
        "host_loop_baseline": base,
        "fused": fused,
        "kv8": {"baseline": kv8_base, "fused": kv8_fused,
                "bit_exact_between_twins": kv8_exact},
        "telemetry_twin": {
            "tok_s_warm_ring_off": ring_off["tok_s_warm"],
            "overhead_pct": overhead_pct,
            "within_2pct": overhead_pct <= 2.0,
            "token_parity": ring_parity,
        },
        "host_iteration_reduction": iter_reduction,
        "token_parity": parity,
        "gates": {
            "min_iter_reduction": min_iter_reduction,
            "iter_reduction_ok": iter_reduction >= min_iter_reduction,
            "exact_parity_fp32": parity,
            "kv8_bit_exact": kv8_exact,
            "fused_tok_s_ge_baseline":
                fused["tok_s_warm"] >= base["tok_s_warm"],
        },
    }
    return res


def run_sampling_bench(requests: int = 48, slots: int = 8,
                       prefill_batch: int = 4, layers: int = 2,
                       hidden: int = 128, heads: int = 4,
                       vocab: int = 2048, seed: int = 0,
                       dtype: str = "fp32", block_size: int = 32,
                       prefill_chunk: int = 128, spec_tokens: int = 4,
                       decode_steps: int = 8, temperature: float = 0.25,
                       top_k: int = 20, top_p: float = 0.95,
                       min_spec_speedup: float = 1.3,
                       min_iter_reduction: float = 4.0,
                       max_tv: float = 0.12):
    """The BENCH_r18 on-device sampling protocol (PR 20, module
    docstring ``--sampling``): per-slot temperature/top-k/top-p sampling
    as fixed-shape device operands on the decode-heavy trace, with the
    speculative rejection verifier, fused decode, and constrained-
    decoding compositions — every gate DETERMINISTIC (counter-based PRNG
    streams are pure functions of (request seed, tokens emitted), so
    the same trace replays bit-identically on any engine/fleet shape).

     - **plain_sampled**: the default (``sampling=True``) engine on a
       mixed greedy+sampled trace; a FRESH twin engine must reproduce
       every stream token-exactly, and at least one sampled stream must
       deviate from greedy (no silent argmax collapse).
     - **greedy_row**: the same prompts at temperature=0 through the
       sampling engine vs a ``sampling=False`` twin vs sequential
       ``generate`` — bit parity (greedy is the temp-0 ROW of the same
       program, not a separate program).
     - **fused**: ``decode_steps=K`` on the sampled trace — token-EXACT
       vs the K=1 engine (``grid_keys`` == per-step ``slot_keys``), host
       iterations per token down >= ``min_iter_reduction``.
     - **speculative**: ``spec_tokens=K`` n-gram with the rejection
       verifier — deterministic twin parity, 2 compiled programs, and
       the throughput headline gated on the DETERMINISTIC counter ratio
       tokens-per-host-decode-iteration >= ``min_spec_speedup`` x the
       plain sampled engine (CPU-sim wall tok/s is recorded, not gated).
     - **statistical parity**: aggregate sampled-token histogram TV
       between the spec and plain lanes must stay within the
       self-calibrated null band — 1.5x the TV between two plain lanes
       differing only in request seeds (+0.02), floored at ``max_tv``.
       Rejection sampling is distribution-exact for any proposer, so
       the spec lane must look statistically identical to plain
       sampling even though the streams differ draw-for-draw.
     - **draft**: a 1-layer draft model on the same trace — exactly 3
       programs (draft/prefill/verify) and twin determinism (draft
       params are seeded, rejection needs no draft probabilities).
     - **constrained / mixed**: a ``logit_masks=True`` engine serving
       greedy + sampled + JSON-constrained requests in ONE trace —
       still 2 programs, sentry strict, every constrained completion
       parses as valid JSON; repeated on a speculative engine.
    """
    import deepspeed_tpu
    from deepspeed_tpu.inference.constrain import (JsonMaskBuilder,
                                                   ascii_token_strings)
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2

    def sampled_trace(trace_seed, greedy_every=4):
        """The decode-heavy trace with per-request sampling params:
        every ``greedy_every``-th request stays greedy (temp 0), the
        rest alternate temperature T / 2T with per-request seeds —
        prompts identical across ``trace_seed`` so reseeded twins
        differ ONLY in the sampling streams."""
        base_reqs = build_trace(requests, vocab, seed, False,
                                decode_heavy=True)
        rng = np.random.default_rng([trace_seed, 7919])
        out = []
        for r in base_reqs:
            if greedy_every and r.uid % greedy_every == greedy_every - 1:
                out.append(Request(uid=r.uid, prompt=r.prompt,
                                   max_new_tokens=r.max_new_tokens))
                continue
            t = temperature * (2.0 if r.uid % greedy_every == 1 else 1.0)
            out.append(Request(uid=r.uid, prompt=r.prompt,
                               max_new_tokens=r.max_new_tokens,
                               temperature=t, top_k=top_k, top_p=top_p,
                               seed=int(rng.integers(1, 2 ** 31 - 1))))
        return out

    reqs = sampled_trace(seed)
    reseeded = sampled_trace(seed + 1)
    greedy_reqs = [Request(uid=r.uid, prompt=r.prompt,
                           max_new_tokens=r.max_new_tokens) for r in reqs]
    budget_tokens = sum(r.max_new_tokens for r in reqs)
    max_total = DECODE_HEAVY_PROMPT_RANGE[1] + DECODE_HEAVY_NEW_RANGE[1]
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": dtype, "tensor_parallel": {"tp_size": 1}})

    def mk(**extra):
        kw = dict(slots=slots, max_seq_len=max_total,
                  prefill_batch=prefill_batch, block_size=block_size,
                  prefill_chunk=prefill_chunk)
        kw.update(extra)
        return ServingEngine(engine, **kw)

    def run_lane(srv, trace, eos=None):
        t0 = time.perf_counter()
        outs = srv.serve(trace, eos_token_id=eos)
        wall = time.perf_counter() - t0
        st = srv.stats()
        gen = st["generated_tokens"]
        # host scheduler decode work: one dispatch per decode program
        # (plain), per verify round (spec), per K-token fence (fused)
        if st["config"]["decode_steps"] > 1:
            host_iters = st["host_fence_waits"]
        else:
            host_iters = st["decode_steps"] + st["spec_rounds"]
        return {
            "tok_s": gen / wall,
            "wall_s": wall,
            "generated_tokens": gen,
            "compiled_programs": srv.compile_count,
            "program_names": sorted(p[0] for p in srv.compiled_programs),
            "host_decode_iterations": host_iters,
            "tokens_per_host_iteration": gen / max(host_iters, 1),
            "sampled_requests": st["sampled_requests"],
            "retraces": st["retraces_observed"],
            "acceptance_rate": st["acceptance_rate"],
            "spec_draft_rejected": st["spec_draft_rejected"],
        }, outs

    def exact(a, b, trace):
        return all(np.array_equal(a[r.uid], b[r.uid]) for r in trace)

    # ------------------------------------------- plain sampled + twin
    plain, plain_outs = run_lane(mk(), reqs)
    _, twin_outs = run_lane(mk(), reqs)
    determinism = exact(plain_outs, twin_outs, reqs)

    # --------------------------------------------------- greedy row
    greedy_on, greedy_on_outs = run_lane(mk(), greedy_reqs)
    greedy_off, greedy_off_outs = run_lane(mk(sampling=False),
                                           greedy_reqs)
    greedy_parity = exact(greedy_on_outs, greedy_off_outs, greedy_reqs)
    seq_subset = all(
        np.array_equal(greedy_on_outs[r.uid],
                       engine.generate(r.prompt[None, :],
                                       max_new_tokens=r.max_new_tokens)[0])
        for r in greedy_reqs[:6])
    deviates = any(not np.array_equal(plain_outs[r.uid],
                                      greedy_on_outs[r.uid])
                   for r in reqs if r.sampled)

    # -------------------------------------------------------- fused
    fused, fused_outs = run_lane(mk(decode_steps=decode_steps), reqs)
    fused_exact = exact(plain_outs, fused_outs, reqs)
    iter_reduction = plain["host_decode_iterations"] / \
        max(fused["host_decode_iterations"], 1)

    # -------------------------------------------------- speculative
    spec, spec_outs = run_lane(mk(spec_tokens=spec_tokens), reqs)
    _, spec_twin_outs = run_lane(mk(spec_tokens=spec_tokens), reqs)
    spec_det = exact(spec_outs, spec_twin_outs, reqs)
    spec_speedup = spec["tokens_per_host_iteration"] / \
        plain["tokens_per_host_iteration"]

    # --------------------------------------------- statistical parity
    _, reseed_outs = run_lane(mk(), reseeded)

    def tail_hist(outs, trace):
        h = np.zeros(vocab, np.float64)
        for r in trace:
            if not r.sampled:
                continue
            h += np.bincount(np.asarray(outs[r.uid])[len(r.prompt):],
                             minlength=vocab)
        return h / max(h.sum(), 1.0)

    def tv(a, b):
        return 0.5 * float(np.abs(a - b).sum())

    h_plain = tail_hist(plain_outs, reqs)
    tv_null = tv(h_plain, tail_hist(reseed_outs, reseeded))
    tv_spec = tv(h_plain, tail_hist(spec_outs, reqs))
    tv_threshold = max(max_tv, 1.5 * tv_null + 0.02)
    stat_parity = tv_spec <= tv_threshold

    # -------------------------------------------------------- draft
    dcfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                           num_layers=1, num_heads=heads,
                           hidden_size=max(hidden // 2, heads * 8))
    draft, draft_outs = run_lane(
        mk(spec_tokens=spec_tokens, draft=gpt2.build(dcfg)), reqs)
    _, draft_twin_outs = run_lane(
        mk(spec_tokens=spec_tokens, draft=gpt2.build(dcfg)), reqs)
    draft_det = exact(draft_outs, draft_twin_outs, reqs)

    # -------------------------------------------- constrained / mixed
    toks = ascii_token_strings(vocab)

    def constrained_reqs(cseed, n=4, max_new=24):
        rng = np.random.default_rng([cseed, 911])
        return [Request(uid=1000 + i,
                        prompt=rng.integers(0, vocab, 12),
                        max_new_tokens=max_new,
                        temperature=0.7, top_k=0, top_p=1.0,
                        seed=int(rng.integers(1, 2 ** 31 - 1)),
                        mask_builder=JsonMaskBuilder(toks,
                                                     eos_token_id=0))
                for i in range(n)]

    def json_valid(outs, trace):
        for r in trace:
            gen = [int(t) for t in np.asarray(outs[r.uid])[len(r.prompt):]]
            if 0 in gen:
                gen = gen[: gen.index(0)]
            try:
                json.loads("".join(toks[t] for t in gen))
            except (ValueError, IndexError):
                return False
        return True

    mixed_trace = reqs[: min(len(reqs), 12)]
    mixed_srv = mk(logit_masks=True)
    cons_a = constrained_reqs(seed)
    mixed_outs = mixed_srv.serve(mixed_trace + cons_a, eos_token_id=0)
    mixed_json_ok = json_valid(mixed_outs, cons_a)
    spec_mixed_srv = mk(spec_tokens=spec_tokens, logit_masks=True)
    cons_b = constrained_reqs(seed + 1)
    spec_mixed_outs = spec_mixed_srv.serve(mixed_trace + cons_b,
                                           eos_token_id=0)
    spec_mixed_json_ok = json_valid(spec_mixed_outs, cons_b)

    return {
        "protocol": "on-device sampling stack (PR 20, BENCH_r18): "
                    "per-slot temperature/top-k/top-p as fixed-shape "
                    "device operands + distribution-exact rejection "
                    "speculative sampling + fused-decode and "
                    "constrained-JSON composition on the decode-heavy "
                    "trace — every gate deterministic (counter-based "
                    "PRNG), zero recompiles across greedy/sampled/"
                    "constrained mixes",
        "trace": f"{requests} decode-heavy requests, prompts "
                 f"{DECODE_HEAVY_PROMPT_RANGE}, new "
                 f"{DECODE_HEAVY_NEW_RANGE}; temps "
                 f"({temperature}, {2 * temperature}, greedy every 4th), "
                 f"top_k={top_k}, top_p={top_p}, per-request seeds",
        "requests": requests,
        "generated_tokens_budget": budget_tokens,
        "plain_sampled": plain,
        "greedy_row": {"on": greedy_on, "off": greedy_off},
        "fused": fused,
        "host_iteration_reduction": iter_reduction,
        "speculative": spec,
        "speedup_spec_tokens_per_host_iter": spec_speedup,
        "draft": draft,
        "statistical_parity": {
            "tv_spec_vs_plain": tv_spec,
            "tv_null_reseeded_plain": tv_null,
            "tv_threshold": tv_threshold,
            "max_tv_floor": max_tv,
        },
        "constrained": {
            "requests": len(cons_a) + len(cons_b),
            "mixed_programs": mixed_srv.compile_count,
            "spec_mixed_programs": spec_mixed_srv.compile_count,
            "mixed_retraces": mixed_srv.sentry.retraces_observed,
            "spec_mixed_retraces":
                spec_mixed_srv.sentry.retraces_observed,
        },
        "gates": {
            "sampled_determinism_exact": determinism,
            "sampled_streams_deviate_from_greedy": deviates,
            "greedy_row_bit_parity": greedy_parity and seq_subset,
            "fused_token_exact_vs_plain": fused_exact,
            "min_iter_reduction": min_iter_reduction,
            "fused_iter_reduction_ok":
                iter_reduction >= min_iter_reduction,
            "spec_determinism_exact": spec_det,
            "draft_determinism_exact": draft_det,
            "min_spec_speedup": min_spec_speedup,
            "spec_host_iter_speedup_ok":
                spec_speedup >= min_spec_speedup,
            "statistical_parity_ok": stat_parity,
            "constrained_json_valid":
                mixed_json_ok and spec_mixed_json_ok,
            "mixed_compile_budget_ok":
                mixed_srv.compile_count == 2
                and spec_mixed_srv.compile_count == 2
                and mixed_srv.sentry.retraces_observed == 0
                and spec_mixed_srv.sentry.retraces_observed == 0,
            "compile_budgets_ok":
                plain["compiled_programs"] == 2
                and fused["compiled_programs"] == 2
                and spec["compiled_programs"] == 2
                and draft["compiled_programs"] == 3,
            "zero_retraces_ok": all(
                lane["retraces"] == 0
                for lane in (plain, greedy_on, greedy_off, fused,
                             spec, draft)),
        },
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }


def run_long_context_bench(requests: int = 3, slots: int = 2,
                           prefill_batch: int = 2, layers: int = 2,
                           hidden: int = 128, heads: int = 4,
                           vocab: int = 2048, seed: int = 0,
                           dtype: str = "fp32", block_size: int = 32,
                           prefill_chunk: int = 128,
                           long_prompt_len: int = 4096,
                           max_new: int = 16, sp_degree: int = 4,
                           window_blocks: int = 16):
    """The BENCH_r17 long-context protocol (PR 19, module docstring
    ``--long-context``): sequence-parallel (Ulysses) prefill + the
    resident-window decode lane on giant single-session prompts.

    Lanes and gates:
     - **sp**: the sp=1 chunked engine vs the ``sp=N`` twin on the
       same long-prompt trace — exact token parity and the unchanged
       compile budget are exit-fatal; the prefill wall-clock speedup
       is recorded and warned only (CPU-sim shard_map emulates the
       all-to-all on one host, so linear scaling is a hardware claim,
       not a CI claim).
     - **window**: a ``resident_window_blocks=W`` engine whose device
       pool holds < 25% of the served context (landmark + window + one
       chunk span per slot) serves the same prompts through the host
       tier — window slides observed, device-residency fraction under
       a quarter, full token budgets produced, host tier actually
       holding cold context, and the unamended compile budget are all
       exit-fatal.  Windowed attention is approximate by design, so
       there is no parity gate on this lane — instead the
       **full-window identity** sub-lane pins bit-equality against the
       plain engine when the window covers the whole (short) context.
     - **probe_128k**: a windowed engine *declared* at a 131072-token
       ``max_seq_len`` (the 100k+ regime: 4096-entry block tables,
       device pool still ~20 blocks) serves a short prompt to prove
       the compiled-program budget is reachable and held at 128k
       scale."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingEngine
    from deepspeed_tpu.models import gpt2
    import jax

    if sp_degree > 1 and len(jax.devices()) < sp_degree:
        sys.exit(f"--long-context needs >= {sp_degree} devices for the "
                 "sp lane; on CPU set XLA_FLAGS="
                 "--xla_force_host_platform_device_count=8")

    rng = np.random.default_rng(seed)
    long_reqs = [Request(uid=i,
                         prompt=rng.integers(0, vocab, long_prompt_len),
                         max_new_tokens=max_new)
                 for i in range(requests)]
    gen_tokens = requests * max_new
    max_total = long_prompt_len + max_new

    def fresh(reqs):
        return [Request(uid=r.uid, prompt=r.prompt,
                        max_new_tokens=r.max_new_tokens) for r in reqs]

    def mk_cfg(seq):
        return gpt2.GPT2Config(vocab_size=vocab, max_seq_len=seq,
                               num_layers=layers, num_heads=heads,
                               hidden_size=hidden)

    def lane_stats(srv, wall):
        st = srv.stats()
        return {
            "wall_s": wall,
            "tok_s": gen_tokens / wall,
            "compiled_programs": srv.compile_count,
            "compile_budget": srv.compile_budget,
            "sp": st["sp"],
            "sp_alltoall_bytes": st["sp_alltoall_bytes"],
            "context_window_slides": st["context_window_slides"],
            "host_blocks_in_use": st["host_blocks_in_use"],
            "swap_out": st["swap_out"],
            "config": srv.resolved_config(),
        }

    # ------------------------------------------------------- sp lane
    def sp_lane(sp):
        deepspeed_tpu.comm.reset_topology()
        srv = deepspeed_tpu.init_serving(
            gpt2.build(mk_cfg(max_total)), config={"dtype": dtype},
            sp=sp, slots=slots, max_seq_len=max_total,
            block_size=block_size, prefill_chunk=prefill_chunk,
            prefill_batch=prefill_batch)
        t0 = time.perf_counter()
        outs = srv.serve(fresh(long_reqs))
        return lane_stats(srv, time.perf_counter() - t0), outs

    sp1, sp1_outs = sp_lane(1)
    spN, spN_outs = sp_lane(sp_degree)
    sp_parity = all(np.array_equal(sp1_outs[r.uid], spN_outs[r.uid])
                    for r in long_reqs)
    sp_speedup = sp1["wall_s"] / max(spN["wall_s"], 1e-9)

    # --------------------------------------------------- window lane
    # device pool per slot: 1 landmark + W window + one chunk span —
    # sized to hold every slot's window at once, nothing more
    chunk_blocks = -(-prefill_chunk // block_size)
    per_slot = 1 + window_blocks + chunk_blocks
    num_blocks = slots * per_slot + 2
    host_blocks = slots * (-(-max_total // block_size)) + 16
    declared = 4 * max_total      # window pool is context-independent
    deepspeed_tpu.comm.reset_topology()
    win = deepspeed_tpu.init_serving(
        gpt2.build(mk_cfg(declared)), config={"dtype": dtype},
        slots=slots, max_seq_len=declared, block_size=block_size,
        prefill_chunk=prefill_chunk, prefill_batch=prefill_batch,
        num_blocks=num_blocks, host_blocks=host_blocks, swap_batch=8,
        resident_window_blocks=window_blocks, debug_checks=True)
    t0 = time.perf_counter()
    win_outs = win.serve(fresh(long_reqs))
    win_stats = lane_stats(win, time.perf_counter() - t0)
    residency_frac = per_slot * block_size / long_prompt_len
    tokens_complete = all(
        len(win_outs[r.uid]) == len(r.prompt) + max_new
        for r in long_reqs)

    # full-window identity: short context entirely inside the window
    short_len = 8 * block_size
    short_reqs = [Request(uid=i,
                          prompt=rng.integers(0, vocab, short_len),
                          max_new_tokens=max_new)
                  for i in range(requests)]
    short_total = short_len + max_new
    deepspeed_tpu.comm.reset_topology()
    plain = deepspeed_tpu.init_serving(
        gpt2.build(mk_cfg(short_total)), config={"dtype": dtype},
        slots=slots, max_seq_len=short_total, block_size=block_size,
        prefill_chunk=prefill_chunk, prefill_batch=prefill_batch)
    plain_outs = plain.serve(fresh(short_reqs))
    cover = -(-short_total // block_size) + chunk_blocks + 1
    deepspeed_tpu.comm.reset_topology()
    full_win = deepspeed_tpu.init_serving(
        gpt2.build(mk_cfg(short_total)), config={"dtype": dtype},
        slots=slots, max_seq_len=short_total, block_size=block_size,
        prefill_chunk=prefill_chunk, prefill_batch=prefill_batch,
        host_blocks=host_blocks, swap_batch=8,
        resident_window_blocks=cover, debug_checks=True)
    full_win_outs = full_win.serve(fresh(short_reqs))
    full_window_identical = all(
        np.array_equal(plain_outs[r.uid], full_win_outs[r.uid])
        for r in short_reqs)

    # ------------------------------------------------ 128k declared
    deepspeed_tpu.comm.reset_topology()
    probe = deepspeed_tpu.init_serving(
        gpt2.build(mk_cfg(131072)), config={"dtype": dtype}, slots=1,
        max_seq_len=131072, block_size=block_size,
        prefill_chunk=prefill_chunk, prefill_batch=1,
        num_blocks=per_slot + 2, host_blocks=64, swap_batch=8,
        resident_window_blocks=window_blocks, debug_checks=True)
    probe_reqs = [Request(uid=0,
                          prompt=rng.integers(0, vocab, 4 * block_size),
                          max_new_tokens=4)]
    probe.serve(probe_reqs)
    probe_stats = {
        "declared_max_seq_len": 131072,
        "block_table_entries": -(-131072 // block_size),
        "device_pool_blocks": per_slot + 2,
        "compiled_programs": probe.compile_count,
        "compile_budget": probe.compile_budget,
    }

    res = {
        "protocol": "long-context serving lane (PR 19, BENCH_r17): "
                    "Ulysses sp prefill parity + compile invariance "
                    "vs sp=1, resident-window decode with the device "
                    "pool under 25% of the served context (slides, "
                    "host-tier demotion, full-window bit-identity), "
                    "and the 128k-declared compile-budget probe",
        "trace": f"{requests} x {long_prompt_len}-token prompts, "
                 f"max_new={max_new}",
        "requests": requests,
        "generated_tokens": gen_tokens,
        "sp_degree": sp_degree,
        "sp1": sp1,
        "spN": spN,
        "sp_speedup": sp_speedup,
        "window": {**win_stats,
                   "window_blocks": window_blocks,
                   "device_residency_frac": residency_frac,
                   "declared_max_seq_len": declared},
        "probe_128k": probe_stats,
        "gates": {
            "sp_exact_parity": sp_parity,
            "sp_compile_budget_ok":
                spN["compiled_programs"] <= spN["compile_budget"]
                and spN["compile_budget"] == sp1["compile_budget"],
            "window_slides_ok":
                win_stats["context_window_slides"] > 0,
            "residency_under_quarter_ok": residency_frac < 0.25,
            "window_tokens_complete_ok": tokens_complete,
            "cold_context_on_host_ok":
                win_stats["host_blocks_in_use"] > 0
                or win_stats["swap_out"] > 0,
            "window_compile_budget_ok":
                win_stats["compiled_programs"]
                <= win_stats["compile_budget"],
            "full_window_identical": full_window_identical,
            "probe_128k_compile_budget_ok":
                probe_stats["compiled_programs"]
                <= probe_stats["compile_budget"],
        },
    }
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prefill-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=32)
    ap.add_argument("--prefill-chunk", type=int, default=128)
    ap.add_argument("--prefix-len", type=int, default=None,
                    help="prepend a shared N-token system prompt to every "
                         "request (prefix-heavy trace); 0 disables, "
                         "default per lane")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dtype", default="fp32")
    ap.add_argument("--grid", action="store_true",
                    help="snap the trace to a small shape grid and report a "
                         "compile-warm second pass for both paths")
    ap.add_argument("--decode-heavy", action="store_true",
                    help="short prompts, long completions — the decode-bound "
                         "trace speculative decoding targets")
    ap.add_argument("--speculative", type=int, default=0, metavar="K",
                    help="add a speculative lane: n-gram proposer drafting "
                         "K tokens per slot per iteration (0 = off)")
    ap.add_argument("--tp", type=int, default=1, metavar="N",
                    help="add a tensor-parallel lane: weights + paged KV "
                         "pool sharded over an N-way tp mesh axis (needs "
                         ">= N devices; on CPU set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--quantize", default=None, metavar="MODES",
                    help="comma list of quantized lanes to add: kv8, w8a8, "
                         "w8a8+kv8 (bounded divergence, not exact parity)")
    ap.add_argument("--sessions", type=int, default=None, metavar="S",
                    help="with --prefix-len: S distinct session prefixes "
                         "dealt round-robin (multi-turn returning-session "
                         "traffic — the tiered-KV scenario)")
    ap.add_argument("--pool-frac", type=float, default=None, metavar="F",
                    help="add the tiered-KV lane (BENCH_r09): size the "
                         "device pool at fraction F of the trace working "
                         "set and compare the host-DRAM tier against the "
                         "evict/preempt baseline (zero parity loss "
                         "asserted for both)")
    ap.add_argument("--swap-batch", type=int, default=8,
                    help="blocks per tiered-KV swap round trip")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="run the multi-replica router protocol "
                         "(BENCH_r10) instead of the single-engine "
                         "lanes: busy-time scaling over 1->2->4 "
                         "replicas (capped at N), affinity vs "
                         "round-robin, drained-replica KV-pull "
                         "migration")
    ap.add_argument("--slo", action="store_true",
                    help="with --replicas N: run the fleet observability "
                         "protocol (BENCH_r12) instead — SLO-classed "
                         "traffic, live /metrics scrape of the federated "
                         "fleet registry, merged distributed trace with "
                         "flow events, FLOPs/MFU profiler, and the "
                         "fleet-wide ≤2%% telemetry overhead twin")
    ap.add_argument("--peak-flops", type=float, default=1e12,
                    help="nominal MFU denominator for the --slo lane's "
                         "FLOPs report (CPU-sim: gauge mechanics, not a "
                         "hardware claim)")
    ap.add_argument("--chaos", action="store_true",
                    help="run the BENCH_r14 fault-tolerance protocol "
                         "(PR 15): seeded crash-at-iteration, flaky "
                         "transport, host-tier corruption, and overload-"
                         "shedding lanes on the returning-sessions "
                         "trace — recovery latency, rehomed/shed "
                         "counts, 100%% checksum detection, and parity "
                         "vs the fault-free twin (add --quantize kv8 "
                         "for the kv8 crash lane)")
    ap.add_argument("--overload", type=int, default=4,
                    help="overload factor for the --chaos shed lane "
                         "(batch traffic = (N-1) x protected)")
    ap.add_argument("--disaggregated", action="store_true",
                    help="run the BENCH_r16 disaggregated-serving "
                         "protocol (ISSUE 17): prefill/decode worker "
                         "split vs the colocated twin (structure + "
                         "threaded interference lanes, victim TPOT "
                         "flatness under a long-prompt burst), the "
                         "NVMe third KV tier over a tmpdir spill file "
                         "(spill/resume/parity/checksum gates), and "
                         "the role='both' + nvme_blocks=0 bit-identity "
                         "lane")
    ap.add_argument("--burst-prompts", type=int, default=6,
                    help="long-prompt admissions fired mid-decode in "
                         "the --disaggregated interference lane")
    ap.add_argument("--burst-prompt-len", type=int, default=576,
                    help="prompt length of each burst admission")
    ap.add_argument("--long-context", action="store_true",
                    help="run the BENCH_r17 long-context protocol "
                         "(PR 19): Ulysses sequence-parallel prefill "
                         "parity + compile invariance vs sp=1, the "
                         "resident-window decode lane with the device "
                         "pool under 25%% of the served context "
                         "(slides + host-tier demotion exit-fatal, "
                         "full-window bit-identity), and the "
                         "128k-declared compile-budget probe (needs "
                         ">= --sp-degree devices; on CPU set "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8)")
    ap.add_argument("--long-prompt-len", type=int, default=4096,
                    help="prompt length for the --long-context lanes")
    ap.add_argument("--sp-degree", type=int, default=4, metavar="N",
                    help="sequence-parallel degree for the "
                         "--long-context sp lane")
    ap.add_argument("--window-blocks", type=int, default=16,
                    metavar="W",
                    help="resident_window_blocks for the "
                         "--long-context window lane")
    ap.add_argument("--autotune", action="store_true",
                    help="run the closed-loop autotuner protocol "
                         "(BENCH_r13) instead of the single-engine "
                         "lanes: successive-halving search over the "
                         "serving knob space on the returning-sessions "
                         "trace, gated on winner >= "
                         "--autotune-min-speedup x the default")
    ap.add_argument("--autotune-trials", type=int, default=None,
                    metavar="N", help="bound on executed trials")
    ap.add_argument("--autotune-min-budget", type=int, default=None,
                    metavar="B", help="rung-0 replay length "
                                      "(default: requests/4)")
    ap.add_argument("--autotune-min-speedup", type=float, default=1.0,
                    metavar="F",
                    help="fail unless measured winner >= F x measured "
                         "default (the committed BENCH_r13 runs at 1.15)")
    ap.add_argument("--autotune-results-dir",
                    default="autotuning_results_serving")
    ap.add_argument("--autotune-resume", action="store_true",
                    help="replay completed trials from exps.json")
    ap.add_argument("--host-loop", action="store_true",
                    help="run the BENCH_r15 fused multi-step decode "
                         "protocol (PR 16): K=1 per-token host loop vs "
                         "the fused decode_steps=K on-device while_loop "
                         "twin on the returning-sessions trace — exact "
                         "fp32 parity, kv8 bit-exact twins, host "
                         "iterations per token down >= the floor")
    ap.add_argument("--decode-steps", type=int, default=8, metavar="K",
                    help="fused window width for the --host-loop lane")
    ap.add_argument("--host-loop-min-reduction", type=float, default=4.0,
                    metavar="F",
                    help="fail the --host-loop lane unless host "
                         "scheduler iterations per generated token drop "
                         "by >= F vs the K=1 baseline")
    ap.add_argument("--sampling", action="store_true",
                    help="run the BENCH_r18 on-device sampling "
                         "protocol (PR 20): per-slot temperature/"
                         "top-k/top-p as fixed-shape device operands "
                         "on the decode-heavy trace — fresh-twin "
                         "determinism, temp-0 bit parity vs greedy, "
                         "fused decode_steps=K token-exact "
                         "composition, spec rejection sampling gated "
                         "on the deterministic tokens-per-host-"
                         "iteration ratio + statistical parity (TV), "
                         "and the mixed greedy/sampled/constrained-"
                         "JSON 2-program zero-recompile gate "
                         "(uses --speculative K and --decode-steps)")
    ap.add_argument("--temperature", type=float, default=0.25,
                    metavar="T",
                    help="headline temperature for the --sampling "
                         "lanes (sampled rows alternate T and 2T)")
    ap.add_argument("--sampling-min-spec-speedup", type=float,
                    default=1.3, metavar="F",
                    help="fail the --sampling lane unless the spec "
                         "engine's tokens per host decode iteration "
                         ">= F x the plain sampled engine's")
    ap.add_argument("--sampling-max-tv", type=float, default=0.12,
                    metavar="TV",
                    help="statistical-parity floor for the --sampling "
                         "lane: spec-vs-plain token-histogram total "
                         "variation must stay within max(TV, 1.5 x "
                         "the reseeded-plain null TV + 0.02)")
    ap.add_argument("--quant-suite", action="store_true",
                    help="run the BENCH_r07 protocol: mixed + prefix-heavy "
                         "+ decode-heavy traces with quantized lanes and a "
                         "tp=4 x kv8 combo point, merged into one JSON")
    ap.add_argument("--telemetry-bench", action="store_true",
                    help="add the telemetry overhead lane (BENCH_r08): "
                         "trace-ring-off vs fully-enabled twin engines, "
                         "interleaved best-of-3 warm passes, ≤2%% contract "
                         "(recorded; breach warns) + Chrome trace schema "
                         "validation")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write the telemetry lane's Chrome trace_event "
                         "JSON here (open at https://ui.perfetto.dev; "
                         "needs --telemetry-bench)")
    ap.add_argument("--emit-metrics", default=None, metavar="PATH",
                    help="dump the serving engine's Prometheus text "
                         "exposition to PATH and the JSON registry "
                         "snapshot to PATH.json alongside the bench JSON")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    if args.slo and args.replicas < 2:
        ap.error("--slo is the fleet observability lane: it needs "
                 "--replicas N with N >= 2")

    quantize = tuple(m for m in (args.quantize or "").split(",") if m)

    def _default(v, lane_default):
        # argparse default is None so an EXPLICIT 0 stays 0 (sessionless
        # / unpressured modes are reachable in every lane)
        return lane_default if v is None else v

    kw = dict(requests=args.requests, slots=args.slots,
              prefill_batch=args.prefill_batch, layers=args.layers,
              hidden=args.hidden, heads=args.heads, vocab=args.vocab,
              seed=args.seed, dtype=args.dtype, block_size=args.block_size,
              prefill_chunk=args.prefill_chunk)
    fail_msg = "serving outputs diverged from sequential generate"
    if args.replicas > 1 and args.slo:
        res = run_fleet_observability_bench(
            replicas=args.replicas, requests=args.requests,
            slots=args.slots, prefill_batch=args.prefill_batch,
            layers=args.layers, hidden=args.hidden, heads=args.heads,
            vocab=args.vocab, seed=args.seed, dtype=args.dtype,
            block_size=args.block_size, prefill_chunk=args.prefill_chunk,
            prefix_len=_default(args.prefix_len, 192),
            sessions=_default(args.sessions, 9),
            swap_batch=args.swap_batch,
            peak_flops=args.peak_flops, emit_metrics=args.emit_metrics,
            trace_out=args.trace_out)
        ok = res["token_parity"] and res["compile_budgets_ok"] and \
            res["federation"]["scrape_agrees_with_snapshot"] and \
            res["federation"]["live_scrapes_during_step_loop"] > 0 and \
            res["flops"]["agreement_within_10pct"] and \
            res["merged_trace"]["kv_pull_crosses_replica_lanes"] and \
            res["merged_trace"]["route_flow_ends"] > 0
        if not res["overhead"]["within_2pct"]:
            print("WARNING: fleet telemetry overhead "
                  f"{res['overhead']['overhead_pct']:.2f}% exceeds the "
                  "2% contract on this run (noise-prone on shared "
                  "boxes; see within_2pct in the JSON)", file=sys.stderr)
    elif args.replicas > 1:
        res = run_replica_bench(
            replicas=args.replicas, requests=args.requests,
            slots=args.slots, prefill_batch=args.prefill_batch,
            layers=args.layers, hidden=args.hidden, heads=args.heads,
            vocab=args.vocab, seed=args.seed, dtype=args.dtype,
            block_size=args.block_size, prefill_chunk=args.prefill_chunk,
            prefix_len=_default(args.prefix_len, 192),
            sessions=_default(args.sessions, 9),
            swap_batch=args.swap_batch,
            emit_metrics=args.emit_metrics)
        ok = res["token_parity"] and \
            all(s["compile_budgets_ok"] for s in res["scaling"].values())
    elif args.chaos:
        res = run_chaos_bench(
            requests=args.requests, slots=args.slots,
            prefill_batch=args.prefill_batch, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            seed=args.seed, dtype=args.dtype, block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            prefix_len=_default(args.prefix_len, 192),
            sessions=_default(args.sessions, 16),
            swap_batch=args.swap_batch, overload=args.overload,
            quantize=quantize)
        ok = res["token_parity"] and \
            res["crash"]["hung_handles"] == 0 and \
            res["crash"]["unfinished"] == 0 and \
            res["crash"]["requests_rehomed"] >= 1 and \
            res["crash"]["compile_budgets_ok"] and \
            res["crash_sampled"]["parity_exact_vs_faultfree"] and \
            res["crash_sampled"]["requests_rehomed"] >= 1 and \
            res["crash_sampled"]["hung_handles"] == 0 and \
            res["crash_sampled"]["compile_budgets_ok"] and \
            res["flaky_transport"]["pulls_landed_through_retries"] and \
            res["corruption"]["detected_100pct"] and \
            res["corruption"]["recovered_via_recompute_parity"] and \
            res["overload_shed"]["batch_absorbed_all_rejections"] and \
            res["overload_shed"]["protected_shed"] == 0 and \
            res["incident"]["bundle_audit_ok"] and \
            res["incident"]["replay_reproduced"] and \
            res["incident"]["recorder_token_identity"] and \
            res["incident"]["watchdog_stalls_detected"] >= 1 and \
            res["incident"]["watchdog_stall_has_thread_stacks"] and \
            res["incident"]["watchdog_parked_served_out"]
        fail_msg = "chaos recovery gate failed (see JSON lanes)"
        if not res["overload_shed"]["protected_within_1p5x"]:
            # wall-clock contract: recorded and warned, not exit-fatal —
            # CPU-sim TTFT on a shared box is noise-prone (the committed
            # BENCH_r14.json pins a passing measurement)
            print("WARNING: protected TTFT p95 ratio "
                  f"{res['overload_shed']['protected_p95_ratio']} "
                  "exceeds the 1.5x shed contract on this run "
                  "(see overload_shed in the JSON)", file=sys.stderr)
        if not res["incident"]["recorder_overhead_within_2pct"]:
            # same convention: the <=2% flight-recorder overhead is a
            # wall-clock contract — recorded + warned, never exit-fatal
            print("WARNING: incident recorder overhead "
                  f"{res['incident']['recorder_overhead_frac']:+.2%} "
                  "exceeds the 2% contract on this run "
                  "(see incident in the JSON)", file=sys.stderr)
    elif args.disaggregated:
        res = run_disaggregated_bench(
            requests=args.requests, slots=args.slots,
            prefill_batch=args.prefill_batch, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            seed=args.seed, dtype=args.dtype,
            block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            prefix_len=_default(args.prefix_len, 192),
            sessions=_default(args.sessions, 12),
            swap_batch=args.swap_batch,
            burst_prompts=args.burst_prompts,
            burst_prompt_len=args.burst_prompt_len)
        ok = res["token_parity"] and \
            res["structure"]["every_admission_handed_off"] and \
            res["structure"]["decode_recompute_bounded"] and \
            res["structure"]["decode_rode_the_pulled_chain"] and \
            res["structure"]["handoff_events_on_timeline"] and \
            res["interference"]["decode_isolated_from_prefill"] and \
            res["nvme"]["spilled_under_pressure"] and \
            res["nvme"]["resumed_from_nvme"] and \
            res["nvme"]["zero_prefix_recompute"] and \
            res["nvme"]["checksum_rejects"] == 0 and \
            res["nvme"]["tier_labeled_metrics"] and \
            res["nvme"]["timeline_events"] and \
            res["bit_identity"]["tokens_identical"] and \
            res["bit_identity"]["swap_counters_identical"] and \
            res["bit_identity"]["schedule_identical"] and \
            res["bit_identity"]["compile_budget_identical"] and \
            res["bit_identity"]["nvme_stats_zero"]
        fail_msg = "disaggregated gate failed (see structure/nvme/" \
                   "bit_identity in the JSON)"
        inter = res["interference"]
        if not inter["tpot_flat_within_1p15"]:
            # wall-clock contract: recorded + warned, not exit-fatal —
            # CPU-sim TPOT on a shared box is noise-prone (the
            # committed BENCH_r16.json pins a passing measurement)
            print("WARNING: disaggregated victim TPOT burst/quiet "
                  f"ratio {inter['disaggregated']['tpot_burst_over_quiet']} "
                  "exceeds the 1.15x flatness contract on this run "
                  "(see interference in the JSON)", file=sys.stderr)
        if not inter["ttft_no_worse_1p1"]:
            print("WARNING: disaggregated burst TTFT p95 "
                  f"{inter['disaggregated']['burst_ttft_p95_s']} vs "
                  f"colocated {inter['colocated']['burst_ttft_p95_s']} "
                  "exceeds the 1.1x contract on this run",
                  file=sys.stderr)
    elif args.long_context:
        # this lane's trace is a few GIANT prompts, not a wide mixed
        # batch — the shared --requests/--slots defaults (64/8) would
        # make it a multi-hour run, so the lane keeps its own
        lc_requests = 3 if args.requests == 64 else args.requests
        lc_slots = 2 if args.slots == 8 else args.slots
        res = run_long_context_bench(
            requests=lc_requests, slots=lc_slots,
            prefill_batch=args.prefill_batch, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            seed=args.seed, dtype=args.dtype,
            block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            long_prompt_len=args.long_prompt_len,
            sp_degree=args.sp_degree,
            window_blocks=args.window_blocks)
        g = res["gates"]
        ok = g["sp_exact_parity"] and g["sp_compile_budget_ok"] and \
            g["window_slides_ok"] and \
            g["residency_under_quarter_ok"] and \
            g["window_tokens_complete_ok"] and \
            g["cold_context_on_host_ok"] and \
            g["window_compile_budget_ok"] and \
            g["full_window_identical"] and \
            g["probe_128k_compile_budget_ok"]
        fail_msg = "long-context gate failed (see gates in the JSON)"
        if res["sp_speedup"] < 1.0:
            # wall-clock contract: recorded + warned, never exit-fatal
            # — CPU-sim shard_map EMULATES the sp mesh on one host, so
            # prefill scaling there is mechanics, not a speedup claim
            print(f"WARNING: sp={res['sp_degree']} prefill wall-clock "
                  f"speedup {res['sp_speedup']:.2f}x < 1 on this "
                  "CPU-sim run (see sp_speedup in the JSON)",
                  file=sys.stderr)
    elif args.sampling:
        res = run_sampling_bench(
            requests=args.requests, slots=args.slots,
            prefill_batch=args.prefill_batch, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            seed=args.seed, dtype=args.dtype,
            block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            spec_tokens=args.speculative or 4,
            decode_steps=args.decode_steps,
            temperature=args.temperature,
            min_spec_speedup=args.sampling_min_spec_speedup,
            max_tv=args.sampling_max_tv)
        g = res["gates"]
        ok = g["sampled_determinism_exact"] and \
            g["sampled_streams_deviate_from_greedy"] and \
            g["greedy_row_bit_parity"] and \
            g["fused_token_exact_vs_plain"] and \
            g["fused_iter_reduction_ok"] and \
            g["spec_determinism_exact"] and \
            g["draft_determinism_exact"] and \
            g["spec_host_iter_speedup_ok"] and \
            g["statistical_parity_ok"] and \
            g["constrained_json_valid"] and \
            g["mixed_compile_budget_ok"] and \
            g["compile_budgets_ok"] and \
            g["zero_retraces_ok"]
        fail_msg = "sampling gate failed (see gates in the JSON)"
    elif args.host_loop:
        res = run_host_loop_bench(
            requests=args.requests, slots=args.slots,
            prefill_batch=args.prefill_batch, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            seed=args.seed, dtype=args.dtype,
            block_size=args.block_size, prefill_chunk=args.prefill_chunk,
            prefix_len=_default(args.prefix_len, 256),
            sessions=_default(args.sessions, 16),
            decode_steps=args.decode_steps,
            min_iter_reduction=args.host_loop_min_reduction)
        ok = res["gates"]["exact_parity_fp32"] and \
            res["gates"]["kv8_bit_exact"] and \
            res["gates"]["iter_reduction_ok"] and \
            res["telemetry_twin"]["token_parity"]
        fail_msg = "fused decode gate failed (see gates in the JSON)"
        if not res["gates"]["fused_tok_s_ge_baseline"]:
            # wall-clock contract: recorded + warned, not exit-fatal
            # (CPU-sim throughput on shared boxes is noise-prone; the
            # committed BENCH_r15.json pins a passing measurement)
            print("WARNING: fused tok/s "
                  f"{res['fused']['tok_s_warm']:.1f} below the K=1 "
                  f"baseline {res['host_loop_baseline']['tok_s_warm']:.1f} "
                  "on this run (see gates in the JSON)", file=sys.stderr)
        if not res["telemetry_twin"]["within_2pct"]:
            print("WARNING: telemetry overhead "
                  f"{res['telemetry_twin']['overhead_pct']:.2f}% exceeds "
                  "the 2% contract on this run (noise-prone on shared "
                  "boxes)", file=sys.stderr)
    elif args.autotune:
        res = run_autotune_bench(
            requests=args.requests, sessions=_default(args.sessions, 16),
            prefix_len=_default(args.prefix_len, 256),
            pool_frac=_default(args.pool_frac, 0.25), slots=args.slots,
            layers=args.layers, hidden=args.hidden, heads=args.heads,
            vocab=args.vocab, seed=args.seed, dtype=args.dtype,
            results_dir=args.autotune_results_dir,
            max_trials=args.autotune_trials,
            min_budget=args.autotune_min_budget,
            min_speedup=args.autotune_min_speedup,
            resume=args.autotune_resume)
        ok = res["gates"]["winner_ge_min_speedup"] and \
            res["gates"]["best_config_roundtrip"]
        fail_msg = None          # the autotune gate prints its own reason
        if not ok:
            print("WARNING: autotune gate failed — winner "
                  f"{res['winner']['measured_tok_s']:.1f} tok/s vs "
                  f"default {res['default']['measured_tok_s']:.1f} "
                  f"(speedup {res['speedup']:.2f}x, floor "
                  f"{args.autotune_min_speedup}x; roundtrip="
                  f"{res['gates']['best_config_roundtrip']})",
                  file=sys.stderr)
    elif args.quant_suite:
        modes = quantize or ("kv8", "w8a8", "w8a8+kv8")
        # the protocol PROMISES a tp x kv8 combo point: default to tp=4
        # when --tp wasn't raised (needs >= 4 devices — run_bench exits
        # with the XLA_FLAGS hint otherwise) so the artifact can't
        # silently ship without it
        suite_tp = args.tp if args.tp > 1 else 4
        res = {
            "protocol": "quantized paged serving (PR 7): tok/s + servable "
                        "blocks-per-chip vs bf16 per trace; bounded "
                        "token divergence vs full-precision sequential "
                        "(tests/unit/quant_divergence.py)",
            "mixed": run_bench(quantize=modes, tp=suite_tp, **kw),
            "prefix_heavy": run_bench(prefix_len=256, quantize=modes,
                                      **kw),
            "decode_heavy": run_bench(decode_heavy=True, quantize=modes,
                                      **kw),
        }
        # the suite's recommended dtype is bf16 (the production serving
        # dtype the headlines are quoted against).  At bf16 even the
        # UNQUANTIZED serving-vs-sequential comparison can see rare
        # near-tie argmax flips — chunked prefill and one-shot generate
        # reduce in different shapes/orders, both equally valid bf16
        # greedy outputs — so bf16 runs gate on a >= 0.95 per-request
        # agreement floor and record the rate; fp32 runs keep the exact
        # bit-parity gate the non-quant benches pin.
        bf16 = str(args.dtype).replace("torch.", "") in (
            "bf16", "bfloat16")
        ok = True
        # the documented divergence bounds (tests/unit/quant_divergence.py
        # / README): a quant lane shipping below its bound must fail the
        # run, not silently land in the committed artifact
        bounds = {"kv8": 0.85, "kv8+tp": 0.85}
        for t in ("mixed", "prefix_heavy", "decode_heavy"):
            frac = 1.0 - len(res[t]["mismatched_uids"]) / res[t]["requests"]
            res[t]["baseline_request_agreement"] = frac
            ok &= res[t]["token_parity"] if not bf16 else frac >= 0.95
            for mode, lane in (res[t].get("serving_quant") or {}).items():
                rate = lane.get("token_match_rate_vs_sequential")
                if rate is None:
                    continue
                floor = bounds.get(mode, 0.70)   # w8a8 lanes: 0.70
                lane["token_match_bound"] = floor
                if rate < floor:
                    print(f"WARNING: {t}/{mode} token match {rate:.3f} "
                          f"below the documented bound {floor}",
                          file=sys.stderr)
                    ok = False
        res["baseline_parity_note"] = (
            "bf16 run: unquantized serving vs sequential is agreement-"
            "gated (>= 0.95 of requests token-exact) — bf16 near-tie "
            "argmax flips between equally valid compute shapes are not a "
            "serving bug; fp32 runs assert exact parity" if bf16 else
            "fp32 run: unquantized lanes assert exact token parity")
    else:
        res = run_bench(grid=args.grid,
                        prefix_len=_default(args.prefix_len, 0),
                        speculative=args.speculative,
                        decode_heavy=args.decode_heavy, tp=args.tp,
                        quantize=quantize,
                        pool_frac=_default(args.pool_frac, 0.0),
                        swap_batch=args.swap_batch,
                        sessions=_default(args.sessions, 0),
                        telemetry_bench=args.telemetry_bench,
                        trace_out=args.trace_out,
                        emit_metrics=args.emit_metrics, **kw)
        ok = res["token_parity"]
        tel = res.get("serving_telemetry")
        if tel is not None and not tel["within_2pct"]:
            # recorded in the JSON (within_2pct) but NOT an exit failure:
            # a wall-clock ratio on a shared box carries ~±5% noise, and
            # the pinned contract artifact is the committed BENCH_r08 run
            # — failing CI on a GC pause would be pure flake
            print(f"WARNING: telemetry overhead {tel['overhead_pct']:.2f}% "
                  "exceeds the 2% contract on this run (noise-prone on "
                  "shared boxes; see within_2pct in the JSON)",
                  file=sys.stderr)
    print(json.dumps(res, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=2)
    if not ok:
        if fail_msg:
            print(f"WARNING: {fail_msg}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
