"""Continuous-batching serving benchmark: slot-pool scheduler vs sequential
``generate`` on a synthetic mixed-length request trace.

Drives the same trace through both paths and reports aggregate generated
tokens/sec plus compile counts:

 - **serving**: ``inference/serving.py`` — slot-based KV pool, iteration-level
   scheduling, bucketed prefill (O(#buckets)+1 compiled programs total).
 - **sequential**: the one-shot ``InferenceEngine.generate`` loop, one request
   at a time (batch 1), one compiled program per exact request shape.

Methodology (PROFILE.md "continuous-batching serving" entry): the default
trace draws ARBITRARY prompt lengths in [32, 512] and completion budgets in
[16, 64] — real mixed traffic, where the sequential path jit-compiles one
program per exact request shape (and, past its 32-entry LRU, recompiles on
repeats too) while the serving loop compiles O(#buckets)+1 programs total.
The headline is aggregate generated tokens/sec over the whole trace, compiles
included on both sides, because per-shape compilation IS the sequential
path's steady state on arbitrary shapes.  ``--grid`` instead snaps the trace
to a small shape grid that fits the sequential LRU and reports a second
compile-warm pass for both paths — the batching/scheduling win isolated from
the compile-caching win.  Greedy decoding; the bench asserts serving outputs
are token-identical to sequential before reporting numbers.

Usage:
  python benchmarks/serving_bench.py [--requests 64] [--slots 8] [--grid]
      [--layers 2] [--hidden 128] [--seed 0] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROMPT_RANGE = (32, 512)
NEW_TOKEN_RANGE = (16, 64)
# --grid shape grids: |prompts| * |budgets| stays under the engine's
# 32-entry LRU so a second sequential pass is compile-free (see module doc)
PROMPT_GRID = (32, 64, 96, 128, 192, 256, 384, 512)
NEW_TOKEN_GRID = (16, 32, 64)


def build_trace(n_requests: int, vocab: int, seed: int, grid: bool):
    from deepspeed_tpu.inference.serving import Request

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n_requests):
        if grid:
            plen = int(rng.choice(PROMPT_GRID))
            mnew = int(rng.choice(NEW_TOKEN_GRID))
        else:
            plen = int(rng.integers(PROMPT_RANGE[0], PROMPT_RANGE[1] + 1))
            mnew = int(rng.integers(NEW_TOKEN_RANGE[0],
                                    NEW_TOKEN_RANGE[1] + 1))
        reqs.append(Request(uid=i, max_new_tokens=mnew,
                            prompt=rng.integers(0, vocab, plen)))
    return reqs


def run_sequential(engine, reqs):
    outs = {}
    t0 = time.perf_counter()
    for r in reqs:
        outs[r.uid] = engine.generate(r.prompt[None, :],
                                      max_new_tokens=r.max_new_tokens)[0]
    return outs, time.perf_counter() - t0


def run_bench(requests: int = 64, slots: int = 8, prefill_batch: int = 4,
              layers: int = 2, hidden: int = 128, heads: int = 4,
              vocab: int = 2048, seed: int = 0, dtype: str = "fp32",
              grid: bool = False):
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models import gpt2

    max_total = max(PROMPT_GRID) + max(NEW_TOKEN_GRID)
    cfg = gpt2.GPT2Config(vocab_size=vocab, max_seq_len=1024,
                          num_layers=layers, num_heads=heads,
                          hidden_size=hidden)
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg), config={"dtype": dtype,
                                 "tensor_parallel": {"tp_size": 1}})
    reqs = build_trace(requests, vocab, seed, grid)
    gen_tokens = sum(r.max_new_tokens for r in reqs)

    # --- sequential pass 1: per-shape compiles included — this IS the
    # sequential path's steady state on arbitrary request shapes
    seq_outs, seq_cold = run_sequential(engine, reqs)
    n_shapes = len({(len(r.prompt), r.max_new_tokens) for r in reqs})
    seq_warm = None
    if grid:
        # grid mode: every shape program survived the LRU, pass 2 is
        # compile-free — the batching win isolated from the compile win
        assert n_shapes <= 32, "shape grid exceeds the LRU"
        _, seq_warm = run_sequential(engine, reqs)

    # --- serving: cold (compiles included), then a warm pass reusing the
    # compiled bucket programs
    def fresh_serving():
        return ServingEngine(
            engine, slots=slots, max_seq_len=max_total,
            prompt_buckets=tuple(PROMPT_GRID), prefill_batch=prefill_batch)

    srv = fresh_serving()
    t0 = time.perf_counter()
    srv_outs = srv.serve(reqs)
    srv_cold = time.perf_counter() - t0
    srv2 = fresh_serving()
    srv2._prefill_fns = srv._prefill_fns       # keep the compiled programs
    srv2._decode_fn = srv._decode_fn
    t0 = time.perf_counter()
    srv_outs2 = srv2.serve(reqs)
    srv_warm = time.perf_counter() - t0

    mismatches = [r.uid for r in reqs
                  if not (np.array_equal(seq_outs[r.uid], srv_outs[r.uid])
                          and np.array_equal(seq_outs[r.uid],
                                             srv_outs2[r.uid]))]
    result = {
        "trace": "shape-grid" if grid else
                 f"arbitrary prompts {PROMPT_RANGE}, new {NEW_TOKEN_RANGE}",
        "requests": requests,
        "request_shapes": n_shapes,
        "generated_tokens": gen_tokens,
        "sequential": {
            "tok_s": gen_tokens / seq_cold,
            "wall_s": seq_cold,
            "tok_s_warm": gen_tokens / seq_warm if seq_warm else None,
            "wall_warm_s": seq_warm,
            "compiled_programs": len(engine._generate_fns),
        },
        "serving": {
            "tok_s": gen_tokens / srv_cold,
            "wall_s": srv_cold,
            "tok_s_warm": gen_tokens / srv_warm,
            "wall_warm_s": srv_warm,
            "compiled_programs": srv.compile_count,
            "slots": slots, "prefill_batch": prefill_batch,
            "decode_steps": srv2.decode_steps,
            "prefill_calls": srv2.prefill_calls,
        },
        "speedup": seq_cold / srv_cold,
        "speedup_warm": (seq_warm / srv_warm) if seq_warm else None,
        "token_parity": not mismatches,
        "mismatched_uids": mismatches,
        "model": f"gpt2-{layers}l-{hidden}d-{vocab}v ({dtype})",
        "backend": __import__("jax").default_backend(),
    }
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prefill-batch", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dtype", default="fp32")
    ap.add_argument("--grid", action="store_true",
                    help="snap the trace to a small shape grid and report a "
                         "compile-warm second pass for both paths")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    res = run_bench(requests=args.requests, slots=args.slots,
                    prefill_batch=args.prefill_batch, layers=args.layers,
                    hidden=args.hidden, heads=args.heads, vocab=args.vocab,
                    seed=args.seed, dtype=args.dtype, grid=args.grid)
    print(json.dumps(res, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=2)
    if not res["token_parity"]:
        print("WARNING: serving outputs diverged from sequential generate",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
