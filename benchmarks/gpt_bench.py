"""Inference latency/throughput harness (reference
``benchmarks/inference/gpt-bench.py``: p50/p90/p99 latency + tokens/sec).

Measures TTFT (prefill latency) and decode tokens/sec for a model served by
``init_inference``.  Runs any registered model name or an HF checkpoint dir.

Usage:
  python benchmarks/gpt_bench.py --model opt-125m --batch 1 --prompt 128 \
      --gen 64 --trials 10 [--dtype bf16] [--tp 1] [--hf-dir /path/to/ckpt]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def percentile(xs, p):
    return float(np.percentile(np.asarray(xs), p))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="opt-125m")
    ap.add_argument("--hf-dir", default=None,
                    help="HF checkpoint dir (overrides --model)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--prompt", type=int, default=128)
    ap.add_argument("--gen", type=int, default=64)
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--int8", action="store_true",
                    help="INT8 weight-only storage (quant.enabled)")
    ap.add_argument("--w8a8", action="store_true",
                    help="INT8 weights + in-kernel activation quant on the "
                         "s8 MXU (quant.type=w8a8; implies --int8)")
    ap.add_argument("--host-init", action="store_true",
                    help="initialize params on host CPU (required for "
                         "multi-billion models: on-device init materializes "
                         "an f32 copy that can exceed HBM)")
    ap.add_argument("--host-init-bf16", action="store_true",
                    help="random bf16 host init built leaf-by-leaf with "
                         "numpy (no f32 jit tree: OPT-30B f32 is 120GB — "
                         "this peaks at the bf16 tree instead; weight "
                         "VALUES are random, for serving-throughput "
                         "measurement only)")
    ap.add_argument("--zero-inference", action="store_true",
                    help="ZeRO-Inference streamed serving: blocks stay "
                         "host-resident and stream per layer "
                         "(inference/zero_inference.py)")
    ap.add_argument("--pin-layers", type=int, default=0)
    ap.add_argument("--prefetch", type=int, default=1)
    args = ap.parse_args()

    import jax

    import deepspeed_tpu

    if args.hf_dir:
        model = args.hf_dir
    else:
        model = deepspeed_tpu.models.get_model(args.model)
    params = None
    if args.host_init and not args.hf_dir:
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            params = jax.jit(model.init_fn, backend="cpu")(
                jax.random.PRNGKey(0))
        params = jax.device_get(params)
    elif args.host_init_bf16 and not args.hf_dir:
        from host_init import host_init_bf16

        params = host_init_bf16(model)
    engine = deepspeed_tpu.init_inference(
        model=model, params=params,
        config={"dtype": args.dtype,
                "tensor_parallel": {"tp_size": args.tp},
                "zero_inference": {"enabled": args.zero_inference,
                                   "pin_layers": args.pin_layers,
                                   "prefetch": args.prefetch},
                "quant": {"enabled": args.int8 or args.w8a8,
                          "type": "w8a8" if args.w8a8 else "weight"}})
    params = None  # free the host dense tree (13B f32 = 51GB) for serving

    rng = np.random.default_rng(0)
    vocab = 1000  # prompt token range; any real vocab exceeds this
    ids = rng.integers(2, vocab, (args.batch, args.prompt)).astype(np.int32)

    # TTFT: prefill + first token == generate(max_new_tokens=1)
    engine.generate(ids, max_new_tokens=1)      # compile
    ttft = []
    for _ in range(args.trials):
        t0 = time.perf_counter()
        out = engine.generate(ids, max_new_tokens=1)
        ttft.append(time.perf_counter() - t0)

    # full decode: tokens/sec over gen tokens
    engine.generate(ids, max_new_tokens=args.gen)  # compile
    lat = []
    for _ in range(args.trials):
        t0 = time.perf_counter()
        out = engine.generate(ids, max_new_tokens=args.gen)
        lat.append(time.perf_counter() - t0)
    assert out.shape == (args.batch, args.prompt + args.gen)

    decode_tok_s = [args.batch * args.gen / t for t in lat]
    print(json.dumps({
        "model": args.model if not args.hf_dir else args.hf_dir,
        "batch": args.batch, "prompt": args.prompt, "gen": args.gen,
        "ttft_ms": {"p50": round(percentile(ttft, 50) * 1e3, 2),
                    "p90": round(percentile(ttft, 90) * 1e3, 2),
                    "p99": round(percentile(ttft, 99) * 1e3, 2)},
        "latency_ms": {"p50": round(percentile(lat, 50) * 1e3, 2),
                       "p90": round(percentile(lat, 90) * 1e3, 2),
                       "p99": round(percentile(lat, 99) * 1e3, 2)},
        "tokens_per_sec": round(percentile(decode_tok_s, 50), 1),
    }))


if __name__ == "__main__":
    main()
