"""Collective benchmark sweep — the ``ds_bench`` analog (reference
``bin/ds_bench`` -> ``benchmarks/communication/run_all.py``): latency and
algorithmic bandwidth for all_reduce / all_gather / reduce_scatter /
all_to_all / ppermute over a size sweep on the current mesh.

Usage: python benchmarks/comm_bench.py [--dp N] [--trials T]
       [--maxsize-mb M] [--op all|all_reduce|...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def algo_bw(op: str, nbytes: int, n: int, seconds: float) -> float:
    """Algorithmic bandwidth GB/s (reference ``communication/utils.py``
    conventions: ring all-reduce moves 2(n-1)/n of the data)."""
    if op == "all_reduce":
        moved = 2 * nbytes * (n - 1) / n
    elif op in ("all_gather", "reduce_scatter", "all_to_all"):
        moved = nbytes * (n - 1) / n
    else:  # ppermute
        moved = nbytes
    return moved / seconds / 1e9


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=None,
                    help="mesh size (default: all devices)")
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--maxsize-mb", type=float, default=64.0)
    ap.add_argument("--op", default="all",
                    choices=["all", "all_reduce", "all_gather",
                             "reduce_scatter", "all_to_all", "ppermute"])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.topology import MeshTopology

    n = args.dp or len(jax.devices())
    mesh = MeshTopology(dp=n).mesh

    ops = {}

    def reg(name):
        def deco(fn):
            ops[name] = fn
            return fn
        return deco

    reg("all_reduce")(lambda x: jax.lax.psum(x, "dp"))
    reg("all_gather")(lambda x: jax.lax.all_gather(x, "dp"))
    reg("reduce_scatter")(
        lambda x: jax.lax.psum_scatter(x, "dp", tiled=True))
    reg("all_to_all")(
        lambda x: jax.lax.all_to_all(x.reshape(n, -1), "dp", 0, 0,
                                     tiled=False))
    reg("ppermute")(lambda x: jax.lax.ppermute(
        x, "dp", [(i, (i + 1) % n) for i in range(n)]))

    selected = list(ops) if args.op == "all" else [args.op]
    sizes = []
    s = 1 << 12
    while s <= args.maxsize_mb * 2 ** 20:
        sizes.append(int(s))
        s *= 8

    results = []
    for op in selected:
        fn = ops[op]
        for nbytes in sizes:
            elems = nbytes // 4
            if elems % n:  # psum_scatter/all_to_all need n | elems
                elems += n - elems % n

            @jax.jit
            def bench(x):
                def body(xw):
                    acc = jnp.zeros((), jnp.float32)
                    for _ in range(args.trials):
                        # chain iterations through a scalar so the compiler
                        # cannot parallelize or elide the collectives
                        y = xw[0] + acc
                        acc = acc + 0.0 * jnp.sum(fn(y)).astype(jnp.float32)
                    return (xw[0] + acc)[None]

                return shard_map(body, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"))(x)

            x = jnp.ones((n, elems), jnp.float32)
            with mesh:
                jax.block_until_ready(bench(x))        # compile
                t0 = time.perf_counter()
                out = bench(x)
                jax.device_get(jnp.sum(out))           # force completion
                dt = (time.perf_counter() - t0) / args.trials
            results.append({
                "op": op, "bytes": nbytes,
                "latency_us": round(dt * 1e6, 1),
                "algo_bw_gbps": round(algo_bw(op, nbytes, n, dt), 2),
            })
            print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    main()
