"""Autotuner v2 acceptance: rediscover the hand-found bench config.

Runs the staged tuner on the REAL bench model (GPT-2 125M, S=1024) on the
TPU and prints the winning config.  Round-2's hand search found
remat_policy=dots_flash + scan_layers=False + gas>=8 + flash blocks
1024x1024 (PROFILE.md); the tuner explores exactly those knob groups and
must land on an equivalent-throughput point.

Usage: python benchmarks/autotune_bench.py  (~15 min on the chip)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models import gpt2

    def factory():
        cfg = gpt2.GPT2Config.gpt2_125m()
        cfg.use_flash = True
        cfg.remat = True  # baseline; the remat stage varies the policy
        return gpt2.build(cfg)

    rng = np.random.default_rng(0)

    def batch(global_batch, seq_len):
        return {"input_ids": rng.integers(
            0, 50257, (global_batch, seq_len + 1)).astype(np.int32)}

    base = {
        "train_micro_batch_size_per_gpu": 32,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "autotuning": {
            "enabled": True,
            "tuner_type": "staged",
            "results_dir": "autotuning_results_bench",
            # the tunneled dev chip needs a long warm window: big unrolled
            # executables keep paying first-execution costs for several
            # steps, and per-dispatch jitter is 1-2s — short windows
            # systematically penalize exactly the configs that win
            "start_profile_step": 4,
            "end_profile_step": 12,
            # micro batch is pinned at 32 (bs>32 is blocked by the dev
            # tunnel's compile service; zero stages are moot on one chip)
            "num_tuning_micro_batch_sizes": 1,
            "zero_stages": [0],
            "stages": ["batch", "remat", "gas", "flash"],
            "remat_policies": ["dots", "dots_flash"],
            "gas_candidates": [1, 16],
            "flash_blocks": [[512, 1024], [1024, 1024]],
        },
    }
    at = Autotuner(factory, base, batch, seq_len=1024)
    best = at.tune()
    print(json.dumps({"best": best["config"],
                      "tok_s": round(best["throughput"], 1)}))


if __name__ == "__main__":
    main()
