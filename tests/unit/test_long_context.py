"""Long-context serving lane: sequence-parallel (Ulysses) prefill over
the ``sp`` mesh axis + resident-window context paging.

Tier-1 (fast) CPU-sim coverage:
 - ``sp=4`` prefill is token-IDENTICAL to ``sp=1`` on a mixed-length
   trace (the all-to-all is a pure layout move), the a2a byte counter
   advances, and the compile contract stays 2 programs — sp reshapes
   the SAME chunked prefill program through shard_map.
 - ``sp=2 x tp=2`` composes on the 8-device CI mesh with the same
   token parity.
 - resident-window decode is BIT-exact with full attention whenever the
   window covers the whole context (the mask reduces to the identity).
 - under tier pressure a giant prompt slides its window: cold blocks
   demote to the host arena, ``serving_context_window_slides_total``
   advances, ``window_slide`` timeline events land, and the paged-state
   invariant audits pass at every step (``debug_checks=True``).
 - the windowed programs REPLACE the plain bodies one-for-one: the
   sentry budget is unchanged and never trips.
 - chain-key regression: keys are fixed-width rolling digests — no
   position-dependent width, prefix-dependence preserved, the batch
   :func:`chain_keys` byte-identical to per-block :func:`chain_key`.

The loud ctor twins of the ``sp_prefill_exclusive`` /
``resident_window_span`` space constraints are audited in
``test_serving_autotune.py``.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.paged import (CHAIN_KEY_BYTES, chain_key,
                                           chain_keys)
from deepspeed_tpu.inference.serving import Request
from deepspeed_tpu.models import gpt2

CFG = gpt2.GPT2Config.tiny(max_seq_len=256)


def _trace(seed, lens, max_new=6):
    rng = np.random.default_rng(seed)
    return [Request(uid=i,
                    prompt=rng.integers(0, CFG.vocab_size, int(n)),
                    max_new_tokens=max_new)
            for i, n in enumerate(lens)]


def _serve(trace_seed, lens, *, config=None, max_new=6, **kw):
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(CFG), config={"dtype": "fp32", **(config or {})},
        slots=4, max_seq_len=256, block_size=8, prefill_chunk=16,
        debug_checks=True, **kw)
    return srv, srv.serve(_trace(trace_seed, lens, max_new))


def _assert_same(a, b, lens):
    for uid in range(len(lens)):
        np.testing.assert_array_equal(a[uid], b[uid],
                                      err_msg=f"uid {uid}")


# ------------------------------------------------------ sp prefill
def test_sp4_prefill_token_parity_and_a2a_accounting():
    """Acceptance: sp=4 Ulysses prefill is exactly token-identical to
    the sp=1 engine, moves bytes through the all-to-all counter, and
    compiles the same 2 programs (the sp budget amendment is +0)."""
    lens = (40, 70, 100, 25)
    s1, out1 = _serve(11, lens)
    s4, out4 = _serve(11, lens, sp=4)
    _assert_same(out1, out4, lens)
    st = s4.stats()
    assert st["sp"] == 4 and s1.stats()["sp"] == 1
    assert st["sp_alltoall_bytes"] > 0
    assert s1.stats()["sp_alltoall_bytes"] == 0
    # same compile contract as the plain engine — sp reshapes the SAME
    # prefill program through shard_map (budget amendment is zero)
    assert s4.compile_budget == s1.compile_budget
    assert s4.compile_count <= s4.compile_budget
    assert any(e["name"] == "sp_prefill" for e in s4.timeline.events())
    assert s4.resolved_config()["sp"] == 4


def test_sp_composes_with_tp_on_8_device_mesh(eight_devices):
    """sp=2 x tp=2 shares the 8-device CI mesh: heads shard over tp,
    the chunk shards over sp, and tokens still match the 1x1 engine."""
    lens = (40, 70)
    s1, out1 = _serve(13, lens)
    s22, out22 = _serve(
        13, lens, sp=2, config={"tensor_parallel": {"tp_size": 2}})
    _assert_same(out1, out22, lens)
    assert s22.stats()["sp"] == 2
    assert s22.stats()["sp_alltoall_bytes"] > 0


def test_sp_ctor_validations():
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(CFG), config={"dtype": "fp32"})
    from deepspeed_tpu.inference.serving import ServingEngine

    with pytest.raises(ValueError, match="sp must be >= 1"):
        ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                      prefill_chunk=16, sp=0)
    # mesh carries no sp axis -> loud shape mismatch with guidance
    with pytest.raises(ValueError, match="sequence_parallel"):
        ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                      prefill_chunk=16, sp=2)


# ------------------------------------------------- resident window
def test_full_window_is_bit_exact_with_full_attention():
    """A window wide enough to cover the whole context never slides,
    and the windowed decode/prefill programs are BIT-identical to the
    plain ones (window_start=0 masks nothing)."""
    lens = (40, 60, 30)
    sp_, outp = _serve(17, lens)
    sw, outw = _serve(17, lens, host_blocks=64, swap_batch=8,
                      resident_window_blocks=32)
    _assert_same(outp, outw, lens)
    st = sw.stats()
    assert st["resident_window_blocks"] == 32
    assert st["context_window_slides"] == 0


def test_window_slides_under_tier_pressure():
    """Acceptance: prompts far wider than the device window stream
    through — the window slides, cold blocks demote host-side, the
    slide counter and timeline events advance, and every step passes
    the paged-state invariant audit (debug_checks=True)."""
    lens = (100, 80, 120)
    sw, outw = _serve(19, lens, max_new=8, num_blocks=40,
                      host_blocks=96, swap_batch=8,
                      resident_window_blocks=4)
    st = sw.stats()
    assert st["context_window_slides"] > 0
    # device residency stayed under the window cap: landmark + window +
    # one chunk span (+ scratch) is the per-slot ceiling, far below the
    # 100+-token contexts served
    slides = [e for e in sw.timeline.events()
              if e["name"] == "window_slide"]
    assert slides and all(e["args"]["window_start"] > 0 for e in slides)
    assert any(e["args"]["demoted"] > 0 or e["args"]["blocks_freed"] > 0
               for e in slides)
    # cold context actually reached the host tier
    assert st["host_blocks_in_use"] > 0 or st["swap_out"] > 0
    # every request still produced its full token budget
    for uid, n in enumerate(lens):
        assert len(outw[uid]) == n + 8
    # compile contract: windowed bodies REPLACE the plain ones — the
    # sentry budget is the plain tiered budget, and it held
    assert sw.compile_count <= sw.compile_budget
    assert sw.resolved_config()["resident_window_blocks"] == 4


def test_window_ctor_validations():
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(CFG), config={"dtype": "fp32"})
    from deepspeed_tpu.inference.serving import ServingEngine

    base = dict(slots=2, max_seq_len=64, block_size=8, prefill_chunk=16)
    with pytest.raises(ValueError, match="host_blocks"):
        ServingEngine(engine, resident_window_blocks=4, **base)
    with pytest.raises(ValueError, match="must be >= 3"):
        ServingEngine(engine, resident_window_blocks=2, host_blocks=8,
                      swap_batch=4, **base)
    with pytest.raises(ValueError, match="speculative"):
        ServingEngine(engine, resident_window_blocks=4, host_blocks=8,
                      swap_batch=4, spec_tokens=2, **base)
    with pytest.raises(ValueError, match="decode_steps"):
        ServingEngine(engine, resident_window_blocks=4, host_blocks=8,
                      swap_batch=4, decode_steps=4, **base)


# ------------------------------------------------- chain-key regression
def test_chain_keys_fixed_width_and_prefix_dependent():
    """Regression for the unbounded-key bug: every chain key is exactly
    CHAIN_KEY_BYTES wide at ANY chain depth (the old raw-chain encoding
    grew linearly with block index), identical token suffixes under
    different prefixes never alias, and the batch helper matches the
    per-block function byte-for-byte."""
    bs = 4
    rng = np.random.default_rng(23)
    toks = rng.integers(0, 512, 64 * bs).astype(np.int32)
    keys = chain_keys(toks, 64, bs)
    assert len(keys) == 64
    assert all(len(k) == CHAIN_KEY_BYTES for k in keys)
    assert len(set(keys)) == 64
    for i in (0, 1, 31, 63):
        assert chain_key(toks, i, bs) == keys[i]
    # prefix-dependence: same block-2 tokens, different block-0 prefix
    a = np.array([1, 2, 3, 4, 5, 6, 7, 8], np.int32)
    b = np.array([9, 2, 3, 4, 5, 6, 7, 8], np.int32)
    assert chain_key(a, 1, bs) != chain_key(b, 1, bs)
    # and equal chains agree
    assert chain_key(a, 1, bs) == chain_key(a.copy(), 1, bs)


def test_chain_keys_no_depth_aliasing():
    """A shallow chain's key can never equal a deep chain's key built
    from different tokens even when the OLD encoding would have made
    their raw byte strings collide-prone; with fixed-width rolling
    digests the (tokens, depth) -> key map stays injective in practice."""
    bs = 2
    x = np.arange(40, dtype=np.int32)
    all_keys = set()
    for depth in range(1, 20):
        all_keys.add(chain_key(x, depth - 1, bs))
    assert len(all_keys) == 19


# ------------------------------------------------- router giant lane
def test_router_giant_context_affinity_and_slo_class():
    """Prompts over the giant_context_tokens threshold force affinity
    routing (even under round_robin), land in the 'giant_context' SLO
    class, and show up in the router's giant counter + timeline."""
    from deepspeed_tpu.serving.router import ReplicaRouter

    deepspeed_tpu.comm.reset_topology()

    def mk():
        return deepspeed_tpu.init_serving(
            gpt2.build(CFG), config={"dtype": "fp32"}, slots=2,
            max_seq_len=256, block_size=8, prefill_chunk=16,
            host_blocks=32, swap_batch=8)

    rt = ReplicaRouter([mk(), mk()], policy="round_robin",
                       giant_context_tokens=64)
    rng = np.random.default_rng(29)
    out = rt.serve([
        Request(uid=0, prompt=rng.integers(0, CFG.vocab_size, 100),
                max_new_tokens=4),
        Request(uid=1, prompt=rng.integers(0, CFG.vocab_size, 20),
                max_new_tokens=4),
    ])
    assert len(out) == 2
    st = rt.stats()
    assert st["giant_context"] == 1
    assert rt.resolved_config()["giant_context_tokens"] == 64
    assert any(e["name"] == "giant_context"
               for e in rt.timeline.events())
    with pytest.raises(ValueError, match="giant_context_tokens"):
        ReplicaRouter([mk(), mk()], giant_context_tokens=-1)
