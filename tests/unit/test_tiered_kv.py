"""Tiered KV cache: host-DRAM offload of cold paged blocks with
overlapped prefetch (``inference/paged.py`` HostBlockStore +
``ops/paged_kv.py`` block gather/scatter + the ServingEngine demote/
promote scheduler paths).

Tier-1 (fast) coverage:
 - host-store units: content-addressed chain keys, LRU eviction that
   never touches in-flight entries, slot accounting, probe runs.
 - device op units: ``paged_block_gather``/``paged_block_scatter``
   round-trip bit-identically on float pools AND quantized ``{qp, ps}``
   records (codes + scale rows travel together).
 - e2e parity under real block pressure: a deliberately small device
   pool (evictions + preemptions) with the host tier serves token-
   identically to sequential ``generate`` AND to the untiered engine,
   with swaps actually happening, preemption-resume recompute collapsing
   to the unfinished tail, and the compile contract at exactly base + 2
   programs (the two fixed-shape swap programs) — sentry-enforced, so
   H2D/D2H traffic can never introduce further programs.
 - kv8 roundtrip: the tiered small-pool int8 engine is BIT-identical to
   the untiered big-pool int8 engine (deterministic quantization + exact
   byte round trips), with the scale-lockstep ledger audited throughout.
 - residency fault injection: a leaked in-flight host block (flagged
   with no staged record) and a staged record over an unflagged entry
   both raise ``PagedStateError`` naming ``residency-conservation``.

Every serve here runs ``debug_checks=True``: the per-iteration audit
covers the new residency invariant alongside refcounts/trie/tables, and
the strict sentry enforces the +2 swap-program budget at trace time.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_serving_engine)
from deepspeed_tpu.inference.paged import (HostBlockStore, chain_key,
                                           chain_keys)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops import paged_kv


# ------------------------------------------------------------- store units
def test_chain_key_is_cumulative_and_block_indexed():
    toks = np.arange(40, dtype=np.int32)
    k0 = chain_key(toks, 0, 8)
    k1 = chain_key(toks, 1, 8)
    # PR 19: keys are fixed-width rolling digests (the raw-chain byte
    # strings grew linearly with block index — quadratic total at 128k
    # contexts); depth never changes the width and distinct chains
    # never share a key
    from deepspeed_tpu.inference.paged import CHAIN_KEY_BYTES
    assert len(k0) == CHAIN_KEY_BYTES == len(k1) and k0 != k1
    # same leading chain => same key, regardless of what follows
    other = np.concatenate([toks[:16], np.full(8, 999, np.int32)])
    assert chain_key(other, 1, 8) == k1
    assert chain_key(other, 2, 8) != chain_key(toks, 2, 8)
    # the O(len) batch spelling is byte-identical to per-block calls —
    # every tier lookup depends on these two never diverging
    assert chain_keys(toks, 5, 8) == [chain_key(toks, i, 8)
                                      for i in range(5)]
    assert chain_keys(toks, 0, 8) == []


def test_host_store_put_read_pop_and_lru():
    store = HostBlockStore(2, [((3, 4), np.float32), ((3,), np.int8)])
    assert store.block_nbytes == 3 * 4 * 4 + 3
    a = [np.full((3, 4), 1.5, np.float32), np.full(3, 7, np.int8)]
    b = [np.full((3, 4), 2.5, np.float32), np.full(3, 8, np.int8)]
    c = [np.full((3, 4), 3.5, np.float32), np.full(3, 9, np.int8)]
    assert store.put(b"a", a) is not None
    assert store.put(b"b", b) is not None
    assert store.blocks_in_use == 2 and len(store) == 2
    np.testing.assert_array_equal(store.read(b"a")[0], a[0])
    # duplicate key keeps the first copy (and refreshes recency)
    assert store.put(b"a", c) is not None
    np.testing.assert_array_equal(store.read(b"a")[1], a[1])
    # arena full: LRU (now b"b") evicts to make room
    assert store.put(b"c", c) is not None
    assert not store.has(b"b") and store.has(b"a") and store.has(b"c")
    assert store.evictions == 1
    store.pop(b"c")
    assert store.blocks_in_use == 1 and not store.has(b"c")


def test_host_store_in_flight_entries_never_evict():
    store = HostBlockStore(2, [((2,), np.float32)])
    store.put(b"a", [np.zeros(2, np.float32)])
    store.put(b"b", [np.ones(2, np.float32)])
    store.mark_in_flight(b"a")
    store.mark_in_flight(b"b")
    # every slot pinned by a staged promotion: the demotion is refused
    assert store.put(b"c", [np.ones(2, np.float32)]) is None
    store.mark_in_flight(b"a", False)
    assert store.put(b"c", [np.ones(2, np.float32)]) is not None
    assert not store.has(b"a") and store.has(b"b")


def test_host_store_probe_run_contiguous():
    bs = 4
    toks = np.arange(20, dtype=np.int32)
    store = HostBlockStore(4, [((2,), np.float32)])
    arr = [np.zeros(2, np.float32)]
    store.put(chain_key(toks, 0, bs), arr)
    store.put(chain_key(toks, 2, bs), arr)      # hole at block 1
    assert store.probe_run(toks, 0, 20, bs) == [chain_key(toks, 0, bs)]
    assert store.probe_run(toks, 2, 20, bs) == [chain_key(toks, 2, bs)]
    assert store.probe_run(toks, 1, 20, bs) == []
    # cap below the full prompt mirrors the trie lookup cap (a 12-token
    # prompt probes with max_tokens=11: block 2 needs tokens 8..11)
    assert store.probe_run(toks, 2, 11, bs) == []


# ------------------------------------------------------------ device ops
def test_paged_block_gather_scatter_roundtrip_float_and_quantized():
    rng = np.random.default_rng(0)
    pool = {"k": jnp.asarray(rng.normal(size=(2, 6, 4, 8, 16)),
                             jnp.float32),
            "v": jnp.asarray(rng.normal(size=(2, 6, 4, 8, 16)),
                             jnp.float32)}
    ids = jnp.asarray([3, 1, 0, 0], jnp.int32)      # pad cols -> scratch
    staged = paged_kv.paged_block_gather(pool, ids)
    assert staged["k"].shape == (2, 4, 4, 8, 16)
    np.testing.assert_array_equal(np.asarray(staged["k"][:, 0]),
                                  np.asarray(pool["k"][:, 3]))
    # scatter into a zeroed pool: targeted blocks restore bit-identically
    zero = jax.tree_util.tree_map(jnp.zeros_like, pool)
    back = paged_kv.paged_block_scatter(zero, staged,
                                        jnp.asarray([3, 1, 0, 0]))
    for n in ("k", "v"):
        np.testing.assert_array_equal(np.asarray(back[n][:, 3]),
                                      np.asarray(pool[n][:, 3]))
        np.testing.assert_array_equal(np.asarray(back[n][:, 1]),
                                      np.asarray(pool[n][:, 1]))
        assert not np.asarray(back[n][:, 2]).any()  # untouched stays zero

    # quantized records: codes + scale rows travel as one tree
    qpool = {"k": {"qp": jnp.asarray(
                       rng.integers(-127, 127, (2, 6, 4, 8, 16)), jnp.int8),
                   "ps": jnp.asarray(rng.normal(size=(2, 6, 4, 8)),
                                     paged_kv.SCALE_DTYPE)}}
    qstaged = paged_kv.paged_block_gather(qpool, jnp.asarray([5, 2]))
    qzero = jax.tree_util.tree_map(jnp.zeros_like, qpool)
    qback = paged_kv.paged_block_scatter(qzero, qstaged,
                                         jnp.asarray([5, 2]))
    for blk in (5, 2):
        np.testing.assert_array_equal(
            np.asarray(qback["k"]["qp"][:, blk]),
            np.asarray(qpool["k"]["qp"][:, blk]))
        np.testing.assert_array_equal(
            np.asarray(qback["k"]["ps"][:, blk]),
            np.asarray(qpool["k"]["ps"][:, blk]))


# ----------------------------------------------------------------- serving
@pytest.fixture(scope="module")
def tiny_engine():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    deepspeed_tpu.comm.reset_topology()
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _pressure_trace(cfg, n=6, seed=5, prefix_len=24, max_new=28):
    """Shared prefix + completions long enough that a 10-block pool (on
    3 slots / block_size 8) must evict the trie and preempt."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len)
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(3, 10)))]),
                    max_new_tokens=max_new)
            for i in range(n)]


_PRESSURE_KW = dict(slots=3, max_seq_len=64, block_size=8,
                    prefill_chunk=16, prefill_batch=2, num_blocks=10,
                    debug_checks=True)


def _sequential(engine, reqs):
    return {r.uid: engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            for r in reqs}


def test_tiered_parity_under_pressure_and_compile_contract(tiny_engine):
    """Acceptance: the tiered engine under real block pressure is token-
    identical to sequential generate and to the untiered engine, swaps
    actually happen in both directions, preemption-resume recompute
    collapses vs the evict/recompute baseline, and the compile contract
    is exactly base + 2 swap programs (strict sentry)."""
    engine, cfg = tiny_engine
    reqs = _pressure_trace(cfg)
    seq = _sequential(engine, reqs)

    srv = ServingEngine(engine, host_blocks=64, swap_batch=4,
                        **_PRESSURE_KW)
    out = srv.serve(reqs)
    st = srv.stats()
    for r in reqs:
        np.testing.assert_array_equal(out[r.uid], seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert st["swap_out"] > 0 and st["swap_in"] > 0
    assert st["swap_bytes"] == (st["swap_out"] + st["swap_in"]) * \
        srv._host.block_nbytes
    assert st["host_blocks_in_use"] > 0
    assert st["compile_count"] == 4 and st["compile_budget"] == 4
    names = sorted(srv.sentry.report())
    assert "kv_demote" in names and "kv_promote" in names

    base = ServingEngine(engine, **_PRESSURE_KW)
    outb = base.serve(reqs)
    stb = base.stats()
    for r in reqs:
        np.testing.assert_array_equal(outb[r.uid], seq[r.uid])
    # both preempt (the pool is the same size) but the tiered resume
    # re-prefills only unfinished tails, not whole prefixes
    assert st["evicted"] > 0 and stb["evicted"] > 0
    assert st["resume_recompute_tokens"] < stb["resume_recompute_tokens"]
    assert stb["swap_out"] == 0 and stb["swap_in"] == 0
    assert stb["compile_budget"] == 2


def test_tiered_warm_pass_promotes_evicted_prefix(tiny_engine):
    """A second pass over the same trace finds its (previously evicted)
    chains in the host tier: promotions run, parity holds, and at least
    part of the prefetch traffic is staged ahead (misses < promotions)."""
    engine, cfg = tiny_engine
    reqs = _pressure_trace(cfg, seed=7)
    seq = _sequential(engine, reqs)
    srv = ServingEngine(engine, host_blocks=64, swap_batch=4,
                        **_PRESSURE_KW)
    srv.serve(reqs)
    in0 = srv.stats()["swap_in"]
    out2 = srv.serve(reqs)
    st = srv.stats()
    for r in reqs:
        np.testing.assert_array_equal(out2[r.uid], seq[r.uid])
    assert st["swap_in"] > in0
    assert st["prefetch_misses"] < st["swap_in"]
    assert st["prefetch_wait_p95_s"] is not None


def test_tiered_kv8_roundtrip_bit_identical(tiny_engine):
    """kv8 x tiered: int8 codes and their per-block scale rows demote and
    promote together, so the tiered small-pool engine reproduces the
    untiered big-pool int8 engine BIT-identically (deterministic
    quantization + byte-exact round trips).  debug_checks audits the
    scale-lockstep ledger and the residency invariant throughout."""
    engine, cfg = tiny_engine
    reqs = _pressure_trace(cfg, seed=9)
    big = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        quantize="kv8", debug_checks=True)
    ref = big.serve(reqs)
    srv = ServingEngine(engine, quantize="kv8", host_blocks=64,
                        swap_batch=4, **_PRESSURE_KW)
    out = srv.serve(reqs)
    st = srv.stats()
    for r in reqs:
        np.testing.assert_array_equal(out[r.uid], ref[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert st["swap_out"] > 0 and st["swap_in"] > 0
    assert st["kv_dtype"] == "int8"
    # the swap tree carries the scale-table leaves: block bytes > codes
    codes = 2 * cfg.num_layers * cfg.num_heads * 8 * \
        (cfg.hidden_size // cfg.num_heads)
    assert srv._host.block_nbytes > codes


def test_tiered_speculative_parity(tiny_engine):
    """n-gram speculative decoding over the tiered pool: token-exact and
    within its 2 + 2 swap-program budget."""
    engine, cfg = tiny_engine
    reqs = _pressure_trace(cfg, seed=11)
    seq = _sequential(engine, reqs)
    srv = ServingEngine(engine, spec_tokens=3, host_blocks=64,
                        swap_batch=4, **_PRESSURE_KW)
    out = srv.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(out[r.uid], seq[r.uid])
    assert srv.compile_budget == 4 and srv.compile_count <= 4
    assert srv.stats()["swap_out"] > 0


def test_residency_fault_injection_names_leaked_in_flight(tiny_engine):
    """Corrupting the in-flight lockstep raises PagedStateError naming
    residency-conservation: (a) a host entry flagged in-flight with no
    staged record — the leaked block whose arena slot can never free —
    and (b) a staged record over an unflagged (LRU-evictable) entry."""
    engine, cfg = tiny_engine
    reqs = _pressure_trace(cfg, seed=13)
    srv = ServingEngine(engine, host_blocks=64, swap_batch=4,
                        **_PRESSURE_KW)
    srv.serve(reqs)
    assert len(srv._host) > 0
    audit_serving_engine(srv, {})               # clean post-serve state
    key = next(iter(srv._host.snapshot()[1]))
    srv._host.mark_in_flight(key)               # no staged record exists
    with pytest.raises(PagedStateError, match="leaked in-flight") as ei:
        audit_serving_engine(srv, {})
    assert ei.value.invariant == "residency-conservation"
    srv._host.mark_in_flight(key, False)
    srv._staged["ghost"] = {"keys": [key], "chunks": []}
    with pytest.raises(PagedStateError, match="NOT flagged") as ei:
        audit_serving_engine(srv, {})
    assert ei.value.invariant == "residency-conservation"
    srv._staged.clear()
    audit_serving_engine(srv, {})


def test_staged_prefetch_records_never_outlive_their_request(tiny_engine):
    """Regression: a prefetch staged for a request whose chain a SHARING
    request promotes first used to leak its record past admission
    (probe_run comes back empty, the early return skipped the take) —
    two leaks then permanently filled the double buffer and the stale
    records pinned in-flight flags.  Every staged record must belong to
    a still-pending request at every scheduler iteration."""
    engine, cfg = tiny_engine
    rng = np.random.default_rng(21)
    prefix = rng.integers(0, cfg.vocab_size, 24)
    # many requests over ONE shared session prefix: consecutive pending
    # entries stage the same chain, the first admission promotes it
    reqs = [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(3, 8)))]),
                    max_new_tokens=24)
            for i in range(8)]
    srv = ServingEngine(engine, host_blocks=64, swap_batch=4,
                        **_PRESSURE_KW)
    orig = srv._issue_prefetch
    leaks = []

    def hooked(pending):
        live = {item.req.uid for item in pending}
        stale = set(srv._staged) - live
        if stale:
            leaks.append(stale)
        return orig(pending)

    srv._issue_prefetch = hooked
    srv.serve(reqs)
    srv.serve(reqs)                     # warm pass: host tier populated
    assert not leaks, f"staged records leaked past admission: {leaks}"
    assert srv._staged == {}


def test_tiered_requires_chunked_prefix_mode(tiny_engine):
    engine, _ = tiny_engine
    with pytest.raises(ValueError, match="tiered KV"):
        ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                      prefix_caching=False, host_blocks=8)
    with pytest.raises(ValueError, match="tiered KV"):
        ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                      prompt_buckets=(64,), host_blocks=8)


def test_tiering_off_is_inert_and_stats_schema_stable(tiny_engine):
    """host_blocks=0 (default): no swap programs, no host arena, zeroed
    tier stats — and the pre-tiering stat keys are untouched."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    srv.serve(_pressure_trace(cfg, n=3, seed=15, max_new=4))
    st = srv.stats()
    assert srv._host is None and st["compile_budget"] == 2
    assert st["host_blocks"] == 0 and st["host_pool_bytes"] == 0
    assert st["swap_in"] == 0 and st["swap_out"] == 0
    for k in ("prefix_cache_hit_rate", "blocks_in_use", "free_blocks",
              "ttft_p50_s", "kv_pool_bytes"):
        assert k in st


def test_init_serving_plumbs_host_blocks(tiny_engine):
    _, cfg = tiny_engine
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        slots=2, max_seq_len=64, block_size=8, host_blocks=16,
        swap_batch=4, debug_checks=True)
    assert srv.host_blocks == 16 and srv.swap_batch == 4
    assert srv._host is not None and srv._host.num_blocks == 16
    assert srv.compile_budget == 4
