"""Threaded serving fleet stress (PR 14 satellite): a 2-replica
``init_router(threaded=True)`` fleet under ``debug_checks=True`` driven
by concurrent submitter threads, mid-flight cancels, a drain +
re-admit, and a live ``/metrics``/``/stats``/``/trace`` scraper thread
— all while the lock sanitizer order-checks every fleet/replica/handle
acquisition.

Asserts: zero sanitizer trips (``lock_violations == 0`` with a nonzero
check count), EXACT token parity for every non-cancelled request vs the
single-threaded sequential run (greedy resume keeps outputs token-exact
across the drain handoff), clean router audits, per-replica compile
budgets unchanged (the strict sentry would have raised mid-run
otherwise), and at least one successful live scrape carrying the
instrumented-lock families.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import audit_router
from deepspeed_tpu.inference.serving import Request
from deepspeed_tpu.models import gpt2


@pytest.fixture(scope="module")
def fleet_setup():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    spec = gpt2.build(cfg)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return spec, cfg, engine


def _session_trace(cfg, n=10, sessions=3, seed=3, prefix_len=24):
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, cfg.vocab_size, prefix_len)
                for _ in range(sessions)]
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefixes[i % sessions],
                         rng.integers(0, cfg.vocab_size,
                                      int(rng.integers(3, 8)))]),
                    max_new_tokens=8)
            for i in range(n)]


def test_threaded_fleet_parity_under_sanitizer(fleet_setup):
    spec, cfg, engine = fleet_setup
    reqs = _session_trace(cfg)
    sequential = {r.uid: engine.generate(r.prompt[None, :],
                                         max_new_tokens=r.max_new_tokens)[0]
                  for r in reqs}

    deepspeed_tpu.comm.reset_topology()
    router = deepspeed_tpu.init_router(
        spec, config={"dtype": "fp32",
                      "tensor_parallel": {"tp_size": 1}},
        params=engine.params, replicas=2, threaded=True,
        slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
        prefill_batch=2, debug_checks=True)
    server = router.start_metrics_server(port=0)

    # ---- live scraper: hammers every endpoint while the fleet runs
    stop_scraping = threading.Event()
    scrapes = {"metrics": 0, "stats": 0, "trace": 0}
    scrape_errors = []

    def scraper():
        while not stop_scraping.is_set():
            for ep in ("metrics", "stats", "trace"):
                try:
                    with urllib.request.urlopen(
                            f"{server.url}/{ep}", timeout=10) as resp:
                        body = resp.read().decode("utf-8")
                except Exception as e:   # noqa: BLE001 — surfaced below
                    scrape_errors.append((ep, repr(e)))
                    return
                if ep == "metrics":
                    if "serving_lock_wait_seconds" in body and \
                            "serving_lock_order_checks_total" in body:
                        scrapes["metrics"] += 1
                else:
                    json.loads(body)
                    scrapes[ep] += 1

    scraper_t = threading.Thread(target=scraper, daemon=True)

    # ---- concurrent submitters (3 threads interleave the trace)
    handles = {}
    handles_mu = threading.Lock()
    submit_errors = []

    def submitter(chunk):
        try:
            for r in chunk:
                h = router.submit(r)
                with handles_mu:
                    handles[r.uid] = h
        except Exception as e:           # noqa: BLE001 — surfaced below
            submit_errors.append(repr(e))

    router.start()
    scraper_t.start()
    chunks = [reqs[0::3], reqs[1::3], reqs[2::3]]
    subs = [threading.Thread(target=submitter, args=(c,)) for c in chunks]
    for t in subs:
        t.start()
    for t in subs:
        t.join(timeout=60)
    assert submit_errors == []

    # ---- cancels racing the workers: two extra requests, cancelled
    # right after submit (either outcome — cancelled or already
    # finished — is legal; the handle must reach a terminal state)
    extras = _session_trace(cfg, n=2, seed=11)
    for i, r in enumerate(extras):
        r.uid = 100 + i
    extra_handles = [router.submit(r) for r in extras]
    cancel_rc = [h.cancel() for h in extra_handles]
    assert all(isinstance(c, bool) for c in cancel_rc)

    # ---- mid-flight drain + re-admit while workers step
    handed = router.drain(0)
    assert handed >= 0
    router.readmit(0)
    # post-handoff cancels still route through the router (fleet +
    # replica locks) — never straight into an engine a worker is
    # stepping
    for h in handles.values():
        assert h._canceller == router.cancel

    # ---- collect: streams finish on the ORIGINAL handles
    for r in reqs:
        out = handles[r.uid].result(timeout=120)
        assert out is not None
        np.testing.assert_array_equal(out, sequential[r.uid])
    for h in extra_handles:
        if h.status != "cancelled":
            assert h.result(timeout=120) is not None
    stop_scraping.set()
    scraper_t.join(timeout=30)
    router.stop()

    # ---- sanitizer: plenty of cross-lock checks, zero violations
    st = router.stats()
    assert st["lock_order_checks"] > 0
    assert st["lock_violations"] == 0
    # the counter family agrees with stats()
    snap = router.metrics.snapshot()
    checks_total = snap["serving_lock_order_checks_total"]["series"][0]
    assert int(checks_total["value"]) == st["lock_order_checks"]
    # contended-or-not, every instrumented acquire observed its wait
    waits = snap["serving_lock_wait_seconds"]["series"]
    assert sum(s["count"] for s in waits) > 0

    # ---- fleet stayed correct: audits, budgets, live scrapes
    audit_router(router)
    for rep in st["per_replica"]:
        assert rep["compile_count"] <= rep["compile_budget"]
    assert scrape_errors == []
    assert scrapes["metrics"] >= 1
    assert scrapes["stats"] >= 1 and scrapes["trace"] >= 1
