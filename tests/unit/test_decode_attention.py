"""KV-cache decode path: kernel correctness + end-to-end generation parity.

Mirrors the reference's inference-kernel tests (``tests/unit/ops/transformer/
inference``) and ``test_inference.py`` output-parity style: every cached path is
checked against the non-cached full-recompute forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.decode_attention import (
    decode_attention_pallas, decode_attention_reference,
    paged_decode_attention_pallas, paged_decode_attention_reference,
    paged_verify_attention_pallas)

pytestmark = pytest.mark.slow  # Pallas interpret mode: minutes on CPU


def _dense_reference(q, k, v, q_pos):
    """Naive masked attention, fp32."""
    b, h, t, d = q.shape
    hkv, s = k.shape[1], k.shape[2]
    if h != hkv:
        rep = h // hkv
        k = np.repeat(k, rep, axis=1)
        v = np.repeat(v, rep, axis=1)
    scores = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(d)
    mask = np.arange(s)[None, :] <= (q_pos + np.arange(t))[:, None]
    scores = np.where(mask[None, None], scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v)


@pytest.mark.parametrize("h,hkv", [(4, 4), (8, 2)])
def test_reference_path_matches_dense(h, hkv):
    rng = np.random.default_rng(0)
    b, s, d, t, pos = 2, 64, 32, 1, 17
    q = rng.standard_normal((b, h, t, d)).astype(np.float32)
    k = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    v = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    out = decode_attention_reference(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), pos)
    np.testing.assert_allclose(np.asarray(out), _dense_reference(q, k, v, pos),
                               rtol=2e-5, atol=2e-5)


def test_reference_path_prefill_matches_dense():
    rng = np.random.default_rng(1)
    b, h, s, d, t = 1, 4, 64, 16, 9
    q = rng.standard_normal((b, h, t, d)).astype(np.float32)
    k = rng.standard_normal((b, h, s, d)).astype(np.float32)
    v = rng.standard_normal((b, h, s, d)).astype(np.float32)
    out = decode_attention_reference(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), 0)
    np.testing.assert_allclose(np.asarray(out), _dense_reference(q, k, v, 0),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,hkv,pos", [(4, 4, 0), (4, 4, 63), (8, 2, 200)])
def test_pallas_kernel_matches_reference(h, hkv, pos):
    rng = np.random.default_rng(2)
    b, s, d = 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    got = decode_attention_pallas(q, k, v, pos, block_k=64, interpret=True)
    want = decode_attention_reference(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,hkv", [(4, 4), (8, 2)])
def test_pallas_kernel_per_sequence_lengths(h, hkv):
    """Ragged lengths[B] (continuous-batching slots): Pallas == reference ==
    per-row scalar, including GQA head sharing and a zero-length slot."""
    rng = np.random.default_rng(7)
    b, s, d = 4, 256, 64
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    lengths = jnp.asarray([0, 17, 200, 255], jnp.int32)
    got = decode_attention_pallas(q, k, v, lengths, block_k=64,
                                  interpret=True)
    want = decode_attention_reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # each row must equal the scalar-position path on that row alone
    for i, pos in enumerate(np.asarray(lengths)):
        row = decode_attention_reference(q[i:i + 1], k[i:i + 1],
                                         v[i:i + 1], int(pos))
        np.testing.assert_allclose(np.asarray(want[i:i + 1]),
                                   np.asarray(row), rtol=1e-6, atol=1e-6)


def test_pallas_kernel_ragged_under_jit_traced_lengths():
    """One compiled program serves every lengths vector (jit-traced)."""
    rng = np.random.default_rng(8)
    b, h, s, d = 2, 4, 128, 32

    @jax.jit
    def step(q, k, v, lengths):
        return decode_attention_pallas(q, k, v, lengths, block_k=64,
                                       interpret=True)

    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    for lens in ([0, 127], [5, 64], [127, 0]):
        lengths = jnp.asarray(lens, jnp.int32)
        got = step(q, k, v, lengths)
        want = decode_attention_reference(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_forward_cached_ragged_matches_full_recompute(family):
    """Per-sequence lengths through forward_cached: ragged bucketed prefill
    + per-row decode == full-recompute logits on each row's own sequence."""
    if family == "gpt2":
        from deepspeed_tpu.models import gpt2 as m

        cfg = m.GPT2Config.tiny()
    else:
        from deepspeed_tpu.models import llama as m

        cfg = m.LlamaConfig.tiny()
    params = m.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    lens = np.array([3, 5, 2], np.int32)
    t = 5
    ids = np.zeros((3, t), np.int32)
    for i, n in enumerate(lens):
        ids[i, :n] = rng.integers(1, cfg.vocab_size, n)
    ids = jnp.asarray(ids)
    cache = m.init_cache(cfg, 3, 64, jnp.float32)
    logits, cache = m.forward_cached(cfg, params, ids, cache, 0,
                                     lengths=jnp.asarray(lens))
    for i, n in enumerate(lens):
        full = m.forward(cfg, params, ids[i:i + 1, :n], train=False)
        np.testing.assert_allclose(np.asarray(logits[i]),
                                   np.asarray(full[0, n - 1]),
                                   rtol=2e-4, atol=2e-4)
    seqs = [list(np.asarray(ids[i, :lens[i]])) for i in range(3)]
    toks = jnp.argmax(logits, -1).astype(jnp.int32)
    cur = lens.copy()
    for _ in range(3):
        for i in range(3):
            seqs[i].append(int(toks[i]))
        logits, cache = m.forward_cached(cfg, params, toks[:, None], cache,
                                         0, lengths=jnp.asarray(cur))
        cur += 1
        for i in range(3):
            full = m.forward(cfg, params, jnp.asarray([seqs[i]], jnp.int32),
                             train=False)
            np.testing.assert_allclose(np.asarray(logits[i]),
                                       np.asarray(full[0, -1]),
                                       rtol=2e-4, atol=2e-4)
        toks = jnp.argmax(logits, -1).astype(jnp.int32)


def test_pallas_kernel_under_jit_traced_pos():
    rng = np.random.default_rng(3)
    b, h, s, d = 1, 4, 128, 32

    @jax.jit
    def step(q, k, v, pos):
        return decode_attention_pallas(q, k, v, pos, block_k=64,
                                       interpret=True)

    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    for pos in [0, 5, 127]:
        got = step(q, k, v, jnp.int32(pos))
        want = decode_attention_reference(q, k, v, pos)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_forward_cached_matches_forward(family):
    """Cached incremental forward == full forward, token by token."""
    if family == "gpt2":
        from deepspeed_tpu.models import gpt2 as m

        cfg = m.GPT2Config.tiny()
    else:
        from deepspeed_tpu.models import llama as m

        cfg = m.LlamaConfig.tiny()
    params = m.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    b, s = 2, 12
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)

    full_logits = m.forward(cfg, params, ids, train=False)  # [B, S, V]

    cache = m.init_cache(cfg, b, 64, jnp.float32)
    prompt = 5
    logits, cache = m.forward_cached(cfg, params, ids[:, :prompt], cache, 0)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full_logits[:, prompt - 1]),
                               rtol=2e-4, atol=2e-4)
    for pos in range(prompt, s):
        logits, cache = m.forward_cached(cfg, params, ids[:, pos:pos + 1],
                                         cache, pos)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full_logits[:, pos]),
                                   rtol=2e-4, atol=2e-4)


def test_generate_kv_cache_matches_recompute():
    """InferenceEngine KV-cache generation == full-recompute generation."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny(max_seq_len=256)
    model = gpt2.build(cfg)
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    rng = np.random.default_rng(5)
    ids = rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32)

    out_cached = engine.generate(ids, max_new_tokens=8)

    model_nocache = gpt2.build(cfg)
    model_nocache.decode_hooks = None
    engine2 = deepspeed_tpu.init_inference(
        model_nocache, config={"dtype": "fp32",
                               "tensor_parallel": {"tp_size": 1}},
        params=engine.params)
    out_full = engine2.generate(ids, max_new_tokens=8)
    np.testing.assert_array_equal(out_cached, out_full)


# ---------------------------------------------------------- paged decode kernel
def _paged_from_contiguous(kc, vc, nb, bs, rng):
    """Scatter a contiguous [B, HKV, S, D] cache into a pool of ``nb``
    blocks via random (non-overlapping) block tables."""
    b, hkv, s, d = kc.shape
    nbper = s // bs
    bt = rng.permutation(np.arange(1, nb))[:b * nbper] \
        .reshape(b, nbper).astype(np.int32)
    kp = np.zeros((nb, hkv, bs, d), kc.dtype)
    vp = np.zeros((nb, hkv, bs, d), vc.dtype)
    for row in range(b):
        for i in range(nbper):
            kp[bt[row, i]] = kc[row, :, i * bs:(i + 1) * bs]
            vp[bt[row, i]] = vc[row, :, i * bs:(i + 1) * bs]
    return kp, vp, bt


@pytest.mark.parametrize("h,hkv", [(4, 4), (8, 2)])
def test_paged_pallas_kernel_matches_reference(h, hkv):
    """The block-table-walking kernel (scalar prefetch) == the gather-based
    reference == the contiguous kernel, with per-row ragged positions
    (including a zero-length slot)."""
    rng = np.random.default_rng(10)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, rng)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    lengths = jnp.asarray([0, 17, 200, 255], jnp.int32)
    want = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                      lengths)
    ref = paged_decode_attention_reference(
        q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), lengths)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    got = paged_decode_attention_pallas(
        q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), lengths,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,hkv,t", [(4, 4, 4), (8, 2, 5)])
def test_paged_verify_pallas_kernel_matches_reference(h, hkv, t):
    """The K+1 speculative verify window (T query rows per slot, each row's
    window starting at its own base) == the gather-based reference == the
    contiguous dense path, with ragged bases including 0 and a window that
    straddles a block boundary."""
    rng = np.random.default_rng(12)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, rng)
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    # bases: fresh slot, mid-block, window straddling the 64-boundary, and
    # a window ending at the last cached position
    bases = jnp.asarray([0, 17, 62, 256 - t], jnp.int32)
    want = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                      bases)
    ref = paged_decode_attention_reference(
        q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), bases)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    got = paged_verify_attention_pallas(
        q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), bases,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_verify_pallas_kernel_under_jit_traced_bases():
    """One compiled verify program serves every (bases, block_table) pair —
    the speculative serving loop's contract."""
    rng = np.random.default_rng(13)
    b, h, s, d, bs, t = 2, 4, 128, 32, 32, 3
    kc = rng.standard_normal((b, h, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, h, s, d)).astype(np.float32)
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)

    @jax.jit
    def step(q, kp, vp, bt, bases):
        return paged_verify_attention_pallas(q, kp, vp, bt, bases,
                                             interpret=True)

    for seed, bases in ((0, [0, 100]), (1, [31, 125 - t])):
        r2 = np.random.default_rng(200 + seed)
        kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, r2)
        bases = jnp.asarray(bases, jnp.int32)
        got = step(q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
                   bases)
        want = decode_attention_reference(q, jnp.asarray(kc),
                                          jnp.asarray(vc), bases)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_paged_pallas_kernel_under_jit_traced_tables():
    """One compiled program serves every (lengths, block_table) pair — the
    serving loop's decode contract."""
    rng = np.random.default_rng(11)
    b, h, s, d, bs = 2, 4, 128, 32, 32
    kc = rng.standard_normal((b, h, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, h, s, d)).astype(np.float32)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)

    @jax.jit
    def step(q, kp, vp, bt, lengths):
        return paged_decode_attention_pallas(q, kp, vp, bt, lengths,
                                             interpret=True)

    for seed, lens in ((0, [0, 127]), (1, [64, 5])):
        r2 = np.random.default_rng(100 + seed)
        kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, r2)
        lengths = jnp.asarray(lens, jnp.int32)
        got = step(q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
                   lengths)
        want = decode_attention_reference(q, jnp.asarray(kc),
                                          jnp.asarray(vc), lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


# -------------------------------------------- tensor-parallel kernel shards
def _tp_mesh(n):
    """A (1,1,1,1,n) mesh over the first n CPU-sim devices — the tp slice
    of the engine topology the serving engine installs via tp_context."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:n]).reshape(1, 1, 1, 1, n)
    return Mesh(devs, ("pp", "dp", "ep", "sp", "tp"))


@pytest.mark.parametrize("h,hkv,tp", [(4, 4, 2), (8, 4, 4), (8, 2, 2)])
def test_paged_pallas_kernel_sharded_matches_reference(h, hkv, tp):
    """Under a configured tp context each chip launches the decode kernel
    on its own HKV/tp head shard of q and the pool; the assembled global
    output equals the unsharded reference bit-for-tolerance."""
    from deepspeed_tpu.ops import paged_kv

    rng = np.random.default_rng(20)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, rng)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    lengths = jnp.asarray([0, 17, 200, 255], jnp.int32)
    want = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                      lengths)
    with paged_kv.tp_context(_tp_mesh(tp)):
        got = jax.jit(
            lambda *a: paged_decode_attention_pallas(*a, interpret=True))(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), lengths)
        ref = jax.jit(paged_decode_attention_reference)(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("h,hkv,tp,t", [(4, 4, 2, 4), (8, 2, 2, 5)])
def test_paged_verify_pallas_kernel_sharded_matches_reference(h, hkv, tp, t):
    """The K+1 verify window shards over heads exactly like single-token
    decode (the T query rows ride inside each head-shard's tile)."""
    from deepspeed_tpu.ops import paged_kv

    rng = np.random.default_rng(21)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, rng)
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    bases = jnp.asarray([0, 17, 62, 256 - t], jnp.int32)
    want = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                      bases)
    with paged_kv.tp_context(_tp_mesh(tp)):
        got = jax.jit(
            lambda *a: paged_verify_attention_pallas(*a, interpret=True))(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), bases)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_ops_gqa_below_tp_fall_back_replicated():
    """HKV smaller than the tp axis cannot shard: head_shards reports 1 and
    the ops run the replicated path — identical results, no error."""
    from deepspeed_tpu.ops import paged_kv

    rng = np.random.default_rng(22)
    b, h, hkv, s, d, bs = 2, 8, 2, 128, 32, 32
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _paged_from_contiguous(kc, vc, 2 * b * (s // bs), bs, rng)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    lengths = jnp.asarray([5, 100], jnp.int32)
    want = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                      lengths)
    with paged_kv.tp_context(_tp_mesh(4)):
        assert paged_kv.head_shards(hkv, h) == 1      # 2 % 4 != 0
        got = paged_decode_attention_reference(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt), lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


# -------------------------------------------------- quantized (int8) pools
def _quantized_from_contiguous(kc, vc, nb, bs, rng):
    """Scatter contiguous [B, HKV, S, D] caches into an int8 record pool
    through random block tables (the write path quantizes per token)."""
    from deepspeed_tpu.ops import paged_kv

    b, hkv, s, d = kc.shape
    nbper = s // bs
    bt = rng.permutation(np.arange(1, nb))[:b * nbper] \
        .reshape(b, nbper).astype(np.int32)
    pool = paged_kv.quantize_pool(jnp.zeros((nb, hkv, bs, d), jnp.float32))
    kp, vp = paged_kv.paged_cache_update(
        pool, pool, jnp.asarray(kc), jnp.asarray(vc),
        jnp.zeros(b, jnp.int32), jnp.asarray(bt))
    return kp, vp, bt


@pytest.mark.parametrize("h,hkv", [(4, 4), (8, 2)])
def test_quantized_paged_pallas_kernel_matches_reference(h, hkv):
    """int8 pool records through the decode kernel: the in-kernel
    scale-fold (scores * k-scale, probs * v-scale) equals the gather +
    dequant reference exactly, and both track the float cache within the
    int8 error envelope."""
    from deepspeed_tpu.ops import paged_kv  # noqa: F401 (fixture helper)

    rng = np.random.default_rng(30)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _quantized_from_contiguous(kc, vc, 2 * b * (s // bs), bs,
                                            rng)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    lengths = jnp.asarray([0, 17, 200, 255], jnp.int32)
    ref = paged_decode_attention_reference(q, kp, vp, jnp.asarray(bt),
                                           lengths)
    got = paged_decode_attention_pallas(q, kp, vp, jnp.asarray(bt), lengths,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dense = decode_attention_reference(q, jnp.asarray(kc), jnp.asarray(vc),
                                       lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(dense),
                               atol=5e-2)


@pytest.mark.parametrize("h,hkv,t", [(4, 4, 4), (8, 2, 5)])
def test_quantized_verify_pallas_kernel_matches_reference(h, hkv, t):
    """The K+1 verify window over an int8 pool: per-row bases, straddled
    block boundaries, in-kernel dequant — same contract as the float
    kernel within kernel tolerance of the dequant reference."""
    rng = np.random.default_rng(31)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _quantized_from_contiguous(kc, vc, 2 * b * (s // bs), bs,
                                            rng)
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    bases = jnp.asarray([0, 17, 62, 256 - t], jnp.int32)
    ref = paged_decode_attention_reference(q, kp, vp, jnp.asarray(bt),
                                           bases)
    got = paged_verify_attention_pallas(q, kp, vp, jnp.asarray(bt), bases,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,hkv,tp", [(8, 4, 4), (8, 2, 2)])
def test_quantized_paged_kernel_sharded_matches_reference(h, hkv, tp):
    """int8 records shard whole under the tp context — codes AND the
    scale table split on the head dim — and the sharded kernel equals the
    unsharded dequant reference (scales are head-local, so sharding
    changes no value)."""
    from deepspeed_tpu.ops import paged_kv

    rng = np.random.default_rng(32)
    b, s, d, bs = 4, 256, 64, 64
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp, vp, bt = _quantized_from_contiguous(kc, vc, 2 * b * (s // bs), bs,
                                            rng)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    lengths = jnp.asarray([0, 17, 200, 255], jnp.int32)
    want = paged_decode_attention_reference(q, kp, vp, jnp.asarray(bt),
                                            lengths)
    with paged_kv.tp_context(_tp_mesh(tp)):
        assert paged_kv.head_shards(hkv, h) == tp
        got = jax.jit(
            lambda *a: paged_decode_attention_pallas(*a, interpret=True))(
            q, kp, vp, jnp.asarray(bt), lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
