"""serving_bench-derived acceptance checks (slow lane: runs a full trace
through both serving paths — minutes on a CPU-sim box).

Asserts the PROFILE.md claims reproduce: aggregate-throughput speedup of the
continuous-batching scheduler over sequential ``generate``, O(#buckets)
compile count, and token parity.  Timing-based, hence ``slow`` — tier-1
covers the functional pieces in test_serving.py.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "benchmarks")))


def test_serving_bench_speedup_parity_and_compiles():
    import serving_bench

    res = serving_bench.run_bench(requests=32, slots=8, layers=2, hidden=64,
                                  heads=4, vocab=512, seed=0)
    assert res["token_parity"], res["mismatched_uids"]
    # chunked prefill: exactly 1 prefill + 1 decode program for the trace
    assert res["serving"]["compiled_programs"] == 2
    # ... no worse than the bucketed fallback's O(#buckets)+1
    assert res["serving"]["compiled_programs"] <= \
        res["serving_bucketed"]["compiled_programs"]
    # the sequential path compiled one program per request SHAPE instead
    # (LRU-capped at 32 entries)
    assert res["sequential"]["compiled_programs"] > \
        res["serving"]["compiled_programs"]
    # acceptance: >= 1.5x aggregate tokens/sec on the mixed-length trace
    assert res["speedup"] >= 1.5, res


def test_serving_bench_speculative_decode_heavy_trace():
    """The BENCH_r05 acceptance lane: a decode-heavy trace (short prompts,
    long completions) with the n-gram speculative lane.  Draft–verify must
    beat the non-speculative chunked path >= 1.3x aggregate decode tok/s in
    the compile-warm steady state, with exact greedy parity, a reported
    acceptance rate, and the bounded compile contract (n-gram: 2 programs)."""
    import serving_bench

    res = serving_bench.run_bench(requests=32, slots=8, layers=2, hidden=64,
                                  heads=4, vocab=512, seed=0,
                                  decode_heavy=True, speculative=4)
    assert res["token_parity"], res["mismatched_uids"]
    spec = res["serving_speculative"]
    assert spec["compiled_programs"] == 2          # prefill + verify
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
    assert spec["stats"]["drafted_tokens"] > 0
    # steady state (compile-warm on both sides): the draft–verify win
    assert res["speedup_spec_vs_chunked_warm"] >= 1.3, res
    # compiles included, speculation must still not lose
    assert res["speedup_spec_vs_chunked"] >= 1.0, res


def test_serving_bench_prefix_heavy_trace():
    """The PagedAttention/RadixAttention acceptance lane: a 64-request
    trace sharing a 256-token system prompt.  Paged + chunked prefill +
    prefix cache must beat the PR 1-style bucketed slot-pool path >= 1.5x
    in the compile-warm steady state, with exact greedy parity and no more
    compiled programs than the bucket ladder."""
    import serving_bench

    res = serving_bench.run_bench(requests=64, slots=8, layers=2, hidden=128,
                                  heads=4, vocab=2048, seed=0,
                                  prefix_len=256, prefill_chunk=64)
    assert res["token_parity"], res["mismatched_uids"]
    assert res["serving"]["compiled_programs"] == 2
    assert res["serving"]["compiled_programs"] <= \
        res["serving_bucketed"]["compiled_programs"]
    stats = res["serving"]["stats"]
    # the shared prefix is reused: most prompt tokens never recompute
    assert stats["prefix_cache_hit_rate"] >= 0.5, stats
    # steady state (compile-warm on both sides): the paged/prefix win
    assert res["speedup_vs_bucketed_warm"] >= 1.5, res
    # compiles included, the paged path must still not lose
    assert res["speedup_vs_bucketed"] >= 1.0, res


def test_serving_bench_tp_lane_shrinks_per_chip_kv():
    """The BENCH_r06 acceptance lane (small edition): the --tp lane serves
    the same trace token-exactly on a tensor-parallel mesh with the paged
    pool head-sharded — per-chip KV bytes shrink by exactly tp and the
    2-program compile contract holds."""
    import serving_bench

    res = serving_bench.run_bench(requests=8, slots=4, layers=1, hidden=64,
                                  heads=4, vocab=512, seed=0, tp=2)
    assert res["token_parity"], res["mismatched_uids"]
    tp = res["serving_tp"]
    assert tp["kv_sharded"] and tp["compiled_programs"] == 2
    assert res["kv_per_chip_shrink"] == 2.0
    assert res["kv_bytes_per_chip_tp"] * 2 == res["kv_bytes_per_chip_replicated"]


def test_serving_bench_tiered_pool_frac_lane():
    """The BENCH_r09 acceptance lane (small edition): returning-session
    traffic on a device pool sized at 25% of the unique working set.  The
    tiered engine must hold exact token parity (both engines are gated on
    it by run_bench), actually swap in both directions, keep the +2
    swap-program compile contract, land most promotions on the prefetch
    path, and beat the evict/preempt baseline in the steady state.  The
    compile-warm speedup floor is conservative (the committed 64-request
    BENCH_r09.json shows 1.47x warm / 1.11x cold)."""
    import serving_bench

    res = serving_bench.run_bench(requests=32, slots=8, layers=2,
                                  hidden=128, heads=4, vocab=2048, seed=0,
                                  prefix_len=256, sessions=10,
                                  pool_frac=0.25)
    assert res["token_parity"], res["mismatched_uids"]
    t = res["serving_tiered"]
    assert t["device_pool_blocks"] < t["working_set_blocks"]
    tiered, base = t["tiered"], t["preemption_baseline"]
    assert tiered["compiled_programs"] == 4      # 2 + demote + promote
    assert base["compiled_programs"] == 2
    assert tiered["swap_out"] > 0 and tiered["swap_in"] > 0
    assert tiered["prefetch_misses"] < tiered["swap_in"]
    assert tiered["prefetch_wait_p95_s"] is not None
    # the session cache survives below the pool: hit rate way above the
    # evicting baseline's, and the steady state is faster
    assert tiered["prefix_cache_hit_rate"] > \
        base["prefix_cache_hit_rate"] + 0.3
    assert t["speedup_tiered_vs_preemption_warm"] >= 1.1, t


def test_serving_bench_quant_lanes():
    """--quantize lanes: kv8 reports >= 1.8x servable blocks per chip vs
    a bf16 pool (hd=32 model: 2·hd/(hd+2) ≈ 1.88x), the w8a8 engine lane
    really carries K-grouped records, both hold the 2-program contract,
    and the measured token match rate vs full-precision sequential clears
    the documented bound."""
    import serving_bench

    res = serving_bench.run_bench(requests=16, slots=4, layers=2,
                                  hidden=128, heads=4, vocab=512, seed=0,
                                  quantize=("kv8", "w8a8+kv8"))
    assert res["token_parity"], res["mismatched_uids"]   # unquantized lanes
    q = res["serving_quant"]
    for mode in ("kv8", "w8a8+kv8"):
        assert q[mode]["compiled_programs"] == 2, q[mode]
        assert q[mode]["kv_dtype"] == "int8"
        assert q[mode]["servable_blocks_per_chip_vs_bf16"] >= 1.8, q[mode]
        assert q[mode]["token_match_rate_vs_sequential"] >= 0.7, q[mode]
        assert q[mode]["kv_scale_bytes"] > 0
    assert q["kv8"]["weight_quant"] is None
    assert q["w8a8+kv8"]["weight_quant"] == "w8a8"


def test_serving_bench_telemetry_lane(tmp_path):
    """The BENCH_r08 acceptance lane (small edition): telemetry-enabled
    vs telemetry-off twin engines on the same trace with token parity, a
    schema-valid exported Chrome trace carrying one span per request, and
    the --emit-metrics Prometheus/JSON artifact pair.  The 2% overhead
    contract itself is pinned by the committed 64-request BENCH_r08 run —
    on a small shared test box this asserts a loose 15% sanity bound."""
    import serving_bench

    trace = tmp_path / "trace.json"
    prom = tmp_path / "metrics.prom"
    res = serving_bench.run_bench(requests=16, slots=4, layers=1, hidden=64,
                                  heads=4, vocab=512, seed=0,
                                  telemetry_bench=True,
                                  trace_out=str(trace),
                                  emit_metrics=str(prom))
    assert res["token_parity"], res["mismatched_uids"]
    tel = res["serving_telemetry"]
    assert tel["token_parity"] and tel["trace_valid"]
    assert tel["trace_events_recorded"] > 0
    # 4 passes (1 warm-up + 3 timed) over 16 requests all land spans
    assert tel["trace_summary"]["request_spans"] == 4 * 16
    assert tel["overhead_pct"] <= 15.0, tel
    import json

    from deepspeed_tpu.telemetry import validate_chrome_trace

    validate_chrome_trace(json.load(open(trace)))
    text = prom.read_text()
    assert "# TYPE serving_iterations_total counter" in text
    assert "serving_ttft_seconds_bucket" in text
    snap = json.load(open(str(prom) + ".json"))
    assert snap["serving_requests_admitted_total"]["series"][0]["value"] > 0


def test_serving_bench_chaos_lane():
    """BENCH_r14 (PR 15, docs/reliability.md): the chaos protocol's
    deterministic gates at test scale — crash re-homing parity vs the
    fault-free twin with zero hung handles, flaky-transport pulls
    landing through retries, 100% checksum detection of injected
    host-arena corruption (exit gates + patrol scrub), and the shed
    lane rejecting only batch-class work.  The wall-clock 1.5x
    protected-TTFT contract is recorded in the JSON (pinned by the
    committed BENCH_r14.json, not asserted here — shared-box noise)."""
    import serving_bench

    res = serving_bench.run_chaos_bench(
        requests=16, slots=4, layers=1, hidden=64, heads=4, vocab=512,
        seed=0, prefix_len=96, sessions=6, swap_batch=4,
        quantize=("kv8",))
    assert res["token_parity"], res["mismatched"]
    crash = res["crash"]
    assert crash["hung_handles"] == 0 and crash["unfinished"] == 0
    assert crash["requests_rehomed"] >= 1
    assert crash["requests_failed"] == 0
    assert crash["parity_exact_vs_faultfree"]
    assert crash["compile_budgets_ok"]
    assert crash["recovery_latency_s"] is not None
    assert res["crash_kv8"]["bit_exact_vs_unfaulted_kv8"]
    flk = res["flaky_transport"]
    assert flk["pulls_landed_through_retries"]
    assert flk["transport_faults_injected"]["transient"] >= 1
    corr = res["corruption"]
    assert corr["detected_100pct"], corr
    assert corr["recovered_via_recompute_parity"]
    shed = res["overload_shed"]
    assert shed["batch_absorbed_all_rejections"]
    assert shed["protected_shed"] == 0
    assert shed["protected_finished"] == shed["protected_requests"]
