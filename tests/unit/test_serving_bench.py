"""serving_bench-derived acceptance checks (slow lane: runs a full trace
through both serving paths — minutes on a CPU-sim box).

Asserts the PROFILE.md claims reproduce: aggregate-throughput speedup of the
continuous-batching scheduler over sequential ``generate``, O(#buckets)
compile count, and token parity.  Timing-based, hence ``slow`` — tier-1
covers the functional pieces in test_serving.py.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "benchmarks")))


def test_serving_bench_speedup_parity_and_compiles():
    import serving_bench

    res = serving_bench.run_bench(requests=32, slots=8, layers=2, hidden=64,
                                  heads=4, vocab=512, seed=0)
    assert res["token_parity"], res["mismatched_uids"]
    # O(#buckets): at most one prefill program per ladder rung + one decode
    assert res["serving"]["compiled_programs"] <= \
        len(serving_bench.PROMPT_GRID) + 1
    # the sequential path compiled one program per request SHAPE instead
    # (LRU-capped at 32 entries)
    assert res["sequential"]["compiled_programs"] > \
        res["serving"]["compiled_programs"]
    # acceptance: >= 1.5x aggregate tokens/sec on the mixed-length trace
    assert res["speedup"] >= 1.5, res
