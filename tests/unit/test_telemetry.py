"""Telemetry layer (``deepspeed_tpu/telemetry/``): metrics registry,
streaming-histogram quantile accuracy, Chrome trace_event export schema,
and the engine wiring.

Tier-1 (fast) coverage:
 - registry units: counter/gauge/histogram cells, label series identity,
   type-conflict rejection, Prometheus text exposition shape, JSON
   snapshot serializability, ``to_events`` monitor routing.
 - histogram quantiles: p50/p95/p99 against ``np.percentile`` on known
   distributions, within one bucket width (the documented accuracy
   contract); monotone in q; empty/overflow edges.
 - trace timeline: bounded ring + dropped accounting, ``capacity=0``
   no-op mode, span/instant/complete emission, ``validate_chrome_trace``
   accepting exports and rejecting seeded schema violations.
 - ``ServingEngine``: ``stats()`` keys byte-for-byte backed by the
   registry, per-request spans + scheduler/sentry/audit events in
   ``dump_trace`` output, ``serve(profile_dir=)``, spec-decode events.
 - ``DeepSpeedEngine``: loss/lr/throughput gauges + wall-clock timer
   histograms routed through the MonitorMaster CSV backend to disk.
"""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.telemetry import (DEFAULT_TIME_BUCKETS_S, Histogram,
                                     MetricsRegistry, TraceTimeline,
                                     validate_chrome_trace)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2


# ---------------------------------------------------------------- registry
def test_counter_gauge_basics_and_type_conflicts():
    r = MetricsRegistry()
    c = r.counter("reqs_total", "requests")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)
    g = r.gauge("blocks_in_use")
    g.set(7)
    g.add(-2)
    assert g.value == 5
    # one name, one type — a silent re-kind is two subsystems colliding
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("reqs_total")
    # get-or-create returns the SAME cell
    assert r.counter("reqs_total") is c


def test_histogram_bucket_conflict_rejected():
    r = MetricsRegistry()
    h = r.histogram("x_ms", buckets=(1.0, 10.0))
    assert r.histogram("x_ms", buckets=(1.0, 10.0)) is h   # same scale: ok
    with pytest.raises(ValueError, match="already exists with buckets"):
        r.histogram("x_ms", buckets=(100.0, 1000.0))


def test_timer_elapsed_probe_keeps_one_histogram_sample():
    """SynchronizedWallClockTimer.log()/elapsed() probing a RUNNING timer
    must not split its interval into two histogram observations."""
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    r = MetricsRegistry()
    timers = SynchronizedWallClockTimer(registry=r)
    t = timers("fwd")
    t.start()
    t.elapsed(reset=False)                # mid-interval probe
    t.stop()
    h = r.snapshot()["train_wall_clock_ms"]["series"][0]
    assert h["count"] == 1                # one logical interval, one sample


def test_registry_label_series_identity():
    r = MetricsRegistry()
    a = r.counter("hits_total", family="gpt2")
    b = r.counter("hits_total", family="llama")
    assert a is not b
    assert r.counter("hits_total", family="gpt2") is a
    a.inc(2)
    b.inc(5)
    snap = r.snapshot()["hits_total"]
    by_label = {s["labels"]["family"]: s["value"] for s in snap["series"]}
    assert by_label == {"gpt2": 2, "llama": 5}


def test_prometheus_text_exposition_shape():
    r = MetricsRegistry()
    r.counter("c_total", "help text").inc(2)
    h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.prometheus_text()
    assert "# HELP c_total help text" in text
    assert "# TYPE c_total counter" in text
    assert "c_total 2.0" in text
    assert "# TYPE lat_seconds histogram" in text
    # cumulative le buckets ending at +Inf == count, plus _sum/_count
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1.0"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    # snapshot is JSON-able as-is (the --emit-metrics artifact)
    json.dumps(r.snapshot())


def test_registry_to_events_monitor_routing():
    r = MetricsRegistry()
    r.gauge("train_loss", monitor_name="Train/Samples/train_loss").set(1.5)
    h = r.histogram("step_ms", buckets=(1.0, 10.0), timer="fwd")
    h.observe(2.0)
    r.histogram("empty_ms", buckets=(1.0,))       # no samples: no events
    events = {name: v for name, v, _ in r.to_events(step=7)}
    assert events["Train/Samples/train_loss"] == 1.5
    assert events["step_ms/fwd_count"] == 1.0
    assert "step_ms/fwd_p50" in events and "step_ms/fwd_p95" in events
    assert not any(n.startswith("empty_ms") for n in events)
    assert all(s == 7 for _, _, s in r.to_events(step=7))


# -------------------------------------------------------------- histograms
@pytest.mark.parametrize("dist", ["uniform", "normal", "exponential"])
def test_histogram_quantiles_within_one_bucket_width(dist):
    """The accuracy contract: p50/p95/p99 within one bucket width of
    ``np.percentile`` on known distributions."""
    rng = np.random.default_rng(0)
    if dist == "uniform":
        vals = rng.uniform(0.0, 100.0, 4000)
    elif dist == "normal":
        vals = np.clip(rng.normal(50.0, 15.0, 4000), 0.0, None)
    else:
        vals = rng.exponential(20.0, 4000)
    width = 4.0
    h = Histogram(bounds=[width * i for i in range(1, 64)])
    for v in vals:
        h.observe(v)
    for q in (50, 95, 99):
        est = h.quantile(q / 100)
        ref = float(np.percentile(vals, q))
        assert abs(est - ref) <= width, (dist, q, est, ref)
    # monotone in q
    qs = [h.quantile(q) for q in (0.1, 0.5, 0.9, 0.95, 0.99)]
    assert qs == sorted(qs)


def test_histogram_edges():
    h = Histogram(bounds=(1.0, 2.0))
    assert h.quantile(0.5) is None and h.mean() is None
    h.observe(10.0)                       # overflow clamps to last edge
    assert h.quantile(0.99) == 2.0
    assert h.bucket_counts() == [(1.0, 0), (2.0, 0), (float("inf"), 1)]
    with pytest.raises(ValueError, match="ascending"):
        Histogram(bounds=(2.0, 1.0))
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        h.quantile(1.5)
    # defaults cover sub-ms..minute latencies
    assert DEFAULT_TIME_BUCKETS_S[0] <= 1e-4 < 60 <= DEFAULT_TIME_BUCKETS_S[-1]


# ---------------------------------------------------------------- timeline
def test_timeline_ring_bounds_and_disabled_mode():
    t = TraceTimeline(capacity=4)
    for i in range(7):
        t.instant(f"e{i}")
    assert len(t) == 4 and t.dropped == 3 and t.emitted == 7
    assert [e["name"] for e in t.events()] == ["e3", "e4", "e5", "e6"]

    off = TraceTimeline(capacity=0)
    assert not off.enabled
    off.instant("x")
    off.complete("y", 0.0)
    with off.span("z"):
        pass
    assert len(off) == 0 and off.emitted == 0


def test_timeline_span_and_chrome_export_schema():
    t = TraceTimeline(capacity=64, pid=3)
    tid = t.thread("req a")
    with t.span("work", tid=tid, k=1):
        t.instant("inside")
    t.complete("req a", 0.0, tid=tid, uid="a")
    doc = t.to_chrome(process_name="test")
    json.dumps(doc)                       # valid JSON document
    summary = validate_chrome_trace(doc)
    assert summary["complete"] == 2 and summary["instant"] == 1
    assert summary["request_spans"] == 1
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"]
    assert "test" in names and "req a" in names and "scheduler" in names
    assert all(e["pid"] == 3 for e in doc["traceEvents"])


def test_validate_chrome_trace_rejects_schema_violations():
    def ev(**kw):
        base = {"name": "e", "ph": "i", "ts": 1.0, "pid": 0, "tid": 0}
        base.update(kw)
        return base

    with pytest.raises(ValueError, match="non-empty list"):
        validate_chrome_trace({"traceEvents": []})
    with pytest.raises(ValueError, match="missing 'pid'"):
        validate_chrome_trace({"traceEvents": [
            {"name": "e", "ph": "i", "ts": 0.0, "tid": 0}]})
    with pytest.raises(ValueError, match="sorted"):
        validate_chrome_trace({"traceEvents": [ev(ts=5.0), ev(ts=1.0)]})
    with pytest.raises(ValueError, match="unknown phase"):
        validate_chrome_trace({"traceEvents": [ev(ph="Q")]})
    with pytest.raises(ValueError, match="dur"):
        validate_chrome_trace({"traceEvents": [ev(ph="X")]})
    with pytest.raises(ValueError, match="E without a matching B"):
        validate_chrome_trace({"traceEvents": [ev(ph="E")]})
    with pytest.raises(ValueError, match="unclosed"):
        validate_chrome_trace({"traceEvents": [ev(ph="B")]})
    # paired B/E and complete X both pass
    validate_chrome_trace({"traceEvents": [
        ev(ph="B"), ev(ph="E", ts=2.0), ev(ph="X", ts=3.0, dur=1.0)]})


def test_validate_chrome_trace_pairs_disagg_handoffs():
    """PR 18 regression: ``handoff`` instants pair per uid — engine park
    half (args carry ``slot``) first, router pump half (``src``/``dst``)
    second.  A router half with no preceding park is a fabricated hop
    (error under strict, counted otherwise); a park the pump never
    collected is legal at dump time and only counts."""
    def ev(ts, **args):
        return {"name": "handoff", "ph": "i", "s": "t", "ts": ts,
                "pid": 0, "tid": 0, "args": args}

    strict = {"otherData": {"sources": ["router", "replica 0"]}}
    paired = {"traceEvents": [ev(1.0, uid="a", slot=2),
                              ev(2.0, uid="a", src=0, dst=1)], **strict}
    s = validate_chrome_trace(paired)
    assert s["handoffs"] == 1 and s["handoff_unmatched"] == 0

    fabricated = {"traceEvents": [ev(1.0, uid="a", src=0, dst=1)],
                  **strict}
    with pytest.raises(ValueError, match="never parked"):
        validate_chrome_trace(fabricated)
    s = validate_chrome_trace(fabricated, strict_flows=False)
    assert s["handoffs"] == 1 and s["handoff_unmatched"] == 1

    # parked-but-not-pumped tolerated EVEN under strict (dump mid-park),
    # but visible in the summary; pairing is per-uid, order per event
    parked = {"traceEvents": [ev(1.0, uid="a", slot=2),
                              ev(2.0, uid="b", slot=3),
                              ev(3.0, uid="b", src=1, dst=0)], **strict}
    s = validate_chrome_trace(parked)
    assert s["handoffs"] == 1 and s["handoff_unmatched"] == 1


# ----------------------------------------------------------- serving engine
@pytest.fixture(scope="module")
def tiny_engine():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _trace(cfg, n, seed=0, max_new=(2, 12)):
    rng = np.random.default_rng(seed)
    return [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(3, 40))),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def test_serving_stats_backed_by_registry(tiny_engine):
    """stats() values and the registry cells are the same data — the
    PR 2–7 key set rides on telemetry/ now."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    reqs = _trace(cfg, 6)
    srv.serve(reqs)
    st = srv.stats()
    snap = srv.metrics.snapshot()

    def val(name):
        return snap[name]["series"][0]["value"]

    assert st["admitted"] == srv.admitted == int(val(
        "serving_requests_admitted_total")) == len(reqs)
    assert st["decode_steps"] == int(val("serving_decode_steps_total"))
    assert st["prefill_calls"] == int(val("serving_prefill_calls_total"))
    assert st["iterations"] == int(val("serving_iterations_total"))
    assert st["invariant_checks_run"] == int(val(
        "serving_invariant_checks_total")) > 0
    # latency percentiles come from the streaming histograms (bounded
    # memory), and the per-request debug view is a bounded deque
    ttft = snap["serving_ttft_seconds"]["series"][0]
    assert ttft["count"] == st["requests_finished"] == len(reqs)
    assert st["ttft_p50_s"] == ttft["p50"] > 0
    assert srv._latencies.maxlen is not None
    # the Prometheus exposition renders the same counters
    assert "serving_requests_finished_total 6.0" in \
        srv.metrics.prometheus_text()
    # ring health keys
    assert st["trace_capacity"] > 0 and st["trace_events"] > 0
    assert st["trace_events_dropped"] == 0


def test_serving_dump_trace_schema_and_event_flow(tiny_engine, tmp_path):
    """The exported timeline is valid Chrome trace JSON carrying the full
    scheduler event flow: per-request spans, prefill/decode phases, the
    sentry's jit_trace events, and the invariant audits."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2, num_blocks=12,
                        debug_checks=True)
    reqs = _trace(cfg, 6, seed=1)
    srv.serve(reqs)
    path = srv.dump_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    summary = validate_chrome_trace(doc)
    assert summary["request_spans"] == len(reqs)
    names = {e["name"] for e in doc["traceEvents"]}
    for expected in ("admit", "prefill", "decode", "invariant_audit",
                     "jit_trace"):
        assert expected in names, (expected, sorted(names))
    # every admission (including preemption resumes) records the prefix
    # hit/miss outcome; every request uid admits at least once
    admits = [e for e in doc["traceEvents"] if e["name"] == "admit"]
    assert {a["args"]["uid"] for a in admits} == \
        {str(r.uid) for r in reqs}
    assert all("prefix_hit_tokens" in a["args"] for a in admits)
    # request spans live on their slot's lane and carry latency args
    span = next(e for e in doc["traceEvents"]
                if e["name"].startswith("req ") and e["ph"] == "X")
    assert span["tid"] >= 1 and span["args"]["new_tokens"] >= 1
    assert span["args"]["ttft_s"] > 0


def test_serving_trace_capacity_zero_disables_ring(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16, trace_capacity=0)
    srv.serve(_trace(cfg, 3, seed=2))
    st = srv.stats()
    assert st["trace_capacity"] == 0 and st["trace_events"] == 0
    assert st["requests_finished"] == 3       # registry stays on
    assert st["ttft_p50_s"] > 0


def test_serving_spec_decode_timeline_events(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2, spec_tokens=3,
                        debug_checks=True)
    srv.serve(_trace(cfg, 4, seed=3, max_new=(4, 10)))
    names = [e["name"] for e in srv.timeline.events()]
    for expected in ("spec_propose", "spec_verify", "spec_accept"):
        assert expected in names, (expected, sorted(set(names)))
    accept = next(e for e in srv.timeline.events()
                  if e["name"] == "spec_accept")
    assert all(0 <= a <= 3 for a in accept["args"]["accept_lens"])
    validate_chrome_trace(srv.timeline.to_chrome())


def test_serve_profile_dir_window(tiny_engine, tmp_path):
    """serve(profile_dir=) brackets scheduler iterations with the
    jax.profiler window, stamping start/stop on the timeline."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16)
    srv.serve(_trace(cfg, 3, seed=4), profile_dir=str(tmp_path / "prof"),
              profile_iters=2)
    names = [e["name"] for e in srv.timeline.events()]
    # start always stamps; stop stamps when the profiler actually opened
    # (unavailable backends degrade to a warning, never an error)
    if "profiler_start" in names:
        assert "profiler_stop" in names


def test_preemption_and_eviction_land_on_timeline(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=32, prefill_batch=2, num_blocks=12,
                        debug_checks=True)
    rng = np.random.default_rng(5)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28) for i in range(5)]
    srv.serve(reqs)
    assert srv.preempted > 0
    names = {e["name"] for e in srv.timeline.events()}
    assert "preempt" in names
    # preempted-and-resumed requests still close exactly one span each
    assert validate_chrome_trace(
        srv.timeline.to_chrome())["request_spans"] == len(reqs)


# ---------------------------------------------------------- training engine
def test_training_engine_registry_routes_monitor_csv(tmp_path):
    """The train loop's loss/lr/throughput gauges and wall-clock timer
    histograms live in engine.metrics and land on disk through the
    MonitorMaster CSV backend (the registry-snapshot routing)."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "csv_monitor": {"enabled": True,
                                "output_path": str(tmp_path),
                                "job_name": "t"},
                "mesh": {}})
    rng = np.random.default_rng(0)
    for _ in range(3):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        engine.train_batch(batch)
    snap = engine.metrics.snapshot()
    assert snap["train_loss"]["series"][0]["value"] > 0
    assert snap["train_global_steps"]["series"][0]["value"] == 3
    timers = {s["labels"]["timer"]: s
              for s in snap["train_wall_clock_ms"]["series"]}
    assert timers["train_batch"]["count"] == 3
    files = sorted(os.listdir(tmp_path / "t"))
    # historical event names preserved (monitor_name), plus throughput
    # and the timer breakdown finally on disk
    assert "Train_Samples_train_loss.csv" in files
    assert "Train_Samples_lr.csv" in files
    assert "Train_Samples_throughput.csv" in files
    assert any(f.startswith("train_wall_clock_ms_train_batch") for f in files)
    # no fp16 in this run: no dead loss_scale series/file
    assert "train_loss_scale" not in snap
    assert "Train_Samples_loss_scale.csv" not in files
    rows = (tmp_path / "t" / "Train_Samples_train_loss.csv").read_text()
    assert rows.splitlines()[0] == "step,Train/Samples/train_loss"
    assert len(rows.splitlines()) == 4        # header + 3 report steps


def test_inference_profile_model_time_feeds_histogram(tiny_engine):
    engine, cfg = tiny_engine
    engine.profile_model_time()
    engine.forward({"input_ids": np.zeros((1, 8), np.int32)})
    times = engine.model_times()
    assert len(times) == 1 and times[0] > 0
    hist = engine.metrics.snapshot()["inference_forward_seconds"]
    assert hist["series"][0]["count"] >= 1    # survives the drain
