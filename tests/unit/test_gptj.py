"""GPT-J tests: HF parity (interleaved rotary, single-ln parallel residual,
bias-free attention projections), decode, training."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gptj

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_gptj(**over):
    kw = dict(vocab_size=96, n_embd=32, n_layer=2, n_head=4, n_inner=None,
              n_positions=64, rotary_dim=4, activation_function="gelu_new",
              attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    kw.update(over)
    cfg = transformers.GPTJConfig(**kw)
    with torch.no_grad():
        m = transformers.GPTJForCausalLM(cfg)
    m.eval()
    return m


def test_gptj_matches_hf():
    hf = _tiny_hf_gptj()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(0).integers(2, 96, (2, 12)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_gptj_kv_cache_decode_matches_forward():
    import jax

    cfg = gptj.GPTJConfig.tiny()
    params = gptj.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 12)).astype(np.int32)
    full = np.asarray(gptj.forward(cfg, params, ids, train=False))

    cache = gptj.init_cache(cfg, 2, 32, dtype=np.float32)
    logits, cache = gptj.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=1e-4)
    for t in range(8, 12):
        logits, cache = gptj.forward_cached(cfg, params, ids[:, t:t + 1],
                                            cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-4)


def test_gptj_trains():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gptj.build(gptj.GPTJConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    # fixed batch: random-uniform tokens start AT the ln(V) entropy floor for
    # this init (uniform logits), so fresh batches show no decrease —
    # memorizing one batch does
    batch = {"input_ids": rng.integers(
        0, 512, size=(engine.train_batch_size(), 17)).astype(np.int32)}
    losses = []
    for _ in range(10):
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
