"""Universal checkpoint: save under one mesh/ZeRO layout, load under another.

Reference: ``checkpoint/deepspeed_checkpoint.py:39`` reshapes DS checkpoints
across TP/PP/DP degrees and ``tests/unit/checkpoint/`` resumes across world
sizes via DistributedFixture.  Here orbax stores the logical arrays, so the
reshard is target-sharding-driven on load — these tests prove that claim
instead of just stating it.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


def _engine(config):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()), config=config)
    return engine


def _cfg(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    cfg.update(over)
    return cfg


def _train(engine, steps=2, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        _, m = engine.train_batch(batch)
    return m


def _full_params(engine):
    import jax

    return {k: np.asarray(v) for k, v in
            zip(_param_names(engine), jax.tree_util.tree_leaves(
                jax.device_get(engine.state["params"])))}


def _param_names(engine):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(engine.state["params"])
    return ["/".join(str(getattr(k, "key", k)) for k in p) for p, _ in flat]


@pytest.mark.parametrize("save_cfg,load_cfg", [
    # dp8/zero2 -> dp4 x tp2 / zero3
    (dict(zero_optimization={"stage": 2}),
     dict(zero_optimization={"stage": 3}, mesh={"tp": 2})),
    # dp8/zero3 -> pp2 x dp4
    (dict(zero_optimization={"stage": 3}),
     dict(mesh={"pp": 2}, train_micro_batch_size_per_gpu=2)),
    # tp2 -> plain dp8
    (dict(mesh={"tp": 2}),
     dict(zero_optimization={"stage": 1})),
])
def test_cross_mesh_reshard(tmp_path, save_cfg, load_cfg, eight_devices):
    """Params saved under one (mesh, ZeRO stage) load bit-equal under
    another; training resumes with finite loss."""
    e1 = _engine(_cfg(**save_cfg))
    _train(e1, steps=2)
    before = _full_params(e1)
    step_before = int(np.asarray(e1.state["step"]))
    e1.save_checkpoint(str(tmp_path / "ck"))

    e2 = _engine(_cfg(**load_cfg))
    e2.load_checkpoint(str(tmp_path / "ck"))
    after = _full_params(e2)
    assert set(before) == set(after)
    for k in before:
        np.testing.assert_array_equal(before[k], after[k]), k
    assert int(np.asarray(e2.state["step"])) == step_before

    m = _train(e2, steps=1, seed=5)
    assert np.isfinite(m["loss"])


def test_optimizer_state_carries_across_mesh(tmp_path, eight_devices):
    """Adam moments survive a dp8 -> dp4xtp2 reshard (not just params)."""
    import jax

    e1 = _engine(_cfg(zero_optimization={"stage": 1}))
    _train(e1, steps=3)
    mom1 = [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(e1.state["opt_state"]))]
    assert any(np.abs(m).max() > 0 for m in mom1 if m.ndim > 0)
    e1.save_checkpoint(str(tmp_path / "ck"))

    e2 = _engine(_cfg(zero_optimization={"stage": 2}, mesh={"tp": 2}))
    e2.load_checkpoint(str(tmp_path / "ck"))
    mom2 = [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(e2.state["opt_state"]))]
    assert len(mom1) == len(mom2)
    for a, b in zip(mom1, mom2):
        np.testing.assert_array_equal(a, b)


def test_zero_to_fp32_offline_extraction(tmp_path, eight_devices):
    """The offline script consolidates fp32 weights without an engine."""
    e1 = _engine(_cfg(zero_optimization={"stage": 3}))
    _train(e1, steps=1)
    expected = _full_params(e1)
    e1.save_checkpoint(str(tmp_path / "ck"))

    from deepspeed_tpu.utils.zero_to_fp32 import (
        get_fp32_state_dict_from_checkpoint, main)

    sd = get_fp32_state_dict_from_checkpoint(str(tmp_path / "ck"))
    assert set(sd) == {k.replace("/", ".") for k in expected}
    for k, v in expected.items():
        np.testing.assert_array_equal(sd[k.replace("/", ".")], v)

    out = str(tmp_path / "consolidated.npz")
    main([str(tmp_path / "ck"), out])
    with np.load(out) as z:
        assert len(z.files) == len(sd)
