"""Bounded-divergence helpers for quantized serving — the ONE definition
of "close enough" shared by ``tests/unit/test_quant_serving.py`` and the
``benchmarks/serving_bench.py --quantize`` lane.

Quantized lanes (int8 KV, w8a8 weights) cannot promise the bit-exact
greedy parity the full-precision serving stack pins: int8 rounding can
flip a near-tie argmax, and greedy decoding then cascades — every token
after the first flip may differ while still being a perfectly valid
greedy continuation of the *quantized* model.  So the contract is two
measurements, neither of which a cascade can game:

 - **token match rate**: positionwise agreement over the whole trace
   (prompt + completion, prompt always matches).  Cascades hurt it, so a
   high rate is strong evidence; thresholds are set per-trace-length.
 - **max logit RMSE**: teacher-forced — both engines score the SAME
   input, so there is no cascade.  This bounds the actual numeric
   perturbation independent of argmax luck.

Not a test module (no ``test_`` prefix) — pytest imports it from the
tests' own directory; the bench inserts ``tests/unit`` on ``sys.path``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def token_match_rate(ref: Dict[Any, np.ndarray],
                     got: Dict[Any, np.ndarray]) -> float:
    """Mean positionwise token agreement across a trace's result dicts
    (``uid -> int32 [prompt + completion]``, the ``serve()`` /
    ``generate`` output shape).  Requests average with equal weight so a
    single long cascade cannot hide behind many short exact requests."""
    if set(ref) != set(got):
        raise ValueError(f"uid sets differ: {set(ref) ^ set(got)}")
    rates = []
    for uid in ref:
        a, b = np.asarray(ref[uid]), np.asarray(got[uid])
        if a.shape != b.shape:
            raise ValueError(f"uid {uid}: shape {a.shape} vs {b.shape}")
        rates.append(float((a == b).mean()))
    return float(np.mean(rates))


def max_logit_rmse(ref_engine, quant_engine, prompts) -> float:
    """Teacher-forced logit error: both engines score the same token
    batches (one forward per prompt); returns the max over prompts of
    the per-prompt RMSE.  No generation, so quantization error is
    measured directly rather than through argmax cascades."""
    worst = 0.0
    for p in prompts:
        ids = np.asarray(p, np.int32)[None, :]
        la = np.asarray(ref_engine.forward({"input_ids": ids}),
                        np.float32)
        lb = np.asarray(quant_engine.forward({"input_ids": ids}),
                        np.float32)
        worst = max(worst, float(np.sqrt(np.mean((la - lb) ** 2))))
    return worst


def assert_bounded_divergence(ref: Dict[Any, np.ndarray],
                              got: Dict[Any, np.ndarray],
                              min_match: float,
                              label: str = "quantized lane") -> float:
    """Assert the trace-level token bound; returns the measured rate so
    callers can log it (the bench records it in the JSON)."""
    rate = token_match_rate(ref, got)
    assert rate >= min_match, (
        f"{label}: token match rate {rate:.3f} below the documented "
        f"bound {min_match}")
    return rate
