"""Collective facade tests (model: reference tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.parallel.topology import MeshTopology


@pytest.fixture
def mesh8(eight_devices):
    topo = MeshTopology(dp=4, tp=2)
    comm.set_topology(topo)
    return topo.mesh


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)


def test_all_reduce(mesh8):
    x = jnp.arange(8.0)

    def f(x):
        return comm.all_reduce(x, group="dp")

    out = _shard_map(f, mesh8, P(("dp",)), P("dp"))(x)
    # each dp shard of 2 elements summed across 4 dp ranks
    expected = np.array([0 + 2 + 4 + 6, 1 + 3 + 5 + 7], dtype=np.float32)
    np.testing.assert_allclose(np.asarray(out)[:2], expected)


def test_all_reduce_ops(mesh8):
    x = jnp.arange(4.0)

    def fmax(x):
        return comm.all_reduce(x, op=comm.ReduceOp.MAX, group="dp")

    out = _shard_map(fmax, mesh8, P("dp"), P("dp"))(x)
    assert np.asarray(out)[0] == 3.0

    def favg(x):
        return comm.all_reduce(x, op=comm.ReduceOp.AVG, group="dp")

    out = _shard_map(favg, mesh8, P("dp"), P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out)[0], 1.5)


def test_all_gather(mesh8):
    x = jnp.arange(4.0)

    def f(x):
        return comm.all_gather(x, group="dp")

    out = _shard_map(f, mesh8, P("dp"), P())(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


def test_reduce_scatter(mesh8):
    x = jnp.ones((8,))

    def f(x):
        return comm.reduce_scatter(x, group="dp")

    out = _shard_map(f, mesh8, P(), P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), 4.0 * np.ones(8))


def test_all_to_all(mesh8):
    x = jnp.arange(16.0).reshape(4, 4)

    def f(x):
        return comm.all_to_all_single(x, group="dp", split_axis=1, concat_axis=0)

    out = _shard_map(f, mesh8, P("dp", None), P("dp", None))(x)
    assert out.shape == (16, 1)


def test_ppermute(mesh8):
    x = jnp.arange(4.0)

    def f(x):
        perm = [(i, (i + 1) % 4) for i in range(4)]
        return comm.ppermute(x, "dp", perm)

    out = _shard_map(f, mesh8, P("dp"), P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.array([3, 0, 1, 2.0]))


def test_world_size_queries(mesh8):
    assert comm.get_world_size() == 8
    assert comm.get_world_size("dp") == 4
    assert comm.get_world_size("tp") == 2
    assert comm.get_world_size(("dp", "tp")) == 8
    assert comm.get_data_parallel_world_size() == 4
    assert comm.get_model_parallel_world_size() == 2


def test_host_ops():
    comm.barrier("test")
    x = {"a": np.arange(3.0)}
    out = comm.broadcast(x, src=0)
    np.testing.assert_allclose(out["a"], x["a"])
    gathered = comm.all_gather_host(np.arange(3.0))
    assert np.asarray(gathered).shape == (1, 3)
    # host all-reduce: single-process identity (multi-host sums over
    # process_allgather — the param-streaming grad-combine path)
    arrs = [np.arange(4.0), np.ones((2, 3))]
    out = comm.host_all_reduce_sum(arrs)
    for a, b in zip(out, arrs):
        np.testing.assert_allclose(a, b)


def test_comms_logger_records(mesh8):
    comm.comms_logger.enabled = True
    comm.comms_logger.prof_all = True
    x = jnp.arange(8.0)

    def f(x):
        return comm.all_reduce(x, group="dp")

    _shard_map(f, mesh8, P("dp"), P("dp"))(x)
    assert "all_reduce" in comm.comms_logger.comms_dict
    summary = comm.log_summary()
    assert summary
    comm.comms_logger.enabled = False
