"""GPT-Neo tests: HF parity (unscaled attention, alternating local/global
banded layers), decode, training."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gptneo

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_neo(**over):
    kw = dict(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
              max_position_embeddings=64, window_size=8,
              attention_types=[[["global", "local"], 1]],
              intermediate_size=None, activation_function="gelu_new",
              attention_dropout=0.0, embed_dropout=0.0, resid_dropout=0.0)
    kw.update(over)
    cfg = transformers.GPTNeoConfig(**kw)
    with torch.no_grad():
        m = transformers.GPTNeoForCausalLM(cfg)
    m.eval()
    return m


def test_gptneo_matches_hf():
    hf = _tiny_hf_neo()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    # long enough that the window (8) actually bites at position > 8
    ids = np.random.default_rng(0).integers(2, 96, (2, 24)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_gptneo_kv_cache_decode_matches_forward():
    import jax

    cfg = gptneo.GPTNeoConfig.tiny()
    params = gptneo.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 14)).astype(np.int32)
    full = np.asarray(gptneo.forward(cfg, params, ids, train=False))

    cache = gptneo.init_cache(cfg, 2, 32, dtype=np.float32)
    logits, cache = gptneo.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=1e-4)
    for t in range(8, 14):
        logits, cache = gptneo.forward_cached(cfg, params, ids[:, t:t + 1],
                                              cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-4)


def test_gptneo_trains():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gptneo.build(gptneo.GPTNeoConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(5):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 17)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
