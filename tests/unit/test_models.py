"""Model zoo tests: llama + mixtral E2E on the CPU-sim mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import get_model, gpt2, llama, mixtral


def make_batch(rng, n, seq=33, vocab=512):
    return {"input_ids": rng.integers(0, vocab, size=(n, seq)).astype(np.int32)}


def run(model, config, steps=4, seed=0):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        _, m = engine.train_batch(make_batch(rng, engine.train_batch_size()))
        losses.append(m["loss"])
    return engine, losses


def base_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    cfg.update(over)
    return cfg


def test_gpt2_fused_ce_matches_checkpointed_head():
    """fused_ce computes identical loss AND grads to the lse head,
    including -100 label masking."""
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, (4, 33)).astype(np.int32)
    labels = ids[:, 1:].copy()
    labels[0, :5] = -100
    batch = {"input_ids": ids[:, :-1], "labels": labels}

    cfg.fused_ce = False
    l_ref, g_ref = jax.value_and_grad(
        lambda p: gpt2.loss_from_batch(cfg, p, batch, train=False))(params)
    cfg2 = gpt2.GPT2Config.tiny()
    cfg2.fused_ce = True
    cfg2.ce_chunks = 4
    l_f, g_f = jax.value_and_grad(
        lambda p: gpt2.loss_from_batch(cfg2, p, batch, train=False))(params)
    np.testing.assert_allclose(float(l_ref), float(l_f), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_llama_rope_rotation_identity():
    cfg = llama.LlamaConfig.tiny()
    cos, sin = llama.rope_angles(cfg, 8)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, cfg.head_dim))
    rotated = llama.apply_rope(x, cos, sin)
    # norms preserved by rotation
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(rotated, axis=-1)),
        np.asarray(jnp.linalg.norm(x, axis=-1)), rtol=1e-5)
    # position 0 is unrotated
    np.testing.assert_allclose(np.asarray(rotated[:, :, 0]),
                               np.asarray(x[:, :, 0]), rtol=1e-6)


def overfit(model, config, steps=6, seed=0):
    """Train repeatedly on ONE fixed batch — loss must drop well below the
    uniform-token entropy floor (ln V), which fresh random batches can't."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    batch = make_batch(np.random.default_rng(seed), engine.train_batch_size())
    losses = []
    for _ in range(steps):
        _, m = engine.train_batch(batch)
        losses.append(m["loss"])
    return engine, losses


def test_llama_trains():
    _, losses = overfit(llama.build(llama.LlamaConfig.tiny()), base_config(),
                        steps=8)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.05, f"no overfit progress: {losses}"


def test_llama_zero3_tp():
    _, base = run(llama.build(llama.LlamaConfig.tiny()),
                  base_config(train_batch_size=8,
                              train_micro_batch_size_per_gpu=None))
    _, z3 = run(llama.build(llama.LlamaConfig.tiny()),
                base_config(train_batch_size=8,
                            train_micro_batch_size_per_gpu=None,
                            zero_optimization={"stage": 3}, mesh={"tp": 2}))
    np.testing.assert_allclose(base, z3, rtol=3e-4, atol=1e-4)


def test_llama_pipeline():
    _, base = run(llama.build(llama.LlamaConfig.tiny()),
                  base_config(train_batch_size=16,
                              train_micro_batch_size_per_gpu=None,
                              gradient_accumulation_steps=2))
    _, pp = run(llama.build(llama.LlamaConfig.tiny()),
                base_config(train_batch_size=16,
                            train_micro_batch_size_per_gpu=None,
                            gradient_accumulation_steps=2, mesh={"pp": 2}))
    np.testing.assert_allclose(base, pp, rtol=3e-4, atol=1e-4)


def test_mixtral_trains_with_ep():
    cfg = base_config(train_batch_size=8, train_micro_batch_size_per_gpu=None,
                      zero_optimization={"stage": 2}, mesh={"ep": 4})
    _, losses = overfit(mixtral.build(mixtral.MixtralConfig.tiny()), cfg,
                        steps=8)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.05, f"no overfit progress: {losses}"


def test_mixtral_experts_sharded(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=mixtral.build(mixtral.MixtralConfig.tiny()),
        config=base_config(mesh={"ep": 4}))
    w1 = engine.state["params"]["blocks"]["experts_w1"]  # [L, E, d, f]
    assert w1.addressable_shards[0].data.shape[1] == 1  # 4 experts / ep=4


def test_mixtral_matches_hf():
    """HF MixtralForCausalLM ingestion: drop-free eval routing must
    reproduce HF's top-2 expert mixing (policy sets eval_capacity_factor
    = num_experts)."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rope_theta=10000.0,
        attention_dropout=0.0)
    with torch.no_grad():
        hf = transformers.MixtralForCausalLM(cfg)
    hf.eval()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(0).integers(2, 96, (2, 12)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-3, rtol=5e-3)


def test_get_model_registry():
    assert get_model("gpt2", **{"vocab_size": 128, "max_seq_len": 32,
                                "num_layers": 1, "num_heads": 2,
                                "hidden_size": 32}) is not None
    with pytest.raises(ValueError):
        get_model("nonexistent-model")


def test_llama_mixtral_bf16_keeps_activation_dtype():
    """bf16 compute must stay bf16 through rope/MoE (scan carries need a
    fixed dtype; fp32 promotion also silently halves MXU throughput)."""
    for mod, cfg in ((llama, llama.LlamaConfig.tiny()),
                     (mixtral, mixtral.MixtralConfig.tiny())):
        params = mod.init_params(cfg, jax.random.PRNGKey(0))
        params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        ids = jnp.zeros((1, 9), jnp.int32)
        loss = mod.loss_from_batch(cfg, params, {"input_ids": ids})
        assert np.isfinite(float(loss)), mod.__name__
        # Direct dtype check: logits must come out bf16, not fp32-promoted.
        if mod is llama:
            logits = mod.forward(cfg, params, ids)
        else:
            logits = mod.forward_with_aux(cfg, params, ids)[0]
        assert logits.dtype == jnp.bfloat16, (mod.__name__, logits.dtype)


def test_llama_mixtral_bf16_train(eight_devices):
    for model in (llama.build(llama.LlamaConfig.tiny()),
                  mixtral.build(mixtral.MixtralConfig.tiny())):
        _, losses = run(model, base_config(bf16={"enabled": True},
                                           zero_optimization={"stage": 2}),
                        steps=3)
        assert np.isfinite(losses).all()
