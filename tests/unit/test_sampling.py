"""On-device sampling primitives (``ops/sampling.py``) and the
distribution-exact rejection verifier's accept walker (``spec.
rejection_accept``) — the PR 20 unit layer under the serving tests in
``test_sampled_serving.py``.

Covers: the temperature=0 exact-one-hot contract (greedy is the zero row
of the SAME filtered-logprobs program), top-k/top-p filtering on known
distributions (ties-in kth threshold, nucleus boundary), logit-mask
application, the counter-based PRNG key schedule (pure function of
(seed, emission position, salt) — the crash re-homing determinism
contract), empirical total-variation checks of the categorical draws,
and the delta-form rejection identity: accept the proposed token with
probability ``p_target(d)``, else draw from the renormalized residual —
marginal EXACTLY ``p_target`` for ANY proposer, no draft probabilities
needed.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.spec import rejection_accept
from deepspeed_tpu.ops import sampling as S


def _np(x):
    return np.asarray(x)


# ------------------------------------------------------ filtered_logprobs
def test_temp0_rows_are_exact_onehot():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(5, 17)).astype(np.float32))
    temps = jnp.zeros(5, jnp.float32)
    greedy, lp = S.filtered_logprobs(logits, temps,
                                     jnp.zeros(5, jnp.int32),
                                     jnp.ones(5, jnp.float32))
    np.testing.assert_array_equal(_np(greedy), _np(logits).argmax(-1))
    lp = _np(lp)
    for i, g in enumerate(_np(greedy)):
        assert lp[i, g] == 0.0                       # exact, not approx
        row = np.delete(lp[i], g)
        assert np.all(np.isneginf(row))


def test_topk_threshold_keeps_ties():
    logits = jnp.asarray([[4.0, 3.0, 3.0, 1.0, 0.0]])
    temps = jnp.ones(1, jnp.float32)
    _, lp = S.filtered_logprobs(logits, temps, jnp.asarray([2]),
                                jnp.ones(1, jnp.float32))
    lp = _np(lp)[0]
    # kth-largest (k=2) is 3.0; BOTH ties at the threshold stay in
    assert np.isfinite(lp[[0, 1, 2]]).all()
    assert np.isneginf(lp[[3, 4]]).all()
    # kept mass renormalizes to 1
    assert np.isclose(np.exp(lp[np.isfinite(lp)]).sum(), 1.0, atol=1e-6)


def test_topp_nucleus_boundary():
    probs = np.array([0.5, 0.3, 0.15, 0.05], np.float32)
    logits = jnp.asarray(np.log(probs)[None, :])
    temps = jnp.ones(1, jnp.float32)
    for p, want in ((0.7, [0, 1]), (0.85, [0, 1, 2]), (1.0, [0, 1, 2, 3])):
        _, lp = S.filtered_logprobs(logits, temps, jnp.zeros(1, jnp.int32),
                                    jnp.asarray([p], jnp.float32))
        kept = np.flatnonzero(np.isfinite(_np(lp)[0]))
        assert kept.tolist() == want, (p, kept)


def test_mask_applies_before_filtering_and_empty_row_is_inert():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 9)).astype(np.float32))
    masks = np.zeros((2, 9), bool)
    masks[0, [2, 5]] = True                 # row 0: constrained to {2, 5}
    # row 1 all-False = the unconstrained-slot sentinel: treated unmasked
    temps = jnp.zeros(2, jnp.float32)
    greedy, lp = S.filtered_logprobs(logits, temps,
                                     jnp.zeros(2, jnp.int32),
                                     jnp.ones(2, jnp.float32),
                                     jnp.asarray(masks))
    assert int(greedy[0]) in (2, 5)
    assert int(greedy[0]) == (2 if logits[0, 2] >= logits[0, 5] else 5)
    assert int(greedy[1]) == int(_np(logits)[1].argmax())
    lp0 = _np(lp)[0]
    assert np.isneginf(np.delete(lp0, [int(greedy[0])])).all()


# -------------------------------------------------------- key schedule
def test_keys_are_pure_functions_of_seed_count_salt():
    seeds = jnp.asarray([7, 7, 9], jnp.uint32)
    counts = jnp.asarray([0, 3, 3], jnp.int32)
    a = _np(S.slot_keys(seeds, counts, S.SALT_TOKEN))
    b = _np(S.slot_keys(seeds, counts, S.SALT_TOKEN))
    np.testing.assert_array_equal(a, b)             # pure
    assert not np.array_equal(a[0], a[1])           # count matters
    assert not np.array_equal(a[1], a[2])           # seed matters
    c = _np(S.slot_keys(seeds, counts, S.SALT_RESIDUAL))
    assert not np.array_equal(a, c)                 # salt streams disjoint
    # grid keys ARE slot keys at offset emission counts — the fused
    # while_loop and a step-at-a-time replay draw identical streams
    g = _np(S.grid_keys(seeds, counts, S.SALT_TOKEN, 4))
    for i in range(4):
        np.testing.assert_array_equal(
            g[:, i], _np(S.slot_keys(seeds, counts + i, S.SALT_TOKEN)))


def _tv(counts, probs):
    freq = counts / counts.sum()
    return 0.5 * np.abs(freq - probs).sum()


def test_categorical_draws_match_distribution():
    probs = np.array([0.45, 0.25, 0.15, 0.1, 0.05], np.float32)
    n = 4000
    lp = jnp.asarray(np.tile(np.log(probs), (n, 1)))
    keys = S.slot_keys(jnp.full(n, 7, jnp.uint32),
                       jnp.arange(n, dtype=jnp.int32), S.SALT_TOKEN)
    draws = _np(S.sample_tokens(lp, keys))
    counts = np.bincount(draws, minlength=5).astype(float)
    assert _tv(counts, probs) < 0.05, counts


def test_delta_rejection_marginal_is_target_distribution():
    """The verifier identity, adversarial case: a proposer that ALWAYS
    proposes the same token.  accept w.p. p_target(d); reject -> draw
    from the d-zeroed renormalized residual.  The marginal must still be
    exactly p_target (here: empirically, TV < 0.05 at n=4000)."""
    probs = np.array([0.4, 0.3, 0.2, 0.1], np.float32)
    d = 3                                   # propose the LEAST likely token
    n = 4000
    lp = jnp.asarray(np.tile(np.log(probs), (n, 1)))
    drafts = jnp.full((n,), d, jnp.int32)
    seeds = jnp.full(n, 11, jnp.uint32)
    counts = jnp.arange(n, dtype=jnp.int32)
    u = _np(S.accept_uniforms(S.slot_keys(seeds, counts, S.SALT_ACCEPT)))
    p_d = _np(S.token_probs(lp, drafts))
    accept = u < p_d
    resid_lp = S.residual_logits(lp, drafts)
    rkeys = S.slot_keys(seeds, counts, S.SALT_RESIDUAL)
    resid_draw = _np(S.sample_tokens(resid_lp, rkeys))
    final = np.where(accept, d, resid_draw)
    # rejected rows never re-emit the proposed token
    assert not np.any(resid_draw[~accept] == d)
    counts_f = np.bincount(final, minlength=4).astype(float)
    assert _tv(counts_f, probs) < 0.05, counts_f
    # acceptance rate ~ p_target(d)
    assert abs(accept.mean() - probs[d]) < 0.03


def test_residual_logits_masks_draft_and_dead_row_falls_back():
    # normal row: the rejected draft goes to -inf, survivors untouched
    lp = jnp.asarray(np.log(np.array([[0.5, 0.3, 0.2]], np.float32)))
    out = _np(S.residual_logits(lp, jnp.asarray([1])))
    assert np.isneginf(out[0, 1])
    np.testing.assert_allclose(out[0, [0, 2]], _np(lp)[0, [0, 2]])
    # one-hot row whose only token IS the draft: nothing survives, so
    # the helper emits the argmax one-hot instead of an all--inf row
    # (the lane is unreachable — the accept prob was exactly 1 — but it
    # must stay NaN-free inside the traced program)
    onehot = jnp.asarray([[0.0, -np.inf, -np.inf]], jnp.float32)
    out = _np(S.residual_logits(onehot, jnp.asarray([0])))
    assert out[0, 0] == 0.0 and np.isneginf(out[0, 1:]).all()


# ------------------------------------------------------ rejection_accept
def test_rejection_accept_walker_prefix_and_rejection_stop():
    # window [pending, d1..d3]; drafts 1..2 accepted, d3 rejected
    window = [10, 11, 12, 13]
    accept = [True, True, False]
    plain = [21, 22, 23, 24]
    resid = [31, 32, 33]
    emitted, accepted, finished = rejection_accept(
        window, accept, plain, resid, 3, None, 100)
    # 2 accepted drafts + the RESIDUAL draw at the rejection position
    assert emitted == [11, 12, 33] and accepted == 2 and not finished


def test_rejection_accept_all_accepted_gets_bonus_and_cap():
    window = [1, 2, 3, 4]
    plain = [9, 9, 55, 77]
    resid = [41, 42, 43]
    emitted, accepted, _ = rejection_accept(
        window, [True, True, True], plain, resid, 3, None, 100)
    assert emitted == [2, 3, 4, 77] and accepted == 3   # bonus plain draw
    # draft-model cap K-1: position K's plain draw replaces the K-th
    # draft (its KV was never written in the draft cache)
    emitted, accepted, _ = rejection_accept(
        window, [True, True, True], plain, resid, 2, None, 100)
    assert emitted == [2, 3, 55] and accepted == 2


def test_rejection_accept_cap_stop_ignores_unconsumed_verdict():
    """REGRESSION: a walk stopped by the accept cap (draft-model K-1,
    constrained 0) must emit the unconditional PLAIN target draw even
    when the verdict at the stop position happens to be False — that
    verdict was never consumed, and conditioning on it (the old
    device-side ``where(accept, plain, resid)`` blend) yields marginal
    ``p(x)(1 + q)`` / ``q^2`` instead of the target distribution."""
    window = [1, 2, 3, 4]
    plain = [50, 51, 52, 53]
    resid = [60, 61, 62]
    # draft-model cap 2: accept[2] is False but the walk stopped at the
    # cap, not on the verdict -> plain[2], never resid[2]
    emitted, accepted, _ = rejection_accept(
        window, [True, True, False], plain, resid, 2, None, 100)
    assert emitted == [2, 3, 52] and accepted == 2
    # constrained cap 0: every round is a cap stop at position 0
    emitted, accepted, _ = rejection_accept(
        window, [False, False, False], plain, resid, 0, None, 100)
    assert emitted == [50] and accepted == 0


def test_rejection_accept_eos_and_budget_truncate():
    window = [1, 7, 8, 9]
    accept = [True, True, True]
    plain = [0, 0, 0, 5]
    resid = [1, 1, 1]
    emitted, accepted, finished = rejection_accept(
        window, accept, plain, resid, 3, 8, 100)
    assert emitted == [7, 8] and finished           # truncated AT eos
    emitted, accepted, finished = rejection_accept(
        window, accept, plain, resid, 3, None, 2)
    assert emitted == [7, 8] and finished           # budget
    with pytest.raises(ValueError):
        rejection_accept(window, accept, plain, resid, 3, None, 0)
    with pytest.raises(ValueError):
        rejection_accept(window, accept, plain[:-1], resid, 3, None, 4)
    with pytest.raises(ValueError):
        rejection_accept(window, accept, plain, resid[:-1], 3, None, 4)
    with pytest.raises(ValueError):
        rejection_accept(window, accept[:-1], plain, resid, 3, None, 4)


def test_rejection_accept_immediate_reject_still_progresses():
    emitted, accepted, finished = rejection_accept(
        [5, 1, 2], [False, False], [40, 41, 42], [45, 46], 2, None, 100)
    assert emitted == [45] and accepted == 0 and not finished
