"""ZeRO-Offload / ZeRO-Infinity: host CPU optimizer + NVMe moment swap.

Model: reference tests/unit/ops/adam/test_cpu_adam.py (CPU Adam vs torch
AdamW), tests/unit/ops/aio/test_aio.py (NVMe roundtrip), and the zero-offload
configs of tests/unit/runtime/zero/test_zero.py (offload loss parity).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops.aio import (AsyncIOHandle, swap_chain_read,
                                   swap_chain_write)
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer


# --------------------------------------------------------------- cpu adam op
def _ref_adamw(p, g, m, v, t, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, wd=0.01):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mh = m / (1 - b1**t)
    vh = v / (1 - b2**t)
    p = p * (1 - lr * wd) - lr * mh / (np.sqrt(vh) + eps)
    return p, m, v


def test_cpu_adam_matches_reference_math():
    rng = np.random.default_rng(0)
    n = 4097  # odd size exercises SIMD tails
    p = rng.normal(size=n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    p_ref, m_ref, v_ref = p.copy().astype(np.float64), m.copy(), v.copy()
    opt = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01)
    for t in range(1, 4):
        g = rng.normal(size=n).astype(np.float32)
        opt.step(p, g, m, v)
        p_ref, m_ref, v_ref = _ref_adamw(p_ref, g, m_ref, v_ref, t)
    np.testing.assert_allclose(p, p_ref, rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------- aio op
def test_aio_roundtrip(tmp_path):
    h = AsyncIOHandle(num_threads=4)
    rng = np.random.default_rng(1)
    bufs = [rng.normal(size=1000 + i).astype(np.float32) for i in range(8)]
    path = str(tmp_path / "swap.bin")
    off = 0
    offsets = []
    for b in bufs:
        h.async_pwrite(b, path, off)
        offsets.append(off)
        off += b.nbytes
    assert h.wait() == 0
    outs = [np.empty_like(b) for b in bufs]
    for o, start in zip(outs, offsets):
        h.async_pread(o, path, start)
    assert h.wait() == 0
    for b, o in zip(bufs, outs):
        np.testing.assert_array_equal(b, o)
    h.close()


def test_aio_backend_reports_and_saturates(tmp_path):
    """On this kernel the native lib should pick the io_uring engine; a
    burst larger than the ring (256 entries) must reap-and-refill without
    loss (exercises the SQ-full path)."""
    h = AsyncIOHandle(num_threads=4)
    assert h.backend in ("io_uring", "threads", "python")
    rng = np.random.default_rng(2)
    data = rng.integers(0, 255, size=(400, 257), dtype=np.uint8)
    path = str(tmp_path / "burst.bin")
    for i in range(400):
        h.async_pwrite(np.ascontiguousarray(data[i]), path, i * 257)
    assert h.wait() == 0
    outs = np.zeros_like(data)
    views = [np.zeros(257, np.uint8) for _ in range(400)]
    for i in range(400):
        h.async_pread(views[i], path, i * 257)
    assert h.wait() == 0
    for i in range(400):
        outs[i] = views[i]
    np.testing.assert_array_equal(outs, data)
    h.close()


def test_aio_read_missing_file_reports_failure(tmp_path):
    h = AsyncIOHandle(num_threads=2)
    buf = np.zeros(16, np.float32)
    h.async_pread(buf, str(tmp_path / "nope.bin"), 0)
    assert h.wait() == 1
    h.close()


def test_aio_wait_statuses_surfaces_the_failed_op(tmp_path):
    """Per-op contract behind the NVMe tier's recompute fallback: a batch
    mixing a good read with a missing-file read must mark the bad ticket
    False.  The python fallback attributes exactly; the native library
    only reports an aggregate count, so there any failure conservatively
    fails the whole batch — either way the bad op is never trusted."""
    path = str(tmp_path / "ok.bin")
    payload = np.arange(64, dtype=np.float32)
    h = AsyncIOHandle(num_threads=2)
    h.async_pwrite(payload, path, 0)
    assert h.wait() == 0
    good_buf = np.zeros_like(payload)
    good = h.async_pread(good_buf, path, 0)
    bad = h.async_pread(np.zeros(16, np.float32),
                        str(tmp_path / "nope.bin"), 0)
    st = h.wait_statuses()
    assert set(st) == {good, bad}
    assert st[bad] is False
    if not h.has_native:                 # python fallback: exact per-op
        assert st[good] is True
        np.testing.assert_array_equal(good_buf, payload)
    h.close()


def test_aio_wait_statuses_python_fallback_short_read(tmp_path,
                                                      monkeypatch):
    """Force the python fallback (no native lib) and check a short read —
    a truncated spill file — fails EXACTLY the op that ran off the end,
    and the chain helpers align per-block status to input order."""
    from deepspeed_tpu.ops import aio as aio_mod

    monkeypatch.setattr(aio_mod.AsyncIOBuilder, "bind",
                        classmethod(lambda cls: None))
    h = aio_mod.AsyncIOHandle()
    assert h.backend == "python"
    path = str(tmp_path / "chain.bin")
    blocks = [np.full(32, i, np.float32) for i in range(2)]
    assert swap_chain_write(h, path, blocks, [0, 128]) == [True, True]
    outs = [np.zeros(32, np.float32) for _ in range(3)]
    # third read starts past EOF -> short read -> that op alone fails
    ok = swap_chain_read(h, path, outs, [0, 128, 256])
    assert ok == [True, True, False]
    np.testing.assert_array_equal(outs[0], blocks[0])
    np.testing.assert_array_equal(outs[1], blocks[1])
    h.close()


# ------------------------------------------------------ host offload optimizer
@pytest.mark.parametrize("device", ["cpu", "nvme"])
def test_host_offload_matches_cpu_adam(device, tmp_path):
    rng = np.random.default_rng(2)
    leaves = [rng.normal(size=s).astype(np.float32)
              for s in [(7, 13), (91,), (3, 4, 5)]]
    flat_ref = np.concatenate([l.reshape(-1) for l in leaves])
    m_ref = np.zeros_like(flat_ref)
    v_ref = np.zeros_like(flat_ref)
    ref_opt = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.0)

    opt = HostOffloadOptimizer(
        leaves, "adam", {"lr": 1e-2}, device=device,
        nvme_path=str(tmp_path), sub_group_size=64)  # forces multi-group swap
    try:
        for _ in range(3):
            grads = [rng.normal(size=l.shape).astype(np.float32)
                     for l in leaves]
            new_leaves = opt.step(grads)
            flat_g = np.concatenate([g.reshape(-1) for g in grads])
            ref_opt.step(flat_ref, flat_g, m_ref, v_ref)
        got = np.concatenate([l.reshape(-1) for l in new_leaves])
        np.testing.assert_allclose(got, flat_ref, rtol=1e-6, atol=1e-7)
    finally:
        opt.close()


def test_host_offload_state_dict_roundtrip(tmp_path):
    rng = np.random.default_rng(3)
    leaves = [rng.normal(size=(17,)).astype(np.float32)]
    opt = HostOffloadOptimizer(leaves, "adam", {"lr": 1e-2}, device="nvme",
                               nvme_path=str(tmp_path), sub_group_size=8)
    opt.step([rng.normal(size=(17,)).astype(np.float32)])
    sd = opt.state_dict()
    opt2 = HostOffloadOptimizer(leaves, "adam", {"lr": 1e-2}, device="cpu")
    opt2.load_state_dict(sd)
    g = rng.normal(size=(17,)).astype(np.float32)
    a = opt.step([g])[0].copy()
    b = opt2.step([g])[0].copy()
    np.testing.assert_allclose(a, b, rtol=1e-6)
    opt.close()
    opt2.close()


# --------------------------------------------------------------- engine E2E
def _run(config, steps=4, seed=0):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()), config=config)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(m["loss"])
    return engine, losses


def _cfg(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {},
    }
    cfg.update(over)
    return cfg


@pytest.mark.parametrize("device", ["cpu", "nvme"])
def test_engine_offload_matches_baseline(device, tmp_path):
    _, base = _run(_cfg(zero_optimization={"stage": 2}))
    _, off = _run(_cfg(zero_optimization={
        "stage": 2,
        "offload_optimizer": {"device": device,
                              "nvme_path": str(tmp_path)},
        "sub_group_size": 4096,
    }))
    np.testing.assert_allclose(base, off, rtol=2e-4, atol=1e-5)


def test_engine_offload_checkpoint_roundtrip(tmp_path):
    cfg = _cfg(zero_optimization={
        "stage": 2, "offload_optimizer": {"device": "cpu"}})
    engine, _ = _run(cfg, steps=2)
    engine.save_checkpoint(str(tmp_path / "ck"))
    m_before = engine._offload_opt.state_dict()["exp_avg"].copy()

    engine2, _ = _run(cfg, steps=1, seed=7)
    engine2.load_checkpoint(str(tmp_path / "ck"))
    m_after = engine2._offload_opt.state_dict()["exp_avg"]
    np.testing.assert_allclose(m_before, m_after, rtol=1e-6)
    # the host master is in the ZeRO-partition (grad sharding) piece layout;
    # compare against the restored params viewed the same way
    partitioned = engine2.to_grad_layout(engine2.state["params"])
    expected = np.concatenate([
        np.asarray(p, np.float32).reshape(-1)
        for p in engine2._offload_pieces_of(partitioned)])
    np.testing.assert_allclose(engine2._offload_opt.master, expected,
                               rtol=1e-6)
    # training continues
    rng = np.random.default_rng(9)
    batch = {"input_ids": rng.integers(
        0, 512, size=(engine2.train_batch_size(), 33)).astype(np.int32)}
    _, m = engine2.train_batch(batch)
    assert np.isfinite(m["loss"])
