"""Accelerator seam tests (reference ``tests/unit/accelerator``)."""

import numpy as np

from deepspeed_tpu.accelerator import get_accelerator, set_accelerator


def test_get_accelerator_singleton_and_api(eight_devices):
    a = get_accelerator()
    assert a is get_accelerator()
    assert a.is_available()
    assert a.device_count() == 8
    assert "cpu" in a.device_name().lower() or "tpu" in a.device_name().lower()
    assert a.communication_backend_name() == "xla"
    a.synchronize()
    key = a.manual_seed(7)
    assert np.asarray(key).shape[-1] == 2 or np.asarray(key).dtype is not None


def test_op_builder_dispatch():
    a = get_accelerator()
    builders = a.op_builder_dict()
    assert "cpu_adam" in builders and "aio" in builders
    assert a.get_op_builder("cpu_adam") is builders["cpu_adam"]
    assert a.get_op_builder("does_not_exist") is None


def test_set_accelerator_override():
    class Fake:
        def device_count(self):
            return 3

    orig = get_accelerator()
    try:
        set_accelerator(Fake())
        assert get_accelerator().device_count() == 3
    finally:
        set_accelerator(orig)
