"""Block-sparse attention tests (reference
``tests/unit/ops/sparse_attention/test_sparse_attention.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                LocalSlidingWindowSparsityConfig,
                                                SparseSelfAttention,
                                                VariableSparsityConfig,
                                                sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    sparse_attention_reference)

pytestmark = pytest.mark.slow  # Pallas interpret mode: minutes on CPU


# ----------------------------------------------------------------- layouts
def test_fixed_layout_structure():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                              num_global_blocks=1, attention="unidirectional")
    lo = cfg.make_layout(16 * 16)
    assert lo.shape == (2, 16, 16)
    # causal: nothing above the diagonal
    assert np.triu(lo[0], 1).sum() == 0
    # every row attends its own block (diagonal set)
    assert np.diag(lo[0]).all()
    # local window: q-block 1 sees block 0 (same window)
    assert lo[0, 1, 0] == 1
    # global summary: block-col 3 (window tail) visible to later windows
    assert lo[0, 8, 3] == 1
    # but a non-summary far block is not
    assert lo[0, 8, 1] == 0


def test_bigbird_layout_structure():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    lo = cfg.make_layout(16 * 12)
    assert lo[0, 5, 4] and lo[0, 5, 5] and lo[0, 5, 6]   # window
    assert lo[0, :, 0].all() and lo[0, 0, :].all()        # global
    density = lo.mean()
    assert 0.1 < density < 0.8                            # actually sparse


def test_longformer_layout_structure():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=5,
                                     global_block_indices=[0, 7])
    lo = cfg.make_layout(16 * 12)
    assert lo[0, :, 7].all() and lo[0, 7, :].all()
    assert lo[0, 10, 8]          # inside the window
    assert lo[0, 10, 1] == 0     # outside window, not global


def test_variable_and_sliding_layouts():
    v = VariableSparsityConfig(num_heads=1, block=16,
                               local_window_blocks=[2, 4],
                               global_block_indices=[0])
    lo = v.make_layout(16 * 8)
    assert lo[0, 1, 0] and lo[0, 1, 1]        # first window size 2
    assert lo[0, 4, 2] and lo[0, 4, 5]        # second window size 4
    s = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                         num_sliding_window_blocks=3)
    lo = s.make_layout(16 * 6)
    assert lo[0, 3, 2] and lo[0, 3, 3] and not lo[0, 3, 4]  # causal window
    assert not lo[0, 3, 0]

    d = DenseSparsityConfig(num_heads=1, block=16)
    assert d.make_layout(64).all()


def test_different_layout_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                              num_global_blocks=1,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    lo = cfg.make_layout(16 * 8)
    assert not np.array_equal(lo[0], lo[1])  # heads differ
    same = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4)
    lo2 = same.make_layout(16 * 8)
    assert np.array_equal(lo2[0], lo2[3])    # propagated


# ------------------------------------------------------------------ kernel
def _qkv(b=1, h=2, s=64, d=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return [jax.random.normal(k, (b, h, s, d), dtype) for k in ks]


def test_sparse_kernel_matches_dense_reference_bidirectional():
    q, k, v = _qkv()
    cfg = BigBirdSparsityConfig(num_heads=2, block=16,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1, num_random_blocks=0)
    layout = cfg.make_layout(64)
    got = np.asarray(sparse_attention(q, k, v, layout, block=16))
    want = np.asarray(sparse_attention_reference(q, k, v, layout, 16))
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_sparse_kernel_matches_dense_reference_causal():
    q, k, v = _qkv(s=64)
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1,
                              attention="unidirectional")
    layout = cfg.make_layout(64)
    got = np.asarray(sparse_attention(q, k, v, layout, block=16, causal=True))
    want = np.asarray(sparse_attention_reference(q, k, v, layout, 16,
                                                 causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_sparse_kernel_gradients_match():
    q, k, v = _qkv(s=48, h=1)
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=3)
    layout = cfg.make_layout(48)

    def loss_kernel(q, k, v):
        return (sparse_attention(q, k, v, layout, 16, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (sparse_attention_reference(q, k, v, layout, 16,
                                           causal=True) ** 2).sum()

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_sparse_self_attention_module():
    q, k, v = _qkv(s=64)
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              attention="unidirectional")
    attn = SparseSelfAttention(cfg)
    out = attn(q, k, v)
    assert out.shape == q.shape
    # layout cached per seq len
    assert 64 in attn._layouts


def test_sparsity_saves_compute_vs_dense():
    """Density of gated blocks < 1 (the compute-skip claim is structural)."""
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=3)
    lo = cfg.make_layout(16 * 32)
    causal_blocks = 32 * 33 / 2
    assert lo.sum() < 0.2 * causal_blocks


def test_sparse_kernel_gqa_gradients_match():
    """GQA x sparse layout (round-3: the dkv kernel's layout map now
    follows the Q head through the rep grid — formerly asserted out)."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    b, h, hkv, s, d = 1, 4, 2, 48, 16
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hkv, s, d))
    v = jax.random.normal(ks[2], (b, hkv, s, d))
    cfg = LocalSlidingWindowSparsityConfig(num_heads=h, block=16,
                                           num_sliding_window_blocks=3)
    layout = cfg.make_layout(s)

    def loss_kernel(q, k, v):
        return (sparse_attention(q, k, v, layout, 16, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (sparse_attention_reference(q, k, v, layout, 16,
                                           causal=True) ** 2).sum()

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gk[1].shape == (b, hkv, s, d)
    for a, r, name in zip(gk, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=3e-4,
                                   err_msg=f"d{name} mismatch")
