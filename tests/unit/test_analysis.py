"""Correctness tooling (``deepspeed_tpu/analysis/``): lint rule fixtures,
recompile-sentry budgets, and paged-state fault injection.

Tier-1 (fast) coverage:
 - ``graft-lint`` rule fixtures: per rule, one minimal snippet that MUST
   fire and a near-miss that must NOT, plus pragma suppression and the
   zero-findings gate over the real package (the same check CI's ``lint``
   job runs).
 - ``RecompileSentry``: a deliberately shape-unstable callable trips its
   budget with an abstract-signature diff; the serving engine's chunked
   and speculative traces do NOT (replacing the old after-the-fact
   ``_cache_size`` probes).
 - ``audit_paged_state`` fault injection: seeded corruption of allocator/
   trie/table state (leaked refcount, double-free, trie/table divergence,
   scratch aliasing) raises :class:`PagedStateError` naming the violated
   invariant; a clean mid-trace engine audits green.
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis import invariants, lint, sentry
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_paged_state)
from deepspeed_tpu.analysis.sentry import RecompileSentry, RetraceError
from deepspeed_tpu.inference.paged import BlockAllocator, PrefixCache
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2

REPO = Path(__file__).resolve().parents[2]


# ------------------------------------------------------------------- lint
def _codes(src):
    return [f.code for f in lint.check_source(src)]


def test_gl001_host_sync_fires_and_near_miss():
    fires = """
import jax, jax.numpy as jnp, numpy as np

def step(x, cache):
    v = x.item()
    f = float(x)
    a = np.asarray(x)
    return v + f + a

jax.jit(step, donate_argnums=(1,))
"""
    codes = _codes(fires)
    assert codes.count("GL001") == 3, codes
    near_miss = """
import jax, jax.numpy as jnp, numpy as np

def step(x, cache):
    n = int(x.shape[0])          # static: shapes are concrete under trace
    y = jnp.asarray(x) * n       # jnp, not np
    return y

def host(x):
    return float(x)              # not a jit body

jax.jit(step, donate_argnums=(1,))
"""
    assert "GL001" not in _codes(near_miss)


def test_gl002_stringify_and_closure_shape_fire_and_near_miss():
    fires = """
import jax

def build(example):
    def step(x):
        msg = f"got {x.shape} / {x}"         # traced shape+value f-string
        n = example.shape[0]                 # baked closure shape
        return x.reshape(n, -1)
    return jax.jit(step)
"""
    codes = _codes(fires)
    assert codes.count("GL002") >= 2, codes
    near_miss = """
import jax

def build(width):
    def step(x):
        n = x.shape[0]                       # own traced arg: static
        return x.reshape(n, width)
    return jax.jit(step)

def host(example):
    print(f"shape {example.shape}")          # not a jit body
"""
    assert "GL002" not in _codes(near_miss)


def test_gl003_missing_donation_fires_and_near_miss():
    fires = """
import jax

def step(tokens, cache):
    return cache

fn = jax.jit(step)
"""
    assert _codes(fires) == ["GL003"]
    near_miss = """
import jax

def step(tokens, cache):
    return cache

def pure(tokens, weights):
    return tokens

a = jax.jit(step, donate_argnums=(1,))
b = jax.jit(step, donate_argnums=())     # explicit decision counts
c = jax.jit(pure)                        # nothing pool-like
"""
    assert "GL003" not in _codes(near_miss)


def test_gl004_axis_literal_fires_and_near_miss():
    fires = """
import jax
from jax.sharding import PartitionSpec as P

def f(x):
    y = jax.lax.psum(x, "tensor")
    spec = P(None, "modle")
    return y, spec
"""
    codes = _codes(fires)
    assert codes.count("GL004") == 2, codes
    near_miss = """
import jax
from jax.sharding import PartitionSpec as P

def f(x, axis_name):
    y = jax.lax.psum(x, "tp")
    z = jax.lax.pmean(x, axis_name)      # variable axis: host decides
    spec = P(None, ("dp", "ep"))
    return y, z, spec
"""
    assert "GL004" not in _codes(near_miss)
    # axis_index takes the name as its SOLE positional argument
    assert _codes("import jax\njax.lax.axis_index('tpx')\n") == ["GL004"]
    assert _codes("import jax\njax.lax.axis_index('dp')\n") == []


def test_gl005_traced_branch_fires_and_near_miss():
    fires = """
import jax

def step(x, y):
    if x == y:
        return x
    return y

jax.jit(step)
"""
    assert _codes(fires) == ["GL005"]
    near_miss = """
import jax, jax.numpy as jnp

def step(x, valid):
    if valid is None:                    # static None check
        valid = jnp.ones_like(x)
    k = 4
    if k > 2:                            # host ints
        x = x * 2
    return jnp.where(x == valid, x, 0)   # expression, not a branch

jax.jit(step)
"""
    assert "GL005" not in _codes(near_miss)
    # traced truthiness hides inside BoolOp / `not` too
    boolop = """
import jax

def step(mask, flag):
    if mask and flag:
        return mask
    while not mask:
        break
    return flag

jax.jit(step)
"""
    assert _codes(boolop).count("GL005") == 2
    static = """
import jax

def step(x):
    if x.shape and len(x.shape) > 1:     # static under trace
        return x
    return x

jax.jit(step)
"""
    assert "GL005" not in _codes(static)


def test_gl006_host_timer_fires_and_near_miss():
    fires = """
import jax, time
from time import perf_counter

def step(x, cache):
    t0 = time.perf_counter()             # trace-time stamp, not device
    t1 = perf_counter()                  # from-import spelling
    t2 = time.time()
    return cache

jax.jit(step, donate_argnums=(1,))
"""
    codes = _codes(fires)
    assert codes.count("GL006") == 3, codes
    near_miss = """
import jax, time

def step(x, cache):
    return cache

def host(x, cache):
    t0 = time.perf_counter()             # host code AROUND the jit call
    out = jax.jit(step, donate_argnums=(1,))(x, cache)
    jax.block_until_ready(out)
    return time.time() - t0, out

class Clock:
    def time(self):
        return 0.0

def host2(c: "Clock"):
    return c.time()                      # not the time module
"""
    assert "GL006" not in _codes(near_miss)


def test_gl007_blocking_transfer_in_loop_fires_and_near_miss():
    fires = """
import jax

def scheduler(reqs, pool):
    outs = []
    while reqs:
        out = step(pool)
        jax.block_until_ready(out)           # per-iteration sync
        outs.append(jax.device_get(out))     # and a second one
    for o in outs:
        o.block_until_ready()                # method spelling
    return outs
"""
    codes = _codes(fires)
    assert codes.count("GL007") == 3, codes
    near_miss = """
import jax

def scheduler(reqs, pool):
    outs = [step(pool) for r in reqs]
    jax.block_until_ready(outs)              # one sync, outside the loop
    return jax.device_get(outs)

def _demote_blocks(blocks, pool):
    for b in blocks:
        host = jax.device_get(gather(pool, b))   # sanctioned helper
    return host

def _promote_wait(staged):
    for leaf in staged:
        leaf.block_until_ready()             # sanctioned helper
    return staged

def driver(xs):
    while xs:
        y = jax.device_put(xs.pop())         # device_put is async
    return y

def once(xs):
    for x in jax.device_get(xs):             # iter expr runs ONCE
        use(x)
    for x in xs:
        pass
    else:
        jax.block_until_ready(xs)            # else clause runs ONCE
"""
    assert "GL007" not in _codes(near_miss)
    # a While TEST re-evaluates per iteration — that one does fire
    while_test = """
import jax

def driver(x):
    while jax.device_get(x) > 0:
        x = step(x)
"""
    assert _codes(while_test) == ["GL007"]
    # comprehensions are loops, and the from-import spelling counts;
    # the first generator's iterable still evaluates once (no fire)
    comp = """
import jax
from jax import device_get

def driver(xs, pool):
    a = [jax.device_get(step(pool)) for x in xs]
    b = {device_get(x) for x in xs}
    c = [f(x) for x in jax.device_get(xs)]      # iterable: runs once
    return a, b, c
"""
    assert _codes(comp).count("GL007") == 2, _codes(comp)
    # a nested def's DEFAULTS/decorators evaluate per iteration (fire);
    # its body only runs when called (no fire)
    nested = """
import jax

def driver(xs):
    for x in xs:
        def f(y=jax.device_get(x)):          # def-time, per iteration
            return jax.device_get(y)         # call-time: not the loop
        h = f
    return h
"""
    assert _codes(nested).count("GL007") == 1, _codes(nested)
    # pragma support: documented per-item commit points stay expressible
    pragma = """
import jax

def driver(xs):
    for x in xs:
        jax.device_get(x)  # graft: noqa(GL007) per-item commit, documented
"""
    assert _codes(pragma) == []


def test_gl008_metric_convention_fires_and_near_miss():
    fires = """
def build(m):
    a = m.counter("serving_requests")            # counter missing _total
    b = m.counter("things_total", "help")        # missing namespace
    c = m.gauge("serving_queue_total")           # gauge claiming _total
    d = m.histogram("serving_lat_seconds", uid="x")  # ad-hoc label key
"""
    codes = _codes(fires)
    assert codes.count("GL008") == 4, codes
    near_miss = """
import collections

def build(m, name):
    a = m.counter("serving_requests_admitted_total", "help")
    b = m.gauge("train_loss", "help", replica="0")
    c = m.histogram("inference_forward_seconds", buckets=(1.0, 2.0),
                    timer="fwd", monitor_name="X/y")
    d = m.counter("serving_kv_swaps_total", direction="out")
    e = m.counter(name)                          # non-literal: out of scope
    f = collections.Counter("abc")               # not a registry call
    g = m.gauge("serving_slo_burn_rate", slo_class="batch", slo="ttft")
"""
    assert "GL008" not in _codes(near_miss)
    # one bad call can violate two conventions at once — both fire
    double = """
def build(m):
    m.counter("queue_depth")   # no namespace AND not _total
"""
    assert _codes(double).count("GL008") == 2
    pragma = """
def build(m):
    m.counter("legacy_hits")  # graft: noqa(GL008) pre-registry name, migrating
"""
    assert _codes(pragma) == []


def test_gl012_scalar_sync_in_scheduler_loop_fires_and_near_miss():
    """GL012: the host-loop scalar concretizations the fused multi-step
    decode program exists to kill (one .item()/int()/bool() per decoded
    token pins the scheduler to device latency)."""
    fires = """
import jax.numpy as jnp

def scheduler(srv, toks):
    while srv.pending:
        tok = jnp.argmax(toks).item()        # scalar per iteration
        if bool(jnp.any(toks > 0)):          # implicit bool sync
            srv.finish()
        n = int(jnp.sum(toks))               # int() concretization
    while jnp.any(toks):                     # While test: per iteration
        toks = step(toks)
"""
    codes = _codes(fires)
    assert codes.count("GL012") == 4, codes
    near_miss = """
import numpy as np
import jax.numpy as jnp

def scheduler(srv, v, out):
    while srv.pending:
        tok = np.asarray(v).item()           # host numpy: no device sync
        n = int(out[0, 0])                   # plain variable: unknowable
        if srv.done:                         # host-state test
            break
    last = jnp.argmax(v).item()              # outside any loop: one-off

def _fence_harvest(arrays):
    for a in arrays:
        n = int(jnp.sum(a))                  # sanctioned fence helper
    return n

def _swap_commit(blocks):
    while blocks:
        b = blocks.pop()
        flag = bool(jnp.any(b))              # sanctioned transfer helper
    return flag
"""
    assert "GL012" not in _codes(near_miss)
    # inside a jit body the same spellings are GL001/GL005 territory —
    # GL012 is host-scheduler-only (no double reporting)
    in_jit = """
import jax, jax.numpy as jnp

def step(x, cache):
    for _ in range(4):
        v = x.item()
    return cache

jax.jit(step, donate_argnums=(1,))
"""
    assert "GL012" not in _codes(in_jit)
    pragma = """
import jax.numpy as jnp

def probe(xs):
    for x in xs:
        v = float(jnp.abs(x))  # graft: noqa(GL012) per-layer harvest, documented
"""
    assert _codes(pragma) == []


def test_gl013_swallowed_exception_fires_scoped_and_pragma():
    """GL013: an ``except`` in fleet-path code (serving/, telemetry/,
    inference/serving.py) that neither re-raises, nor uses the caught
    name, nor emits telemetry/logging swallows the failure — invisible
    to the flight recorder."""
    fires = """
def pull(rep):
    try:
        rep.step()
    except Exception:
        pass
"""
    in_scope = "deepspeed_tpu/serving/router.py"
    codes = [f.code for f in lint.check_source(fires, path=in_scope)]
    assert codes == ["GL013"], codes
    # finding anchors to the `except` line (where the pragma goes)
    f = lint.check_source(fires, path=in_scope)[0]
    assert f.line == 5
    # same source outside the fleet path: silent by design (tests,
    # analysis tools, and models/ are allowed terse cleanup handlers)
    assert lint.check_source(fires, path="deepspeed_tpu/models/gpt2.py") \
        == []
    assert lint.check_source(fires) == []
    # inference/serving.py is in scope despite not living under serving/
    assert [f.code for f in lint.check_source(
        fires, path="deepspeed_tpu/inference/serving.py")] == ["GL013"]

    near_misses = """
from ..utils.logging import logger

def pull(rep, metrics, errors):
    try:
        rep.step()
    except Exception:
        raise
    try:
        rep.step()
    except Exception as e:
        errors["step"] = repr(e)
    try:
        rep.step()
    except Exception:
        metrics.counter("serving_pull_fail_total").inc()
    try:
        rep.step()
    except Exception:
        logger.warning("step failed; degrading")
"""
    assert lint.check_source(near_misses, path=in_scope) == []

    pragma = """
def close(path):
    try:
        os.unlink(path)
    except OSError:  # graft: noqa(GL013) best-effort temp cleanup
        pass
"""
    assert lint.check_source(pragma, path=in_scope) == []
    kept = lint.check_source(pragma, path=in_scope, keep_suppressed=True)
    assert [f.code for f in kept] == ["GL013"]


def test_gl014_global_rng_fires_scoped_exempts_and_pragma():
    """GL014: process-global RNG draws (``random.*`` / ``np.random.*``
    module singletons) in fleet-path code are interleaving-order
    dependent — a replayed/re-homed request cannot reproduce them.
    Seeded instance constructors through the same modules are the fix
    spelling and must stay CLEAN."""
    in_scope = "deepspeed_tpu/serving/router.py"
    fires = """
import random
import numpy as np

def jitter(base):
    d = random.uniform(0.0, base)
    k = np.random.randint(0, 4)
    np.random.seed(0)
    return d + k
"""
    codes = [f.code for f in lint.check_source(fires, path=in_scope)]
    assert codes == ["GL014"] * 3, codes
    # out of fleet scope (tests, models, analysis tools): silent
    assert lint.check_source(fires, path="deepspeed_tpu/models/gpt2.py") \
        == []
    assert lint.check_source(fires) == []
    # inference/serving.py shares GL013's file-level scope rule
    assert [f.code for f in lint.check_source(
        fires, path="deepspeed_tpu/inference/serving.py")] == ["GL014"] * 3

    near_misses = """
import random
import numpy as np

def jitter(base, rng, entry):
    g = np.random.default_rng([7, 11])     # seeded instance ctor
    r = random.Random(42)                  # seeded instance ctor
    ss = np.random.SeedSequence(3)
    d = rng.uniform(0.0, base)             # instance method, not module
    k = entry.random.choice([1, 2])        # attribute chain, not np.random
    return g.integers(0, 4) + r.random() + d + k, ss
"""
    assert lint.check_source(near_misses, path=in_scope) == []

    pragma = """
import random

def backoff(base):
    return random.uniform(0.0, base)  # graft: noqa(GL014) jitter, non-replayed path
"""
    assert lint.check_source(pragma, path=in_scope) == []
    kept = lint.check_source(pragma, path=in_scope, keep_suppressed=True)
    assert [f.code for f in kept] == ["GL014"]


def test_noqa_pragma_suppresses_named_rule_only():
    src = """
import jax

def step(x, cache):
    v = x.item()  # graft: noqa(GL001) host commit point, documented
    f = float(x)
    return v + f

jax.jit(step, donate_argnums=(1,))
"""
    assert _codes(src) == ["GL001"]          # only the unsuppressed float()
    all_kept = lint.check_source(src, keep_suppressed=True)
    assert [f.code for f in all_kept].count("GL001") == 2
    bare = src.replace("noqa(GL001) host commit point, documented", "noqa")
    bare = bare.replace("f = float(x)", "f = 0.0")
    assert _codes(bare) == []


def test_wrapped_jit_callable_still_detected():
    """jax.jit(sentry.wrap(step, ...)) — the body resolves through the
    wrapper call, so the serving engine's own entry points stay linted."""
    src = """
import jax

def step(tokens, cache):
    bad = float(tokens)
    return cache

fn = jax.jit(wrapper.wrap(step, "decode"), donate_argnums=(1,))
"""
    assert _codes(src) == ["GL001"]


def test_lint_package_is_clean_and_cli_exit_codes(tmp_path):
    """The merged tree lints clean (the CI gate), and the CLI exits
    nonzero on a finding."""
    findings, nfiles = lint.lint_paths([str(REPO / "deepspeed_tpu")])
    assert nfiles > 100
    assert findings == [], [f.render() for f in findings]

    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\ndef f(x, cache):\n    return cache\n\n"
                   "jax.jit(f)\n")
    proc = subprocess.run(
        [sys.executable, str(REPO / "bin" / "graft-lint"), str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 1 and "GL003" in proc.stdout
    ok = subprocess.run(
        [sys.executable, str(REPO / "bin" / "graft-lint"),
         str(REPO / "deepspeed_tpu" / "analysis")],
        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # a typo'd path must fail loudly, not no-op the CI gate
    typo = subprocess.run(
        [sys.executable, str(REPO / "bin" / "graft-lint"),
         str(tmp_path / "no_such_dir")],
        capture_output=True, text=True)
    assert typo.returncode == 2 and "no Python files" in typo.stderr


# ----------------------------------------------------------------- sentry
def test_sentry_trips_on_shape_unstable_callable_with_diff():
    import jax
    import jax.numpy as jnp

    s = RecompileSentry(name="t", strict=True)
    f = jax.jit(s.wrap(lambda x: x * 2, "f"))
    np.testing.assert_array_equal(np.asarray(f(jnp.zeros(4))), np.zeros(4))
    np.testing.assert_array_equal(np.asarray(f(jnp.zeros(4))), np.zeros(4))
    assert s.traces == 1                       # cache hit: no retrace
    with pytest.raises(RetraceError) as ei:
        f(jnp.zeros(8))                        # new shape: budget 1 blown
    msg = str(ei.value)
    assert "'t:f'" in msg and "[4]" in msg and "[8]" in msg, msg
    assert ei.value.name == "f"


def test_sentry_nonstrict_counts_and_total_budget():
    import jax
    import jax.numpy as jnp

    s = RecompileSentry(strict=False)
    f = jax.jit(s.wrap(lambda x: x + 1, "f"))
    f(jnp.zeros(2)); f(jnp.zeros(3)); f(jnp.zeros(4))
    assert s.traces == 3 and s.retraces_observed == 2
    assert s.report()["f"]["traces"] == 3

    s2 = RecompileSentry(strict=True, total_budget=2)
    g = jax.jit(s2.wrap(lambda x: x - 1, "g", budget=None))
    g(jnp.zeros(2)); g(jnp.zeros(3))
    with pytest.raises(RetraceError, match="total compile budget"):
        g(jnp.zeros(4))

    # non-strict total-budget drift is still OBSERVED: two entries each
    # within their own budget can blow the engine total (an unexpected
    # new program), and retraces_observed must say so
    s3 = RecompileSentry(strict=False, total_budget=2)
    a = jax.jit(s3.wrap(lambda x: x, "a"))
    b = jax.jit(s3.wrap(lambda x: x, "b"))
    c = jax.jit(s3.wrap(lambda x: x, "c"))
    a(jnp.zeros(2)); b(jnp.zeros(2))
    assert s3.retraces_observed == 0
    c(jnp.zeros(2))                            # 3 programs vs budget 2
    assert s3.retraces_observed == 1


def test_compile_listener_counts_backend_compiles():
    """The jax.monitoring hook sees real backend compiles — pins the
    '/jax/core/compile/backend_compile' event prefix against jax renames
    (a silent rename would make backend_compiles() report 0 forever)."""
    import jax
    import jax.numpy as jnp

    counter = sentry.install_compile_listener()
    assert sentry.install_compile_listener() is counter   # idempotent
    before = counter.count
    jax.jit(lambda x: x * 3 + 1)(jnp.zeros(5))            # fresh program
    assert counter.count > before
    assert sentry.backend_compiles() == counter.count


def test_sentry_abstract_signature_distinguishes_dtype_and_statics():
    import jax.numpy as jnp

    a = sentry.abstract_signature((jnp.zeros((2, 3), jnp.int32),), {})
    b = sentry.abstract_signature((jnp.zeros((2, 3), jnp.float32),), {})
    assert a != b
    d = sentry.signature_diff(a, b)
    assert d and "int32" in d[0] and "float32" in d[0]


@pytest.fixture(scope="module")
def tiny_engine():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _mixed_trace(cfg, n, seed):
    rng = np.random.default_rng(seed)
    return [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(3, 40))),
                    max_new_tokens=int(rng.integers(1, 12)))
            for i in range(n)]


def test_sentry_enforces_serving_compile_contracts(tiny_engine):
    """Acceptance: the chunked 2-program and speculative contracts are
    enforced LIVE (strict sentry raises at trace time) instead of the old
    after-the-fact compile_count asserts — two serve calls over fresh
    shapes stay within budget."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    assert srv.compile_budget == 2
    srv.serve(_mixed_trace(cfg, 8, seed=0))
    srv.serve(_mixed_trace(cfg, 4, seed=1))    # new shapes: no new traces
    assert srv.sentry.traces == 2
    assert srv.stats()["retraces_observed"] == 0
    assert sorted(srv.sentry.report()) == ["decode", "prefill[w16]"]

    spec = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=4,
                         debug_checks=True)
    assert spec.compile_budget == 2            # n-gram: prefill + verify
    spec.serve(_mixed_trace(cfg, 6, seed=2))
    assert sorted(spec.sentry.report()) == ["prefill[w16]", "verify"]
    assert spec.stats()["retraces_observed"] == 0


def test_serve_debug_checks_override_and_counters(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2)
    assert not srv.debug_checks and not srv.sentry.strict
    srv.serve(_mixed_trace(cfg, 3, seed=3), debug_checks=True)
    assert srv.debug_checks and srv.sentry.strict
    st = srv.stats()
    assert st["debug_checks"] and st["invariant_checks_run"] > 0
    assert st["retraces_observed"] == 0 and st["compile_budget"] == 2
    # debug_checks installs the process-wide compile listener
    assert st["backend_compiles"] is not None and st["backend_compiles"] > 0


def test_init_serving_plumbs_debug_checks(tiny_engine):
    _, cfg = tiny_engine
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        slots=2, max_seq_len=128, block_size=8, debug_checks=True)
    assert srv.debug_checks and srv.sentry.strict


def test_training_engine_registers_step_with_sentry():
    """The DP training engine's fused step is a registered entry point:
    one trace for the whole run (fixed batch shapes), zero drift."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    for _ in range(3):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        engine.train_batch(batch)
    rep = engine.sentry.report()
    assert rep["train_step"]["traces"] == 1, rep
    assert engine.sentry.retraces_observed == 0


# ------------------------------------------------------- paged invariants
def _tiny_state():
    """A hand-built consistent state: 2 slots, block_size 4; slot 0 holds
    blocks [1, 2] (block 1 shared with the trie), slot 1 holds [3]."""
    a = BlockAllocator(8)
    pc = PrefixCache(block_size=4)
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    pc.register(np.arange(4), [b1], a)          # trie holds b1 too
    tables = np.zeros((2, 4), np.int32)
    tables[0, :2] = [b1, b2]
    tables[1, 0] = b3
    held = [[b1, b2], [b3]]
    needs = {0: 7, 1: 3}
    return a, pc, tables, held, needs


def _audit(a, pc, tables, held, needs):
    audit_paged_state(a, tables, held, prefix=pc, active_needs=needs,
                      block_size=4)


def test_audit_passes_on_consistent_state():
    _audit(*_tiny_state())
    # the checker's scratch-id mirror must track the allocator's
    from deepspeed_tpu.inference import paged

    assert invariants.SCRATCH_BLOCK == paged.SCRATCH_BLOCK


def test_audit_catches_leaked_refcount():
    a, pc, tables, held, needs = _tiny_state()
    a.incref(held[0][1])                        # phantom owner
    with pytest.raises(PagedStateError, match="leaked") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "refcount-conservation"


def test_audit_catches_double_free():
    a, pc, tables, held, needs = _tiny_state()
    a.decref(held[1][0])                        # freed while still held
    with pytest.raises(PagedStateError, match="double-free") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "refcount-conservation"


def test_audit_catches_trie_table_divergence():
    a, pc, tables, held, needs = _tiny_state()
    tables[0, 0] = held[1][0]                   # table no longer matches held
    with pytest.raises(PagedStateError, match="diverge") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "length-occupancy"


def test_audit_catches_trie_structure_corruption():
    a, pc, tables, held, needs = _tiny_state()
    pc.entries()[0].children = 3                # counter out of sync
    with pytest.raises(PagedStateError) as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "trie-parent-child"


def test_audit_catches_trie_out_of_range_block():
    a, pc, tables, held, needs = _tiny_state()
    pc.entries()[0].block = -1                  # corrupt id must not wrap
    with pytest.raises(PagedStateError, match="out-of-range") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "refcount-conservation"


def test_audit_catches_scratch_aliasing():
    a, pc, tables, held, needs = _tiny_state()
    # slot 0 needs 2 blocks for 7 tokens; unset its second table entry so
    # its writes would land in (and reads come from) scratch block 0
    tables[0, 1] = 0
    held[0] = held[0][:1]
    a.decref(2)
    with pytest.raises(PagedStateError, match="scratch") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "scratch-aliasing"


def test_audit_catches_inactive_slot_residue():
    a, pc, tables, held, needs = _tiny_state()
    del needs[1]                                # slot 1 "released" but dirty
    with pytest.raises(PagedStateError, match="inactive") as ei:
        _audit(a, pc, tables, held, needs)
    assert ei.value.invariant == "length-occupancy"


def test_audit_runs_green_mid_trace(tiny_engine):
    """audit_serving_engine holds on REAL scheduler state mid-iteration:
    hook the decode step to audit with live actives (prefix reuse +
    preemption pressure in the trace)."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=32, prefill_batch=2, num_blocks=12,
                        debug_checks=True)
    rng = np.random.default_rng(5)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28) for i in range(5)]
    audits = []
    orig = srv._run_plain_decode

    def hooked(params):
        invariants.audit_serving_engine(srv, srv._active)
        audits.append(len(srv._active))
        return orig(params)

    srv._run_plain_decode = hooked
    srv.serve(reqs)
    assert srv.preempted > 0 and audits
    assert srv.invariant_checks_run > 0
