"""Flash-attention kernel vs einsum reference (interpret mode on CPU).

Model: reference tests/unit/ops/* comparing CUDA kernels to eager torch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import flash_attention, mha_reference

pytestmark = pytest.mark.slow  # Pallas interpret mode: minutes on CPU


def rand_qkv(key, b=2, h=4, s=256, d=64, hkv=None, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    hkv = hkv or h
    q = jax.random.normal(ks[0], (b, h, s, d), dtype)
    k = jax.random.normal(ks[1], (b, hkv, s, d), dtype)
    v = jax.random.normal(ks[2], (b, hkv, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = rand_qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_forward_unaligned_seq():
    # seq 200 not a multiple of the 128 block: padding + key masking path
    q, k, v = rand_qkv(jax.random.PRNGKey(1), s=200)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_forward_small_seq():
    q, k, v = rand_qkv(jax.random.PRNGKey(2), s=32)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_gqa_heads():
    q, k, v = rand_qkv(jax.random.PRNGKey(3), h=8, hkv=2, s=128)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_reference(causal):
    q, k, v = rand_qkv(jax.random.PRNGKey(4), b=1, h=2, s=256, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True)**2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal)**2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_backward_unaligned():
    q, k, v = rand_qkv(jax.random.PRNGKey(5), b=1, h=2, s=200, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True)**2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True)**2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_backward_gqa():
    # exercises the fused-v2 backward's rep-grid dk/dv accumulation
    q, k, v = rand_qkv(jax.random.PRNGKey(7), b=1, h=8, hkv=2, s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True)**2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True)**2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_long_seq_v1_fallback():
    # kv > _V2_MAX_KV falls back to the v1 two-kernel backward
    q, k, v = rand_qkv(jax.random.PRNGKey(8), b=1, h=1, s=4096, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True,
                                       block_q=512, block_k=512)**2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True)**2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4, err_msg=f"d{name} mismatch")


def test_bf16_runs():
    q, k, v = rand_qkv(jax.random.PRNGKey(6), s=128, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2)
