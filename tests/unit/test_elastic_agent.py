"""ElasticAgent tests (reference elastic_agent.py DSElasticAgent):
supervision, restart-on-failure, membership-change restart, world election.
Workers are tiny subprocesses — no jax involved."""

import json
import sys

import pytest

from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent

CFG = {"elasticity": {"enabled": True, "max_train_batch_size": 16,
                      "micro_batch_sizes": [1, 2], "min_gpus": 1,
                      "max_gpus": 16, "min_time": 0,
                      "prefer_larger_batch": True, "version": 0.2},
       "train_micro_batch_size_per_gpu": 2,
       "gradient_accumulation_steps": 1}


def _agent(probe, launch, **kw):
    kw.setdefault("monitor_interval", 0.1)
    return ElasticAgent(CFG, probe, launch, **kw)


def test_elect_world_picks_largest_valid():
    agent = _agent(lambda: [], lambda h, e: [])
    hosts = [f"h{i}" for i in range(5)]
    # valid chip counts include 4 (16/4=4 micro 2 gas 2 etc.); 5 is not a
    # divisor-friendly count for batch 16 -> largest valid <= 5 is 4
    elected = agent.elect_world(hosts)
    assert len(elected) == 4
    assert elected == hosts[:4]


def test_elect_world_incompatible_raises():
    agent = _agent(lambda: [], lambda h, e: [], chips_per_host=32)
    with pytest.raises(RuntimeError):
        agent.elect_world(["h0"])


def test_run_succeeds_when_workers_exit_zero():
    agent = _agent(lambda: ["a", "b"],
                   lambda host, env: [sys.executable, "-c", "pass"])
    assert agent.run() == 0
    assert agent.restart_count == 0


def test_run_restarts_on_failure(tmp_path):
    """First generation fails; after the flag file exists workers succeed."""
    flag = tmp_path / "ok"
    prog = (f"import os,sys;"
            f"sys.exit(0 if os.path.exists({str(flag)!r}) else "
            f"(open({str(flag)!r},'w').close() or 1))")
    agent = _agent(lambda: ["a", "b"],
                   lambda host, env: [sys.executable, "-c", prog])
    assert agent.run() == 0
    assert agent.restart_count >= 1
    # restart count surfaced to workers via env
    env = agent._env_for("a", 0, ["a", "b"])
    assert env["DS_ELASTIC_RESTART_COUNT"] == str(agent.restart_count)


def test_membership_change_triggers_restart(tmp_path):
    """Hosts shrink 4 -> 2 mid-run: the group restarts on 2 hosts.

    Load-independent by construction (the 1-core box makes wall-clock
    margins flaky): the probe keeps reporting 4 hosts until all four
    first-group workers have provably written their line, and workers key
    their lifetime off the agent-injected DS_ELASTIC_RESTART_COUNT — the
    first group idles until killed by the restart, the second exits
    immediately so the agent observes SUCCEEDED."""
    log = tmp_path / "worlds.jsonl"

    def probe():
        lines = log.read_text().splitlines() if log.exists() else []
        if len(lines) < 4:
            return ["a", "b", "c", "d"]
        return ["a", "b"]

    prog = ("import os,time,json;"
            f"f=open({str(log)!r},'a');"
            "json.dump({'n': os.environ['JAX_NUM_PROCESSES']}, f);"
            "f.write('\\n');f.close();"
            "time.sleep(120.0) if os.environ['DS_ELASTIC_RESTART_COUNT'] "
            "== '0' else None")
    agent = _agent(probe, lambda host, env: [sys.executable, "-c", prog],
                   monitor_interval=2.0)
    assert agent.run() == 0
    worlds = [json.loads(l)["n"] for l in log.read_text().splitlines()]
    assert worlds.count("4") == 4 and worlds.count("2") == 2, worlds
    assert agent.restart_count >= 1


def test_slot_count_change_triggers_restart(tmp_path):
    """Dict probe: hostfile slot edits must take effect at the next election
    — chips_per_host is re-derived per probe, and a capacity change with an
    IDENTICAL host set restarts the group with the new WORLD_SIZE.  Same
    load-independence construction as the membership-change test."""
    log = tmp_path / "worlds.jsonl"

    def probe():
        lines = log.read_text().splitlines() if log.exists() else []
        if len(lines) < 2:
            return {"a": 1, "b": 1}
        return {"a": 4, "b": 4}   # slice grew: 4 chips/host now

    prog = ("import os,time,json;"
            f"f=open({str(log)!r},'a');"
            "json.dump({'ws': os.environ['WORLD_SIZE']}, f);"
            "f.write('\\n');f.close();"
            "time.sleep(120.0) if os.environ['DS_ELASTIC_RESTART_COUNT'] "
            "== '0' else None")
    agent = _agent(probe, lambda host, env: [sys.executable, "-c", prog],
                   monitor_interval=2.0)
    assert agent.run() == 0
    worlds = [json.loads(l)["ws"] for l in log.read_text().splitlines()]
    # first group: 2 hosts x 1 chip = WS 2; second: 2 hosts x 4 = WS 8
    assert worlds.count("2") == 2 and worlds.count("8") == 2, worlds
    assert agent.restart_count >= 1


def test_het_dict_probe_shrinks_mid_run(tmp_path):
    """Heterogeneous probe dict SHRINKING mid-run: the pool loses its
    2-chip members, chips_per_host re-derives to the new minimum (4),
    and the group restarts at the higher per-host capacity with the
    smaller host set — the elastic slice-resize path."""
    log = tmp_path / "worlds.jsonl"

    def probe():
        lines = log.read_text().splitlines() if log.exists() else []
        if len(lines) < 4:
            # 4 hosts, min capacity 1 => WORLD_SIZE 4*1 = 4
            return {"a": 4, "b": 1, "c": 4, "d": 1}
        return {"a": 4, "c": 4}   # 1-chip hosts died: 2 hosts x 4 chips

    prog = ("import os,time,json;"
            f"f=open({str(log)!r},'a');"
            "json.dump({'ws': os.environ['WORLD_SIZE']}, f);"
            "f.write('\\n');f.close();"
            "time.sleep(120.0) if os.environ['DS_ELASTIC_RESTART_COUNT'] "
            "== '0' else None")
    agent = _agent(probe, lambda host, env: [sys.executable, "-c", prog],
                   monitor_interval=2.0)
    assert agent.run() == 0
    worlds = [json.loads(l)["ws"] for l in log.read_text().splitlines()]
    assert worlds[:4] == ["4"] * 4, worlds      # gen 1: 4 hosts x 1 chip
    assert worlds[4:] == ["8"] * 2, worlds      # gen 2: 2 hosts x 4
    assert agent.chips_per_host == 4
    assert agent.restart_count >= 1


def test_partial_grace_ticks_expiry():
    """One worker exits 0 while its peer hangs: PARTIAL persists past
    ``partial_grace_ticks`` monitor ticks, the group restarts, and the
    second generation (both exiting 0) SUCCEEDS.  Within-grace completion
    skew must NOT have burned more than one restart."""
    prog = ("import os,time,sys;"
            "hang = (os.environ['DS_ELASTIC_RESTART_COUNT'] == '0' and "
            "os.environ['JAX_PROCESS_ID'] == '1');"
            "time.sleep(120.0) if hang else sys.exit(0)")
    agent = _agent(lambda: ["a", "b"],
                   lambda host, env: [sys.executable, "-c", prog],
                   monitor_interval=0.2, partial_grace_ticks=2)
    assert agent.run() == 0
    # exactly one restart: the grace window absorbed the skew ticks, the
    # expiry (tick 3) restarted the hung survivor's group once
    assert agent.restart_count == 1


def test_elect_all_flag_elects_every_host():
    """elect_all=True (the launcher --serve replica-supervision mode):
    every live host is elected, no batch constraint; WITHOUT the flag a
    missing/disabled elasticity block still fails fast — a typo'd
    training config must not silently launch on every host."""
    agent = ElasticAgent({}, lambda: [], lambda h, e: [],
                         monitor_interval=0.1, elect_all=True)
    hosts = [f"r{i}" for i in range(5)]
    assert agent.elect_world(hosts) == hosts
    with pytest.raises(RuntimeError):
        agent.elect_world([])
    for cfg in ({}, {"elasticity": {"enabled": False}}):
        strict = ElasticAgent(cfg, lambda: [], lambda h, e: [],
                              monitor_interval=0.1)
        with pytest.raises(Exception):
            strict.elect_world(["x"])


def test_zero_slot_hosts_excluded():
    """A slots=0 hostfile line behaves like an excluded host: it is not
    elected and does not drag chips_per_host to 1."""
    agent = _agent(lambda: {"a": 4, "b": 0, "c": 4},
                   lambda host, env: [sys.executable, "-c", "pass"])
    hosts = agent._probe()
    assert hosts == ["a", "c"]
    assert agent.chips_per_host == 4
