"""Launcher pure-unit tests (model: reference tests/unit/launcher/test_run.py
and test_multinode_runner.py — no ssh, just parsing + command construction)."""

import base64
import json

import pytest

from deepspeed_tpu.launcher.launch import build_env, decode_world_info
from deepspeed_tpu.launcher.runner import (OpenMPIRunner, PDSHRunner,
                                           SlurmRunner, encode_world_info,
                                           fetch_hostfile, parse_args,
                                           parse_resource_filter)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("""
worker-0 slots=4
worker-1 slots=4
# a comment
worker-2 slots=8
""")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 8}


def test_fetch_hostfile_missing(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "hf"
    p.write_text("worker-0 slots=four\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "hf"
    p.write_text("w slots=2\nw slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_resource_filter_include():
    pool = {"worker-0": 4, "worker-1": 4}
    active = parse_resource_filter(pool, include_str="worker-1:0,2")
    assert active == {"worker-1": [0, 2]}
    active = parse_resource_filter(pool, include_str="worker-0")
    assert active == {"worker-0": [0, 1, 2, 3]}


def test_resource_filter_exclude():
    pool = {"worker-0": 4, "worker-1": 4}
    active = parse_resource_filter(pool, exclude_str="worker-1")
    assert list(active.keys()) == ["worker-0"]
    active = parse_resource_filter(pool, exclude_str="worker-0:1,3")
    assert active["worker-0"] == [0, 2]


def test_resource_filter_conflicts():
    with pytest.raises(ValueError):
        parse_resource_filter({"w": 2}, include_str="w", exclude_str="w")
    with pytest.raises(ValueError):
        parse_resource_filter({"w": 2}, include_str="bogus-host")


def test_world_info_roundtrip():
    active = {"worker-0": [0, 1], "worker-1": [0]}
    encoded = encode_world_info(active)
    assert decode_world_info(encoded) == active


def _args(extra=None):
    return parse_args((extra or []) + ["train.py", "--foo", "bar"])


def test_pdsh_cmd_construction():
    args = _args(["--master_addr", "worker-0"])
    runner = PDSHRunner(args, encode_world_info({"worker-0": [0], "worker-1": [0]}))
    cmd = runner.get_cmd({}, {"worker-0": [0], "worker-1": [0]})
    assert cmd[0] == "pdsh"
    assert "worker-0,worker-1" in cmd
    joined = " ".join(cmd)
    assert "deepspeed_tpu.launcher.launch" in joined
    assert "--master_addr=worker-0" in joined
    assert "train.py" in joined and "--foo bar" in joined


def test_openmpi_cmd_construction():
    args = _args()
    runner = OpenMPIRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0]})
    assert cmd[0] == "mpirun"
    assert "-n" in cmd and cmd[cmd.index("-n") + 1] == "2"
    assert "train.py" in cmd


def test_mpich_cmd_construction():
    from deepspeed_tpu.launcher.runner import MPICHRunner

    args = _args()
    runner = MPICHRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0]})
    assert cmd[0] == "mpirun"
    assert cmd[cmd.index("-n") + 1] == "2"
    assert cmd[cmd.index("-ppn") + 1] == "1"
    assert "train.py" in cmd


def test_mvapich_cmd_construction(tmp_path, monkeypatch):
    from deepspeed_tpu.launcher.runner import MVAPICHRunner

    args = _args()
    runner = MVAPICHRunner(args, "x")
    monkeypatch.setattr(MVAPICHRunner, "hostfile_path",
                        str(tmp_path / "mvapich_hosts"))
    cmd = runner.get_cmd({}, {"a": [0], "b": [0], "c": [0]})
    assert cmd[0] == "mpirun_rsh"
    assert cmd[cmd.index("-np") + 1] == "3"
    hosts = (tmp_path / "mvapich_hosts").read_text().split()
    assert hosts == ["a", "b", "c"]


def test_slurm_cmd_construction():
    args = _args()
    runner = SlurmRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0], "c": [0]})
    assert cmd[0] == "srun"
    assert cmd[cmd.index("-N") + 1] == "3"


def test_build_env():
    world = {"worker-0": [0, 1], "worker-1": [0, 1]}
    env = build_env(world, node_rank=1, master_addr="worker-0",
                    master_port=1234, base_env={})
    assert env["JAX_COORDINATOR_ADDRESS"] == "worker-0:1234"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["WORLD_SIZE"] == "4"


# --------------------------------------------------------------------------
# elastic training through the CLI (reference launcher/launch.py:257-310:
# --enable_elastic_training starts the elastic agent)
# --------------------------------------------------------------------------
def test_elastic_flag_requires_config(tmp_path):
    from deepspeed_tpu.launcher import runner

    hf = tmp_path / "hostfile"
    hf.write_text("a slots=1\nb slots=1\n")
    with pytest.raises(ValueError, match="elastic_config"):
        runner.main(["--hostfile", str(hf), "--enable_elastic_training",
                     "--launcher", "local", "train.py"])


def test_elastic_cli_restarts_dead_worker(tmp_path):
    """CLI path end to end: a worker dies mid-run, the agent re-elects and
    restarts the group; workers of the second generation (keyed off the
    agent-injected DS_ELASTIC_RESTART_COUNT) finish cleanly."""
    import sys as _sys

    from deepspeed_tpu.launcher import runner

    hf = tmp_path / "hostfile"
    hf.write_text("hostA slots=1\nhostB slots=1\n")
    cfg = tmp_path / "ds.json"
    cfg.write_text(json.dumps({
        "elasticity": {"enabled": True, "max_train_batch_size": 8,
                       "micro_batch_sizes": [1, 2], "min_gpus": 1,
                       "max_gpus": 8, "min_time": 0, "version": 0.2},
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
    }))
    log = tmp_path / "gens.jsonl"
    script = tmp_path / "worker.py"
    # generation 0: rank 1 crashes mid-run (the "killed worker"), rank 0
    # idles so only the agent's restart can reap it; generation 1+ exits 0
    script.write_text(f"""
import json, os, sys, time
with open({str(log)!r}, "a") as f:
    json.dump({{"gen": os.environ["DS_ELASTIC_RESTART_COUNT"],
               "n": os.environ["JAX_NUM_PROCESSES"],
               "rank": os.environ["JAX_PROCESS_ID"]}}, f)
    f.write("\\n")
if os.environ["DS_ELASTIC_RESTART_COUNT"] == "0":
    if os.environ["JAX_PROCESS_ID"] == "1":
        time.sleep(0.3)
        sys.exit(1)
    time.sleep(120)
""")
    code = None
    try:
        runner.main(["--hostfile", str(hf), "--enable_elastic_training",
                     "--elastic_config", str(cfg),
                     "--elastic_monitor_interval", "0.2",
                     "--launcher", "local", str(script)])
    except SystemExit as e:
        code = e.code
    assert code == 0
    gens = [json.loads(l) for l in log.read_text().splitlines()]
    g0 = [g for g in gens if g["gen"] == "0"]
    g1 = [g for g in gens if g["gen"] != "0"]
    assert len(g0) == 2 and len(g1) >= 2, gens
    assert {g["n"] for g in gens} == {"2"}  # both hosts elected each time


# --------------------------------------------------------------------------
# serving-replica mode (--serve): ElasticAgent supervision without elastic
# batch election — one replica worker per host / --replicas N local workers
# --------------------------------------------------------------------------
def test_serve_flag_parses():
    args = parse_args(["--serve", "--replicas", "3", "serve_worker.py"])
    assert args.serve and args.replicas == 3
    assert parse_args(["train.py"]).serve is False


def test_serve_mode_supervises_local_replicas(tmp_path):
    """--serve --replicas 2 without a hostfile: two local replica workers
    run under the agent, each seeing its DS_REPLICA_ID / DS_NUM_REPLICAS,
    and a clean fleet exit returns 0 with no restart burned."""
    from deepspeed_tpu.launcher import runner

    log = tmp_path / "replicas.jsonl"
    script = tmp_path / "replica.py"
    script.write_text(f"""
import json, os
with open({str(log)!r}, "a") as f:
    json.dump({{"rid": os.environ["DS_REPLICA_ID"],
               "n": os.environ["DS_NUM_REPLICAS"]}}, f)
    f.write("\\n")
""")
    code = None
    try:
        runner.main(["--serve", "--replicas", "2",
                     "--hostfile", str(tmp_path / "no_hostfile"),
                     "--elastic_monitor_interval", "0.2",
                     "--launcher", "local", str(script)])
    except SystemExit as e:
        code = e.code
    assert code == 0
    seen = [json.loads(l) for l in log.read_text().splitlines()]
    assert {s["rid"] for s in seen} == {"0", "1"}
    assert {s["n"] for s in seen} == {"2"}


def test_serve_mode_restarts_dead_replica_alone(tmp_path):
    """PR 15: a crashed replica worker is restarted ALONE (generation
    keyed off DS_ELASTIC_RESTART_COUNT) — the healthy replica keeps
    running through the restart instead of being killed with the group
    (the process-level half of the fail/readmit crash protocol)."""
    from deepspeed_tpu.launcher import runner

    log = tmp_path / "gens.jsonl"
    script = tmp_path / "replica.py"
    script.write_text(f"""
import json, os, sys, time
with open({str(log)!r}, "a") as f:
    json.dump({{"gen": os.environ["DS_ELASTIC_RESTART_COUNT"],
               "rid": os.environ["DS_REPLICA_ID"]}}, f)
    f.write("\\n")
if os.environ["DS_ELASTIC_RESTART_COUNT"] == "0":
    if os.environ["DS_REPLICA_ID"] == "1":
        time.sleep(0.1)
        sys.exit(1)
    time.sleep(0.6)
""")
    code = None
    try:
        runner.main(["--serve", "--replicas", "2",
                     "--hostfile", str(tmp_path / "no_hostfile"),
                     "--elastic_monitor_interval", "0.2",
                     "--launcher", "local", str(script)])
    except SystemExit as e:
        code = e.code
    assert code == 0
    gens = [json.loads(l) for l in log.read_text().splitlines()]
    assert {g["rid"] for g in gens if g["gen"] == "0"} == {"0", "1"}
    # the dead replica came back at a later generation...
    assert any(g["gen"] != "0" and g["rid"] == "1" for g in gens)
    # ...and the healthy one was NEVER killed/relaunched (single-worker
    # restart — the whole point): replica 0 only ever logged gen 0
    assert all(g["gen"] == "0" for g in gens if g["rid"] == "0")
