"""Launcher pure-unit tests (model: reference tests/unit/launcher/test_run.py
and test_multinode_runner.py — no ssh, just parsing + command construction)."""

import base64
import json

import pytest

from deepspeed_tpu.launcher.launch import build_env, decode_world_info
from deepspeed_tpu.launcher.runner import (OpenMPIRunner, PDSHRunner,
                                           SlurmRunner, encode_world_info,
                                           fetch_hostfile, parse_args,
                                           parse_resource_filter)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("""
worker-0 slots=4
worker-1 slots=4
# a comment
worker-2 slots=8
""")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 8}


def test_fetch_hostfile_missing(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "hf"
    p.write_text("worker-0 slots=four\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "hf"
    p.write_text("w slots=2\nw slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_resource_filter_include():
    pool = {"worker-0": 4, "worker-1": 4}
    active = parse_resource_filter(pool, include_str="worker-1:0,2")
    assert active == {"worker-1": [0, 2]}
    active = parse_resource_filter(pool, include_str="worker-0")
    assert active == {"worker-0": [0, 1, 2, 3]}


def test_resource_filter_exclude():
    pool = {"worker-0": 4, "worker-1": 4}
    active = parse_resource_filter(pool, exclude_str="worker-1")
    assert list(active.keys()) == ["worker-0"]
    active = parse_resource_filter(pool, exclude_str="worker-0:1,3")
    assert active["worker-0"] == [0, 2]


def test_resource_filter_conflicts():
    with pytest.raises(ValueError):
        parse_resource_filter({"w": 2}, include_str="w", exclude_str="w")
    with pytest.raises(ValueError):
        parse_resource_filter({"w": 2}, include_str="bogus-host")


def test_world_info_roundtrip():
    active = {"worker-0": [0, 1], "worker-1": [0]}
    encoded = encode_world_info(active)
    assert decode_world_info(encoded) == active


def _args(extra=None):
    return parse_args((extra or []) + ["train.py", "--foo", "bar"])


def test_pdsh_cmd_construction():
    args = _args(["--master_addr", "worker-0"])
    runner = PDSHRunner(args, encode_world_info({"worker-0": [0], "worker-1": [0]}))
    cmd = runner.get_cmd({}, {"worker-0": [0], "worker-1": [0]})
    assert cmd[0] == "pdsh"
    assert "worker-0,worker-1" in cmd
    joined = " ".join(cmd)
    assert "deepspeed_tpu.launcher.launch" in joined
    assert "--master_addr=worker-0" in joined
    assert "train.py" in joined and "--foo bar" in joined


def test_openmpi_cmd_construction():
    args = _args()
    runner = OpenMPIRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0]})
    assert cmd[0] == "mpirun"
    assert "-n" in cmd and cmd[cmd.index("-n") + 1] == "2"
    assert "train.py" in cmd


def test_mpich_cmd_construction():
    from deepspeed_tpu.launcher.runner import MPICHRunner

    args = _args()
    runner = MPICHRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0]})
    assert cmd[0] == "mpirun"
    assert cmd[cmd.index("-n") + 1] == "2"
    assert cmd[cmd.index("-ppn") + 1] == "1"
    assert "train.py" in cmd


def test_mvapich_cmd_construction(tmp_path, monkeypatch):
    from deepspeed_tpu.launcher.runner import MVAPICHRunner

    args = _args()
    runner = MVAPICHRunner(args, "x")
    monkeypatch.setattr(MVAPICHRunner, "hostfile_path",
                        str(tmp_path / "mvapich_hosts"))
    cmd = runner.get_cmd({}, {"a": [0], "b": [0], "c": [0]})
    assert cmd[0] == "mpirun_rsh"
    assert cmd[cmd.index("-np") + 1] == "3"
    hosts = (tmp_path / "mvapich_hosts").read_text().split()
    assert hosts == ["a", "b", "c"]


def test_slurm_cmd_construction():
    args = _args()
    runner = SlurmRunner(args, "x")
    cmd = runner.get_cmd({}, {"a": [0], "b": [0], "c": [0]})
    assert cmd[0] == "srun"
    assert cmd[cmd.index("-N") + 1] == "3"


def test_build_env():
    world = {"worker-0": [0, 1], "worker-1": [0, 1]}
    env = build_env(world, node_rank=1, master_addr="worker-0",
                    master_port=1234, base_env={})
    assert env["JAX_COORDINATOR_ADDRESS"] == "worker-0:1234"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["WORLD_SIZE"] == "4"
