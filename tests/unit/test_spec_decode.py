"""Speculative decoding for the paged serving engine: accept/rollback
units, proposer units, greedy token parity, and the compile contract.

Tier-1 (fast) CPU-sim coverage:
 - ``spec.greedy_accept`` ragged acceptance arithmetic: longest matching
   prefix + correction, eos INSIDE an accepted window, budget truncation,
   and the draft-model K-1 acceptance cap.
 - ``spec.NGramProposer`` prompt-lookup drafting (longest match first,
   most recent occurrence, fallback).
 - ``ServingEngine(spec_tokens=K)`` end-to-end: token parity with the
   non-speculative chunked path AND sequential ``generate`` across
   families (gpt2 + the newly paged bloom in tier-1; llama/opt slow),
   with both proposers (n-gram and a small same-family draft model).
 - The <= 3 compiled-programs contract: prefill + verify (n-gram), plus
   the draft rollout (draft model) — stable across serve calls and new
   request shapes.
 - Constructor validation: clear errors naming the missing hook / bad
   configuration combinations.

The Pallas K+1 verify-attention kernel's interpret-mode twin lives in
``test_decode_attention.py`` (slow lane); the decode-heavy bench lane is
``test_serving_bench.py`` (slow).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.inference.spec import NGramProposer, greedy_accept
from deepspeed_tpu.models import gpt2


# -------------------------------------------------------------- greedy_accept
def test_greedy_accept_longest_prefix_plus_correction():
    # window [pending, d1..d4]; target scores: d1, d2 match, d3 diverges
    window = [10, 11, 12, 13, 14]
    scored = [11, 12, 99, 7, 8]            # scored[2]=99 != d3=13
    emitted, accepted, finished = greedy_accept(window, scored, 4, None, 100)
    assert emitted == [11, 12, 99]         # 2 accepted drafts + correction
    assert accepted == 2 and not finished


def test_greedy_accept_no_match_still_progresses():
    emitted, accepted, finished = greedy_accept(
        [5, 1, 2], [7, 9, 9], 2, None, 100)
    assert emitted == [7] and accepted == 0 and not finished


def test_greedy_accept_full_match_and_draft_cap():
    window = [1, 2, 3, 4]
    scored = [2, 3, 4, 55]                 # every draft matches
    emitted, accepted, _ = greedy_accept(window, scored, 3, None, 100)
    # all K drafts + the target's continuation after the last one
    assert emitted == [2, 3, 4, 55] and accepted == 3
    # draft-model cap K-1: the K-th draft becomes the "correction" token,
    # acceptance stops one earlier so the draft cache stays
    # position-aligned (its K-th KV entry was never written)
    emitted, accepted, _ = greedy_accept(window, scored, 2, None, 100)
    assert emitted == [2, 3, 4] and accepted == 2


def test_greedy_accept_eos_inside_accepted_window():
    window = [1, 7, 8, 9]
    scored = [7, 8, 9, 5]                  # all accepted; 8 is eos
    emitted, accepted, finished = greedy_accept(window, scored, 3, 8, 100)
    assert emitted == [7, 8]               # truncated AT the eos
    assert finished


def test_greedy_accept_budget_truncation():
    window = [1, 7, 8, 9]
    scored = [7, 8, 9, 5]
    emitted, accepted, finished = greedy_accept(window, scored, 3, None, 2)
    assert emitted == [7, 8] and finished
    with pytest.raises(ValueError):
        greedy_accept(window, scored, 3, None, 0)
    with pytest.raises(ValueError):
        greedy_accept(window, scored[:-1], 3, None, 4)  # length mismatch


# -------------------------------------------------------------- NGramProposer
def test_ngram_proposer_prefers_longest_then_most_recent():
    p = NGramProposer(k=3, max_n=2, min_n=1)
    # tail 2-gram (7, 8) occurred earlier, followed by 5, 6
    ctx = [7, 8, 5, 6, 1, 7, 8]
    np.testing.assert_array_equal(p.propose(ctx), [5, 6, 1])
    # two occurrences of the tail: the most recent one wins
    ctx = [7, 8, 1, 0, 7, 8, 2, 3, 7, 8]
    np.testing.assert_array_equal(p.propose(ctx), [2, 3, 7])


def test_ngram_proposer_backoff_and_fallback():
    p = NGramProposer(k=2, max_n=3, min_n=1)
    # no 3/2-gram match, 1-gram (4) matched -> continuation [9, 4]
    np.testing.assert_array_equal(p.propose([4, 9, 4]), [9, 4])
    # nothing matches: repeat the final token
    np.testing.assert_array_equal(p.propose([1, 2, 3]), [3, 3])
    np.testing.assert_array_equal(p.propose([5]), [5, 5])
    with pytest.raises(ValueError):
        NGramProposer(k=0)
    with pytest.raises(ValueError):
        NGramProposer(k=2, max_n=1, min_n=2)


# --------------------------------------------------------------- end-to-end
@pytest.fixture(scope="module")
def tiny_engine():
    """One shared tiny-gpt2 engine: serve() drains its slots, so multiple
    ServingEngines stack on it safely (same pattern as
    test_paged_serving.py)."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _trace(cfg, n, seed=0, plen=(5, 30), max_new=(6, 24)):
    rng = np.random.default_rng(seed)
    return [Request(uid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        int(rng.integers(*plen))),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def test_spec_ngram_matches_plain_and_sequential(tiny_engine):
    """Acceptance: speculative (n-gram) outputs are token-identical to the
    non-speculative chunked path and to sequential generate, and the new
    stats fire."""
    engine, cfg = tiny_engine
    reqs = _trace(cfg, 6)
    plain = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                          prefill_chunk=16, prefill_batch=2,
                          debug_checks=True)
    spec = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=4,
                         debug_checks=True)
    res_p = plain.serve(reqs)
    res_s = spec.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res_p[r.uid], want,
                                      err_msg=f"plain uid {r.uid}")
        np.testing.assert_array_equal(res_s[r.uid], want,
                                      err_msg=f"spec uid {r.uid}")
    st = spec.stats()
    assert st["speculative"] == "ngram" and st["spec_tokens"] == 4
    assert st["spec_rounds"] > 0
    # every round drafts K tokens per participating decode slot
    assert st["drafted_tokens"] >= 4 * st["spec_rounds"]
    assert 0.0 <= st["acceptance_rate"] <= 1.0
    assert st["accepted_tokens"] <= st["drafted_tokens"]
    # speculative rounds replace single-token decode steps entirely
    assert st["decode_steps"] == 0
    # per-request latency percentiles (recorded for every finished request)
    assert st["requests_finished"] == len(reqs)
    assert st["ttft_p50_s"] > 0 and st["ttft_p95_s"] >= st["ttft_p50_s"]
    assert st["tpot_p50_s"] >= 0 and st["tpot_p95_s"] >= st["tpot_p50_s"]


def test_spec_draft_model_matches_sequential(tiny_engine):
    """A small same-family draft model proposes; greedy parity holds at
    whatever acceptance rate the draft earns, and the trace compiles
    exactly 3 programs (fused prefill + draft rollout + verify)."""
    engine, cfg = tiny_engine
    dcfg = gpt2.GPT2Config(vocab_size=cfg.vocab_size, max_seq_len=128,
                           num_layers=1, num_heads=2, hidden_size=32)
    spec = ServingEngine(engine, slots=3, max_seq_len=128, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=3,
                         draft=gpt2.build(dcfg), debug_checks=True)
    reqs = _trace(cfg, 5, seed=1)
    res = spec.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    assert spec.compile_count == 3, spec.compiled_programs
    kinds = sorted(p[0] for p in spec.compiled_programs)
    assert kinds == ["draft", "prefill", "verify"]
    assert spec.stats()["speculative"].startswith("draft:")


def test_spec_eos_inside_window_end_to_end(tiny_engine):
    """eos emitted mid-window truncates the accepted run exactly where
    sequential generate stops (back-fill semantics included)."""
    engine, cfg = tiny_engine
    reqs = _trace(cfg, 4, seed=2, max_new=(6, 16))
    probe = engine.generate(reqs[0].prompt[None, :], max_new_tokens=6)
    eos = int(probe[0, len(reqs[0].prompt) + 3])   # mid-stream token as eos
    spec = ServingEngine(engine, slots=3, max_seq_len=128, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=4,
                         debug_checks=True)
    res = spec.serve(reqs, eos_token_id=eos)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens,
                               eos_token_id=eos)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_spec_compile_contract_holds_across_traces(tiny_engine):
    """Acceptance: a full speculative trace compiles <= 3 programs —
    n-gram mode needs exactly 2 (prefill + verify), and new request shapes
    in a second serve call add none."""
    engine, cfg = tiny_engine
    spec = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=4,
                         debug_checks=True)
    spec.serve(_trace(cfg, 6, seed=3))
    assert spec.compile_count == 2, spec.compiled_programs
    assert sorted(p[0] for p in spec.compiled_programs) == \
        ["prefill", "verify"]
    spec.serve(_trace(cfg, 4, seed=4, plen=(30, 60), max_new=(2, 30)))
    assert spec.compile_count == 2, spec.compiled_programs
    assert spec.compile_count <= 3
    # no silent retraces inside the jitted fns either: the sentry counts
    # actual Python-body traces against the 2-program budget (and, with
    # debug_checks on above, would have raised at trace time)
    assert spec.sentry.traces == 2, spec.sentry.report()
    assert spec.sentry.retraces_observed == 0


def test_spec_preemption_pressure_keeps_parity(tiny_engine):
    """Speculative block demand (K+1-token windows) under an oversubscribed
    pool: preemption + recompute still yields exact greedy outputs."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=32, prefill_batch=2, num_blocks=12,
                        spec_tokens=4, debug_checks=True)
    rng = np.random.default_rng(5)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28) for i in range(5)]
    res = srv.serve(reqs)
    assert srv.preempted > 0, srv.stats()
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_spec_parity_bloom_family():
    """The newly ported bloom family (ALiBi, paged lengths/block_tables)
    serves under the engine — plain chunked AND speculative."""
    deepspeed_tpu.comm.reset_topology()
    from deepspeed_tpu.models import bloom

    cfg = bloom.BloomConfig.tiny(max_seq_len=64)
    engine = deepspeed_tpu.init_inference(
        bloom.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    rng = np.random.default_rng(6)
    reqs = [Request(uid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        int(rng.integers(4, 20))),
                    max_new_tokens=int(rng.integers(3, 10)))
            for i in range(4)]
    spec = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=3,
                         debug_checks=True)
    res = spec.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    assert spec.compile_count == 2


@pytest.mark.slow  # extra engine builds — gpt2/bloom cover tier-1
@pytest.mark.parametrize("family", ["llama", "opt"])
def test_spec_parity_other_families(family):
    """Per-row rope offsets (llama) / offset learned positions (opt) hold
    through the K+1 verify window."""
    deepspeed_tpu.comm.reset_topology()
    if family == "llama":
        from deepspeed_tpu.models import llama as m

        cfg = m.LlamaConfig.tiny()
    else:
        from deepspeed_tpu.models import opt as m

        cfg = m.OPTConfig.tiny()
    engine = deepspeed_tpu.init_inference(
        m.build(cfg), config={"dtype": "fp32",
                              "tensor_parallel": {"tp_size": 1}})
    spec = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                         prefill_chunk=16, prefill_batch=2, spec_tokens=3)
    rng = np.random.default_rng(7)
    reqs = [Request(uid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        int(rng.integers(4, 16))),
                    max_new_tokens=int(rng.integers(3, 10)))
            for i in range(4)]
    res = spec.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


# ---------------------------------------------------------------- validation
def test_ctor_validation_names_the_problem(tiny_engine):
    engine, cfg = tiny_engine
    with pytest.raises(ValueError, match="spec_tokens"):
        ServingEngine(engine, draft=object())   # draft without spec_tokens
    with pytest.raises(ValueError, match="chunked"):
        ServingEngine(engine, max_seq_len=64, prompt_buckets=(64,),
                      spec_tokens=4)            # bucketed mode can't verify
    with pytest.raises(ValueError, match="spec_tokens"):
        ServingEngine(engine, spec_tokens=-1)

    deepspeed_tpu.comm.reset_topology()
    from deepspeed_tpu.models import gptj

    legacy = deepspeed_tpu.init_inference(
        gptj.build(gptj.GPTJConfig.tiny()),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    # pre-lengths model: the error names the missing hook up front
    with pytest.raises(ValueError, match="supports_lengths"):
        ServingEngine(legacy)
    with pytest.raises(ValueError, match="supports_lengths"):
        ServingEngine(legacy, spec_tokens=4)


def test_ctor_validation_rejects_mismatched_draft_vocab(tiny_engine):
    engine, cfg = tiny_engine
    dcfg = gpt2.GPT2Config(vocab_size=cfg.vocab_size + 1, max_seq_len=128,
                           num_layers=1, num_heads=2, hidden_size=32)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(engine, spec_tokens=3, draft=gpt2.build(dcfg))


def test_plain_serving_latency_stats(tiny_engine):
    """TTFT/TPOT percentiles are recorded for the non-speculative path
    too (the satellite metric — not tied to speculation)."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2)
    srv.serve(_trace(cfg, 3, seed=8))
    st = srv.stats()
    assert st["requests_finished"] == 3
    assert st["ttft_p50_s"] > 0 and st["tpot_p95_s"] >= 0
    assert len(srv._latencies) == 3 and \
        all(m["new_tokens"] >= 1 for m in srv._latencies)
