"""Optimizer ops: the LAMB trust-ratio clamp (reference
``fused_lamb_cuda_kernel.cu`` clamps the per-leaf coefficient to
``[min_coeff, max_coeff]``) and the fused_lamb chain around it."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (fused_lamb,
                                          scale_by_clamped_trust_ratio)


def _apply(tx, updates, params):
    state = tx.init(params)
    out, _ = tx.update(updates, state, params)
    return out


def test_trust_ratio_clamps_low_edge():
    """||p||/||u|| below min_coeff scales by exactly min_coeff."""
    tx = scale_by_clamped_trust_ratio(0.01, 0.3)
    p = {"w": jnp.full((4,), 0.0005)}           # ||p|| = 0.001
    u = {"w": jnp.full((4,), 0.5)}              # ||u|| = 1.0
    out = _apply(tx, u, p)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(u["w"]) * 0.01, rtol=1e-6)


def test_trust_ratio_clamps_high_edge():
    """||p||/||u|| above max_coeff scales by exactly max_coeff."""
    tx = scale_by_clamped_trust_ratio(0.01, 0.3)
    p = {"w": jnp.full((4,), 50.0)}             # ||p|| = 100
    u = {"w": jnp.full((4,), 0.5)}              # ||u|| = 1.0
    out = _apply(tx, u, p)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(u["w"]) * 0.3, rtol=1e-6)


def test_trust_ratio_in_range_passes_through():
    tx = scale_by_clamped_trust_ratio(0.01, 0.3)
    p = {"w": jnp.full((4,), 0.05)}             # ||p|| = 0.1
    u = {"w": jnp.full((4,), 0.5)}              # ||u|| = 1.0
    out = _apply(tx, u, p)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(u["w"]) * 0.1, rtol=1e-6)


def test_trust_ratio_zero_norms_stay_neutral():
    """A zero param or update norm keeps ratio 1 (kernel semantics) — in
    particular a zero update must stay zero, not become NaN."""
    tx = scale_by_clamped_trust_ratio(0.01, 0.3)
    p = {"a": jnp.zeros((3,)), "b": jnp.ones((3,))}
    u = {"a": jnp.ones((3,)), "b": jnp.zeros((3,))}
    out = _apply(tx, u, p)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.ones(3))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.zeros(3))


def test_trust_ratio_validates_bounds_and_params():
    with pytest.raises(ValueError, match="min_coeff"):
        scale_by_clamped_trust_ratio(0.0, 0.3)
    with pytest.raises(ValueError, match="min_coeff"):
        scale_by_clamped_trust_ratio(0.5, 0.3)
    tx = scale_by_clamped_trust_ratio()
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.ones(2)}, tx.init({"w": jnp.ones(2)}), None)


def test_fused_lamb_step_applies_clamped_ratio():
    """End-to-end: with huge params the unclamped ratio would be enormous;
    the clamp caps the step at max_coeff * lr * adam_direction."""
    lr, max_coeff = 0.1, 0.3
    tx = fused_lamb(lr=lr, weight_decay=0.0, max_coeff=max_coeff)
    p = {"w": jnp.full((4,), 1e6)}
    g = {"w": jnp.full((4,), 1.0)}
    state = tx.init(p)
    upd, _ = tx.update(g, state, p)
    # first adam step normalizes to ~1 per element -> ||u|| ~ 2; ratio
    # ||p||/||u|| ~ 1e6 >> max_coeff -> clamped
    np.testing.assert_allclose(np.asarray(upd["w"]),
                               -lr * max_coeff * np.ones(4), rtol=1e-3)
    assert np.all(np.isfinite(np.asarray(upd["w"])))
