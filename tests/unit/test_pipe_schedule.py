"""Schedule math invariants (pure, device-free — reference keeps these pure
too: ``tests/unit/runtime/pipe/test_pipe_schedule.py``)."""

import pytest

from deepspeed_tpu.runtime.pipe import schedule as sched


@pytest.mark.parametrize("M,PP", [(1, 2), (4, 2), (4, 4), (8, 4), (3, 5)])
def test_every_microbatch_fwd_and_bwd_once(M, PP):
    arr = sched.schedule_arrays(M, PP)
    for s in range(PP):
        fwd_mbs = [m for m in arr["fwd"][:, s] if m >= 0]
        bwd_mbs = [m for m in arr["bwd"][:, s] if m >= 0]
        assert sorted(fwd_mbs) == list(range(M))
        assert sorted(bwd_mbs) == list(range(M))


@pytest.mark.parametrize("M,PP", [(4, 2), (8, 4), (3, 5)])
def test_backward_after_forward(M, PP):
    arr = sched.schedule_arrays(M, PP)
    T = arr["fwd"].shape[0]
    for s in range(PP):
        f_tick = {arr["fwd"][t, s]: t for t in range(T) if arr["fwd"][t, s] >= 0}
        b_tick = {arr["bwd"][t, s]: t for t in range(T) if arr["bwd"][t, s] >= 0}
        for m in range(M):
            assert b_tick[m] >= f_tick[m]
            if s == PP - 1:  # last stage: bwd fires the tick fwd completes
                assert b_tick[m] == f_tick[m]


@pytest.mark.parametrize("M,PP", [(8, 4), (3, 5)])
def test_stage_dependencies(M, PP):
    """Stage s cannot run fwd of m before stage s-1 did; symmetric for bwd."""
    arr = sched.schedule_arrays(M, PP)
    T = arr["fwd"].shape[0]
    f_tick = {(s, arr["fwd"][t, s]): t
              for t in range(T) for s in range(PP) if arr["fwd"][t, s] >= 0}
    b_tick = {(s, arr["bwd"][t, s]): t
              for t in range(T) for s in range(PP) if arr["bwd"][t, s] >= 0}
    for m in range(M):
        for s in range(1, PP):
            assert f_tick[(s, m)] > f_tick[(s - 1, m)]
            assert b_tick[(s - 1, m)] > b_tick[(s, m)]


def test_inflight_is_O_pp_not_O_m():
    """The 1F1B property: stash peak independent of microbatch count."""
    for pp in (2, 4, 8):
        p_small = sched.peak_inflight(0, pp, micro_batches=4 * pp)
        p_large = sched.peak_inflight(0, pp, micro_batches=16 * pp)
        assert p_large == p_small <= sched.stash_slots(pp)
        # later stages hold strictly fewer
        assert sched.peak_inflight(pp - 1, pp, 16 * pp) <= p_large


def test_ring_buffer_no_collisions():
    """A slot (mb mod 2*PP) is never overwritten while its backward is
    pending."""
    M, PP = 32, 4
    K = sched.stash_slots(PP)
    arr = sched.schedule_arrays(M, PP)
    T = arr["fwd"].shape[0]
    for s in range(PP):
        slots = {}
        for t in range(T):
            f = arr["fwd"][t, s]
            if f >= 0:
                slot = f % K
                assert slot not in slots, f"stage {s} slot {slot} clobbered"
                slots[slot] = f
            b = arr["bwd"][t, s]
            if b >= 0:
                del slots[b % K]


def test_tick_count_and_bubble():
    assert sched.num_ticks(8, 4) == 8 + 2 * 3
    assert sched.num_ticks(1, 1) == 1
    assert sched.bubble_fraction(8, 1) == 0.0
    assert 0 < sched.bubble_fraction(8, 4) < 1
    # more microbatches amortize the bubble
    assert sched.bubble_fraction(64, 4) < sched.bubble_fraction(8, 4)
