"""Schedule tests (model: reference tests/unit/runtime/pipe/test_pipe_schedule.py)."""

import pytest

from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                 InferenceSchedule,
                                                 LoadMicroBatch, OptimizerStep,
                                                 PipeSchedule, RecvActivation,
                                                 SendActivation, TrainSchedule)


def _flatten(sched):
    return [cmd for step in sched for cmd in step]


def test_pipe_schedule_bounds():
    with pytest.raises(AssertionError):
        TrainSchedule(micro_batches=1, stages=2, stage_id=2)


def test_inference_schedule_firststage():
    sched = InferenceSchedule(micro_batches=4, stages=3, stage_id=0)
    assert sched.num_pipe_buffers() == 2
    cmds = _flatten(sched)
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
    assert sum(isinstance(c, LoadMicroBatch) for c in cmds) == 4
    assert sum(isinstance(c, SendActivation) for c in cmds) == 4
    assert not any(isinstance(c, RecvActivation) for c in cmds)


def test_inference_schedule_laststage():
    sched = InferenceSchedule(micro_batches=4, stages=3, stage_id=2)
    cmds = _flatten(sched)
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
    assert sum(isinstance(c, RecvActivation) for c in cmds) == 4
    assert not any(isinstance(c, SendActivation) for c in cmds)


@pytest.mark.parametrize("micro_batches,stages", [(4, 2), (8, 4), (3, 3)])
def test_train_schedule_counts(micro_batches, stages):
    for stage in range(stages):
        sched = TrainSchedule(micro_batches=micro_batches, stages=stages,
                              stage_id=stage)
        cmds = _flatten(sched)
        assert sum(isinstance(c, ForwardPass) for c in cmds) == micro_batches
        assert sum(isinstance(c, BackwardPass) for c in cmds) == micro_batches
        assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1


def test_train_schedule_ordering():
    """Every microbatch's forward precedes its backward on each stage."""
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for step in sched:
        for cmd in step:
            if isinstance(cmd, ForwardPass):
                seen_fwd.add(cmd.buffer_id)
            if isinstance(cmd, BackwardPass):
                assert cmd.buffer_id in seen_fwd


def test_train_schedule_buffer_counts():
    # earlier stages need more in-flight buffers (1F1B property)
    s0 = TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    s3 = TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    assert s0.num_pipe_buffers() == 4
    assert s3.num_pipe_buffers() == 2


def test_schedule_steps_total():
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    assert len(list(sched.steps())) == 2 * (4 + 2 - 1)
