"""Property/fuzz tests for the flash kernels: random shapes, GQA ratios,
causal flags — every case must match the einsum reference in interpret
mode.  Each shape runs through BOTH the v2 fused path and (via the
DS_FLASH_V2=0 kill switch) the v1 two-kernel fallback, so padding/masking
edges are covered on both code paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import flash_attention, mha_reference

pytestmark = pytest.mark.slow

CASES = []
_rng = np.random.default_rng(20260731)
for _ in range(10):
    d = int(_rng.choice([32, 64, 128]))
    h_kv = int(_rng.choice([1, 2, 4]))
    rep = int(_rng.choice([1, 2, 4]))
    s = int(_rng.choice([64, 120, 200, 256, 384, 512]))
    causal = bool(_rng.choice([True, False]))
    CASES.append((2, h_kv * rep, h_kv, s, d, causal))


@pytest.mark.parametrize("kernel_ver", ["v2", "v1", "v3"])
@pytest.mark.parametrize("b,h,hkv,s,d,causal", CASES)
def test_fuzz_matches_reference(b, h, hkv, s, d, causal, kernel_ver,
                                monkeypatch):
    # pin ALL branches: an ambient DS_FLASH_V2/V3 from a debugging shell
    # must not silently collapse the matrix onto one path
    monkeypatch.setenv("DS_FLASH_V2", "1" if kernel_ver == "v2" else "0")
    monkeypatch.setenv("DS_FLASH_V3", "1" if kernel_ver == "v3" else "0")
    if kernel_ver == "v3":
        # the long-sequence path: force it down to fuzz-sized shapes so the
        # chunked-grid + compact-lse logic runs with several KV chunks
        monkeypatch.setenv("DS_FLASH_V3_MIN_KV", "1")
    ks = jax.random.split(jax.random.PRNGKey(hash((b, h, s, d)) % 2**31), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, hkv, s, d))
    v = jax.random.normal(ks[2], (b, hkv, s, d))
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, r, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=1e-3,
                                   rtol=1e-3, err_msg=f"d{name} {(s, d)}")
