"""Elasticity tests (reference ``tests/unit/elasticity/test_elastic.py``)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import (compute_elastic_config,
                                      get_compatible_accelerator_counts)
from deepspeed_tpu.elasticity.config import (ElasticityConfigError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.models import gpt2

BASE = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 2000,
        "micro_batch_sizes": [2, 4, 6],
        "min_gpus": 1,
        "max_gpus": 64,
        "version": 0.2,
    }
}


def test_compute_elastic_config_invariant():
    """Every valid world size realizes the SAME global batch."""
    batch, valid = compute_elastic_config(BASE)
    assert batch <= 2000 and len(valid) >= 8
    micros = BASE["elasticity"]["micro_batch_sizes"]
    for w in valid:
        assert any(batch % (m * w) == 0 for m in micros), (batch, w)


def test_world_size_validation_and_microbatch():
    valid_ws = 8
    batch, valid, micro = compute_elastic_config(
        BASE, world_size=valid_ws, return_microbatch=True)
    assert valid_ws in valid
    assert micro in BASE["elasticity"]["micro_batch_sizes"]
    assert batch % (micro * valid_ws) == 0

    bad = dict(BASE)
    bad["elasticity"] = dict(BASE["elasticity"], max_gpus=4)
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(bad, world_size=64)


def test_v02_model_parallel_step():
    cfg = {"elasticity": dict(BASE["elasticity"], model_parallel_size=2,
                              num_gpus_per_node=4)}
    batch, valid = compute_elastic_config(cfg)
    assert all(w % 8 == 0 for w in valid)  # multiples of 4*2


def test_prefer_larger_batch():
    small = {"elasticity": dict(BASE["elasticity"],
                                prefer_larger_batch=False)}
    b_large, _ = compute_elastic_config(BASE)
    b_small, _ = compute_elastic_config(small)
    assert b_small <= b_large


def test_invalid_configs():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": True,
                                               "micro_batch_sizes": []}})
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": False}})
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {
            "enabled": True, "version": 99}})


def test_engine_adopts_elastic_batch(eight_devices):
    """initialize() with elasticity derives the batch triple itself."""
    deepspeed_tpu.comm.reset_topology()
    cfg = {
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "elasticity": dict(BASE["elasticity"], max_gpus=16,
                           micro_batch_sizes=[1, 2, 4],
                           max_train_batch_size=64),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()), config=cfg)
    assert engine.train_batch_size() <= 64
    assert engine.train_batch_size() == (
        engine.train_micro_batch_size_per_gpu() *
        engine.gradient_accumulation_steps() * 8)
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
    _, m = engine.train_batch(batch)
    assert np.isfinite(m["loss"])


def test_engine_rejects_conflicting_batch_config(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    with pytest.raises(Exception, match="elastic"):
        deepspeed_tpu.initialize(
            model=gpt2.build(gpt2.GPT2Config.tiny()),
            config={
                "train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "elasticity": dict(BASE["elasticity"], max_gpus=16,
                                   micro_batch_sizes=[1, 2, 4],
                                   max_train_batch_size=64),
            })
