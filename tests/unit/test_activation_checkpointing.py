"""activation_checkpointing config block -> model remat selection.

Reference behavior: ``deepspeed.checkpointing.configure`` consumes the
``activation_checkpointing`` json block (checkpointing.py:749).  Here the
engine maps it onto the model's ``remat`` / ``remat_policy`` /
``remat_offload`` knobs (runtime/remat.py) before the first trace.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.remat import remat_policy


def _cfg(extra=None):
    c = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if extra:
        c.update(extra)
    return c


def _batch(vocab, engine, s=33):
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(
        0, vocab, size=(engine.train_batch_size(), s)).astype(np.int32)}


def _fresh_model():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    assert cfg.remat is False
    return cfg, gpt2.build(cfg)


def test_config_switches_remat_on():
    cfg, model = _fresh_model()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config=_cfg({"activation_checkpointing": {"enabled": True,
                                                  "policy": "dots"}}))
    assert cfg.remat is True
    assert cfg.remat_policy == "dots"
    _, m = engine.train_batch(_batch(cfg.vocab_size, engine))
    assert np.isfinite(float(m["loss"]))


def test_reference_keys_switch_remat_on():
    # a reference-style block with only partition_activations set must
    # still enable checkpointing (no silent no-op)
    cfg, model = _fresh_model()
    deepspeed_tpu.initialize(
        model=model,
        config=_cfg({"activation_checkpointing":
                     {"partition_activations": True}}))
    assert cfg.remat is True


def test_absent_block_leaves_model_alone():
    cfg, model = _fresh_model()
    deepspeed_tpu.initialize(model=model, config=_cfg())
    assert cfg.remat is False


def test_loss_parity_with_and_without_remat():
    cfg, model = _fresh_model()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=_cfg())
    batch = _batch(cfg.vocab_size, engine)
    _, m0 = engine.train_batch(batch)

    cfg2, model2 = _fresh_model()
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model2,
        config=_cfg({"activation_checkpointing": {"enabled": True}}))
    _, m1 = engine2.train_batch(batch)
    # remat changes scheduling, not math
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=2e-5)


def test_cpu_checkpointing_offload_single_device():
    # cpu_checkpointing -> host offload of saved residuals.  XLA's SPMD
    # partitioner rejects the placement custom-calls under a >1-device
    # mesh, so offload is honored single-device (the engine gates it);
    # here: model-level grad parity with the offload policy active.
    cfg = gpt2.GPT2Config.tiny()
    cfg.remat, cfg.remat_policy, cfg.remat_offload = True, "dots", True
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 33)).astype(np.int32)}
    g_off = jax.jit(jax.grad(
        lambda p: gpt2.loss_from_batch(cfg, p, batch)))(params)
    cfg.remat_offload = False
    g_dev = jax.jit(jax.grad(
        lambda p: gpt2.loss_from_batch(cfg, p, batch)))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_off),
                    jax.tree_util.tree_leaves(g_dev)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_cpu_checkpointing_gated_on_mesh():
    # on the 8-device sim the engine must keep remat but drop the offload
    cfg, model = _fresh_model()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config=_cfg({"activation_checkpointing": {"enabled": True,
                                                  "policy": "dots",
                                                  "cpu_checkpointing": True}}))
    assert cfg.remat is True
    assert cfg.remat_offload is False
    _, m = engine.train_batch(_batch(cfg.vocab_size, engine))
    assert np.isfinite(float(m["loss"]))


def test_policy_resolution():
    assert remat_policy(None) is None
    assert remat_policy("full") is None
    assert remat_policy("dots") is not None
    assert remat_policy("dots_flash") is not None
    assert remat_policy("dots", offload=True) is not None
    with pytest.raises(ValueError):
        remat_policy("bogus")
