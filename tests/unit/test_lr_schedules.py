"""LR schedule tests (model: reference tests/unit/runtime/test_lr_schedulers.py)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRScheduler, get_lr_schedule,
                                                lr_range_test, one_cycle,
                                                warmup_decay_lr, warmup_lr)


def test_warmup_lr_linear():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10,
                  warmup_type="linear")
    assert float(s(0)) == 0.0
    np.testing.assert_allclose(float(s(5)), 0.5)
    assert float(s(10)) == 1.0
    assert float(s(100)) == 1.0


def test_warmup_lr_log():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=100,
                  warmup_type="log")
    assert float(s(1)) == 0.0
    np.testing.assert_allclose(float(s(10)), 0.5, rtol=1e-5)
    assert float(s(100)) == 1.0


def test_warmup_decay():
    s = warmup_decay_lr(total_num_steps=100, warmup_min_lr=0.0, warmup_max_lr=1.0,
                        warmup_num_steps=10, warmup_type="linear")
    np.testing.assert_allclose(float(s(5)), 0.5)
    np.testing.assert_allclose(float(s(100)), 0.0, atol=1e-6)
    mid = float(s(55))
    assert 0.0 < mid < 1.0


def test_lr_range_test():
    s = lr_range_test(lr_range_test_min_lr=0.1, lr_range_test_step_size=10,
                      lr_range_test_step_rate=1.0)
    np.testing.assert_allclose(float(s(0)), 0.1)
    np.testing.assert_allclose(float(s(10)), 0.2)
    staircase = lr_range_test(lr_range_test_min_lr=0.1, lr_range_test_step_size=10,
                              lr_range_test_step_rate=1.0,
                              lr_range_test_staircase=True)
    np.testing.assert_allclose(float(staircase(9)), 0.1)
    np.testing.assert_allclose(float(staircase(10)), 0.2)


def test_one_cycle():
    s = one_cycle(cycle_min_lr=0.0, cycle_max_lr=1.0, cycle_first_step_size=10)
    np.testing.assert_allclose(float(s(0)), 0.0)
    np.testing.assert_allclose(float(s(10)), 1.0)
    np.testing.assert_allclose(float(s(20)), 0.0, atol=1e-6)


def test_get_lr_schedule_names():
    s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.5})
    assert s is not None
    with pytest.raises(ValueError):
        get_lr_schedule("Bogus", {})


def test_scheduler_object_api():
    s = LRScheduler(warmup_lr(warmup_max_lr=1.0, warmup_num_steps=10,
                              warmup_type="linear"))
    s.step()
    s.step()
    lr = s.get_lr()[0]
    assert 0 < lr < 1.0
    sd = s.state_dict()
    s2 = LRScheduler(warmup_lr())
    s2.load_state_dict(sd)
    assert s2.last_batch_iteration == s.last_batch_iteration
