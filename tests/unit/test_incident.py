"""Black-box flight recorder (telemetry/incident.py, ISSUE 18):
trigger classes, atomic bundle structure, deterministic replay
(token-exact, fp32 + kv8), the stall watchdog, and the windowed
burn-rate signal it polls.

The real-fleet lanes (crash -> bundle -> replay) run once on a
module-scoped 2-replica tiny fleet; everything else drives the
recorder/watchdog deterministically through injected clocks and
duck-typed fakes (the ``test_replica_router.py`` idiom)."""

import json
import os
import threading

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_incident_bundle)
from deepspeed_tpu.analysis.sentry import RetraceError
from deepspeed_tpu.autotuning.trace import TraceRecorder
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import FaultPlan, ReplicaRouter
from deepspeed_tpu.telemetry.incident import (MANIFEST_KEYS,
                                              TRIGGER_KINDS,
                                              IncidentRecorder,
                                              StallWatchdog,
                                              gpt2_model_meta, is_bundle,
                                              load_bundle, replay_bundle)
from deepspeed_tpu.telemetry.metrics import MetricsRegistry
from deepspeed_tpu.telemetry.slo import (SLOTracker, merged_slo_report,
                                         merged_windowed_burn)
from deepspeed_tpu.telemetry.trace import TraceTimeline


CFG = gpt2.GPT2Config.tiny(max_seq_len=128)


def _mk_fleet(n=2, quantize=None, threaded=False, **router_kw):
    deepspeed_tpu.comm.reset_topology()
    srvs, params = [], None
    for _ in range(n):
        eng = deepspeed_tpu.init_inference(
            gpt2.build(CFG),
            config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
            params=params)
        params = eng.params
        kw = dict(slots=2, max_seq_len=64, block_size=8,
                  prefill_chunk=16)
        if quantize:
            kw["quantize"] = quantize
        srvs.append(ServingEngine(eng, **kw))
    return ReplicaRouter(srvs, threaded=threaded, **router_kw)


def _reqs(n=6, seed=0):
    rng = np.random.default_rng(seed)
    return [Request(uid=f"u{i}",
                    prompt=rng.integers(0, CFG.vocab_size, 9 + i % 3),
                    max_new_tokens=4) for i in range(n)]


@pytest.fixture(scope="module")
def crashed(tmp_path_factory):
    """One recorded crash: 2-replica fleet, seeded kill at iteration 3,
    recorder armed -> (bundle_path, finished token streams)."""
    out = tmp_path_factory.mktemp("bundles")
    router = _mk_fleet()
    rec = IncidentRecorder(str(out), vocab=CFG.vocab_size,
                           model_meta=gpt2_model_meta(CFG))
    rec.attach(router)
    router.arm_faults(FaultPlan(
        seed=7, crashes=[{"replica": 1, "at_step": 3}]))
    handles = [router.submit(r) for r in _reqs()]
    while router.step():
        pass
    rec.detach()
    outs = {h.uid: h.tokens() for h in handles}
    assert len(rec.bundles) == 1
    return rec.bundles[0], outs


# ----------------------------------------------------------- bundle shape
def test_crash_dumps_audited_bundle(crashed):
    bpath, _ = crashed
    assert is_bundle(bpath)
    audit_incident_bundle(bpath)        # raises PagedStateError on rot
    b = load_bundle(bpath)
    m = b["manifest"]
    assert set(m) == MANIFEST_KEYS
    assert m["trigger"]["kind"] == "replica_fail"
    assert m["trigger"]["replica"] == 1
    assert m["trigger"]["step"] == 3
    assert m["trigger"]["exception_type"] == "SimulatedCrash"
    assert m["replayable"] is True
    # the capture carries every submitted request and the fault plan
    assert len(b["request_trace"]["entries"]) == 6
    assert b["fault_plan"]["crashes"] == [{"replica": 1, "at_step": 3}]
    assert b["fault_report"]["seed"] == 7
    # per-replica resolved configs rebuild engines (replay's input)
    assert len(b["replica_configs"]) == 2
    assert all("slots" in c for c in b["replica_configs"])


def test_bundle_files_match_manifest(crashed):
    bpath, _ = crashed
    m = load_bundle(bpath)["manifest"]
    assert sorted(m["files"]) == sorted(os.listdir(bpath))


def test_progress_snapshot_is_pre_incident(crashed):
    bpath, outs = crashed
    prog = load_bundle(bpath)["progress"]
    assert set(prog) == {f"u{i}" for i in range(6)}
    for uid, entry in prog.items():
        # dumped at the fail hook: a prefix of the final stream (KV
        # salvage + re-home never rewrites already-committed tokens)
        assert entry["tokens"] == outs[uid][:len(entry["tokens"])]


def test_partial_tmp_dir_is_never_a_bundle(tmp_path):
    tmp = tmp_path / ".incident-001-replica_fail.tmp-123"
    tmp.mkdir()
    (tmp / "router_stats.json").write_text("{}")
    assert not is_bundle(str(tmp))
    done = tmp_path / "incident-002-replica_fail"
    done.mkdir()
    (done / "manifest.json").write_text(json.dumps(
        {"bundle_format": "something-else", "schema_version": 1}))
    assert not is_bundle(str(done))
    (done / "manifest.json").write_text("not json {")
    assert not is_bundle(str(done))
    with pytest.raises(ValueError, match="not a complete"):
        load_bundle(str(done))


def test_audit_rejects_missing_file(crashed, tmp_path):
    import shutil
    bpath, _ = crashed
    broken = tmp_path / "broken"
    shutil.copytree(bpath, broken)
    os.unlink(broken / "request_trace.json")
    with pytest.raises(PagedStateError, match="bundle-file-list"):
        audit_incident_bundle(str(broken))


# ---------------------------------------------------------------- replay
def test_replay_reproduces_trigger_and_tokens(crashed):
    bpath, _ = crashed
    report = replay_bundle(bpath)
    assert report["reproduced"], report["mismatches"]
    assert report["trigger"]["kind"] == "replica_fail"
    assert report["trigger"]["replica"] == 1
    assert report["trigger"]["step"] == 3
    assert report["uids"] == 6


@pytest.mark.slow
def test_replay_kv8_lane(tmp_path):
    """A kv8 fleet's crash bundle replays bit-exactly too: the resolved
    configs carry ``quantize``, so the rebuilt fleet quantizes the same
    pools the original did."""
    router = _mk_fleet(quantize="kv8")
    rec = IncidentRecorder(str(tmp_path), vocab=CFG.vocab_size,
                           model_meta=gpt2_model_meta(CFG))
    rec.attach(router)
    router.arm_faults(FaultPlan(
        seed=11, crashes=[{"replica": 1, "at_step": 3}]))
    for r in _reqs(4, seed=1):
        router.submit(r)
    while router.step():
        pass
    rec.detach()
    assert len(rec.bundles) == 1
    assert load_bundle(rec.bundles[0])["replica_configs"][0][
        "quantize"] == "kv8"
    report = replay_bundle(rec.bundles[0])
    assert report["reproduced"], report["mismatches"]


def test_replay_refuses_non_replayable(tmp_path):
    router = _FakeRouter()
    rec = IncidentRecorder(str(tmp_path))   # no vocab => no capture
    path = rec.dump(router, "watchdog_stall", detail={"outstanding": 1},
                    stacks="--- thread MainThread\n", lockless=True)
    assert is_bundle(path)
    assert load_bundle(path)["manifest"]["replayable"] is False
    with pytest.raises(ValueError, match="not replayable"):
        replay_bundle(path)


# ------------------------------------------------------- trigger classes
class _FakeHandle:
    def __init__(self, uid, status="active", tokens=()):
        self.uid = uid
        self.status = status
        self._tokens = list(tokens)


class _FakeReplica:
    def __init__(self):
        self.iterations = 0
        self._c_checksum_fail = type("C", (), {"value": 0.0})()
        self._slo = None


class _FakeRouter:
    """Duck-typed dump/watchdog target: the recorder's gather sections
    degrade into ``gather_errors`` on whatever surface is missing — the
    bundle still lands atomically (partial beats none)."""

    def __init__(self, n=2):
        self.replicas = [_FakeReplica() for _ in range(n)]
        self.metrics = MetricsRegistry()
        self.timeline = TraceTimeline(capacity=64)
        self._handles = {}
        self._injector = None
        self._worker_errors = {}
        self._failed = set()
        self._drained = set()
        self._incident = None
        self._lock = threading.RLock()

    def _all_locks(self):
        return self._lock

    def stats(self):
        return {"replicas": len(self.replicas)}

    def resolved_config(self):
        return {"threaded": False}


def test_trigger_classification_per_exception(tmp_path):
    router = _FakeRouter()
    rec = IncidentRecorder(str(tmp_path), cooldown_s=0.0, max_bundles=8)
    rec.attach(router)
    rec.on_engine_error(router, 0, PagedStateError("x", "detail"))
    rec.on_engine_error(router, 1, RetraceError("budget", name="decode"))
    rec.on_replica_fail(router, 0, RuntimeError("worker died"))
    kinds = [os.path.basename(p).split("-", 2)[2] for p in rec.bundles]
    assert kinds == ["invariant_violation", "retrace", "replica_fail"]
    for p, kind in zip(rec.bundles, kinds):
        m = load_bundle(p)["manifest"]
        assert m["trigger"]["kind"] == kind
        assert kind in TRIGGER_KINDS
        audit_incident_bundle(p)
    assert int(router.metrics.counter(
        "serving_incident_bundles_total").value) == 3
    rec.detach()
    assert router._incident is None


def test_checksum_burst_trigger(tmp_path):
    t = {"now": 0.0}
    router = _FakeRouter()
    rec = IncidentRecorder(str(tmp_path), checksum_burst=8,
                           checksum_window_s=2.0, cooldown_s=0.0,
                           poll_min_s=0.0, clock=lambda: t["now"])
    rec.attach(router)
    rec.on_step_poll(router)            # baseline sample
    t["now"] = 0.5
    router.replicas[0]._c_checksum_fail.value = 5
    rec.on_step_poll(router)
    assert rec.bundles == []            # 5 < 8 in window
    t["now"] = 1.0
    router.replicas[1]._c_checksum_fail.value = 4
    rec.on_step_poll(router)            # 9 failures in 1s
    assert len(rec.bundles) == 1
    trig = load_bundle(rec.bundles[0])["manifest"]["trigger"]
    assert trig["kind"] == "checksum_burst"
    assert trig["detail"]["failures_in_window"] == 9


def test_burn_rate_breach_trigger(tmp_path):
    t = {"now": 100.0}
    clock = lambda: t["now"]  # noqa: E731
    router = _FakeRouter()
    tr = SLOTracker(MetricsRegistry(), clock=clock)
    router.replicas[0]._slo = tr
    rec = IncidentRecorder(str(tmp_path), burn_threshold=10.0,
                           burn_window_s=10.0, burn_min_requests=4,
                           cooldown_s=0.0, poll_min_s=0.0, clock=clock)
    rec.attach(router)
    for _ in range(4):                  # all miss the realtime TTFT SLO
        tr.observe("realtime", ttft_s=10.0, tpot_s=1.0)
    rec.on_step_poll(router)
    assert len(rec.bundles) == 1
    trig = load_bundle(rec.bundles[0])["manifest"]["trigger"]
    assert trig["kind"] == "burn_rate_breach"
    assert trig["detail"]["slo_class"] == "realtime"


def test_cooldown_and_max_bundles(tmp_path):
    t = {"now": 0.0}
    router = _FakeRouter()
    rec = IncidentRecorder(str(tmp_path), cooldown_s=30.0, max_bundles=2,
                           clock=lambda: t["now"])
    rec.attach(router)
    assert rec.dump(router, "replica_fail", replica=0) is not None
    assert rec.dump(router, "replica_fail", replica=0) is None  # cooldown
    t["now"] = 31.0
    assert rec.dump(router, "replica_fail", replica=0) is not None
    t["now"] = 62.0
    assert rec.dump(router, "replica_fail", replica=0) is None  # cap
    assert len(rec.bundles) == 2
    with pytest.raises(ValueError, match="unknown trigger kind"):
        rec.dump(router, "nonsense")


def test_foreign_recorder_attach_rejected(tmp_path):
    router = _FakeRouter()
    IncidentRecorder(str(tmp_path / "a")).attach(router)
    with pytest.raises(RuntimeError, match="already has an incident"):
        IncidentRecorder(str(tmp_path / "b")).attach(router)
    with pytest.raises(TypeError, match="no _incident hook"):
        IncidentRecorder(str(tmp_path / "c")).attach(object())


# --------------------------------------------------------------- watchdog
def test_watchdog_fires_once_on_stalled_fake():
    t = {"now": 0.0}
    router = _FakeRouter()
    router._handles["u0"] = (_FakeHandle("u0"), 0)
    wd = StallWatchdog(router, deadline_s=5.0, poll_s=0.1,
                       clock=lambda: t["now"])
    assert wd.check() is False          # fresh: nothing aged yet
    t["now"] = 6.0
    assert wd.check() is True           # aged + frozen past deadline
    assert wd.stalls == 1
    t["now"] = 12.0
    assert wd.check() is False          # once per episode
    assert wd.stalls == 1
    evs = [e for e in router.timeline.events()
           if e["name"] == "watchdog_stall"]
    assert len(evs) == 1 and evs[0]["args"]["outstanding"] == 1
    assert int(router.metrics.counter(
        "serving_watchdog_stalls_total").value) == 1


def test_watchdog_rearms_after_progress_and_stays_quiet_when_healthy():
    t = {"now": 0.0}
    router = _FakeRouter()
    h = _FakeHandle("u0")
    router._handles["u0"] = (h, 0)
    wd = StallWatchdog(router, deadline_s=5.0, clock=lambda: t["now"])
    wd.check()
    # healthy: progress every tick (tokens stream, iterations move)
    for i in range(1, 20):
        t["now"] = float(i)
        h._tokens.append(i)
        router.replicas[0].iterations += 1
        assert wd.check() is False
    assert wd.stalls == 0
    # then the fleet wedges: fires once the signal freezes past deadline
    t["now"] = 30.0
    assert wd.check() is True
    # progress resumes -> episode ends -> a later stall fires AGAIN
    t["now"] = 31.0
    h._tokens.append(99)
    assert wd.check() is False
    t["now"] = 40.0
    assert wd.check() is True
    assert wd.stalls == 2


def test_watchdog_dumps_stall_bundle_with_stacks(tmp_path):
    t = {"now": 0.0}
    router = _FakeRouter()
    router._handles["u0"] = (_FakeHandle("u0"), 0)
    rec = IncidentRecorder(str(tmp_path), clock=lambda: t["now"])
    rec.attach(router)
    wd = StallWatchdog(router, deadline_s=1.0, recorder=rec,
                       clock=lambda: t["now"])
    wd.check()
    t["now"] = 2.0
    assert wd.check() is True
    assert len(rec.bundles) == 1
    b = load_bundle(rec.bundles[0])
    assert b["manifest"]["trigger"]["kind"] == "watchdog_stall"
    assert "MainThread" in b["threads"]
    assert b["manifest"]["trigger"]["detail"]["outstanding"] == 1
    audit_incident_bundle(rec.bundles[0])


@pytest.mark.slow
def test_watchdog_silent_on_healthy_threaded_fleet():
    router = _mk_fleet(n=1, threaded=True)
    router.start()
    wd = StallWatchdog(router, deadline_s=15.0, poll_s=0.02).start()
    try:
        outs = router.serve(_reqs(4, seed=2))
        assert all(v is not None for v in outs.values())
        assert wd.stalls == 0
    finally:
        wd.stop()
        router.stop()


# ------------------------------------------------- supporting subsystems
def test_trace_recorder_chain_preserves_foreign_observer():
    calls = []

    class _Target:
        _submit_observer = None

    tgt = _Target()
    tgt._submit_observer = lambda req, **kw: calls.append(req.uid)
    tr = TraceRecorder(512)
    tr.attach(tgt, chain=True)
    req = Request(uid="c0", prompt=np.array([1, 2, 3]), max_new_tokens=2)
    tgt._submit_observer(req, priority=1, slo_class="batch")
    assert calls == ["c0"]                       # incumbent fired first
    assert tr.entries[0].uid == "c0"
    assert tr.entries[0].slo_class == "batch"
    tr.detach()
    tgt._submit_observer(req, priority=0)
    assert calls == ["c0", "c0"]                 # restored, not wrapped
    assert len(tr.entries) == 1
    # without chain=True a foreign observer still refuses loudly
    with pytest.raises(RuntimeError, match="chain=True"):
        TraceRecorder(512).attach(tgt)


def test_windowed_burn_decays_where_cumulative_never_does():
    t = {"now": 1000.0}
    tr = SLOTracker(MetricsRegistry(), window_s=60.0,
                    clock=lambda: t["now"])
    tr.observe("realtime", ttft_s=10.0, tpot_s=10.0)     # total miss
    w = tr.windowed_burn()["realtime"]
    assert w["ttft_burn_rate"] > 1.0 and w["requests"] == 1
    t["now"] += 30.0
    for _ in range(3):
        tr.observe("realtime", ttft_s=0.0, tpot_s=0.0)   # recovered
    t["now"] += 45.0            # the miss ages out of the window
    w = tr.windowed_burn()["realtime"]
    assert w["ttft_burn_rate"] == 0.0 and w["requests"] == 3
    # cumulative burn still remembers the miss (1/4 missed)
    cum = merged_slo_report([tr])["realtime"]["ttft_burn_rate"]
    assert cum > 0.0
    # empty window: no traffic, no burn, attainment undefined
    t["now"] += 120.0
    w = tr.windowed_burn()["realtime"]
    assert w["requests"] == 0 and w["ttft_attainment"] is None


def test_merged_windowed_burn_sums_trackers():
    t = {"now": 0.0}
    a = SLOTracker(MetricsRegistry(), window_s=60.0,
                   clock=lambda: t["now"])
    b = SLOTracker(MetricsRegistry(), window_s=60.0,
                   clock=lambda: t["now"])
    a.observe("batch", ttft_s=0.0, tpot_s=0.0)
    b.observe("batch", ttft_s=1e9, tpot_s=0.0)
    m = merged_windowed_burn([a, b])["batch"]
    assert m["requests"] == 2
    assert m["ttft_attainment"] == 0.5
