"""Telemetry schema stability: the observable dict surfaces —
``ServingEngine.stats()``, ``ReplicaRouter.stats()`` (+ per-replica
rows), and ``slo_report()`` — are PINNED key-for-key.

Dashboards, the bench JSON artifacts, and every PR 2–11 test read these
dicts by key; a silently dropped or renamed key is a breaking API change
nothing else would catch until a dashboard 404s.  The frozen sets below
are the contract: every pre-existing key must stay byte-identical
(the PR 12 acceptance criterion), and a NEW key is added here
deliberately, in the same PR that introduces it.
"""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import ReplicaRouter
import pytest


@pytest.fixture(scope="module")
def served():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16)
    router = ReplicaRouter([srv])
    rng = np.random.default_rng(0)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 9 + i),
                    max_new_tokens=3) for i in range(3)]
    router.serve(reqs)
    return srv, router


#: ServingEngine.stats() — the PR 2–11 key set, frozen byte-identical,
#: + PR 13's "config" (the round-trippable init_serving kwargs sub-dict
#: autotuner trials and bench JSONs reproduce engines from)
ENGINE_STATS_KEYS = frozenset({
    "acceptance_rate", "accepted_tokens", "admitted", "backend_compiles",
    "block_size", "blocks_in_use", "cancelled", "compile_budget",
    "compile_count", "config", "debug_checks", "decode_steps",
    "drafted_tokens", "engine_mode",
    "evicted", "free_blocks", "fused_iterations", "generated_tokens",
    "host_blocks",
    "host_blocks_in_use", "host_fence_waits", "host_pool_bytes",
    "invariant_checks_run",
    "handoffs",
    "iterations", "kv_dtype", "kv_pool_bytes", "kv_pool_bytes_per_chip",
    "kv_pool_shape", "kv_scale_bytes", "kv_sharded", "mode",
    "num_blocks", "nvme_blocks", "nvme_blocks_in_use", "nvme_loads",
    "nvme_spills", "prefetch_misses", "prefetch_wait_p50_s",
    "prefetch_wait_p95_s", "prefill_calls", "prefix_cache_entries",
    "prefix_cache_evictions", "prefix_cache_hit_rate",
    "prefix_hit_tokens", "prompt_tokens", "quantize", "queue_depth",
    "requests_finished", "resume_recompute_tokens", "retraces_observed",
    "role",
    "sampling", "spec_verifier", "logit_masks", "sampled_requests",
    "spec_draft_rejected",
    "sp", "resident_window_blocks", "context_window_slides",
    "sp_alltoall_bytes",
    "spec_rounds", "spec_tokens", "speculative", "swap_bytes", "swap_in",
    "swap_out", "tp_degree", "tpot_p50_s", "tpot_p95_s",
    "trace_capacity", "trace_events", "trace_events_dropped",
    "ttft_p50_s", "ttft_p95_s", "weight_quant",
})

#: stats()["config"] / resolved_config() — the ``init_serving`` kwargs
#: dict pinned key-for-key: bench JSONs, ``best_config.json``, and the
#: autotuner's trial records must stay mutually loadable across PRs
CONFIG_KEYS = frozenset({
    "block_size", "chunked_prefill", "debug_checks", "decode_steps",
    "engine_mode", "host_blocks",
    "max_seq_len", "ngram_max", "ngram_min", "num_blocks",
    "nvme_blocks", "nvme_high_watermark", "nvme_path", "peak_flops",
    "prefill_batch", "prefill_chunk", "prefix_caching", "prompt_buckets",
    "quantize", "resident_window_blocks", "role", "sampling", "shard_kv",
    "slo_targets", "slots", "sp", "spec_tokens", "spec_verifier",
    "logit_masks",
    "swap_batch", "topology", "trace_capacity",
})

#: ReplicaRouter.stats() — PR 11 keys + PR 12's "metrics_endpoint" +
#: PR 14's lock-sanitizer counters (0 when debug_checks is off) +
#: PR 15's failure/recovery surface ("failed" replica list, crash and
#: re-home counters, typed-failure count, pull retries, per-class sheds)
ROUTER_STATS_KEYS = frozenset({
    "busy_s", "drained", "drains", "failed", "generated_tokens",
    "giant_context", "handoffs",
    "kv_pull", "kv_pull_blocks", "kv_pull_bytes", "kv_pull_retries",
    "kv_pulls", "lock_order_checks",
    "lock_violations", "metrics_endpoint",
    "per_replica", "policy", "prefix_cache_hit_rate", "prompt_tokens",
    "readmits", "replica_failures", "replicas", "requests_failed",
    "requests_rehomed", "requests_shed", "routed_affinity",
    "routed_balance",
})

PER_REPLICA_KEYS = frozenset({
    "active", "admitted", "blocks_in_use", "busy_s", "compile_budget",
    "compile_count", "config", "drained", "generated_tokens",
    "prefix_cache_hit_rate", "queue_depth", "replica", "role",
})

#: slo_report() — one entry per class, each with this exact shape
SLO_CLASSES = frozenset({"realtime", "interactive", "standard", "batch",
                         "giant_context"})
SLO_CLASS_KEYS = frozenset({
    "objective", "requests",
    "ttft_attained", "ttft_attainment", "ttft_burn_rate",
    "ttft_p50_s", "ttft_p95_s", "ttft_target_s",
    "tpot_attained", "tpot_attainment", "tpot_burn_rate",
    "tpot_p50_s", "tpot_p95_s", "tpot_target_s",
})

#: windowed_burn() — PR 18's incident-trigger signal, per class
SLO_WINDOW_KEYS = frozenset({
    "objective", "requests", "window_s",
    "ttft_attainment", "ttft_burn_rate",
    "tpot_attainment", "tpot_burn_rate",
})

#: ReplicaRouter.resolved_config() — PR 18: incident bundles persist
#: this dict and ``graft-replay`` rebuilds the fleet by splatting it
#: back into the constructor, so its key set is a compatibility surface
#: between bundles dumped by one build and replayed by another
ROUTER_CONFIG_KEYS = frozenset({
    "policy", "kv_pull", "threaded", "debug_checks", "trace_capacity",
    "max_queue_depth", "shed_classes", "burn_threshold", "pull_retries",
    "pull_backoff_s", "pull_timeout_s", "max_rehomes",
    "giant_context_tokens",
})

#: incident bundle manifest.json — PR 18: the on-disk contract between
#: the flight recorder and ``graft-replay``/postmortem tooling; bundles
#: outlive the process that dumped them, so a key change here needs a
#: BUNDLE_SCHEMA_VERSION bump, not a silent rename
MANIFEST_KEYS = frozenset({
    "schema_version", "bundle_format", "trigger", "wall_time_s",
    "wall_time_iso", "step_clocks", "seeds", "git_describe", "files",
    "replicas", "model", "router_config", "replayable", "gather_errors",
})


def test_engine_stats_keys_pinned(served):
    srv, _ = served
    assert set(srv.stats().keys()) == ENGINE_STATS_KEYS


def test_engine_stats_keys_pinned_with_draft_pool_extras(served):
    """The only engine stats() extension point: a draft pool adds its
    two byte-accounting keys (PR 5 behavior, unchanged)."""
    srv, _ = served
    st = set(srv.stats().keys())
    assert "draft_pool_bytes" not in st       # no draft on this engine


def test_stats_config_keys_pinned_and_roundtrippable(served):
    """The config sub-dict is pinned key-for-key, JSON-able, and a
    fixpoint of ``init_serving``: rebuilding from it resolves to the
    identical dict (trials/benches reproduce engines from artifacts
    alone)."""
    import json

    srv, router = served
    cfg = srv.stats()["config"]
    assert set(cfg.keys()) == CONFIG_KEYS
    assert cfg == srv.resolved_config()
    json.dumps(cfg)
    assert router.stats()["per_replica"][0]["config"] == cfg
    deepspeed_tpu.comm.reset_topology()
    rebuilt = deepspeed_tpu.init_serving(
        gpt2.build(gpt2.GPT2Config.tiny(max_seq_len=128)),
        config={"dtype": "fp32"}, **cfg)
    assert rebuilt.resolved_config() == cfg


def test_router_stats_keys_pinned(served):
    _, router = served
    st = router.stats()
    assert set(st.keys()) == ROUTER_STATS_KEYS
    assert set(st["per_replica"][0].keys()) == PER_REPLICA_KEYS


def test_lock_metric_schema_pinned(served):
    """PR 14: the instrumented-lock telemetry surface — a debug_checks
    router registers ``serving_lock_wait_seconds{lock=fleet|replica}``
    and ``serving_lock_order_checks_total`` (GL008-compliant names),
    and ``stats()`` carries integer ``lock_order_checks`` /
    ``lock_violations``; with debug off the families are absent and the
    stats keys read 0."""
    srv, router = served
    st = router.stats()
    assert st["lock_order_checks"] == 0 and st["lock_violations"] == 0
    snap = router.metrics.snapshot()
    assert "serving_lock_wait_seconds" not in snap      # off: no family

    dbg = ReplicaRouter([ServingEngine(
        srv.engine, slots=2, max_seq_len=64, block_size=8,
        prefill_chunk=16, debug_checks=True)], debug_checks=True)
    snap = dbg.metrics.snapshot()
    fam = snap["serving_lock_wait_seconds"]
    assert fam["type"] == "histogram"
    assert sorted(s["labels"]["lock"] for s in fam["series"]) == \
        ["fleet", "replica"]
    assert snap["serving_lock_order_checks_total"]["type"] == "counter"
    st = dbg.stats()
    assert isinstance(st["lock_order_checks"], int)
    assert isinstance(st["lock_violations"], int)
    assert set(st.keys()) == ROUTER_STATS_KEYS


def test_slo_report_schema_pinned(served):
    srv, router = served
    for rep in (srv.slo_report(), router.slo_report()):
        assert set(rep.keys()) == SLO_CLASSES
        for cls, entry in rep.items():
            assert set(entry.keys()) == SLO_CLASS_KEYS, cls


def test_windowed_burn_schema_pinned(served):
    srv, _ = served
    win = srv._slo.windowed_burn()
    assert set(win.keys()) == SLO_CLASSES
    for cls, entry in win.items():
        assert set(entry.keys()) == SLO_WINDOW_KEYS, cls


def test_router_resolved_config_keys_pinned(served):
    _, router = served
    cfg = router.resolved_config()
    assert set(cfg.keys()) == ROUTER_CONFIG_KEYS
    import json

    json.dumps(cfg)
    srv = served[0]
    rebuilt = ReplicaRouter([ServingEngine(
        srv.engine, slots=2, max_seq_len=64, block_size=8,
        prefill_chunk=16)], **cfg)
    assert rebuilt.resolved_config() == cfg


def test_incident_manifest_keys_pinned():
    from deepspeed_tpu.telemetry import incident

    assert incident.MANIFEST_KEYS == MANIFEST_KEYS
    assert incident.BUNDLE_SCHEMA_VERSION == 1
    assert incident.TRIGGER_KINDS == (
        "replica_fail", "invariant_violation", "retrace",
        "checksum_burst", "burn_rate_breach", "watchdog_stall")


def test_flops_report_schema_pinned(served):
    srv, _ = served
    rep = srv.flops_report()
    assert set(rep.keys()) == {
        "programs", "program_calls", "model_flops_total",
        "flops_per_generated_token", "generated_tokens", "window_s",
        "peak_flops", "mfu", "busy_fractions"}
    for prog in rep["programs"].values():
        assert set(prog.keys()) == {
            "rows", "width", "flops_analytic", "flops_cost_analysis",
            "flops_per_call", "tokens_per_call", "source"}
