"""Fleet observability (PR 12): metrics federation, the live exposition
server, distributed request tracing with Chrome flow events, SLO
attainment accounting, and the FLOPs/MFU profiler.

Tier-1 (fast) coverage:
 - trace-ring overflow: dropped-event counter exact at capacity, and a
   wrapped ring still exports a schema-valid document.
 - exact-parity: tracing on/off changes NOTHING about scheduling
   (admission order, per-iteration step log, outputs).
 - merged multi-replica trace: unique pid lanes, globally sorted ts,
   matched B/E and s/f pairs, route flows closing on replica lanes, a
   cross-replica kv_pull flow crossing source->target lanes — all via
   ``validate_chrome_trace`` on the ONE merged document.
 - federation: ``replica=`` labels, the bucket-wise-summed
   ``replica="fleet"`` histograms, router registry under
   ``replica="router"``, and a training-style registry joining the same
   federation.
 - live server: /metrics parses as Prometheus text and agrees with the
   federated snapshot; /stats, /trace, /healthz, 404s; stop() releases.
 - SLO: per-class accounting with deterministic attainment edges
   (infinite vs zero targets), engine report <-> router merged report.
 - FLOPs: cost_analysis vs analytic within 10% on at least one family,
   profiling traces ZERO new programs (sentry counts + compile_count
   byte-identical before/after), MFU gauge + busy-fraction breakdown.
"""

import json
import urllib.request

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import ReplicaRouter, RouterSupervisor
from deepspeed_tpu.telemetry import (MetricsRegistry, TraceTimeline,
                                     federate, merge_chrome_traces,
                                     merge_histograms,
                                     validate_chrome_trace)
from deepspeed_tpu.telemetry.aggregate import FLEET_LABEL


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    spec = gpt2.build(cfg)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return spec, cfg, engine


def _mk_engine(spec, params):
    return deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        params=params)


_SRV_KW = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
               prefill_batch=2, debug_checks=True)


def _session_trace(cfg, n=9, sessions=3, seed=0, prefix_len=24,
                   max_new=8):
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, cfg.vocab_size, prefix_len)
                for _ in range(sessions)]
    return prefixes, [
        Request(uid=i,
                prompt=np.concatenate(
                    [prefixes[i % sessions],
                     rng.integers(0, cfg.vocab_size,
                                  int(rng.integers(3, 8)))]),
                max_new_tokens=max_new)
        for i in range(n)]


def _trace(cfg, n, seed=0, max_new=(2, 10)):
    rng = np.random.default_rng(seed)
    return [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(5, 30))),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


@pytest.fixture()
def pulled_fleet(tiny):
    """A 2-replica router that has served session traffic, drained its
    busier replica, and KV-pulled continuations onto the survivor — the
    full distributed-tracing story on one fixture."""
    spec, cfg, engine = tiny
    params = engine.params
    reps = [ServingEngine(_mk_engine(spec, params), host_blocks=32,
                          swap_batch=4, **_SRV_KW) for _ in range(2)]
    router = ReplicaRouter(reps, policy="affinity")
    prefixes, reqs = _session_trace(cfg, n=9, sessions=3)
    classes = ("realtime", "interactive", "standard")
    handles = [router.submit(r, slo_class=classes[i % 3])
               for i, r in enumerate(reqs)]
    while router.step():
        pass
    outs = {h.uid: h.result(timeout=0) for h in handles}
    rid0 = int(np.argmax([r._alloc.blocks_in_use or r.admitted
                          for r in reps]))
    router.drain(rid0)
    rng = np.random.default_rng(7)
    conts = [Request(uid=f"c{i}",
                     prompt=np.concatenate(
                         [prefixes[i % 3],
                          rng.integers(0, cfg.vocab_size, 4 + i)]),
                     max_new_tokens=4) for i in range(3)]
    router.serve(conts)
    yield router, reps, reqs, outs
    router.stop()


# -------------------------------------------------------- ring overflow
def test_trace_ring_overflow_dropped_counter_exact():
    t = TraceTimeline(capacity=8)
    for i in range(20):
        t.instant("e", i=i)
    assert len(t) == 8
    assert t.emitted == 20
    assert t.dropped == 12                      # exactly emitted - capacity
    # the retained window is the NEWEST events, still schema-valid
    doc = t.to_chrome()
    assert validate_chrome_trace(doc)["instant"] == 8
    assert doc["otherData"] == {"dropped_events": 12,
                                "emitted_events": 20}
    assert [e["args"]["i"] for e in doc["traceEvents"]
            if e["ph"] == "i"] == list(range(12, 20))


def test_engine_ring_overflow_counter_and_valid_export(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16, trace_capacity=16)
    srv.serve(_trace(cfg, 5, seed=1))
    st = srv.stats()
    assert st["trace_events"] == 16
    assert st["trace_events_dropped"] == srv.timeline.emitted - 16 > 0
    validate_chrome_trace(srv.timeline.to_chrome())   # wrapped ring: valid


def test_tracing_on_off_exact_scheduling_parity(tiny):
    """trace_capacity=0 vs a live ring: admission order, the per-
    iteration step log, and every output token are byte-identical —
    telemetry observes, never steers."""
    spec, cfg, engine = tiny
    reqs = _trace(cfg, 8, seed=2)
    logs = {}
    outs = {}
    for cap in (0, 16384):
        srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                            prefill_chunk=16, prefill_batch=2,
                            num_blocks=14, trace_capacity=cap)
        adm, step = [], []
        outs[cap] = srv.serve([Request(uid=r.uid, prompt=r.prompt,
                                       max_new_tokens=r.max_new_tokens)
                               for r in reqs],
                              admission_log=adm, step_log=step)
        logs[cap] = (adm, step)
    assert logs[0][0] == logs[16384][0]         # admission order
    assert logs[0][1] == logs[16384][1]         # per-iteration counters
    for r in reqs:
        assert np.array_equal(outs[0][r.uid], outs[16384][r.uid])


# ------------------------------------------------- merged trace + flows
def test_merged_trace_lanes_flows_and_validation(pulled_fleet):
    router, reps, reqs, _ = pulled_fleet
    assert router.stats()["kv_pulls"] > 0       # the fixture's premise
    doc = router.merged_trace()
    summary = validate_chrome_trace(doc)        # sorted ts, B/E + s/f
    assert summary["flow_starts"] == summary["flow_ends"] > 0
    # unique pid lanes: router 0, replicas 1..N, named by M metadata
    assert doc["otherData"]["sources"] == \
        {"router": 0, "replica 0": 1, "replica 1": 2}
    procs = {e["pid"]: e["args"]["name"]
             for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {0: "router", 1: "replica 0", 2: "replica 1"}
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    # every route flow starts on the ROUTER lane and finishes on a
    # REPLICA lane — the router->replica end-to-end linkage
    route_pairs = [v for v in by_id.values() if v[0]["name"] == "route"]
    assert route_pairs
    for pair in route_pairs:
        starts = [e for e in pair if e["ph"] == "s"]
        ends = [e for e in pair if e["ph"] == "f"]
        assert starts and ends
        assert all(e["pid"] == 0 for e in starts)
        assert all(e["pid"] in (1, 2) for e in ends)
    # the cross-replica kv_pull flow crosses source -> target lanes
    pull_pairs = [v for v in by_id.values() if v[0]["name"] == "kv_pull"]
    assert pull_pairs
    assert any(s["pid"] != f["pid"]
               for pair in pull_pairs
               for s in pair if s["ph"] == "s"
               for f in pair if f["ph"] == "f")
    # request spans still close exactly once per finished request across
    # the whole fleet document
    assert summary["request_spans"] >= len(reqs)


def test_merge_chrome_traces_rebases_epochs():
    clock = [0.0]
    t1 = TraceTimeline(capacity=8, clock=lambda: clock[0])
    clock[0] = 5.0                               # t2's epoch: +5s
    t2 = TraceTimeline(capacity=8, clock=lambda: clock[0])
    clock[0] = 5.5
    t2.instant("late")                           # local ts 0.5s
    t1.instant("later")                          # local ts 5.5s
    doc = merge_chrome_traces([("a", t1), ("b", t2)])
    body = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    # rebased onto the COMMON epoch: both events happened at wall-clock
    # 5.5s, so both land at ts 5.5e6 despite b's later epoch
    assert {e["name"] for e in body} == {"late", "later"}
    assert body[0]["ts"] == body[1]["ts"] == pytest.approx(5.5e6)
    validate_chrome_trace(doc)


def test_validator_flow_pairing_rules():
    def ev(**kw):
        base = {"name": "e", "ph": "i", "ts": 1.0, "pid": 0, "tid": 0}
        base.update(kw)
        return base

    # matched s/f passes and is counted
    s = validate_chrome_trace({"traceEvents": [
        ev(ph="s", id=7), ev(ph="f", id=7, ts=2.0)]})
    assert s["flow_starts"] == s["flow_ends"] == 1
    assert s["flow_unmatched"] == 0
    with pytest.raises(ValueError, match="without a preceding flow"):
        validate_chrome_trace({"traceEvents": [ev(ph="f", id=7)]},
                              strict_flows=True)
    with pytest.raises(ValueError, match="without a finish"):
        validate_chrome_trace({"traceEvents": [ev(ph="s", id=7)]},
                              strict_flows=True)
    with pytest.raises(ValueError, match="missing 'id'"):
        validate_chrome_trace({"traceEvents": [ev(ph="s")]})
    # a SINGLE ring legitimately holds half of a cross-ring flow — the
    # default is lenient (counts, doesn't raise); a merged document
    # (otherData.sources) auto-enables strict pairing
    lone = validate_chrome_trace({"traceEvents": [ev(ph="f", id=7)]})
    assert lone["flow_unmatched"] == 1
    with pytest.raises(ValueError, match="without a preceding flow"):
        validate_chrome_trace(
            {"traceEvents": [ev(ph="f", id=7)],
             "otherData": {"sources": {"router": 0}}})


def test_single_replica_ring_of_routed_fleet_still_validates(pulled_fleet):
    """dump_trace of ONE replica that served routed traffic holds only
    its halves of the route/kv_pull flows — per-ring validation must
    stay usable (the merged document is where pairing is enforced)."""
    router, reps, _, _ = pulled_fleet
    for tl in [router.timeline] + [r.timeline for r in reps]:
        summary = validate_chrome_trace(tl.to_chrome())
        assert summary["flow_starts"] + summary["flow_ends"] > 0 or True
    # and the merged doc pairs them all (strict via the sources marker)
    merged = validate_chrome_trace(router.merged_trace())
    assert merged["flow_unmatched"] == 0


# ------------------------------------------------------------ federation
def test_federation_labels_and_fleet_histogram_sum(pulled_fleet):
    router, reps, _, _ = pulled_fleet
    fed = router.fleet_registry()
    snap = fed.snapshot()
    fin = {tuple(sorted(s["labels"].items())): s["value"]
           for s in snap["serving_requests_finished_total"]["series"]}
    total = sum(int(r._c_finished.value) for r in reps)
    assert fin[(("replica", "0"),)] + fin[(("replica", "1"),)] == total
    # router families land under replica="router" (and keep their
    # serving_ namespace — lint GL008)
    routed = snap["serving_routed_affinity_total"]["series"]
    assert routed[0]["labels"] == {"replica": "router"}
    # the router's per-replica gauges KEEP their own replica label (no
    # re-labeling to "router", and gauges get no fleet aggregate)
    g = {s["labels"]["replica"]
         for s in snap["serving_replica_queue_depth"]["series"]}
    assert g == {"0", "1"}
    # fleet histograms: bucket-wise sum over the replica series
    ttft = snap["serving_ttft_seconds"]["series"]
    by_rep = {s["labels"]["replica"]: s for s in ttft}
    assert by_rep[FLEET_LABEL]["count"] == \
        by_rep["0"]["count"] + by_rep["1"]["count"] == total
    exp = [c0 + c1 for (_, c0), (_, c1) in
           zip(by_rep["0"]["buckets"], by_rep["1"]["buckets"])]
    assert [c for _, c in by_rep[FLEET_LABEL]["buckets"]] == exp
    # the federated exposition renders and parses
    assert 'serving_requests_finished_total{replica="0"}' in \
        fed.prometheus_text()


def test_federate_accepts_training_style_registry():
    """The training registry joins the same federation — federate() is
    source-agnostic (the PR 8 DeepSpeedEngine.metrics families merge
    beside the serving fleet's)."""
    train = MetricsRegistry()
    train.gauge("train_loss", "loss").set(2.5)
    train.counter("train_steps_total", "steps").inc(3)
    serve = MetricsRegistry()
    serve.counter("serving_requests_finished_total", "done").inc(7)
    fed = federate({"train": train, "0": serve})
    snap = fed.snapshot()
    assert snap["train_loss"]["series"][0] == \
        {"labels": {"replica": "train"}, "value": 2.5}
    assert snap["serving_requests_finished_total"]["series"][0] == \
        {"labels": {"replica": "0"}, "value": 7.0}


def test_merge_histograms_rejects_mismatched_buckets():
    from deepspeed_tpu.telemetry import Histogram

    a, b = Histogram((1.0, 2.0)), Histogram((1.0, 3.0))
    with pytest.raises(ValueError, match="different buckets"):
        merge_histograms([a, b])
    c = Histogram((1.0, 2.0))
    a.observe(0.5)
    c.observe(1.5)
    m = merge_histograms([a, c])
    assert m.count == 2 and m.counts == [1, 1, 0]


# ------------------------------------------------------------ live server
def test_metrics_server_endpoints_and_agreement(pulled_fleet):
    router, reps, _, _ = pulled_fleet
    server = router.start_metrics_server(port=0)
    assert router.start_metrics_server() is server     # idempotent
    url = f"http://127.0.0.1:{server.port}"
    assert router.stats()["metrics_endpoint"] == url
    text = urllib.request.urlopen(url + "/metrics").read().decode()
    # quiesced fleet: the scrape IS the federated exposition
    assert text == router.fleet_metrics_text()
    assert 'serving_kv_pulls_total{replica="router"}' in text
    stats = json.loads(urllib.request.urlopen(url + "/stats").read())
    assert set(stats) == {"stats", "slo", "metrics"}
    assert stats["stats"]["kv_pulls"] == router.stats()["kv_pulls"]
    trace = json.loads(urllib.request.urlopen(url + "/trace").read())
    validate_chrome_trace(trace)
    assert urllib.request.urlopen(url + "/healthz").read() == b"ok"
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope")
    assert e.value.code == 404
    router.stop()
    assert router.metrics_server is None


def test_supervisor_owns_metrics_server(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, **_SRV_KW)
    router = ReplicaRouter([srv])
    sup = RouterSupervisor(router, lambda: [0], metrics_port=0)
    assert sup.metrics_server is router.metrics_server is not None
    url = f"http://127.0.0.1:{sup.metrics_server.port}"
    urllib.request.urlopen(url + "/healthz")
    sup.close()
    assert router.metrics_server is None
    # a server the OPERATOR attached outlives supervision: close() only
    # stops what the supervisor itself started
    operator_server = router.start_metrics_server(port=0)
    sup2 = RouterSupervisor(router, lambda: [0])
    sup2.close()
    assert router.metrics_server is operator_server
    router.stop()


def test_flops_bucketed_prefill_billed_per_width(tiny):
    """Bucketed mode compiles one prefill program per bucket width —
    each is costed and call-counted at ITS width (a single last-built
    entry would mis-bill every other bucket by the width ratio)."""
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prompt_buckets=(16, 64), prefill_batch=2)
    rng = np.random.default_rng(8)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, n),
                    max_new_tokens=3)
            for i, n in enumerate((8, 12, 40, 48))]
    srv.serve(reqs)
    assert set(srv._prefill_calls_by_width) == {16, 64}
    rep = srv.flops_report()
    entries = {f for f in rep["programs"] if f.startswith("prefill")}
    assert entries == {"prefill[w16]", "prefill[w64]"}
    w16 = rep["programs"]["prefill[w16]"]
    w64 = rep["programs"]["prefill[w64]"]
    assert w16["width"] == 16 and w64["width"] == 64
    assert w64["flops_per_call"] > w16["flops_per_call"]
    assert rep["program_calls"]["prefill[w16]"] == \
        srv._prefill_calls_by_width[16]
    # the total is the per-width sum, not any single width x all calls
    expected = (w16["flops_per_call"] * srv._prefill_calls_by_width[16] +
                w64["flops_per_call"] * srv._prefill_calls_by_width[64] +
                rep["programs"]["decode"]["flops_per_call"] *
                srv.decode_steps)
    assert rep["model_flops_total"] == pytest.approx(expected)


def test_training_engine_start_metrics_server():
    """The PR 8 training registry joins the live exposition layer."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    engine.train_batch({"input_ids": rng.integers(
        0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)})
    server = engine.start_metrics_server(port=0)
    try:
        url = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "train_loss" in text and "train_wall_clock_ms" in text
        snap = json.loads(urllib.request.urlopen(url + "/stats").read())
        assert snap["train_global_steps"]["series"][0]["value"] == 1
    finally:
        server.stop()


# ------------------------------------------------------------------- SLO
def test_slo_accounting_deterministic_attainment(tiny):
    spec, cfg, engine = tiny
    # infinite targets attain everything; zero targets attain nothing —
    # the two burn-rate edges are exact regardless of box speed
    targets = {"realtime": {"ttft_s": 1e9, "tpot_s": 1e9,
                            "objective": 0.99},
               "batch": {"ttft_s": 0.0, "tpot_s": 0.0, "objective": 0.9}}
    srv = ServingEngine(engine, slo_targets=targets, **_SRV_KW)
    reqs = _trace(cfg, 6, seed=3)
    for i, r in enumerate(reqs):
        srv.submit(r, slo_class="realtime" if i % 2 else "batch")
    while srv.step():
        pass
    rep = srv.slo_report()
    # PR 19 added the giant_context class (pinned in test_schema_stability)
    assert set(rep) == {"realtime", "interactive", "standard", "batch",
                        "giant_context"}
    rt, bt = rep["realtime"], rep["batch"]
    assert rt["requests"] == bt["requests"] == 3
    assert rt["ttft_attainment"] == rt["tpot_attainment"] == 1.0
    assert rt["ttft_burn_rate"] == 0.0
    assert bt["ttft_attainment"] == 0.0
    # attainment 0 burns the whole budget: 1 / (1 - 0.9) = 10x
    assert bt["ttft_burn_rate"] == pytest.approx(10.0)
    assert bt["ttft_p95_s"] >= bt["ttft_p50_s"] > 0
    # classes with no traffic stay in the report with a stable shape
    assert rep["interactive"]["requests"] == 0
    assert rep["interactive"]["ttft_attainment"] is None
    # the cells live on the engine registry (scrapes see them)
    snap = srv.metrics.snapshot()
    series = {s["labels"]["slo_class"]: s["count"]
              for s in snap["serving_slo_ttft_seconds"]["series"]}
    assert series["realtime"] == 3 and series["batch"] == 3


def test_unclassified_requests_account_as_standard(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, **_SRV_KW)
    srv.serve(_trace(cfg, 4, seed=4))
    rep = srv.slo_report()
    assert rep["standard"]["requests"] == 4
    assert sum(c["requests"] for c in rep.values()) == 4


def test_router_slo_report_merges_replicas(pulled_fleet):
    router, reps, reqs, _ = pulled_fleet
    fleet = router.slo_report()
    per_engine = [r.slo_report() for r in reps]
    for cls in fleet:
        assert fleet[cls]["requests"] == sum(
            p[cls]["requests"] for p in per_engine)
        assert fleet[cls]["ttft_attained"] == sum(
            p[cls]["ttft_attained"] for p in per_engine)
    assert sum(c["requests"] for c in fleet.values()) >= len(reqs)


# ----------------------------------------------------------------- FLOPs
def test_flops_profiler_agreement_and_zero_new_programs(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, **_SRV_KW)
    srv.serve(_trace(cfg, 5, seed=5))
    compiles0 = srv.compile_count
    traces0 = srv.sentry.traces
    rep = srv.flops_report(peak_flops=1e12)
    # profiling lowers raw bodies only: ZERO new compiled programs and
    # ZERO sentry-visible traces (the acceptance contract)
    assert srv.compile_count == compiles0
    assert srv.sentry.traces == traces0
    assert srv.stats()["retraces_observed"] == 0
    assert set(rep["programs"]) == {"prefill", "decode"}
    rel = {f: abs(p["flops_per_call"] - p["flops_analytic"])
           / p["flops_analytic"] for f, p in rep["programs"].items()}
    # cost_analysis and the analytic model agree within 10% on at least
    # one family (acceptance criterion; on CPU both land well inside)
    assert min(rel.values()) <= 0.10, rel
    assert all(p["flops_cost_analysis"] is not None
               for p in rep["programs"].values())
    assert rep["model_flops_total"] > 0
    assert rep["flops_per_generated_token"] > 0
    assert rep["mfu"] == pytest.approx(
        rep["model_flops_total"] / (rep["window_s"] * 1e12))
    bf = rep["busy_fractions"]
    assert set(bf) == {"window_s", "prefill", "decode", "swap", "idle"}
    assert 0 < bf["prefill"] + bf["decode"] <= 1.0 + 1e-9
    assert bf["idle"] >= 0.0
    # the metric cells landed on the engine registry
    snap = srv.metrics.snapshot()
    assert snap["serving_model_flops_total"]["series"][0]["value"] == \
        rep["model_flops_total"]
    phases = {s["labels"]["phase"]
              for s in snap["serving_busy_fraction"]["series"]}
    assert phases == {"prefill", "decode", "swap", "idle"}


def test_flops_profiler_speculative_and_swap_families(tiny):
    spec, cfg, engine = tiny
    srv = ServingEngine(engine, spec_tokens=3, host_blocks=24,
                        swap_batch=4, num_blocks=10, **_SRV_KW)
    srv.serve(_trace(cfg, 6, seed=6, max_new=(4, 10)))
    rep = srv.flops_report()
    # verify replaces decode; the swap pair is data movement (no entry)
    assert "verify" in rep["programs"] and "decode" not in rep["programs"]
    assert "kv_demote" not in rep["programs"]
    rel = {f: abs(p["flops_per_call"] - p["flops_analytic"])
           / p["flops_analytic"] for f, p in rep["programs"].items()}
    assert min(rel.values()) <= 0.10, rel
    # mfu stays None without a peak_flops denominator
    assert rep["mfu"] is None and rep["peak_flops"] is None
    if srv.stats()["swap_out"]:
        assert rep["busy_fractions"]["swap"] > 0.0


def test_flops_layer_scan_correction(tiny):
    """gpt2 scans its layers — raw cost_analysis counts the loop body
    once; the profiler's reconciliation scales it by num_layers (the
    correction that puts the two sources within 10%)."""
    spec, cfg, engine = tiny
    assert cfg.num_layers > 1
    srv = ServingEngine(engine, **_SRV_KW)
    srv.serve(_trace(cfg, 3, seed=7))
    rep = srv.flops_report()
    dec = rep["programs"]["decode"]
    assert dec["source"] == "cost_analysis+layer_scan"
    # the corrected value exceeds the raw single-body report
    assert dec["flops_per_call"] > dec["flops_cost_analysis"]
