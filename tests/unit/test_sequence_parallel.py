"""Sequence parallelism: ring + Ulysses attention vs plain attention.

The reference has no SP at v0.8.2 (SURVEY §5.7) — this is the capability
upgrade the TPU build adds; numerics are checked against the einsum reference
on the 8-device CPU-sim mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import comm
from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.parallel import sequence as seq
from deepspeed_tpu.parallel.topology import MeshTopology

pytestmark = pytest.mark.slow  # Pallas interpret mode: minutes on CPU


def make_qkv(key, b=2, h=4, s=32, d=8, hkv=None):
    hkv = hkv or h
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.float32)
    return q, k, v


def mesh_for(sp, tp=1):
    topo = MeshTopology(sp=sp, tp=tp)
    comm.set_topology(topo)
    return topo.mesh


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_attention_matches_reference(causal, sp):
    mesh = mesh_for(sp)
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    out = seq.ring_attention(q, k, v, causal=causal, mesh=mesh)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gqa():
    mesh = mesh_for(4)
    q, k, v = make_qkv(jax.random.PRNGKey(1), h=4, hkv=2)
    out = seq.ring_attention(q, k, v, causal=True, mesh=mesh)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_attention_grads(sp):
    mesh = mesh_for(sp)
    q, k, v = make_qkv(jax.random.PRNGKey(2), b=1, h=2, s=16, d=8)

    def ring_loss(q, k, v):
        o = seq.ring_attention(q, k, v, causal=True, mesh=mesh)
        return jnp.sum(o * o)

    def ref_loss(q, k, v):
        o = mha_reference(q, k, v, causal=True)
        return jnp.sum(o * o)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("hkv", [2, 1])
def test_ulysses_gqa(hkv):
    # hkv=2, sp=2: kv rides the all-to-all un-repeated; hkv=1: repeat fallback
    mesh = mesh_for(2)
    q, k, v = make_qkv(jax.random.PRNGKey(7), h=4, hkv=hkv)
    out = seq.ulysses_attention(q, k, v, causal=True, mesh=mesh)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_uneven_block_chunk():
    # chunk c=24 is not a multiple of 128: gcd-based block picking must cope
    mesh = mesh_for(4)
    q, k, v = make_qkv(jax.random.PRNGKey(8), s=96)
    out = seq.ring_attention(q, k, v, causal=True, mesh=mesh)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    mesh = mesh_for(4)
    q, k, v = make_qkv(jax.random.PRNGKey(3))
    out = seq.ulysses_attention(q, k, v, causal=causal, mesh=mesh)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads():
    mesh = mesh_for(2)
    q, k, v = make_qkv(jax.random.PRNGKey(4), b=1, h=2, s=16, d=8)

    def uly_loss(q, k, v):
        o = seq.ulysses_attention(q, k, v, causal=True, mesh=mesh)
        return jnp.sum(o * o)

    def ref_loss(q, k, v):
        o = mha_reference(q, k, v, causal=True)
        return jnp.sum(o * o)

    g = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_dispatcher_picks_ulysses_then_ring():
    mesh = mesh_for(4)
    # h=4, tp=1 -> 4 % 4 == 0 -> ulysses ok; h=2 -> ring fallback
    q, k, v = make_qkv(jax.random.PRNGKey(5), h=2, s=32)
    out = seq.sequence_parallel_attention(q, k, v, causal=True, mesh=mesh)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_engine_sp_loss_matches_dp(impl):
    """Tiny llama trained with mesh sp=2 matches the pure-DP loss curve."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    def run(mesh_cfg):
        deepspeed_tpu.comm.reset_topology()
        cfg = llama.LlamaConfig.tiny()
        cfg.sp_impl = impl
        cfg.use_flash = False  # sp path overrides; dense path for baseline
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=llama.build(cfg),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": mesh_cfg,
            })
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(3):
            # seq 65 -> model sees 64 after the label shift: divisible by
            # sp=2 so the SP attention path really runs (33 would fall back)
            batch = {"input_ids": rng.integers(
                0, 512, size=(engine.train_batch_size(), 65)).astype(np.int32)}
            _, m = engine.train_batch(batch)
            losses.append(m["loss"])
        return losses

    # same dp world (= same global batch/data) with the spare axis as tp vs sp
    base = run({"dp": 4, "tp": 2})
    sp = run({"dp": 4, "sp": 2})
    np.testing.assert_allclose(base, sp, rtol=2e-4, atol=1e-5)


def test_sp_with_tp_combined():
    mesh = mesh_for(sp=2, tp=2)  # dp=2 absorbs the rest
    q, k, v = make_qkv(jax.random.PRNGKey(6), b=2, h=4, s=32, d=8)
    for impl in ("ring", "ulysses"):
        out = seq.sequence_parallel_attention(q, k, v, causal=True, impl=impl,
                                              mesh=mesh)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=impl)


# --------------------------------------------------------------------------
# zigzag ring attention: balanced causal work (VERDICT r3 item 7; the SP
# capability SURVEY 5.7 requires beyond the reference)
# --------------------------------------------------------------------------
def test_zigzag_order_roundtrip():
    zig, inv = seq.zigzag_order(32, 4)
    x = np.arange(32)
    assert (x[zig][inv] == x).all()
    # device 0 gets blocks 0 and 7, device 3 gets blocks 3 and 4
    assert list(zig[:8]) == list(range(4)) + list(range(28, 32))
    assert list(zig[-8:]) == list(range(12, 20))


@pytest.mark.parametrize("sp,hkv", [(2, 4), (4, 4), (4, 2)])
def test_zigzag_matches_reference_with_grads(sp, hkv):
    mesh = mesh_for(sp)
    q, k, v = make_qkv(jax.random.PRNGKey(3), h=4, s=16 * sp, d=8, hkv=hkv)

    def zz_loss(q, k, v):
        o = seq.ring_attention(q, k, v, causal=True, mesh=mesh, zigzag=True)
        return jnp.sum(o * o)

    def ref_loss(q, k, v):
        o = mha_reference(q, k, v, causal=True)
        return jnp.sum(o * o)

    o = seq.ring_attention(q, k, v, causal=True, mesh=mesh, zigzag=True)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(mha_reference(q, k, v, causal=True)),
                               rtol=2e-5, atol=2e-5)
    g_zz = jax.grad(zz_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_zz, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{nm}")


def test_zigzag_work_balance(monkeypatch):
    """Every issued forward kernel must be a HALF-chunk pair and the step
    count must be 3 + 2*(sp-1) — i.e. no discarded full-chunk kernels (the
    contiguous path issues sp full-chunk kernels, ~2x the causal FLOPs)."""
    from deepspeed_tpu.ops import flash_attention as fa_mod

    sp = 4
    mesh = mesh_for(sp)
    s = 16 * sp
    ch = (s // sp) // 2
    q, k, v = make_qkv(jax.random.PRNGKey(4), h=4, s=s, d=8)

    calls = []
    real_fwd = fa_mod._fwd

    def counting_fwd(qf, kf, vf, *a, **kw):
        calls.append((qf.shape[1], kf.shape[1]))
        return real_fwd(qf, kf, vf, *a, **kw)

    monkeypatch.setattr(fa_mod, "_fwd", counting_fwd)
    seq.ring_attention(q, k, v, causal=True, mesh=mesh, zigzag=True)
    assert len(calls) == 3 + 2 * (sp - 1), calls
    assert all(c == (ch, ch) for c in calls), calls

    calls.clear()
    seq.ring_attention(q, k, v, causal=True, mesh=mesh, zigzag=False)
    c_full = s // sp
    assert len(calls) == sp, calls
    assert all(c == (c_full, c_full) for c in calls), calls
