"""1-bit optimizer + compressed-collective tests (reference
``tests/onebit/test_onebit.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.comm.compressed import (CompressedBackend,
                                                   compressed_allreduce,
                                                   error_shapes)
from deepspeed_tpu.runtime.fp16.onebit import (onebit_adam, onebit_lamb,
                                               zero_one_adam)


# --------------------------------------------------------- compressed comm
def test_compressed_allreduce_error_feedback(eight_devices):
    """Per-step the reduction is lossy, but error feedback makes the
    *accumulated* sum track the true accumulated mean (the 1-bit Adam
    convergence argument)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.topology import MeshTopology

    mesh = MeshTopology(dp=8).mesh
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 64)).astype(np.float32)
    true_mean = x.mean(axis=0)
    we_s, se_s = error_shapes((64,), 8)

    @jax.jit
    def step(xs, wes, ses):
        def body(xw, wew, sew):
            m, nwe, nse = compressed_allreduce(xw[0], wew[0], sew[0], "dp")
            return m[None], nwe[None], nse[None]

        return shard_map(body, mesh=mesh, in_specs=(P("dp"),) * 3,
                         out_specs=(P("dp"),) * 3)(xs, wes, ses)

    with mesh:
        xs = jax.device_put(x)
        wes = jnp.zeros((8,) + we_s, jnp.float32)
        ses = jnp.zeros((8,) + se_s, jnp.float32)
        acc = np.zeros(64, np.float32)
        # same x re-reduced: accumulated compressed means -> k * true_mean,
        # with error decaying ~1/k (bounded error feedback)
        errs_at = {}
        for k in range(1, 101):
            mean, wes, ses = step(xs, wes, ses)
            acc += np.asarray(mean)[0]
            if k in (10, 100):
                errs_at[k] = np.abs(acc / k - true_mean).max()
    assert errs_at[100] < 0.06
    assert errs_at[100] < errs_at[10] / 2  # 1/k decay, not bias
    # single-shot error is visibly nonzero (it IS lossy)
    one, _, _ = step(xs, jnp.zeros_like(wes), jnp.zeros_like(ses))
    assert np.abs(np.asarray(one)[0] - true_mean).max() > 1e-4


def test_compressed_backend_stateful(eight_devices):
    from deepspeed_tpu.parallel.topology import MeshTopology

    deepspeed_tpu.comm.reset_topology()
    mesh = MeshTopology(dp=8).mesh
    be = CompressedBackend(mesh, "dp")
    x = np.random.default_rng(1).normal(size=(8, 32)).astype(np.float32)
    with mesh:
        acc = np.zeros(32, np.float32)
        k = 80
        for _ in range(k):
            acc += np.asarray(be.allreduce("g", jnp.asarray(x)))[0]
    np.testing.assert_allclose(acc / k, x.mean(0), atol=0.1)


# ------------------------------------------------------------- optimizers
def _rosenbrockish_losses(tx, steps=260):
    def loss(p):
        return jnp.sum((p["a"] - 1.0) ** 2) + 2.0 * jnp.sum(p["b"] ** 2)

    params = {"a": jnp.zeros(8), "b": jnp.ones(4)}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        l, g = jax.value_and_grad(loss)(params)
        upd, state = tx.update(g, state, params)
        return optax_apply(params, upd), state, l

    import optax

    def optax_apply(p, u):
        return optax.apply_updates(p, u)

    ls = []
    for _ in range(steps):
        params, state, l = step(params, state)
        ls.append(float(l))
    return ls


@pytest.mark.parametrize("maker", [
    lambda: onebit_adam(lr=3e-2, freeze_step=50),
    lambda: onebit_lamb(lr=0.5, freeze_step=50),  # trust-ratio clamps to
    # [0.01, 0.3] x lr, so the effective step needs a larger base lr
    lambda: zero_one_adam(lr=3e-2, var_freeze_step=50),
])
def test_onebit_optimizers_converge(maker):
    ls = _rosenbrockish_losses(maker())
    assert ls[-1] < 1e-2 * ls[0], (ls[0], ls[-1])
    # loss keeps improving after entering the compressed stage
    assert min(ls[55:]) < min(ls[:50])


def test_variance_freezes_after_freeze_step():
    from deepspeed_tpu.runtime.fp16.onebit import scale_by_onebit_adam

    tx = scale_by_onebit_adam(freeze_step=3)
    params = {"w": jnp.ones(4)}
    state = tx.init(params)
    # non-uniform grads: a uniform tensor quantizes exactly (zero residual)
    g = {"w": jnp.asarray([0.1, 0.5, -0.7, 0.2])}
    for _ in range(3):
        _, state = tx.update(g, state, params)
    v_frozen = np.asarray(state.v["w"]).copy()
    g2 = {"w": jnp.full(4, 100.0)}  # huge grad: v would change if learning
    _, state = tx.update(g2, state, params)
    np.testing.assert_array_equal(np.asarray(state.v["w"]), v_frozen)
    # error feedback active in compressed stage
    assert np.abs(np.asarray(state.error["w"])).max() > 0


def test_engine_accepts_onebit_adam():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-3, "freeze_step": 2}}})
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(5):
        batch = {"input_ids": rng.integers(
            0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
