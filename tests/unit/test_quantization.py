"""INT8 weight-quantization tests (reference csrc/quantization + the
DS-Inference GroupQuantizer INT8 path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops import quantization as quant


@pytest.mark.parametrize("symmetric", [True, False])
def test_quantize_roundtrip_error_bounded(symmetric):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 128)).astype(np.float32)
    rec = quant.quantize(jnp.asarray(w), group_size=32, symmetric=symmetric)
    assert rec["q"].dtype == jnp.int8 and rec["q"].shape == w.shape
    assert rec["scale"].shape == (64, 4)
    deq = np.asarray(quant.dequantize(rec, jnp.float32))
    # max error <= scale/2 per group
    scale = np.asarray(rec["scale"])
    bound = np.repeat(scale, 32, axis=-1) * 0.51
    assert (np.abs(deq - w) <= bound).all()


def test_quantize_pytree_filters():
    params = {"big": jnp.ones((64, 128)), "small": jnp.ones((4, 4)),
              "ints": jnp.ones((64, 128), jnp.int32),
              "odd": jnp.ones((64, 100)),  # 100 % 64 != 0
              "stacked_norms": jnp.ones((12, 768)),  # [L, d] — not a matrix
              "stacked_weights": jnp.ones((12, 768, 256))}
    q = quant.quantize_pytree(params, group_size=64, min_size=1024)
    assert quant.is_quantized(q["big"])
    assert not quant.is_quantized(q["small"])
    assert not quant.is_quantized(q["ints"])
    assert not quant.is_quantized(q["odd"])
    # weight-only: stacked per-layer norm scales/biases ([L, d], small
    # penultimate dim) must NOT be quantized; stacked matrices must
    assert not quant.is_quantized(q["stacked_norms"])
    assert quant.is_quantized(q["stacked_weights"])
    assert quant.quantized_nbytes(q) < sum(
        x.nbytes for x in params.values())


def test_int8_inference_close_to_fp():
    """init_inference with quant.enabled generates the same tokens as the
    full-precision engine on a tiny model (reference INT8 kernel-inject
    rows of the inference sweep)."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(vocab_size=512)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))

    model = gpt2.build(cfg)
    e_fp = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32"}, params=params)
    deepspeed_tpu.comm.reset_topology()
    e_q = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32",
                       "quant": {"enabled": True, "group_size": 16}},
        params=params)

    ids = np.random.default_rng(1).integers(0, 512, (2, 8)).astype(np.int32)
    out_fp = e_fp.generate(ids, max_new_tokens=8)
    out_q = e_q.generate(ids, max_new_tokens=8)
    # int8 weight error may flip a late token once distributions diverge;
    # the first few decoded tokens must agree
    np.testing.assert_array_equal(out_fp[:, :11], out_q[:, :11])

    logits_fp = np.asarray(e_fp({"input_ids": ids}))
    logits_q = np.asarray(e_q({"input_ids": ids}))
    assert np.abs(logits_fp - logits_q).max() < 0.15


def test_int8_inference_opt_quant_aware():
    """OPT is quant_aware: INT8 weights dequantize per layer at point of use
    (the path the OPT-6.7B single-chip serving config needs — a whole-tree
    dequant would double peak memory)."""
    from deepspeed_tpu.models import opt

    deepspeed_tpu.comm.reset_topology()
    cfg = opt.OPTConfig.tiny(vocab_size=512)
    model = opt.build(cfg)
    assert model.quant_aware
    params = opt.init_params(cfg, jax.random.PRNGKey(0))
    e_fp = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32"}, params=params)
    deepspeed_tpu.comm.reset_topology()
    e_q = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32",
                       "quant": {"enabled": True, "group_size": 16}},
        params=params)
    ids = np.random.default_rng(2).integers(0, 512, (2, 8)).astype(np.int32)
    out_fp = e_fp.generate(ids, max_new_tokens=8)
    out_q = e_q.generate(ids, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out_fp)[:, :11],
                                  np.asarray(out_q)[:, :11])
