"""Config tests (model: reference tests/unit/runtime/test_ds_config_dict.py)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.zero.config import (DeepSpeedZeroConfig,
                                               OffloadDeviceEnum, ZeroStageEnum)


def test_batch_triple_full():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2
        },
        world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triple_derive_gas():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
        world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triple_derive_micro():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "gradient_accumulation_steps": 2}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_triple_derive_train():
    cfg = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 2},
        world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triple_only_train():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 2
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triple_inconsistent():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            {
                "train_batch_size": 32,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 4
            },
            world_size=4)


def test_batch_triple_none_given():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=4)


def test_precision_flags():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True}},
                          world_size=1)
    assert cfg.bfloat16_enabled and not cfg.fp16_enabled
    assert cfg.precision_dtype == "bfloat16"
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {
                "train_batch_size": 8,
                "bf16": {"enabled": True},
                "fp16": {"enabled": True}
            },
            world_size=1)


def test_fp16_scaler_args():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 8,
            "fp16": {
                "enabled": True,
                "initial_scale_power": 8,
                "loss_scale_window": 500,
                "hysteresis": 4
            }
        },
        world_size=1)
    assert cfg.fp16_enabled
    assert cfg.dynamic_loss_scale_args["init_scale"] == 256
    assert cfg.dynamic_loss_scale_args["scale_window"] == 500
    assert cfg.dynamic_loss_scale_args["delayed_shift"] == 4


def test_zero_config_defaults():
    z = DeepSpeedZeroConfig()
    assert z.stage == ZeroStageEnum.disabled
    assert z.overlap_comm is False
    z3 = DeepSpeedZeroConfig(stage=3)
    assert z3.overlap_comm is True


def test_zero_config_aliases():
    z = DeepSpeedZeroConfig(stage=3, stage3_max_live_parameters=1000,
                            stage3_prefetch_bucket_size=500)
    assert z.max_live_parameters == 1000
    assert z.prefetch_bucket_size == 500


def test_zero_offload_configs():
    z = DeepSpeedZeroConfig(
        stage=2, offload_optimizer={"device": "cpu", "pin_memory": True})
    assert z.offload_optimizer.device == OffloadDeviceEnum.cpu
    assert z.offload_optimizer.pin_memory


def test_zero_deprecated_cpu_offload():
    z = DeepSpeedZeroConfig(stage=2, cpu_offload=True)
    assert z.offload_optimizer is not None
    assert z.offload_optimizer.device == OffloadDeviceEnum.cpu


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3, "betas": [0.9, 0.99]}},
            "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}}
        },
        world_size=1)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 1e-3
    assert cfg.scheduler_name == "WarmupLR"


def test_checkpoint_tag_validation_modes():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 8, "checkpoint": {"tag_validation": "Fail"}},
        world_size=1)
    assert cfg.checkpoint_config.tag_validation == "Fail"
    with pytest.raises(Exception):
        DeepSpeedConfig(
            {"train_batch_size": 8, "checkpoint": {"tag_validation": "bogus"}},
            world_size=1)


def test_duplicate_json_keys(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_gradient_clipping():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "gradient_clipping": 1.0},
                          world_size=1)
    assert cfg.gradient_clipping == 1.0


def test_auto_values_resolve():
    """Reference "auto" contract: batch keys derive, ZeRO buckets use the
    hidden-size formulas when known, unknown autos fall to defaults."""
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              resolve_auto_config)

    pd = {"train_batch_size": 16,
          "train_micro_batch_size_per_gpu": "auto",
          "gradient_accumulation_steps": "auto",
          "gradient_clipping": "auto",
          "zero_optimization": {"stage": 3, "reduce_bucket_size": "auto",
                                "stage3_prefetch_bucket_size": "auto",
                                "stage3_param_persistence_threshold": "auto"}}
    cfg = DeepSpeedConfig(dict(pd), world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.train_micro_batch_size_per_gpu * 4 * \
        cfg.gradient_accumulation_steps == 16
    # schema defaults applied for the dropped autos
    assert cfg.zero_config.param_persistence_threshold == int(1e5)

    resolved = resolve_auto_config(pd, hidden_size=768)
    z = resolved["zero_optimization"]
    assert z["reduce_bucket_size"] == 768 * 768
    assert z["stage3_param_persistence_threshold"] == 7680
