"""Config tests (model: reference tests/unit/runtime/test_ds_config_dict.py)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.zero.config import (DeepSpeedZeroConfig,
                                               OffloadDeviceEnum, ZeroStageEnum)


def test_batch_triple_full():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2
        },
        world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triple_derive_gas():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
        world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triple_derive_micro():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "gradient_accumulation_steps": 2}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_triple_derive_train():
    cfg = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 2},
        world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triple_only_train():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 2
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triple_inconsistent():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            {
                "train_batch_size": 32,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 4
            },
            world_size=4)


def test_batch_triple_none_given():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=4)


def test_precision_flags():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True}},
                          world_size=1)
    assert cfg.bfloat16_enabled and not cfg.fp16_enabled
    assert cfg.precision_dtype == "bfloat16"
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {
                "train_batch_size": 8,
                "bf16": {"enabled": True},
                "fp16": {"enabled": True}
            },
            world_size=1)


def test_fp16_scaler_args():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 8,
            "fp16": {
                "enabled": True,
                "initial_scale_power": 8,
                "loss_scale_window": 500,
                "hysteresis": 4
            }
        },
        world_size=1)
    assert cfg.fp16_enabled
    assert cfg.dynamic_loss_scale_args["init_scale"] == 256
    assert cfg.dynamic_loss_scale_args["scale_window"] == 500
    assert cfg.dynamic_loss_scale_args["delayed_shift"] == 4


def test_zero_config_defaults():
    z = DeepSpeedZeroConfig()
    assert z.stage == ZeroStageEnum.disabled
    assert z.overlap_comm is False
    z3 = DeepSpeedZeroConfig(stage=3)
    assert z3.overlap_comm is True


def test_zero_config_aliases():
    z = DeepSpeedZeroConfig(stage=3, stage3_max_live_parameters=1000,
                            stage3_prefetch_bucket_size=500)
    assert z.max_live_parameters == 1000
    assert z.prefetch_bucket_size == 500


def test_zero_offload_configs():
    z = DeepSpeedZeroConfig(
        stage=2, offload_optimizer={"device": "cpu", "pin_memory": True})
    assert z.offload_optimizer.device == OffloadDeviceEnum.cpu
    assert z.offload_optimizer.pin_memory


def test_zero_deprecated_cpu_offload():
    z = DeepSpeedZeroConfig(stage=2, cpu_offload=True)
    assert z.offload_optimizer is not None
    assert z.offload_optimizer.device == OffloadDeviceEnum.cpu


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3, "betas": [0.9, 0.99]}},
            "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}}
        },
        world_size=1)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 1e-3
    assert cfg.scheduler_name == "WarmupLR"


def test_checkpoint_tag_validation_modes():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 8, "checkpoint": {"tag_validation": "Fail"}},
        world_size=1)
    assert cfg.checkpoint_config.tag_validation == "Fail"
    with pytest.raises(Exception):
        DeepSpeedConfig(
            {"train_batch_size": 8, "checkpoint": {"tag_validation": "bogus"}},
            world_size=1)


def test_duplicate_json_keys(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_gradient_clipping():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "gradient_clipping": 1.0},
                          world_size=1)
    assert cfg.gradient_clipping == 1.0


def test_auto_values_resolve():
    """Reference "auto" contract: batch keys derive, ZeRO buckets use the
    hidden-size formulas when known, unknown autos fall to defaults."""
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              resolve_auto_config)

    pd = {"train_batch_size": 16,
          "train_micro_batch_size_per_gpu": "auto",
          "gradient_accumulation_steps": "auto",
          "gradient_clipping": "auto",
          "zero_optimization": {"stage": 3, "reduce_bucket_size": "auto",
                                "stage3_prefetch_bucket_size": "auto",
                                "stage3_param_persistence_threshold": "auto"}}
    cfg = DeepSpeedConfig(dict(pd), world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.train_micro_batch_size_per_gpu * 4 * \
        cfg.gradient_accumulation_steps == 16
    # schema defaults applied for the dropped autos
    assert cfg.zero_config.param_persistence_threshold == int(1e5)

    resolved = resolve_auto_config(pd, hidden_size=768)
    z = resolved["zero_optimization"]
    assert z["reduce_bucket_size"] == 768 * 768
    assert z["stage3_param_persistence_threshold"] == 7680


# ------------------------------------------------------ round-3 API shims
def test_nebula_config_block_parses():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    c = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "nebula": {"enabled": True,
                   "persistent_storage_path": "/tmp/nebula",
                   "persistent_time_interval": 50}})
    assert c.nebula_config.enabled
    assert c.nebula_config.persistent_storage_path == "/tmp/nebula"


def test_on_device_meta_init():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    model = gpt2.build(gpt2.GPT2Config.tiny())
    with deepspeed_tpu.OnDevice(dtype=jax.numpy.bfloat16, device="meta"):
        abstract = model.init(jax.random.PRNGKey(0))
    leaves = jax.tree_util.tree_leaves(abstract)
    assert all(isinstance(x, jax.ShapeDtypeStruct) for x in leaves)
    assert all(x.dtype == jax.numpy.bfloat16 for x in leaves
               if jax.numpy.issubdtype(x.dtype, jax.numpy.floating))
    # outside the context: real arrays again
    real = model.init(jax.random.PRNGKey(0))
    assert all(hasattr(x, "addressable_shards") or hasattr(x, "devices")
               for x in jax.tree_util.tree_leaves(real))


def test_nebula_path_is_default_checkpoint_root(tmp_path):
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "nebula": {"enabled": True,
                           "persistent_storage_path": str(tmp_path / "neb")}})
    rng = np.random.default_rng(0)
    engine.train_batch({"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)})
    path = engine.save_checkpoint()  # no dir: nebula root is the default
    assert str(tmp_path / "neb") in path
    engine.load_checkpoint()
    # without any default configured, a missing dir raises clearly
    deepspeed_tpu.comm.reset_topology()
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    with pytest.raises(ValueError, match="persistent_storage_path"):
        engine2.save_checkpoint()


def test_on_device_rejects_non_meta():
    import deepspeed_tpu

    with pytest.raises(ValueError, match="only 'meta'"):
        deepspeed_tpu.OnDevice(device="cpu")


def test_engine_init_unaffected_by_on_device():
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    with deepspeed_tpu.OnDevice(device="meta"):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=gpt2.build(cfg),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        rng = np.random.default_rng(0)
        _, m = engine.train_batch({"input_ids": rng.integers(
            0, cfg.vocab_size,
            size=(engine.train_batch_size(), 17)).astype(np.int32)})
    assert np.isfinite(float(m["loss"]))


def test_nebula_load_path_redirects_loads(tmp_path):
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    warm = str(tmp_path / "warmstart")
    fresh = str(tmp_path / "fresh")
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    e1, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(e1.train_batch_size(), 17)).astype(np.int32)}
    e1.train_batch(batch)
    e1.save_checkpoint(warm)

    deepspeed_tpu.comm.reset_topology()
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "nebula": {"enabled": True,
                           "persistent_storage_path": fresh,
                           "load_path": warm}})
    path, _ = e2.load_checkpoint()  # no dir: load_path wins for loads
    assert path is not None and warm in path
    assert e2.global_steps == 1
    # saves still go to the persistent root
    out = e2.save_checkpoint()
    assert fresh in out


def test_pipeline_and_profiler_init_immune_to_on_device():
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.profiling.flops_profiler import get_model_profile

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    batch = {"input_ids": np.zeros((1, 17), np.int32)}
    with deepspeed_tpu.OnDevice(device="meta"):
        prof = get_model_profile(model, batch)
        deepspeed_tpu.comm.reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=gpt2.build(cfg),
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "mesh": {"pp": 2, "tp": 2}})
        rng = np.random.default_rng(0)
        _, m = engine.train_batch({"input_ids": rng.integers(
            0, cfg.vocab_size,
            size=(engine.train_batch_size(), 17)).astype(np.int32)})
    assert prof["params"] > 0
    assert np.isfinite(float(m["loss"]))


def test_monitor_config_round_trips_optional_wandb_fields():
    """WandbConfig's group/team are Optional[str] (they were annotated
    bare ``str`` with a ``None`` default, which pydantic v2 accepts as a
    default but rejects on explicit assignment — so a dumped config could
    not be re-validated)."""
    from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                              get_monitor_config)

    cfg = get_monitor_config(
        {"wandb": {"enabled": True, "group": None, "team": None}})
    assert cfg.wandb.group is None and cfg.wandb.team is None
    # round-trip: dump -> re-validate, explicit Nones included
    again = DeepSpeedMonitorConfig(**cfg.model_dump())
    assert again.model_dump() == cfg.model_dump()

    named = get_monitor_config(
        {"wandb": {"enabled": True, "group": "g1", "team": "t1"},
         "csv_monitor": {"enabled": True, "output_path": "/tmp/x"}})
    rt = DeepSpeedMonitorConfig(**named.model_dump())
    assert rt.wandb.group == "g1" and rt.wandb.team == "t1"
    assert rt.csv_monitor.enabled and rt.enabled
