"""Autotuner tests (reference ``tests/unit/autotuning/test_autotuning.py``)."""

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner
from deepspeed_tpu.models import gpt2


def _factory():
    return gpt2.build(gpt2.GPT2Config.tiny())


def _batch(global_batch, seq_len):
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(
        0, 512, (global_batch, seq_len + 1)).astype(np.int32)}


def _base(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "start_profile_step": 1,
                       "end_profile_step": 2,
                       "num_tuning_micro_batch_sizes": 2,
                       "tuner_type": "gridsearch",
                       "zero_stages": [0, 1]},
    }
    cfg.update(over)
    return cfg


def test_experiment_space():
    at = Autotuner(_factory, _base(), _batch, seq_len=16)
    space = at.experiment_space()
    # 2 stages x 2 micro batches
    assert len(space) == 4
    stages = {e["zero_optimization"]["stage"] for e in space}
    micros = {e["train_micro_batch_size_per_gpu"] for e in space}
    assert stages == {0, 1} and micros == {1, 2}


def test_tune_picks_feasible_best(tmp_path, eight_devices):
    base = _base()
    base["autotuning"]["results_dir"] = str(tmp_path / "results")
    at = Autotuner(_factory, base, _batch, seq_len=16)
    best = at.tune()
    assert best["feasible"] and best["throughput"] > 0
    assert len(at.results) == 4
    assert all("config" in r for r in at.results)
    assert best["throughput"] == max(
        r["throughput"] for r in at.results if r.get("feasible"))
    import json
    import os

    best_cfg = json.load(open(os.path.join(str(tmp_path / "results"),
                                           "best_config.json")))
    assert "autotuning" not in best_cfg
    assert best_cfg["zero_optimization"]["stage"] in (0, 1)


def test_infeasible_configs_recorded_not_fatal(tmp_path, eight_devices):
    """A bad stage in the space is recorded infeasible; tuning continues."""
    base = _base()
    base["autotuning"]["zero_stages"] = [99, 0]  # 99: invalid stage
    base["autotuning"]["results_dir"] = str(tmp_path / "results")
    at = Autotuner(_factory, base, _batch, seq_len=16)
    best = at.tune()
    assert best["feasible"]
    assert any(not r.get("feasible") for r in at.results)


# ------------------------------------------------------------ staged (v2)
def test_staged_tunes_model_knobs(tmp_path, eight_devices):
    """The v2 staged search must sweep the knobs that actually set TPU
    throughput (remat policy, scan_layers, gas, flash blocks) and keep
    per-stage winners (VERDICT r2 #5: the old tuner could not rediscover
    the hand-found bench config because it never touched them)."""
    base = _base()
    base["autotuning"].update({
        "tuner_type": "staged",
        "results_dir": str(tmp_path / "results"),
        "gas_candidates": [1, 2],
        "remat_policies": ["full", "dots"],
        "flash_blocks": [[64, 64]],
        "stages": ["batch", "remat", "gas", "flash"],
    })
    at = Autotuner(_factory, base, _batch, seq_len=16)
    best = at.tune()
    assert best["feasible"]
    stages_run = {r.get("stage") for r in at.results}
    assert {"batch", "remat", "gas", "flash"} <= stages_run
    # model knobs were exercised
    model_knobs = [r["config"].get("_model", {}) for r in at.results]
    assert any("remat_policy" in m for m in model_knobs)
    assert any("scan_layers" in m for m in model_knobs)
    assert any("flash_block_q" in m for m in model_knobs)
    assert any(r["config"].get("gradient_accumulation_steps") == 2
               for r in at.results)
    # ranked report emitted
    import os
    report = open(os.path.join(str(tmp_path / "results"), "report.md")).read()
    assert "| rank |" in report and "tok/s" in report
    # noise-free merge property: staged descent carries the batch-stage
    # keys through every later stage, so whichever record wins, its config
    # must still hold them (which stage wins IS measurement noise)
    assert "train_micro_batch_size_per_gpu" in best["config"]
    assert "zero_optimization" in best["config"]


def test_model_based_ordering(tmp_path, eight_devices):
    base = _base()
    base["autotuning"].update({
        "tuner_type": "model_based",
        "results_dir": str(tmp_path / "results"),
        "gas_candidates": [1, 2],
        "remat_policies": ["dots"],
        "flash_blocks": [],
        "stages": ["batch", "gas"],
    })
    at = Autotuner(_factory, base, _batch, seq_len=16)
    best = at.tune()
    assert best["feasible"] and best["throughput"] > 0
