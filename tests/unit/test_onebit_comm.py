"""1-bit compressed DP gradient exchange (engine mode).

Reference: ``runtime/comm/nccl.py:52 compressed_allreduce`` — past
freeze_step, OneBitAdam's gradient all-reduce ships int8 signs + per-chunk
scales with error feedback.  Here the engine swaps its train step for a
shard_map variant at the freeze boundary (engine._install_onebit_step).
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


def _engine(freeze_step, opt_type="OneBitAdam", gas=1):
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": opt_type,
                      "params": {"lr": 1e-3, "freeze_step": freeze_step}},
    })
    return cfg, engine


def _batches(cfg, engine, n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
        for _ in range(n)]


def test_mode_enabled_and_switches_at_freeze():
    cfg, engine = _engine(freeze_step=2)
    assert engine.onebit_comm_enabled
    assert not engine._onebit_compressed
    for b in _batches(cfg, engine, 2):
        engine.train_batch(b)
    assert not engine._onebit_compressed  # steps 0,1 are warmup
    engine.train_batch(_batches(cfg, engine, 1)[0])
    assert engine._onebit_compressed


def test_convergence_parity_vs_dense():
    """Compressed exchange with error feedback must track the dense run:
    identical during warmup, and within a loose band after freeze (the
    exchange is lossy per step but unbiased across steps)."""
    cfg, e1 = _engine(freeze_step=3)
    batches = _batches(cfg, e1, 1) * 12  # fixed batch: loss must descend
    lc = [float(e1.train_batch(b)[1]["loss"]) for b in batches]

    # dense baseline: same optimizer semantics, freeze far beyond the run
    cfg2, e2 = _engine(freeze_step=10_000)
    ld = [float(e2.train_batch(b)[1]["loss"]) for b in batches]

    np.testing.assert_allclose(lc[:3], ld[:3], rtol=1e-5)  # warmup identical
    # after freeze: the compressed run keeps descending on the same trend
    # (lossy per step; error feedback keeps it unbiased across steps —
    # measured ~0.28 of a 1.14 total descent behind dense at 12 steps on
    # this 8-worker toy, so the band is 0.35)
    assert abs(lc[-1] - ld[-1]) < 0.35 * abs(ld[0] - ld[-1]) + 0.02, (lc, ld)
    assert lc[-1] < lc[0]
    assert lc[-1] < lc[3]  # descent continues through the compressed phase


def test_wire_bytes_drop_in_comms_logger():
    """The comms logger's trace-time records must show the compressed
    exchange shipping ~1/32 the dense bytes: signs travel packed 8/byte
    (reference ``compress_by_chunk``/``unpackbits``,
    ``runtime/comm/nccl.py:78-85``)."""
    cfg, engine = _engine(freeze_step=1)
    total = sum(x.size for x in
                jax.tree_util.tree_leaves(engine.state["params"]))
    logger = deepspeed_tpu.comm.comms_logger
    logger.enabled = True
    logger.prof_all = True
    try:
        logger.reset()
        for b in _batches(cfg, engine, 3):
            engine.train_batch(b)
        recs = logger.comms_dict
        comp = {name: recs[name] for name in recs
                if "compressed_allreduce" in name}
        assert comp, f"no compressed records in {list(recs)}"
        # per-device payload per exchange round: [n, c/8] packed uint8
        # (~1/8 byte/param) vs the 4-byte dense words fp32 would ship
        byte_counts = [sz for by_size in comp.values() for sz in by_size]
        dense = total * 4
        assert max(byte_counts) <= total / 8 * 1.2  # bit-packed payload
        assert max(byte_counts) < dense / 24        # >24x below dense
    finally:
        logger.enabled = False
        logger.prof_all = False
        logger.reset()


def test_multi_step_dispatch_after_freeze():
    cfg, engine = _engine(freeze_step=1)
    engine.train_batch(_batches(cfg, engine, 1)[0])  # warmup step 0
    engine.train_batch(_batches(cfg, engine, 1)[0])  # switches, step 1
    assert engine._onebit_compressed
    _, m = engine.train_batches(_batches(cfg, engine, 3, seed=1))
    assert np.isfinite(float(m["loss"]))


def test_unsupported_combo_raises_by_default():
    """Strict mode (default): OneBitAdam + ZeRO>=2 fails loudly, like the
    reference's stage checks, instead of silently going dense."""
    deepspeed_tpu.comm.reset_topology()
    model = gpt2.build(gpt2.GPT2Config.tiny())
    with pytest.raises(ValueError, match="compressed gradient exchange"):
        deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
        })


def test_gated_off_with_zero_stage2():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "strict": False,  # documented opt-in to the dense exchange
    })
    assert not engine.onebit_comm_enabled
    b = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
    _, m = engine.train_batch(b)  # dense path still trains
    assert np.isfinite(float(m["loss"]))


def _zero1_engine(freeze_step):
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": freeze_step}},
        "zero_optimization": {"stage": 1},
    })
    return cfg, engine


def test_zero1_compressed_parity_vs_dense():
    """The compressed exchange composes with ZeRO-1 (the reference runs its
    1-bit optimizers under stage 1, ``fp16/onebit/adam.py:11``): optimizer
    state stays dp-partitioned while the gradient exchange ships packed
    sign bits, and the loss tracks the dense stage-1 run."""
    cfg, e1 = _zero1_engine(freeze_step=3)
    assert e1.onebit_comm_enabled
    batches = _batches(cfg, e1, 1) * 12
    lc = [float(e1.train_batch(b)[1]["loss"]) for b in batches]
    assert e1._onebit_compressed

    # optimizer state really is partitioned over dp under the onebit step
    opt_shardings = jax.tree_util.tree_leaves(e1.state_shardings["opt_state"])
    assert any("dp" in str(getattr(s, "spec", "")) for s in opt_shardings)

    cfg2, e2 = _zero1_engine(freeze_step=10_000)  # dense stage-1 baseline
    ld = [float(e2.train_batch(b)[1]["loss"]) for b in batches]

    np.testing.assert_allclose(lc[:3], ld[:3], rtol=1e-5)  # warmup identical
    assert abs(lc[-1] - ld[-1]) < 0.35 * abs(ld[0] - ld[-1]) + 0.02, (lc, ld)
    assert lc[-1] < lc[0]
    assert lc[-1] < lc[3]


def test_fp16_overflow_rolls_back_error_feedback():
    """An fp16 overflow must not poison the error-feedback buffers: the
    skipped step's we/se roll back with the param update (a NaN residual
    would otherwise make every later step NaN)."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": 0}},
        # huge initial scale forces overflow on the first step(s)
        "fp16": {"enabled": True, "initial_scale_power": 32},
    })
    assert engine.onebit_comm_enabled
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
    saw_overflow = False
    for _ in range(6):
        _, m = engine.train_batch(batch)
        saw_overflow = saw_overflow or bool(m["overflow"])
        we = np.asarray(engine.state["onebit"]["we"])
        assert np.isfinite(we).all(), "error feedback poisoned by overflow"
    assert saw_overflow  # the scenario actually exercised an overflow
    assert np.isfinite(float(m["loss"]))


def test_sparse_gradients_excludes_compressed_mode():
    """sparse_embedding_lookup opens its own shard_map; nesting inside the
    onebit step is rejected by jax.  Strict mode raises; with
    ``"strict": false`` the engine keeps the dense exchange."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    cfg.tie_embeddings = False
    model = gpt2.build(cfg)
    with pytest.raises(ValueError, match="sparse_gradients"):
        deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 1}},
            "sparse_gradients": True,
        })
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": 1}},
        "sparse_gradients": True,
        "strict": False,
    })
    assert not engine.onebit_comm_enabled
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
    for _ in range(3):  # crosses freeze_step without crashing
        _, m = engine.train_batch(batch)
    assert np.isfinite(float(m["loss"]))
