"""Topology math tests (model: reference tests/unit/runtime/pipe/test_topology.py)."""

import pytest

from deepspeed_tpu.parallel.topology import (MeshTopology,
                                             PipeModelDataParallelTopology,
                                             ProcessTopology,
                                             topology_from_config)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.get_axis_names() == ["row", "col"]


def test_topology_dims():
    topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size == 24
    assert topo.get_dim("a") == 2
    assert topo.get_dim("b") == 3
    assert topo.get_dim("c") == 4
    assert topo.get_dim("missing") == 0


def test_topology_comm_lists():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size == 8
    pipe_lists = topo.get_axis_comm_lists("pipe")
    for lst in pipe_lists:
        assert len(lst) == 2
    assert sorted(sum(pipe_lists, [])) == list(range(8))
    model_lists = topo.get_axis_comm_lists("model")
    # model axis is innermost: consecutive ranks
    for lst in model_lists:
        assert lst[1] == lst[0] + 1
    assert topo.get_axis_comm_lists("missing") == []


def test_topology_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    ranks = topo.filter_match(pipe=0, data=1)
    assert len(ranks) == 2
    for r in ranks:
        coord = topo.get_coord(r)
        assert coord.pipe == 0 and coord.data == 1


def test_topology_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    r = topo.get_rank_repr(rank=0)
    assert "pipe_00" in r and "model_00" in r and "data" not in r


def test_mesh_topology_infer_dp(eight_devices):
    topo = MeshTopology(tp=2)
    assert topo.axis_sizes["dp"] == 4
    assert topo.data_parallel_size == 4
    assert topo.model_parallel_size == 2
    assert topo.world_size == 8


def test_mesh_topology_explicit(eight_devices):
    topo = MeshTopology(pp=2, dp=2, tp=2)
    assert topo.world_size == 8
    m = topo.mesh
    assert m.shape["pp"] == 2 and m.shape["dp"] == 2 and m.shape["tp"] == 2
    assert m.shape["ep"] == 1 and m.shape["sp"] == 1


def test_mesh_topology_bad_sizes(eight_devices):
    with pytest.raises(AssertionError):
        MeshTopology(dp=3, tp=2)  # 6 != 8
    with pytest.raises(AssertionError):
        MeshTopology(tp=3)  # 8 % 3 != 0


def test_topology_from_config(eight_devices):
    topo = topology_from_config({"tensor_parallel_size": 2, "pp": 2})
    assert topo.model_parallel_size == 2
    assert topo.pipe_parallel_size == 2
    assert topo.data_parallel_size == 2
    with pytest.raises(ValueError):
        topology_from_config({"bogus_axis": 2})


def test_expert_data_split(eight_devices):
    topo = MeshTopology(ep=4)
    assert topo.expert_parallel_size == 4
    assert topo.expert_data_parallel_size == 2
    assert topo.data_parallel_size == 8  # dp * ep = full DP world
