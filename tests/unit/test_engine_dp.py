"""End-to-end engine tests: tiny GPT-2 over the 8-device CPU-sim mesh.

Model: reference tests/unit/runtime/zero/test_zero.py (stage-vs-baseline loss
parity) and tests/unit/runtime/half_precision tests.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


def tiny_model():
    return gpt2.build(gpt2.GPT2Config.tiny())


def make_batch(rng, n, seq=33, vocab=512):
    return {"input_ids": rng.integers(0, vocab, size=(n, seq)).astype(np.int32)}


def base_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {},
    }
    cfg.update(over)
    return cfg


def run_steps(config, steps=5, seed=0):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=config)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        batch = make_batch(rng, engine.train_batch_size())
        _, metrics = engine.train_batch(batch)
        losses.append(metrics["loss"])
    return engine, losses


def test_train_loss_decreases():
    _, losses = run_steps(base_config(), steps=8)
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_train_batches_matches_per_step():
    """k steps via one train_batches dispatch == k train_batch calls."""
    deepspeed_tpu.comm.reset_topology()
    engine_a, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(),
                                                 config=base_config())
    rng = np.random.default_rng(7)
    batches = [make_batch(rng, engine_a.train_batch_size())
               for _ in range(4)]
    for b in batches:
        _, m_a = engine_a.train_batch(b)

    deepspeed_tpu.comm.reset_topology()
    engine_b, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(),
                                                 config=base_config())
    _, m_b = engine_b.train_batches(batches)

    assert engine_b.global_steps == engine_a.global_steps == 4
    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                               rtol=1e-5)
    pa = jax.tree_util.tree_leaves(engine_a.state["params"])
    pb = jax.tree_util.tree_leaves(engine_b.state["params"])
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_unrolled_layers_match_scan():
    """cfg.scan_layers=False is numerically identical to the scan path."""
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.arange(2 * 17, dtype=np.int32).reshape(2, 17) % cfg.vocab_size
    logits_scan = gpt2.forward(cfg, params, ids, train=False)
    cfg_u = gpt2.GPT2Config.tiny()
    cfg_u.scan_layers = False
    logits_unroll = gpt2.forward(cfg_u, params, ids, train=False)
    np.testing.assert_allclose(np.asarray(logits_scan),
                               np.asarray(logits_unroll),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_baseline(stage):
    _, base_losses = run_steps(base_config(), steps=4)
    _, z_losses = run_steps(
        base_config(zero_optimization={"stage": stage}), steps=4)
    np.testing.assert_allclose(base_losses, z_losses, rtol=2e-4, atol=1e-5)


def test_zero3_small_params_stay_persistent(eight_devices):
    """Default stage3_param_persistence_threshold (1e5, reference
    ``parameter_offload.py:316``) keeps tiny params replicated."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=tiny_model(), config=base_config(zero_optimization={"stage": 3}))
    qkv = engine.state["params"]["blocks"]["qkv_w"]  # 24k elems < 1e5
    assert qkv.addressable_shards[0].data.size == qkv.size


def test_zero3_state_is_sharded(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=tiny_model(), config=base_config(zero_optimization={
            "stage": 3, "stage3_param_persistence_threshold": 0}))
    qkv = engine.state["params"]["blocks"]["qkv_w"]
    # 8-way dp: each device holds 1/8 of the tensor
    shard_size = qkv.addressable_shards[0].data.size
    assert shard_size == qkv.size // 8
    m = engine.state["opt_state"]
    leaves = [x for x in jax.tree_util.tree_leaves(m)
              if x.ndim > 0 and x.size > 8]
    assert leaves, "no optimizer moment buffers found"
    for leaf in leaves:
        assert leaf.addressable_shards[0].data.size < leaf.size


def test_gradient_accumulation_equivalence():
    # gas=2 with half micro-batch == gas=1 with full batch (same global batch)
    _, l1 = run_steps(base_config(train_micro_batch_size_per_gpu=2,
                                  gradient_accumulation_steps=1), steps=3)
    _, l2 = run_steps(base_config(train_micro_batch_size_per_gpu=1,
                                  gradient_accumulation_steps=2), steps=3)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)


def test_micro_step_shims():
    """The reference-style forward/backward/step loop trains equivalently."""
    deepspeed_tpu.comm.reset_topology()
    config = base_config(gradient_accumulation_steps=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=config)
    rng = np.random.default_rng(0)
    for i in range(2):
        for g in range(2):
            batch = make_batch(rng, engine.micro_batch_global())
            loss = engine.forward(batch)
            engine.backward(loss)
            if engine.is_gradient_accumulation_boundary():
                engine.step()
    assert engine.global_steps == 2
    assert engine.micro_steps == 4


def test_bf16_training():
    _, losses = run_steps(base_config(bf16={"enabled": True}), steps=5)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_fp16_dynamic_loss_scale():
    deepspeed_tpu.comm.reset_topology()
    config = base_config(fp16={"enabled": True, "initial_scale_power": 8})
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=config)
    rng = np.random.default_rng(0)
    for _ in range(3):
        _, metrics = engine.train_batch(make_batch(rng, engine.train_batch_size()))
    assert metrics["loss_scale"] == 256.0
    assert engine.loss_scale() == 256.0


def test_tp_mesh_training(eight_devices):
    """tp=2 x dp=4: model-parallel matmuls + data-parallel grads, same loss.

    train_batch_size is pinned so both runs consume identical global batches
    (micro-batch per chip derives to 1 vs 2)."""
    _, base_losses = run_steps(base_config(train_batch_size=8,
                                           train_micro_batch_size_per_gpu=None,
                                           gradient_accumulation_steps=None), steps=3)
    _, tp_losses = run_steps(base_config(train_batch_size=8,
                                         train_micro_batch_size_per_gpu=None,
                                         gradient_accumulation_steps=None,
                                         mesh={"tp": 2}), steps=3)
    np.testing.assert_allclose(base_losses, tp_losses, rtol=2e-4, atol=1e-5)


def test_dataloader_path():
    deepspeed_tpu.comm.reset_topology()
    rng = np.random.default_rng(1)
    data = [{"input_ids": rng.integers(0, 512, size=(33,)).astype(np.int32)}
            for _ in range(64)]
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=tiny_model(), config=base_config(), training_data=data)
    assert loader is not None
    _, metrics = engine.train_batch()  # pulls from its own loader
    assert np.isfinite(metrics["loss"])


def test_checkpoint_save_load_resume(tmp_path):
    deepspeed_tpu.comm.reset_topology()
    config = base_config()
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=config)
    rng = np.random.default_rng(0)
    for _ in range(2):
        engine.train_batch(make_batch(rng, engine.train_batch_size()))
    engine.save_checkpoint(str(tmp_path), client_state={"note": "hi"})

    deepspeed_tpu.comm.reset_topology()
    engine2, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=config)
    path, client_state = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client_state == {"note": "hi"}
    assert engine2.global_steps == 2
    # resumed state trains identically to continuing the original
    batch = make_batch(np.random.default_rng(9), engine.train_batch_size())
    _, m1 = engine.train_batch(batch)
    _, m2 = engine2.train_batch(batch)
    np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-5)


def test_checkpoint_elastic_reshard(tmp_path):
    """Save under zero-3 sharding, load under zero-0 (replicated) — the orbax
    restore reshards: this is the universal-checkpoint capability (SURVEY §5.4)."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=tiny_model(), config=base_config(zero_optimization={"stage": 3}))
    rng = np.random.default_rng(0)
    engine.train_batch(make_batch(rng, engine.train_batch_size()))
    engine.save_checkpoint(str(tmp_path))

    deepspeed_tpu.comm.reset_topology()
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=tiny_model(), config=base_config())
    engine2.load_checkpoint(str(tmp_path))
    batch = make_batch(np.random.default_rng(5), engine.train_batch_size())
    _, m1 = engine.train_batch(batch)
    _, m2 = engine2.train_batch(batch)
    np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=2e-4, atol=1e-5)
