"""TiledLinear tests (reference tests/unit/runtime/zero/test_zero_tiled.py):
tiled forward/backward must match the dense linear."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.zero.tiling import TiledLinear, _splits


def test_splits_uniform_and_remainder():
    assert _splits(12, 3) == [4, 4, 4]
    assert _splits(13, 3) == [5, 4, 4]
    with pytest.raises(AssertionError):
        _splits(2, 3)


@pytest.mark.parametrize("in_splits,out_splits", [(1, 1), (2, 3), (3, 2),
                                                  (4, 4)])
def test_tiled_matches_dense(in_splits, out_splits):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(24, 36)).astype(np.float32)
    b = rng.normal(size=(36,)).astype(np.float32)
    x = rng.normal(size=(5, 24)).astype(np.float32)

    tl, params = TiledLinear.from_dense(w, b, in_splits=in_splits,
                                        out_splits=out_splits)
    y = np.asarray(tl(params, jnp.asarray(x)))
    np.testing.assert_allclose(y, x @ w + b, rtol=1e-5, atol=1e-5)


def test_tiled_gradients_match_dense():
    rng = np.random.default_rng(1)
    w = rng.normal(size=(16, 10)).astype(np.float32)
    x = rng.normal(size=(4, 16)).astype(np.float32)

    tl, params = TiledLinear.from_dense(w, None, in_splits=2, out_splits=2)

    def tiled_loss(p):
        return (tl(p, jnp.asarray(x)) ** 2).sum()

    def dense_loss(wd):
        return ((jnp.asarray(x) @ wd) ** 2).sum()

    g_tiled = jax.grad(tiled_loss)(params)
    g_dense = np.asarray(jax.grad(dense_loss)(jnp.asarray(w)))

    # reassemble the tile grads into the dense layout
    rows = []
    r0 = 0
    for i, ins in enumerate(tl.in_sizes):
        cols = [np.asarray(g_tiled["tiles"][i][j])
                for j in range(len(tl.out_sizes))]
        rows.append(np.concatenate(cols, axis=1))
        r0 += ins
    g_re = np.concatenate(rows, axis=0)
    np.testing.assert_allclose(g_re, g_dense, rtol=1e-5, atol=1e-5)


def test_tiled_presplit_input_and_uncombined_output():
    rng = np.random.default_rng(2)
    w = rng.normal(size=(12, 8)).astype(np.float32)
    x = rng.normal(size=(3, 12)).astype(np.float32)
    tl, params = TiledLinear.from_dense(w, None, in_splits=3, out_splits=2)
    tl.combine_out_splits = False
    xs = np.split(x, np.cumsum(tl.in_sizes)[:-1], axis=-1)
    outs = tl(params, [jnp.asarray(p) for p in xs],
              input_is_already_split=True)
    assert len(outs) == 2
    np.testing.assert_allclose(np.concatenate([np.asarray(o) for o in outs],
                                              axis=-1),
                               x @ w, rtol=1e-5, atol=1e-5)
