"""BLOOM family tests: HF parity (ALiBi + interleaved-qkv conversion),
decode, training (reference: bloom rows of the inference sweep)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import bloom

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_bloom():
    cfg = transformers.BloomConfig(
        vocab_size=96, hidden_size=32, n_layer=2, n_head=4,
        hidden_dropout=0.0, attention_dropout=0.0)
    with torch.no_grad():
        m = transformers.BloomForCausalLM(cfg)
    m.eval()
    return m


def test_alibi_slopes_match_published_values():
    s8 = bloom.alibi_slopes(8)
    np.testing.assert_allclose(s8, [2 ** -i for i in range(1, 9)], rtol=1e-6)
    s12 = bloom.alibi_slopes(12)  # non-power-of-two path
    assert len(s12) == 12 and (np.diff(s12[:8]) < 0).all()


def test_bloom_matches_hf():
    hf = _tiny_hf_bloom()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(0).integers(2, 96, (2, 12)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_bloom_kv_cache_decode_matches_forward():
    import jax

    cfg = bloom.BloomConfig.tiny()
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 12)).astype(np.int32)
    full = np.asarray(bloom.forward(cfg, params, ids, train=False))

    cache = bloom.init_cache(cfg, 2, 32, dtype=np.float32)
    logits, cache = bloom.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=1e-4)
    for t in range(8, 12):
        logits, cache = bloom.forward_cached(cfg, params, ids[:, t:t + 1],
                                             cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-4)


def test_bloom_trains_and_generates():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=bloom.build(bloom.BloomConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    fixed = {"input_ids": rng.integers(
        0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
    losses = [float(engine.train_batch(fixed)[1]["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]  # overfits one batch

    deepspeed_tpu.comm.reset_topology()
    ie = deepspeed_tpu.init_inference(
        model=bloom.build(bloom.BloomConfig.tiny()),
        config={"dtype": "float32"})
    out = ie.generate(np.full((1, 4), 7, np.int32), max_new_tokens=4)
    assert out.shape == (1, 8)


def test_bloom_hf_generate_parity():
    deepspeed_tpu.comm.reset_topology()
    hf = _tiny_hf_bloom()
    engine = deepspeed_tpu.init_inference(model=hf,
                                          config={"dtype": "float32"})
    ids = np.full((1, 4), 7, np.int32)
    out = engine.generate(ids, max_new_tokens=3)
    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(ids), max_new_tokens=3,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(out, hf_out)
