"""VAE (AutoencoderKL) tests: shapes, roundtrip behavior, training.
No diffusers package exists in this image, so parity is structural —
the converter is exercised against a fabricated diffusers-named dict."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import vae


def test_encode_decode_shapes():
    cfg = vae.VAEConfig.tiny()
    params = vae.init_params(cfg, jax.random.PRNGKey(0))
    x = jnp.zeros((2, 3, 32, 32))
    mean, logvar = vae.encode(cfg, params, x)
    # 2 channel mults -> one downsample -> 16x16 latents
    assert mean.shape == (2, 4, 16, 16) and logvar.shape == mean.shape
    recon = vae.decode(cfg, params, mean)
    assert recon.shape == x.shape


def test_vae_trains():
    deepspeed_tpu.comm.reset_topology()
    cfg = vae.VAEConfig.tiny()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=vae.build(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    batch = {"pixel_values": rng.normal(
        size=(engine.train_batch_size(), 3, 32, 32)).astype(np.float32) * 0.5}
    losses = []
    for _ in range(6):
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses


def test_hf_naming_roundtrip():
    """from_hf_state_dict consumes the published diffusers naming: fabricate
    the dict FROM our params, reload, and require identical outputs."""
    cfg = vae.VAEConfig.tiny()
    params = vae.init_params(cfg, jax.random.PRNGKey(1))

    sd = {}

    def put_conv(name, p):
        sd[name + ".weight"] = np.asarray(p["w"])
        sd[name + ".bias"] = np.asarray(p["b"])

    def put_gn(name, p):
        sd[name + ".weight"] = np.asarray(p["scale"])
        sd[name + ".bias"] = np.asarray(p["bias"])

    def put_dense(name, p):
        sd[name + ".weight"] = np.asarray(p["w"]).T
        sd[name + ".bias"] = np.asarray(p["b"])

    def put_resnet(prefix, p):
        put_gn(prefix + ".norm1", p["norm1"])
        put_conv(prefix + ".conv1", p["conv1"])
        put_gn(prefix + ".norm2", p["norm2"])
        put_conv(prefix + ".conv2", p["conv2"])
        if "shortcut" in p:
            put_conv(prefix + ".conv_shortcut", p["shortcut"])

    def put_attn(prefix, p):
        put_gn(prefix + ".group_norm", p["norm"])
        put_dense(prefix + ".to_q", p["q"])
        put_dense(prefix + ".to_k", p["k"])
        put_dense(prefix + ".to_v", p["v"])
        put_dense(prefix + ".to_out.0", p["proj"])

    enc, dec = params["encoder"], params["decoder"]
    put_conv("encoder.conv_in", enc["conv_in"])
    for i, blk in enumerate(enc["down"]):
        for j, r in enumerate(blk["resnets"]):
            put_resnet(f"encoder.down_blocks.{i}.resnets.{j}", r)
        if "down" in blk:
            put_conv(f"encoder.down_blocks.{i}.downsamplers.0.conv",
                     blk["down"])
    put_resnet("encoder.mid_block.resnets.0", enc["mid"]["res1"])
    put_attn("encoder.mid_block.attentions.0", enc["mid"]["attn"])
    put_resnet("encoder.mid_block.resnets.1", enc["mid"]["res2"])
    put_gn("encoder.conv_norm_out", enc["norm_out"])
    put_conv("encoder.conv_out", enc["conv_out"])

    put_conv("decoder.conv_in", dec["conv_in"])
    put_resnet("decoder.mid_block.resnets.0", dec["mid"]["res1"])
    put_attn("decoder.mid_block.attentions.0", dec["mid"]["attn"])
    put_resnet("decoder.mid_block.resnets.1", dec["mid"]["res2"])
    for i, blk in enumerate(dec["up"]):
        for j, r in enumerate(blk["resnets"]):
            put_resnet(f"decoder.up_blocks.{i}.resnets.{j}", r)
        if "up" in blk:
            put_conv(f"decoder.up_blocks.{i}.upsamplers.0.conv", blk["up"])
    put_gn("decoder.conv_norm_out", dec["norm_out"])
    put_conv("decoder.conv_out", dec["conv_out"])
    put_conv("quant_conv", params["quant_conv"])
    put_conv("post_quant_conv", params["post_quant_conv"])

    reloaded = vae.from_hf_state_dict(cfg, sd)
    x = np.random.default_rng(2).normal(size=(1, 3, 32, 32)).astype(np.float32)
    m1, _ = vae.encode(cfg, params, jnp.asarray(x))
    m2, _ = vae.encode(cfg, reloaded, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-6)
    r1 = vae.decode(cfg, params, m1)
    r2 = vae.decode(cfg, reloaded, m2)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=1e-6)
