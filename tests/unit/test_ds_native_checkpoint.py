"""Torch-DeepSpeed checkpoint ingestion (the migration path).

Fixtures are hand-built in the reference on-disk format
(``mp_rank_XX_model_states.pt`` + ``zero_pp_rank_*_optim_states.pt``,
``deepspeed/checkpoint/deepspeed_checkpoint.py:39`` /
``utils/zero_to_fp32.py`` protocol) and must load into our GPT-2 pytree
with exact values.
"""

import math
from collections import OrderedDict

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import (DeepSpeedNativeCheckpoint,
                                      load_ds_checkpoint_into)
from deepspeed_tpu.models import gpt2

V, S, L, H, D = 96, 32, 2, 2, 16


def _hf_gpt2_sd(rng):
    """Random fp32 HF-GPT-2-named state dict for the tiny shape."""
    def t(*shape):
        return torch.tensor(rng.standard_normal(shape).astype(np.float32))

    sd = OrderedDict()
    sd["wte.weight"] = t(V, D)
    sd["wpe.weight"] = t(S, D)
    for i in range(L):
        sd[f"h.{i}.ln_1.weight"] = t(D)
        sd[f"h.{i}.ln_1.bias"] = t(D)
        sd[f"h.{i}.attn.c_attn.weight"] = t(D, 3 * D)
        sd[f"h.{i}.attn.c_attn.bias"] = t(3 * D)
        sd[f"h.{i}.attn.c_proj.weight"] = t(D, D)
        sd[f"h.{i}.attn.c_proj.bias"] = t(D)
        sd[f"h.{i}.ln_2.weight"] = t(D)
        sd[f"h.{i}.ln_2.bias"] = t(D)
        sd[f"h.{i}.mlp.c_fc.weight"] = t(D, 4 * D)
        sd[f"h.{i}.mlp.c_fc.bias"] = t(4 * D)
        sd[f"h.{i}.mlp.c_proj.weight"] = t(4 * D, D)
        sd[f"h.{i}.mlp.c_proj.bias"] = t(D)
    sd["ln_f.weight"] = t(D)
    sd["ln_f.bias"] = t(D)
    return sd


def _write_zero2_ckpt(dirpath, sd, dp=2):
    """Reference ZeRO-2 layout: fp16 module + per-dp-rank flat fp32
    partitions with 2*world alignment padding (zero_to_fp32.py:253)."""
    flat = torch.cat([v.reshape(-1) for v in sd.values()])
    align = 2 * dp
    padded = math.ceil(flat.numel() / align) * align
    flat = torch.cat([flat, torch.zeros(padded - flat.numel())])
    part = padded // dp
    (dirpath / "mp_rank_00_model_states.pt").parent.mkdir(
        parents=True, exist_ok=True)
    torch.save({
        "module": OrderedDict((k, v.half()) for k, v in sd.items()),
        "param_shapes": [OrderedDict((k, v.shape) for k, v in sd.items())],
        "buffer_names": [],
        "ds_version": "0.8.2",
        "global_steps": 7,
    }, dirpath / "mp_rank_00_model_states.pt")
    for r in range(dp):
        torch.save({
            "optimizer_state_dict": {
                "zero_stage": 2,
                "partition_count": dp,
                "single_partition_of_fp32_groups":
                    [flat[r * part:(r + 1) * part].clone()],
            }
        }, dirpath / f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt")


def _write_zero3_ckpt(dirpath, sd, dp=2):
    """ZeRO-3: partitions zip at EACH param boundary with per-param
    padding (zero_to_fp32.py zero3_partitioned_param_info)."""
    per_rank = [[] for _ in range(dp)]
    for v in sd.values():
        flat = v.reshape(-1)
        part = math.ceil(flat.numel() / dp)
        flat = torch.cat([flat, torch.zeros(part * dp - flat.numel())])
        for r in range(dp):
            per_rank[r].append(flat[r * part:(r + 1) * part])
    dirpath.mkdir(parents=True, exist_ok=True)
    torch.save({
        "module": OrderedDict((k, v.half()) for k, v in sd.items()),
        "param_shapes": [OrderedDict((k, v.shape) for k, v in sd.items())],
        "buffer_names": [],
        "ds_version": "0.8.2",
    }, dirpath / "mp_rank_00_model_states.pt")
    for r in range(dp):
        torch.save({
            "optimizer_state_dict": {
                "zero_stage": 3,
                "fp32_flat_groups": [torch.cat(per_rank[r])],
            }
        }, dirpath / f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt")


def _write_tp2_ckpt(dirpath, sd):
    """tp=2 module-only checkpoint: column weights split on the out dim,
    row weights on the in dim, norms replicated."""
    from deepspeed_tpu.checkpoint.ds_native import (GPT2_CAT_DIMS,
                                                    GPT2_QKV_FUSED,
                                                    GPT2_REPLICATED)

    dirpath.mkdir(parents=True, exist_ok=True)
    for r in range(2):
        shard = OrderedDict()
        for name, v in sd.items():
            if any(p.fullmatch(name) for p in GPT2_QKV_FUSED):
                # Megatron/AutoTP fused-qkv sharding: each rank gets its
                # head-slice of EACH of q, k, v, concatenated q_r|k_r|v_r
                q, k_, v_ = torch.chunk(v, 3, dim=-1)
                shard[name] = torch.cat(
                    [torch.chunk(t, 2, dim=-1)[r] for t in (q, k_, v_)],
                    dim=-1)
                continue
            dim = None
            for pat, d in GPT2_CAT_DIMS:
                if pat.fullmatch(name):
                    dim = d % v.ndim
            if any(p.fullmatch(name) for p in GPT2_REPLICATED):
                dim = None
            if dim is None:
                shard[name] = v
            else:
                shard[name] = torch.chunk(v, 2, dim=dim)[r]
        torch.save({"module": shard,
                    "param_shapes": [OrderedDict(
                        (k, v.shape) for k, v in shard.items())],
                    "buffer_names": [], "ds_version": "0.8.2"},
                   dirpath / f"mp_rank_{r:02d}_model_states.pt")


def _expected_params(sd):
    cfg = gpt2.GPT2Config(vocab_size=V, max_seq_len=S, num_layers=L,
                          num_heads=H, hidden_size=D)
    from deepspeed_tpu.module_inject.replace_policy import _gpt2_convert

    return cfg, _gpt2_convert(cfg, sd)


def _assert_tree_close(got, want, atol=0.0):
    import jax

    for g, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol)


def test_zero2_checkpoint_roundtrip(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(0))
    _write_zero2_ckpt(tmp_path / "global_step7", sd, dp=2)
    (tmp_path / "latest").write_text("global_step7")

    ck = DeepSpeedNativeCheckpoint(str(tmp_path))
    assert ck.tp_degree == 1 and ck.dp_degree == 2
    fp32 = ck.fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(fp32[name], v.numpy())

    params, icfg, client = load_ds_checkpoint_into(str(tmp_path))
    _, want = _expected_params(sd)
    _assert_tree_close(params, want)
    assert client["global_steps"] == 7


def test_zero3_checkpoint_roundtrip(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(1))
    _write_zero3_ckpt(tmp_path / "ck", sd, dp=2)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    fp32 = ck.fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(fp32[name], v.numpy())


def test_tp2_module_merge(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(2))
    _write_tp2_ckpt(tmp_path / "ck", sd)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    assert ck.tp_degree == 2
    merged = ck.merged_fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(merged[name], v.numpy())


def test_loaded_params_run_forward(tmp_path):
    import jax

    sd = _hf_gpt2_sd(np.random.default_rng(3))
    _write_zero2_ckpt(tmp_path / "ck", sd, dp=2)
    params, icfg, _ = load_ds_checkpoint_into(str(tmp_path / "ck"))
    cfg, _ = _expected_params(sd)
    logits = gpt2.forward(cfg, params,
                          np.zeros((1, 8), np.int32), train=False)
    assert np.isfinite(np.asarray(logits)).all()


def _tp2_shard(name, v, r):
    """TP shard of one param by the GPT-2 merge rules (inverse of merge)."""
    from deepspeed_tpu.checkpoint.ds_native import (GPT2_CAT_DIMS,
                                                    GPT2_QKV_FUSED,
                                                    GPT2_REPLICATED)

    if any(p.fullmatch(name) for p in GPT2_QKV_FUSED):
        q, k_, v_ = torch.chunk(v, 3, dim=-1)
        return torch.cat([torch.chunk(t, 2, dim=-1)[r] for t in (q, k_, v_)],
                         dim=-1)
    if any(p.fullmatch(name) for p in GPT2_REPLICATED):
        return v
    for pat, d in GPT2_CAT_DIMS:
        if pat.fullmatch(name):
            return torch.chunk(v, 2, dim=d % v.ndim)[r]
    return v


def _write_pp2_tp2_ckpt(dirpath, sd):
    """Pipeline-staged pp=2 x tp=2 layout (reference pipe/module.py
    save_state_dict): layer_{idx:02d}-model_{tp:02d}-model_states.pt with
    LOCAL names; stage 0 holds layers 0..L/2, stage 1 the rest."""
    dirpath.mkdir(parents=True, exist_ok=True)
    layers = {0: {"wte.weight": sd["wte.weight"],
                  "wpe.weight": sd["wpe.weight"]}}
    for i in range(L):
        layers[1 + i] = {
            local: sd[f"h.{i}.{local}"] for local in (
                "ln_1.weight", "ln_1.bias", "attn.c_attn.weight",
                "attn.c_attn.bias", "attn.c_proj.weight", "attn.c_proj.bias",
                "ln_2.weight", "ln_2.bias", "mlp.c_fc.weight",
                "mlp.c_fc.bias", "mlp.c_proj.weight", "mlp.c_proj.bias")}
    layers[L + 1] = {"ln_f.weight": sd["ln_f.weight"],
                     "ln_f.bias": sd["ln_f.bias"]}
    for idx, params in layers.items():
        gname = (lambda local, idx=idx:
                 local if idx in (0, L + 1) else f"h.{idx - 1}.{local}")
        for r in range(2):
            shard = OrderedDict(
                (local, _tp2_shard(gname(local), v, r))
                for local, v in params.items())
            torch.save(shard,
                       dirpath / f"layer_{idx:02d}-model_{r:02d}"
                                 f"-model_states.pt")


def test_pp2_tp2_pipeline_merge(tmp_path):
    """A pipeline-staged (pp=2 x tp=2) torch-DeepSpeed checkpoint loads and
    every value matches the unsharded original (reference layout:
    pipe/module.py:551 ckpt_layer_path; reshape_3d_utils concepts)."""
    from deepspeed_tpu.checkpoint.ds_native import DeepSpeedNativeCheckpoint

    rng = np.random.default_rng(11)
    sd = _hf_gpt2_sd(rng)
    _write_pp2_tp2_ckpt(tmp_path / "ck", sd)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    assert ck.tp_degree == 2
    assert len(ck.layer_files) == L + 2
    out = ck.merged_fp32_state_dict()
    assert set(out) == set(sd)
    for name, v in sd.items():
        np.testing.assert_allclose(out[name], v.numpy(), atol=1e-6,
                                   err_msg=name)


# ------------------------------------------------- non-GPT-2 merge families
# The reference's TP reshape handles arbitrary model layouts via per-model
# policy maps (module_inject containers); here each family is a rule table
# (ds_native.TP_MERGE_FAMILIES) detected from the HF weight names.

def _hf_opt_sd(rng, v=96, s=32, l=2, d=16, ffn=64):
    def t(*shape):
        return torch.tensor(rng.standard_normal(shape).astype(np.float32))

    sd = OrderedDict()
    sd["embed_tokens.weight"] = t(v, d)
    sd["embed_positions.weight"] = t(s + 2, d)
    for i in range(l):
        p = f"layers.{i}."
        sd[p + "self_attn_layer_norm.weight"] = t(d)
        sd[p + "self_attn_layer_norm.bias"] = t(d)
        for proj in ("q_proj", "k_proj", "v_proj"):
            sd[p + f"self_attn.{proj}.weight"] = t(d, d)   # [out, in]
            sd[p + f"self_attn.{proj}.bias"] = t(d)
        sd[p + "self_attn.out_proj.weight"] = t(d, d)
        sd[p + "self_attn.out_proj.bias"] = t(d)
        sd[p + "final_layer_norm.weight"] = t(d)
        sd[p + "final_layer_norm.bias"] = t(d)
        sd[p + "fc1.weight"] = t(ffn, d)
        sd[p + "fc1.bias"] = t(ffn)
        sd[p + "fc2.weight"] = t(d, ffn)
        sd[p + "fc2.bias"] = t(d)
    sd["final_layer_norm.weight"] = t(d)
    sd["final_layer_norm.bias"] = t(d)
    return sd


def _hf_llama_sd(rng, v=96, l=2, d=16, ffn=32, kv=1, heads=2):
    hd = d // heads

    def t(*shape):
        return torch.tensor(rng.standard_normal(shape).astype(np.float32))

    sd = OrderedDict()
    sd["embed_tokens.weight"] = t(v, d)
    for i in range(l):
        p = f"layers.{i}."
        sd[p + "input_layernorm.weight"] = t(d)
        sd[p + "self_attn.q_proj.weight"] = t(d, d)
        sd[p + "self_attn.k_proj.weight"] = t(kv * hd, d)
        sd[p + "self_attn.v_proj.weight"] = t(kv * hd, d)
        sd[p + "self_attn.o_proj.weight"] = t(d, d)
        sd[p + "post_attention_layernorm.weight"] = t(d)
        sd[p + "mlp.gate_proj.weight"] = t(ffn, d)
        sd[p + "mlp.up_proj.weight"] = t(ffn, d)
        sd[p + "mlp.down_proj.weight"] = t(d, ffn)
    sd["norm.weight"] = t(d)
    sd["lm_head.weight"] = t(v, d)
    return sd


def _write_family_tp2_ckpt(dirpath, sd, family):
    """tp=2 module-only checkpoint sharded by a family's merge rules
    (the inverse of ds_native._merge_tp for that family)."""
    from deepspeed_tpu.checkpoint.ds_native import TP_MERGE_FAMILIES

    cat_dims, replicated, _ = TP_MERGE_FAMILIES[family]
    dirpath.mkdir(parents=True, exist_ok=True)
    for r in range(2):
        shard = OrderedDict()
        for name, v in sd.items():
            dim = None
            for pat, dm in cat_dims:
                if pat.fullmatch(name):
                    dim = dm % v.ndim
            if any(p.fullmatch(name) for p in replicated):
                dim = None
            shard[name] = v if dim is None else torch.chunk(v, 2, dim=dim)[r]
        torch.save({"module": shard,
                    "param_shapes": [OrderedDict(
                        (k, v.shape) for k, v in shard.items())],
                    "buffer_names": [], "ds_version": "0.8.2"},
                   dirpath / f"mp_rank_{r:02d}_model_states.pt")


def test_opt_tp2_family_merge(tmp_path):
    """An OPT tp=2 torch-DeepSpeed checkpoint merges exactly: the family is
    detected from the weight names (fc1 + q_proj) and the nn.Linear
    [out, in] cat dims apply (transpose of GPT-2's Conv1D rules)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models import opt

    sd = _hf_opt_sd(np.random.default_rng(20))
    _write_family_tp2_ckpt(tmp_path / "ck", sd, "opt")
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    merged = ck.merged_fp32_state_dict()
    assert ck.family == "opt"
    for name, v in sd.items():
        np.testing.assert_array_equal(merged[name], v.numpy(), err_msg=name)

    params, icfg, _ = load_ds_checkpoint_into(str(tmp_path / "ck"))
    assert icfg.num_layers == 2 and icfg.ffn_size == 64
    assert icfg.max_seq_len == 32
    icfg.num_heads = 2  # shape inference guesses d//64; tiny fixture is 2
    logits = opt.forward(icfg, params, np.zeros((1, 8), np.int32),
                         train=False)
    assert np.isfinite(np.asarray(logits)).all()

    # sharded load must equal the unsharded convert
    from deepspeed_tpu.module_inject.replace_policy import _opt_convert
    _assert_tree_close(params, _opt_convert(icfg, sd))


def test_llama_tp2_family_merge(tmp_path):
    """A Llama (GQA) tp=2 checkpoint merges exactly under the llama rule
    table — separate q/k/v (no fused reassembly), gate/up column-parallel,
    o/down row-parallel."""
    from deepspeed_tpu.models.llama import LlamaConfig

    sd = _hf_llama_sd(np.random.default_rng(21))
    _write_family_tp2_ckpt(tmp_path / "ck", sd, "llama")
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    merged = ck.merged_fp32_state_dict()
    assert ck.family == "llama"
    for name, v in sd.items():
        np.testing.assert_array_equal(merged[name], v.numpy(), err_msg=name)

    cfg = LlamaConfig(vocab_size=96, max_seq_len=64, num_layers=2,
                      num_heads=2, num_kv_heads=1, hidden_size=16,
                      ffn_size=32, remat=False)
    params, _, _ = load_ds_checkpoint_into(str(tmp_path / "ck"), cfg=cfg)
    from deepspeed_tpu.module_inject.replace_policy import _llama_convert
    _assert_tree_close(params, _llama_convert(cfg, sd))


def test_family_explicit_override(tmp_path):
    """``family=`` wins over detection; unknown families raise."""
    sd = _hf_opt_sd(np.random.default_rng(22))
    _write_family_tp2_ckpt(tmp_path / "ck", sd, "opt")
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"), family="opt")
    assert ck.family == "opt"
    with pytest.raises(ValueError):
        DeepSpeedNativeCheckpoint(str(tmp_path / "ck"), family="nope")


def test_pipeline_non_gpt2_family_requires_name_map(tmp_path):
    """A pipeline-staged OPT/Llama checkpoint with the DEFAULT (gpt2-shaped)
    name map must refuse loudly: the mapped h.N.* names can never match the
    family's TP merge rules, so a silent rank-0 fallback would return a
    half-sharded model."""
    sd = _hf_opt_sd(np.random.default_rng(23))
    d = tmp_path / "ck"
    d.mkdir()
    locals_by_layer = {0: {"embed_tokens.weight": sd["embed_tokens.weight"],
                           "embed_positions.weight":
                               sd["embed_positions.weight"]}}
    for i in range(2):
        locals_by_layer[1 + i] = {
            k[len(f"layers.{i}."):]: v for k, v in sd.items()
            if k.startswith(f"layers.{i}.")}
    locals_by_layer[3] = {"final_layer_norm.weight":
                              sd["final_layer_norm.weight"],
                          "final_layer_norm.bias":
                              sd["final_layer_norm.bias"]}
    for idx, params in locals_by_layer.items():
        for r in range(2):
            shard = OrderedDict(
                (local, torch.chunk(v, 2, dim=0)[r]
                 if local.endswith("q_proj.weight") else v)
                for local, v in params.items())
            torch.save(shard, d / f"layer_{idx:02d}-model_{r:02d}"
                                  f"-model_states.pt")
    ck = DeepSpeedNativeCheckpoint(str(d))
    with pytest.raises(NotImplementedError, match="name_map"):
        ck.pipeline_module_state_dict()


def test_unknown_family_tp2_raises(tmp_path):
    """A tp=2 checkpoint whose names match no family's markers must refuse
    to merge (silent rank-0 fallback = half-sharded model)."""
    d = tmp_path / "ck"
    d.mkdir()
    for r in range(2):
        shard = OrderedDict(
            [("some.exotic.proj.weight",
              torch.zeros(8, 4)), ("other.norm.weight", torch.zeros(8))])
        torch.save({"module": shard,
                    "param_shapes": [OrderedDict(
                        (k, v.shape) for k, v in shard.items())],
                    "buffer_names": [], "ds_version": "0.8.2"},
                   d / f"mp_rank_{r:02d}_model_states.pt")
    ck = DeepSpeedNativeCheckpoint(str(d))
    with pytest.raises(ValueError, match="TP merge family"):
        ck.merged_fp32_state_dict()
