"""Torch-DeepSpeed checkpoint ingestion (the migration path).

Fixtures are hand-built in the reference on-disk format
(``mp_rank_XX_model_states.pt`` + ``zero_pp_rank_*_optim_states.pt``,
``deepspeed/checkpoint/deepspeed_checkpoint.py:39`` /
``utils/zero_to_fp32.py`` protocol) and must load into our GPT-2 pytree
with exact values.
"""

import math
from collections import OrderedDict

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import (DeepSpeedNativeCheckpoint,
                                      load_ds_checkpoint_into)
from deepspeed_tpu.models import gpt2

V, S, L, H, D = 96, 32, 2, 2, 16


def _hf_gpt2_sd(rng):
    """Random fp32 HF-GPT-2-named state dict for the tiny shape."""
    def t(*shape):
        return torch.tensor(rng.standard_normal(shape).astype(np.float32))

    sd = OrderedDict()
    sd["wte.weight"] = t(V, D)
    sd["wpe.weight"] = t(S, D)
    for i in range(L):
        sd[f"h.{i}.ln_1.weight"] = t(D)
        sd[f"h.{i}.ln_1.bias"] = t(D)
        sd[f"h.{i}.attn.c_attn.weight"] = t(D, 3 * D)
        sd[f"h.{i}.attn.c_attn.bias"] = t(3 * D)
        sd[f"h.{i}.attn.c_proj.weight"] = t(D, D)
        sd[f"h.{i}.attn.c_proj.bias"] = t(D)
        sd[f"h.{i}.ln_2.weight"] = t(D)
        sd[f"h.{i}.ln_2.bias"] = t(D)
        sd[f"h.{i}.mlp.c_fc.weight"] = t(D, 4 * D)
        sd[f"h.{i}.mlp.c_fc.bias"] = t(4 * D)
        sd[f"h.{i}.mlp.c_proj.weight"] = t(4 * D, D)
        sd[f"h.{i}.mlp.c_proj.bias"] = t(D)
    sd["ln_f.weight"] = t(D)
    sd["ln_f.bias"] = t(D)
    return sd


def _write_zero2_ckpt(dirpath, sd, dp=2):
    """Reference ZeRO-2 layout: fp16 module + per-dp-rank flat fp32
    partitions with 2*world alignment padding (zero_to_fp32.py:253)."""
    flat = torch.cat([v.reshape(-1) for v in sd.values()])
    align = 2 * dp
    padded = math.ceil(flat.numel() / align) * align
    flat = torch.cat([flat, torch.zeros(padded - flat.numel())])
    part = padded // dp
    (dirpath / "mp_rank_00_model_states.pt").parent.mkdir(
        parents=True, exist_ok=True)
    torch.save({
        "module": OrderedDict((k, v.half()) for k, v in sd.items()),
        "param_shapes": [OrderedDict((k, v.shape) for k, v in sd.items())],
        "buffer_names": [],
        "ds_version": "0.8.2",
        "global_steps": 7,
    }, dirpath / "mp_rank_00_model_states.pt")
    for r in range(dp):
        torch.save({
            "optimizer_state_dict": {
                "zero_stage": 2,
                "partition_count": dp,
                "single_partition_of_fp32_groups":
                    [flat[r * part:(r + 1) * part].clone()],
            }
        }, dirpath / f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt")


def _write_zero3_ckpt(dirpath, sd, dp=2):
    """ZeRO-3: partitions zip at EACH param boundary with per-param
    padding (zero_to_fp32.py zero3_partitioned_param_info)."""
    per_rank = [[] for _ in range(dp)]
    for v in sd.values():
        flat = v.reshape(-1)
        part = math.ceil(flat.numel() / dp)
        flat = torch.cat([flat, torch.zeros(part * dp - flat.numel())])
        for r in range(dp):
            per_rank[r].append(flat[r * part:(r + 1) * part])
    dirpath.mkdir(parents=True, exist_ok=True)
    torch.save({
        "module": OrderedDict((k, v.half()) for k, v in sd.items()),
        "param_shapes": [OrderedDict((k, v.shape) for k, v in sd.items())],
        "buffer_names": [],
        "ds_version": "0.8.2",
    }, dirpath / "mp_rank_00_model_states.pt")
    for r in range(dp):
        torch.save({
            "optimizer_state_dict": {
                "zero_stage": 3,
                "fp32_flat_groups": [torch.cat(per_rank[r])],
            }
        }, dirpath / f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt")


def _write_tp2_ckpt(dirpath, sd):
    """tp=2 module-only checkpoint: column weights split on the out dim,
    row weights on the in dim, norms replicated."""
    from deepspeed_tpu.checkpoint.ds_native import (GPT2_CAT_DIMS,
                                                    GPT2_QKV_FUSED,
                                                    GPT2_REPLICATED)

    dirpath.mkdir(parents=True, exist_ok=True)
    for r in range(2):
        shard = OrderedDict()
        for name, v in sd.items():
            if any(p.fullmatch(name) for p in GPT2_QKV_FUSED):
                # Megatron/AutoTP fused-qkv sharding: each rank gets its
                # head-slice of EACH of q, k, v, concatenated q_r|k_r|v_r
                q, k_, v_ = torch.chunk(v, 3, dim=-1)
                shard[name] = torch.cat(
                    [torch.chunk(t, 2, dim=-1)[r] for t in (q, k_, v_)],
                    dim=-1)
                continue
            dim = None
            for pat, d in GPT2_CAT_DIMS:
                if pat.fullmatch(name):
                    dim = d % v.ndim
            if any(p.fullmatch(name) for p in GPT2_REPLICATED):
                dim = None
            if dim is None:
                shard[name] = v
            else:
                shard[name] = torch.chunk(v, 2, dim=dim)[r]
        torch.save({"module": shard,
                    "param_shapes": [OrderedDict(
                        (k, v.shape) for k, v in shard.items())],
                    "buffer_names": [], "ds_version": "0.8.2"},
                   dirpath / f"mp_rank_{r:02d}_model_states.pt")


def _expected_params(sd):
    cfg = gpt2.GPT2Config(vocab_size=V, max_seq_len=S, num_layers=L,
                          num_heads=H, hidden_size=D)
    from deepspeed_tpu.module_inject.replace_policy import _gpt2_convert

    return cfg, _gpt2_convert(cfg, sd)


def _assert_tree_close(got, want, atol=0.0):
    import jax

    for g, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol)


def test_zero2_checkpoint_roundtrip(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(0))
    _write_zero2_ckpt(tmp_path / "global_step7", sd, dp=2)
    (tmp_path / "latest").write_text("global_step7")

    ck = DeepSpeedNativeCheckpoint(str(tmp_path))
    assert ck.tp_degree == 1 and ck.dp_degree == 2
    fp32 = ck.fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(fp32[name], v.numpy())

    params, icfg, client = load_ds_checkpoint_into(str(tmp_path))
    _, want = _expected_params(sd)
    _assert_tree_close(params, want)
    assert client["global_steps"] == 7


def test_zero3_checkpoint_roundtrip(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(1))
    _write_zero3_ckpt(tmp_path / "ck", sd, dp=2)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    fp32 = ck.fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(fp32[name], v.numpy())


def test_tp2_module_merge(tmp_path):
    sd = _hf_gpt2_sd(np.random.default_rng(2))
    _write_tp2_ckpt(tmp_path / "ck", sd)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    assert ck.tp_degree == 2
    merged = ck.merged_fp32_state_dict()
    for name, v in sd.items():
        np.testing.assert_array_equal(merged[name], v.numpy())


def test_loaded_params_run_forward(tmp_path):
    import jax

    sd = _hf_gpt2_sd(np.random.default_rng(3))
    _write_zero2_ckpt(tmp_path / "ck", sd, dp=2)
    params, icfg, _ = load_ds_checkpoint_into(str(tmp_path / "ck"))
    cfg, _ = _expected_params(sd)
    logits = gpt2.forward(cfg, params,
                          np.zeros((1, 8), np.int32), train=False)
    assert np.isfinite(np.asarray(logits)).all()


def _tp2_shard(name, v, r):
    """TP shard of one param by the GPT-2 merge rules (inverse of merge)."""
    from deepspeed_tpu.checkpoint.ds_native import (GPT2_CAT_DIMS,
                                                    GPT2_QKV_FUSED,
                                                    GPT2_REPLICATED)

    if any(p.fullmatch(name) for p in GPT2_QKV_FUSED):
        q, k_, v_ = torch.chunk(v, 3, dim=-1)
        return torch.cat([torch.chunk(t, 2, dim=-1)[r] for t in (q, k_, v_)],
                         dim=-1)
    if any(p.fullmatch(name) for p in GPT2_REPLICATED):
        return v
    for pat, d in GPT2_CAT_DIMS:
        if pat.fullmatch(name):
            return torch.chunk(v, 2, dim=d % v.ndim)[r]
    return v


def _write_pp2_tp2_ckpt(dirpath, sd):
    """Pipeline-staged pp=2 x tp=2 layout (reference pipe/module.py
    save_state_dict): layer_{idx:02d}-model_{tp:02d}-model_states.pt with
    LOCAL names; stage 0 holds layers 0..L/2, stage 1 the rest."""
    dirpath.mkdir(parents=True, exist_ok=True)
    layers = {0: {"wte.weight": sd["wte.weight"],
                  "wpe.weight": sd["wpe.weight"]}}
    for i in range(L):
        layers[1 + i] = {
            local: sd[f"h.{i}.{local}"] for local in (
                "ln_1.weight", "ln_1.bias", "attn.c_attn.weight",
                "attn.c_attn.bias", "attn.c_proj.weight", "attn.c_proj.bias",
                "ln_2.weight", "ln_2.bias", "mlp.c_fc.weight",
                "mlp.c_fc.bias", "mlp.c_proj.weight", "mlp.c_proj.bias")}
    layers[L + 1] = {"ln_f.weight": sd["ln_f.weight"],
                     "ln_f.bias": sd["ln_f.bias"]}
    for idx, params in layers.items():
        gname = (lambda local, idx=idx:
                 local if idx in (0, L + 1) else f"h.{idx - 1}.{local}")
        for r in range(2):
            shard = OrderedDict(
                (local, _tp2_shard(gname(local), v, r))
                for local, v in params.items())
            torch.save(shard,
                       dirpath / f"layer_{idx:02d}-model_{r:02d}"
                                 f"-model_states.pt")


def test_pp2_tp2_pipeline_merge(tmp_path):
    """A pipeline-staged (pp=2 x tp=2) torch-DeepSpeed checkpoint loads and
    every value matches the unsharded original (reference layout:
    pipe/module.py:551 ckpt_layer_path; reshape_3d_utils concepts)."""
    from deepspeed_tpu.checkpoint.ds_native import DeepSpeedNativeCheckpoint

    rng = np.random.default_rng(11)
    sd = _hf_gpt2_sd(rng)
    _write_pp2_tp2_ckpt(tmp_path / "ck", sd)
    ck = DeepSpeedNativeCheckpoint(str(tmp_path / "ck"))
    assert ck.tp_degree == 2
    assert len(ck.layer_files) == L + 2
    out = ck.merged_fp32_state_dict()
    assert set(out) == set(sd)
    for name, v in sd.items():
        np.testing.assert_allclose(out[name], v.numpy(), atol=1e-6,
                                   err_msg=name)
