"""graft-race dynamic half (``analysis/concurrency.py``): fault
injection for the runtime lock-order / blocking sanitizer.

Covers the two acceptance scenarios — a deliberate two-thread
lock-order inversion and a blocking-call-under-lock, each raising with
BOTH acquisition sites named under ``debug_checks=True`` and passing
untouched with ``debug_checks=False`` — plus the primitive-level
contracts: declared-rank and ascending-key checks, Condition
integration (``wait`` releases the held-set entry), re-entrancy, and
the check/violation counters the router surfaces.

Everything here is jax-free: the router scenarios run on the same fake
replicas ``test_replica_router.py`` uses for routing units.
"""

import threading

import numpy as np
import pytest

from deepspeed_tpu.analysis.concurrency import (
    BlockingUnderLockError, LockOrderError, LockSanitizer, OrderedLock,
    held_locks, ordered_condition)
from deepspeed_tpu.inference.serving import Request, RequestHandle
from deepspeed_tpu.serving import ReplicaRouter


# ----------------------------------------------------------- fake replica
class _FakeReplica:
    """Minimal ServingEngine protocol for jax-free router construction
    (mirrors test_replica_router.py's double)."""

    block_size = 8
    _host = None
    _prefix = None

    def __init__(self):
        from deepspeed_tpu.telemetry import MetricsRegistry, TraceTimeline

        self.metrics = MetricsRegistry()
        self.timeline = TraceTimeline(capacity=0)
        self._pending = []
        self._active = {}
        self._cancel_flags = set()
        self._slo = None
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.admitted = 0
        self.compile_count = 0
        self.compile_budget = 2
        self._c_gen_tokens = type("C", (), {"value": 0.0})()

        class _Alloc:
            blocks_in_use = 0
        self._alloc = _Alloc()

    def affinity_probe(self, prompt):
        return {"device_blocks": 0, "host_blocks": 0, "blocks_in_use": 0,
                "queue_depth": 0, "active": 0}

    def submit(self, request, **kw):
        return RequestHandle(request)

    def step(self):
        return False


def _mk_router(debug_checks):
    return ReplicaRouter([_FakeReplica(), _FakeReplica()],
                         kv_pull=False, debug_checks=debug_checks,
                         trace_capacity=0)


def _run_in_thread(fn):
    err = {}

    def runner():
        try:
            fn()
        except BaseException as e:        # noqa: BLE001 — reraised below
            err["e"] = e
    t = threading.Thread(target=runner)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive(), "injected scenario thread hung"
    return err.get("e")


# ----------------------------------------- injected lock-order inversion
def _inversion_scenario(router):
    """Two threads acquiring (fleet -> replica0) then (replica0 ->
    fleet), sequenced so no real deadlock can occur — the sanitizer must
    still catch the POTENTIAL deadlock from the order graph."""
    fleet, rep0 = router._fleet_lock, router._locks[0]

    def forward():
        with fleet:
            with rep0:
                pass

    def inverted():
        with rep0:
            with fleet:               # replica -> fleet: inverted
                pass

    e1 = _run_in_thread(forward)
    if e1 is not None:
        raise e1
    e2 = _run_in_thread(inverted)
    if e2 is not None:
        raise e2


def test_injected_inversion_raises_with_both_sites_under_debug():
    router = _mk_router(debug_checks=True)
    with pytest.raises(LockOrderError) as ei:
        _inversion_scenario(router)
    msg = str(ei.value)
    # both acquisition sites (this file) are named
    assert msg.count(__file__) >= 2, msg
    assert "serving.fleet" in msg and "serving.replica" in msg
    assert router.stats()["lock_violations"] >= 1
    assert router.stats()["lock_order_checks"] >= 1


def test_injected_inversion_passes_with_debug_off():
    router = _mk_router(debug_checks=False)
    assert isinstance(router._fleet_lock, type(threading.RLock()))
    _inversion_scenario(router)           # plain RLocks: no sanitizer
    st = router.stats()
    assert st["lock_order_checks"] == 0 and st["lock_violations"] == 0


def test_two_thread_cycle_detected_across_threads():
    """The order graph is cross-thread: thread 1 records a->b, thread 2
    trips on b->a."""
    san = LockSanitizer()
    a = OrderedLock("test.a", sanitizer=san)
    b = OrderedLock("test.b", sanitizer=san)

    def t1():
        with a:
            with b:
                pass
    assert _run_in_thread(t1) is None

    def t2():
        with b:
            with a:
                pass
    err = _run_in_thread(t2)
    assert isinstance(err, LockOrderError)
    assert "opposite order" in str(err)
    assert san.violations == 1


# -------------------------------------------- injected blocking-under-lock
def _blocking_scenario(router):
    """``handle.result()`` (a blocking wait) entered while the calling
    thread holds the fleet lock — the scheduler that would finish the
    request could never run: a guaranteed deadlock without the
    timeout."""
    rep = router.replicas[0]
    handle = RequestHandle(Request(uid=7, prompt=np.array([1, 2, 3])),
                           lock_sanitizer=getattr(rep, "_lock_sanitizer",
                                                  None))
    handle._on_finish(np.array([1, 2, 3, 4]))
    with router._fleet_lock:
        return handle.result(timeout=1.0)


def test_injected_blocking_under_lock_raises_with_both_sites():
    router = _mk_router(debug_checks=True)
    # the router shares its sanitizer with every replica (handles the
    # replicas mint from now on participate in the checks)
    assert router.replicas[0]._lock_sanitizer is router._sanitizer
    with pytest.raises(BlockingUnderLockError) as ei:
        _blocking_scenario(router)
    msg = str(ei.value)
    assert "RequestHandle.result" in msg
    assert "serving.fleet" in msg
    assert msg.count(__file__) >= 2, msg   # wait site + acquire site
    assert router.stats()["lock_violations"] >= 1


def test_injected_blocking_passes_with_debug_off():
    router = _mk_router(debug_checks=False)
    out = _blocking_scenario(router)
    np.testing.assert_array_equal(out, np.array([1, 2, 3, 4]))


def test_condition_wait_under_foreign_lock_raises():
    san = LockSanitizer()
    cond = ordered_condition("serving.handle", san)
    other = OrderedLock("serving.fleet", sanitizer=san)
    with pytest.raises(BlockingUnderLockError):
        with other:
            with cond:
                cond.wait(0.01)
    assert held_locks() == []             # unwound cleanly


# --------------------------------------------------- primitive contracts
def test_declared_rank_and_key_order():
    san = LockSanitizer()
    fleet = OrderedLock("serving.fleet", sanitizer=san)
    r0 = OrderedLock("serving.replica", key=0, sanitizer=san)
    r1 = OrderedLock("serving.replica", key=1, sanitizer=san)
    with fleet:
        with r0:
            with r1:                      # ascending keys: fine
                pass
    with pytest.raises(LockOrderError, match="ascending key"):
        with r1:
            with r0:
                pass
    with pytest.raises(LockOrderError, match="declared-order"):
        with r0:
            with fleet:
                pass
    assert held_locks() == []


def test_reentrant_acquire_is_not_a_violation():
    san = LockSanitizer()
    lk = OrderedLock("serving.fleet", sanitizer=san)
    with lk:
        with lk:
            assert len(held_locks()) == 2
    assert held_locks() == []
    assert san.violations == 0


def test_condition_wait_notify_roundtrip_keeps_held_set_exact():
    san = LockSanitizer()
    cond = ordered_condition("serving.handle", san)
    state = {"ready": False}

    def setter():
        with cond:
            state["ready"] = True
            cond.notify_all()

    with cond:
        threading.Thread(target=setter, daemon=True).start()
        assert cond.wait_for(lambda: state["ready"], timeout=10)
        assert len(held_locks()) == 1     # re-acquired after the wait
    assert held_locks() == []


def test_check_counter_callback_fires():
    san = LockSanitizer()
    ticks = []
    san.on_check = lambda: ticks.append(1)
    a = OrderedLock("serving.fleet", sanitizer=san)
    b = OrderedLock("serving.replica", sanitizer=san)
    with a:
        with b:
            pass
    assert san.checks == 1 and ticks == [1]


def test_wait_observer_records_contended_wait():
    waits = []
    san = LockSanitizer()
    lk = OrderedLock("serving.fleet", sanitizer=san,
                     wait_observer=waits.append)
    hold = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            hold.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert hold.wait(5)
    start_len = len(waits)

    def contender():
        with lk:
            pass

    t2 = threading.Thread(target=contender, daemon=True)
    t2.start()
    import time as _time
    _time.sleep(0.05)
    release.set()
    t2.join(5)
    t.join(5)
    assert len(waits) >= start_len + 1
    assert max(waits) >= 0.02             # the contender really waited
