"""SPMD pipeline engine E2E (model: reference tests/unit/runtime/pipe/test_pipe.py,
which trains a pipelined model and compares loss to the DP baseline)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


def tiny_model():
    return gpt2.build(gpt2.GPT2Config.tiny())


def config(pp=1, gas=4, tp=1):
    # train_batch=32, gas=4 -> micro_global=8, divisible by dp for every mesh
    # variant used here, so all runs consume identical global batches
    return {
        "train_batch_size": 32,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"pp": pp, "tp": tp},
    }


def run(cfg, steps=3, seed=0):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(m["loss"])
    return engine, losses


def test_pipeline_engine_selected(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(),
                                               config=config(pp=2))
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    assert isinstance(engine, PipelineEngine)
    with pytest.raises(RuntimeError):
        engine.forward({"input_ids": np.zeros((2, 33), np.int32)})


def test_pp2_matches_dp_baseline(eight_devices):
    _, base = run(config(pp=1))
    _, pp = run(config(pp=2))
    np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-4)


def test_pp4_matches_dp_baseline(eight_devices):
    cfg4 = gpt2.GPT2Config(vocab_size=512, max_seq_len=64, num_layers=4,
                           num_heads=4, hidden_size=64)

    def run4(cfg):
        deepspeed_tpu.comm.reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(model=gpt2.build(cfg4),
                                                   config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(3):
            batch = {"input_ids": rng.integers(
                0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
            _, m = engine.train_batch(batch)
            losses.append(m["loss"])
        return losses

    base = run4(config(pp=1))
    pp = run4(config(pp=4))
    np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-4)


def test_pp_with_tp(eight_devices):
    _, base = run(config(pp=1))
    _, pptp = run(config(pp=2, tp=2))
    np.testing.assert_allclose(base, pptp, rtol=2e-4, atol=1e-4)


def test_pp_blocks_sharded(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(),
                                               config=config(pp=2))
    qkv = engine.state["params"]["blocks"]["qkv_w"]  # [2, d, 3d]
    assert qkv.addressable_shards[0].data.shape[0] == 1  # layer dim split 2-way


def test_pp_labels_with_ignore_index_matches_dp(eight_devices):
    """pp>1 must honor explicit labels incl. -100 masking, like the DP path."""
    def run_labeled(cfg):
        deepspeed_tpu.comm.reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_model(),
                                                   config=cfg)
        rng = np.random.default_rng(7)
        losses = []
        for _ in range(2):
            ids = rng.integers(0, 512,
                               size=(engine.train_batch_size(), 32)).astype(np.int32)
            labels = ids.copy()
            labels[:, :5] = -100  # mask a prefix (HF ignore convention)
            _, m = engine.train_batch({"input_ids": ids, "labels": labels})
            losses.append(m["loss"])
        return losses

    base = run_labeled(config(pp=1))
    pp = run_labeled(config(pp=2))
    np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-4)
