"""sparse_gradients: true -> row-sparse embedding-grad exchange.

Reference behavior: the engine all-reduces embedding grads as (indices,
values) pairs instead of dense [V, D] (runtime/engine.py:2461-2476
``sparse_allreduce_no_retain``).  Here the model's wte lookup routes
through ``sparse_embedding_lookup`` whose backward all-gathers only the
touched rows inside shard_map (runtime/sparse_tensor.py).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.sparse_tensor import sparse_embedding_lookup


def _cfg(extra=None):
    c = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if extra:
        c.update(extra)
    return c


def _fresh(sparse):
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny()
    cfg.tie_embeddings = False  # tied head adds a dense [V,D] grad anyway
    model = gpt2.build(cfg)
    extra = {"sparse_gradients": True} if sparse else None
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=_cfg(extra))
    return cfg, engine


def test_config_flips_model_knob():
    cfg, _ = _fresh(sparse=True)
    assert cfg.sparse_embedding_grad is True
    cfg2, _ = _fresh(sparse=False)
    assert cfg2.sparse_embedding_grad is False


def test_loss_and_grad_parity_vs_dense():
    # the sparse exchange is exact (duplicates accumulate in the scatter):
    # training curves must match the dense path
    rng = np.random.default_rng(0)
    cfg, dense_eng = _fresh(sparse=False)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(dense_eng.train_batch_size(), 33)).astype(np.int32)}
    dense_losses = [float(dense_eng.train_batch(batch)[1]["loss"])
                    for _ in range(3)]

    _, sparse_eng = _fresh(sparse=True)
    sparse_losses = [float(sparse_eng.train_batch(batch)[1]["loss"])
                     for _ in range(3)]
    np.testing.assert_allclose(dense_losses, sparse_losses, rtol=1e-5)


def test_exchange_volume_drops_in_hlo():
    # behavioral proof at the compiler level: with the sparse exchange the
    # program has NO dense-[V,D]-shaped all-reduce; it all-gathers the
    # [T_local, D] cotangent rows instead
    from jax.sharding import NamedSharding, PartitionSpec as P

    v, d, b, s = 4096, 64, 8, 16  # tokens-per-device (16) << vocab
    deepspeed_tpu.comm.reset_topology()
    mesh = deepspeed_tpu.comm.get_mesh()  # default: all devices on dp
    assert mesh.shape["dp"] == 8
    try:
        table = jnp.zeros((v, d), jnp.float32)
        ids = jnp.zeros((b, s), jnp.int32)

        def loss_sparse(t, i):
            return jnp.sum(sparse_embedding_lookup(t, i) ** 2)

        def loss_dense(t, i):
            return jnp.sum(t[i] ** 2)

        tspec = NamedSharding(mesh, P())
        ispec = NamedSharding(mesh, P("dp"))
        dense_hlo = jax.jit(
            jax.grad(loss_dense),
            in_shardings=(tspec, ispec), out_shardings=tspec,
        ).lower(table, ids).compile().as_text()
        sparse_hlo = jax.jit(
            jax.grad(loss_sparse),
            in_shardings=(tspec, ispec), out_shardings=tspec,
        ).lower(table, ids).compile().as_text()
    finally:
        deepspeed_tpu.comm.reset_topology()

    def dense_allreduce_count(hlo):
        # any all-reduce over a [V, D]-sized f32 operand
        return len(re.findall(rf"all-reduce[^\n]*f32\[{v},{d}\]", hlo))

    assert dense_allreduce_count(dense_hlo) >= 1, "dense baseline missing AR"
    assert dense_allreduce_count(sparse_hlo) == 0
    assert "all-gather" in sparse_hlo


def test_single_device_path():
    # no data axes -> plain local scatter, still exact
    deepspeed_tpu.comm.reset_topology()
    table = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
    ids = jnp.array([[1, 2, 2, 5]], jnp.int32)
    ct = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 8))

    def f(t):
        return jnp.sum(sparse_embedding_lookup(t, ids) * ct)

    def f_ref(t):
        return jnp.sum(t[ids] * ct)

    g = jax.grad(f)(table)
    gr = jax.grad(f_ref)(table)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-6)
