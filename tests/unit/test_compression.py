"""Compression suite tests (reference ``tests/unit/compression/
test_compression.py``: quantization/pruning numerics + init_compression)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (fake_quantize, head_pruning_mask,
                                       init_compression, redundancy_clean,
                                       row_pruning_mask, sparse_pruning_mask)
from deepspeed_tpu.compression.compress import apply_layer_reduction
from deepspeed_tpu.models import gpt2


# ------------------------------------------------------------------- quant
def test_fake_quantize_symmetric_8bit_accuracy():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 64)).astype(np.float32)
    q = np.asarray(fake_quantize(jnp.asarray(w), 8, 4, "symmetric", False))
    assert not np.array_equal(q, w)            # actually quantized
    assert np.abs(q - w).max() < np.abs(w).max() / 60  # 8-bit error bound
    # quantization is idempotent
    q2 = np.asarray(fake_quantize(jnp.asarray(q), 8, 4, "symmetric", False))
    np.testing.assert_allclose(q2, q, atol=1e-6)


def test_fake_quantize_asymmetric():
    w = np.linspace(0.0, 1.0, 256).astype(np.float32).reshape(16, 16)
    q = np.asarray(fake_quantize(jnp.asarray(w), 4, 1, "asymmetric", False))
    assert len(np.unique(q.round(6))) <= 16    # 4 bits -> <=16 levels
    assert np.abs(q - w).max() < 0.05


def test_fake_quantize_straight_through_gradient():
    w = jnp.asarray(np.random.default_rng(1).normal(size=(8, 8)),
                    jnp.float32)
    g = jax.grad(lambda w: jnp.sum(fake_quantize(w, 4, 1, "symmetric",
                                                 False) ** 2))(w)
    # STE: gradient flows as if identity (2*q(w), not zero)
    assert np.abs(np.asarray(g)).max() > 0.1


# ----------------------------------------------------------------- pruning
def test_sparse_pruning_mask_ratio():
    w = jnp.asarray(np.random.default_rng(2).normal(size=(32, 32)),
                    jnp.float32)
    m = np.asarray(sparse_pruning_mask(w, 0.25))
    assert abs(m.mean() - 0.25) < 0.01
    kept = np.abs(np.asarray(w))[m > 0]
    dropped = np.abs(np.asarray(w))[m == 0]
    assert kept.min() >= dropped.max() - 1e-6  # magnitude criterion


def test_row_pruning_mask():
    w = jnp.asarray(np.diag(np.arange(1.0, 9.0)), jnp.float32)
    m = np.asarray(row_pruning_mask(w, 0.5))
    assert m[:4].sum() == 0 and m[4:].sum() == 4 * 8  # smallest rows dropped


def test_head_pruning_mask():
    # 4 heads x head_dim 2, out 8; zero out heads 0-1
    w = np.ones((8, 8), np.float32)
    w[:4] = 1e-4
    m = np.asarray(head_pruning_mask(jnp.asarray(w), 0.5, num_heads=4))
    assert m[:4].sum() == 0 and m[4:].sum() == 4 * 8


# --------------------------------------------------------- init_compression
def _compression_cfg():
    return {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "quantize_groups": 2},
            "different_groups": {
                "wq1": {"params": {"target_bits": 8},
                        "modules": ["*fc_w*", "*proj_w*"]}},
        },
        "sparse_pruning": {
            "shared_parameters": {"enabled": True},
            "different_groups": {
                "sp1": {"params": {"dense_ratio": 0.5},
                        "modules": ["*qkv_w*"]}},
        },
    }}


def test_init_compression_wraps_model_and_trains():
    deepspeed_tpu.comm.reset_topology()
    spec = gpt2.build(gpt2.GPT2Config.tiny())
    wrapped = init_compression(spec, _compression_cfg())
    assert wrapped.name.endswith("+compressed")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=wrapped,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(4):
        batch = {"input_ids": rng.integers(
            0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_redundancy_clean_bakes_compression():
    spec = gpt2.build(gpt2.GPT2Config.tiny())
    params = spec.init(jax.random.PRNGKey(0))
    cleaned = redundancy_clean(params, _compression_cfg())
    qkv = np.asarray(cleaned["blocks"]["qkv_w"])
    # sparse pruning at 0.5 -> about half the qkv weights are zero
    assert 0.4 < (qkv == 0).mean() < 0.6
    # untouched leaves unchanged
    np.testing.assert_array_equal(np.asarray(cleaned["wte"]),
                                  np.asarray(params["wte"]))


def test_layer_reduction_student_init():
    spec = gpt2.build(gpt2.GPT2Config.tiny())
    params = spec.init(jax.random.PRNGKey(0))
    student = apply_layer_reduction(params, ("blocks",), [1])
    assert jax.tree_util.tree_leaves(student["blocks"])[0].shape[0] == 1
    np.testing.assert_array_equal(
        np.asarray(student["blocks"]["fc_w"][0]),
        np.asarray(params["blocks"]["fc_w"][1]))


# ----------------------------------------------------------- MoQ/eigenvalue
def test_moq_bit_schedule():
    from deepspeed_tpu.runtime.quantize import Quantizer

    q = Quantizer(q_target_bits=4, q_start_bits=8, q_period=10, q_offset=5)
    assert q.current_bits(0) == 8
    assert q.current_bits(15) == 7
    assert q.current_bits(1000) == 4
    # eigenvalue guidance slows sensitive layers
    assert q.current_bits(15, eigenvalue_ratio=1.0) == 8


def test_power_iteration_finds_leading_eigenvalue():
    from deepspeed_tpu.runtime.eigenvalue import power_iteration

    a = np.diag([5.0, 1.0, 0.1]).astype(np.float32)
    lam, v = power_iteration(lambda x: jnp.asarray(a) @ x,
                             jnp.ones(3), iters=50)
    assert abs(float(lam) - 5.0) < 1e-3
    assert abs(abs(float(v[0])) - 1.0) < 1e-2


def test_hessian_eigenvalue_quadratic():
    from deepspeed_tpu.runtime.eigenvalue import hessian_eigenvalue

    # loss = sum(c_i x_i^2): Hessian eigenvalues 2*c -> leading 6
    def loss(p):
        return jnp.sum(jnp.asarray([3.0, 1.0, 0.5]) * p["x"] ** 2)

    lam = hessian_eigenvalue(loss, {"x": jnp.ones(3)}, iters=50)
    assert abs(float(lam) - 6.0) < 1e-2


def test_progressive_layer_drop_schedule():
    from deepspeed_tpu.runtime.progressive_layer_drop import (
        ProgressiveLayerDrop)

    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta(0) == pytest.approx(1.0)
    assert pld.get_theta(10**6) == pytest.approx(0.5)
    assert pld.get_theta(100) < pld.get_theta(10)
    # deeper layers drop more
    assert pld.layer_keep_prob(11, 12, 1000) < \
        pld.layer_keep_prob(0, 12, 1000)


def test_schedule_offset_delays_compression():
    """Before schedule_offset the forward sees raw weights; after, quantized
    (reference applies compression from schedule_offset onward)."""
    deepspeed_tpu.comm.reset_topology()
    cfg = {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 2,
                                  "quantize_groups": 1},
            "different_groups": {
                "g": {"params": {"target_bits": 2},  # 2 bits: huge effect
                      "modules": ["*fc_w*"]}}},
    }}
    spec = gpt2.build(gpt2.GPT2Config.tiny())
    wrapped = init_compression(spec, cfg)
    assert not wrapped._compression_toggle.active()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=wrapped,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 0.0}}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
    _, m1 = engine.train_batch(batch)        # step 1: uncompressed
    assert not wrapped._compression_toggle.active()
    _, m2 = engine.train_batch(batch)        # step 2: uncompressed
    _, m3 = engine.train_batch(batch)        # step 3: compressed (2-bit!)
    assert wrapped._compression_toggle.active()
    # lr=0 so params don't change: loss delta isolates the quantization
    assert abs(m2["loss"] - m1["loss"]) < 1e-5
    assert abs(m3["loss"] - m2["loss"]) > 1e-3


def test_stochastic_rounding_rejected():
    with pytest.raises(NotImplementedError, match="stochastic"):
        init_compression(gpt2.build(gpt2.GPT2Config.tiny()),
                         {"compression_training": {"weight_quantization": {
                             "shared_parameters": {
                                 "enabled": True, "rounding": "stochastic"},
                             "different_groups": {
                                 "g": {"modules": ["*"]}}}}})


# ------------------------------------------------ round-3 depth mechanisms
def test_channel_pruning_mask_and_rule():
    from deepspeed_tpu.compression import channel_pruning_mask
    from deepspeed_tpu.compression.compress import (_build_transform,
                                                    compress_params)
    from deepspeed_tpu.compression.config import get_compression_config

    w = jnp.asarray(np.random.default_rng(0).standard_normal((3, 3, 4, 8)),
                    jnp.float32)
    mask = channel_pruning_mask(w, 0.5)
    kept = np.unique(np.asarray(mask).reshape(-1, 8).sum(0))
    # structured: a channel is fully kept or fully zero
    per_channel = np.asarray(mask).any(axis=(0, 1, 2))
    assert per_channel.sum() == 4
    assert np.all((np.asarray(mask).sum(axis=(0, 1, 2)) == 0) |
                  (np.asarray(mask).sum(axis=(0, 1, 2)) == 36))

    cfg = get_compression_config({"compression_training": {
        "channel_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"cp1": {"params": {"dense_ratio": 0.5},
                                         "modules": ["conv"]}}}}})
    rules = _build_transform(cfg, None)
    params = {"conv_w": w, "dense": jnp.ones((4, 4))}
    # before the offset: untouched; after: channels zeroed
    before = compress_params(params, rules, step=0)
    np.testing.assert_array_equal(np.asarray(before["conv_w"]),
                                  np.asarray(w))
    after = compress_params(params, rules, step=5)
    zeroed = (np.asarray(after["conv_w"]).sum(axis=(0, 1, 2)) == 0).sum()
    assert zeroed == 4
    np.testing.assert_array_equal(np.asarray(after["dense"]), 1.0)


def test_embedding_quantization_via_weight_group():
    """Embedding quantization = a weight_quantization group targeting the
    embedding leaves (reference Embedding_Compress)."""
    from deepspeed_tpu.compression import init_compression

    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    wrapped = init_compression(model, {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "quantize_groups": 4},
            "different_groups": {"emb": {"params": {"target_bits": 4},
                                         "modules": ["wte"]}}}}})
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    from deepspeed_tpu.compression.compress import compress_params

    q = compress_params(params, wrapped._compression_rules, step=0)
    wte_q = np.asarray(q["wte"])
    # quantized to a 4-bit grid: few unique values per group
    assert len(np.unique(wte_q)) < len(np.unique(np.asarray(params["wte"])))
    # other leaves untouched
    np.testing.assert_array_equal(np.asarray(q["blocks"]["qkv_w"]),
                                  np.asarray(params["blocks"]["qkv_w"]))


def test_activation_quantization_behavioral():
    """activation_quantization flips the model's act_quant_bits knob:
    losses differ vs the dense model, grads stay finite (STE)."""
    from deepspeed_tpu.compression import init_compression

    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)
    wrapped = init_compression(model, {"compression_training": {
        "activation_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"aq": {"params": {"target_bits": 4}}}}}})
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 17)).astype(np.int32)}
    dense = float(model.loss_fn(params, batch, None, True))
    # wrapped loss: act quant active at step 0
    lq, grads = jax.value_and_grad(
        lambda p: wrapped.loss_fn(p, batch, None, True))(params)
    assert cfg.act_quant_bits == 4
    assert abs(float(lq) - dense) > 1e-4  # 4-bit acts change the math
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree_util.tree_leaves(grads))

    # schedule_offset honored: a fresh wrap with offset 100 stays dense
    cfg2 = gpt2.GPT2Config.tiny()
    model2 = gpt2.build(cfg2)
    wrapped2 = init_compression(model2, {"compression_training": {
        "activation_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 100},
            "different_groups": {"aq": {"params": {"target_bits": 4}}}}}})
    l0 = float(wrapped2.loss_fn(params, batch, None, True))
    assert cfg2.act_quant_bits is None
    np.testing.assert_allclose(l0, dense, rtol=1e-6)
    wrapped2._compression_toggle.step = 100
    l100 = float(wrapped2.loss_fn(params, batch, None, True))
    assert cfg2.act_quant_bits == 4
    assert abs(l100 - dense) > 1e-4


def test_activation_quantization_without_knob_is_strict():
    """A model with no act_quant_bits hook: strict (default) raises instead
    of silently ignoring the setting; "strict": false keeps the old
    warn-and-ignore behavior."""
    from deepspeed_tpu.compression import init_compression

    class Bare:  # no model_config / act_quant_bits
        loss_fn = staticmethod(lambda *a: 0.0)

    aq_cfg = {"compression_training": {
        "activation_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"aq": {"params": {"target_bits": 4}}}}}}
    with pytest.raises(ValueError, match="act_quant_bits"):
        init_compression(Bare(), aq_cfg)
    out = init_compression(Bare(), {**aq_cfg, "strict": False})
    assert out is not None  # proceeds, ignoring the knob


def test_distillation_loss_and_wrapper():
    from deepspeed_tpu.compression import (distillation_loss,
                                           init_distillation,
                                           student_initialization)

    # math check: alpha=0 -> hard loss; alpha=1, same logits -> ~0 KL
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((2, 5, 8)),
                         jnp.float32)
    hard = jnp.asarray(1.7)
    np.testing.assert_allclose(
        float(distillation_loss(logits, logits, hard, alpha=0.0)), 1.7,
        rtol=1e-6)
    assert float(distillation_loss(logits, logits, jnp.asarray(0.0),
                                   alpha=1.0, temperature=2.0)) < 1e-5

    # wrapper: student trained against a frozen teacher converges toward
    # the teacher's predictions on a fixed batch
    tcfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=16, num_layers=2,
                           num_heads=2, hidden_size=16)
    teacher = gpt2.build(tcfg)
    tparams = gpt2.init_params(tcfg, jax.random.PRNGKey(0))

    scfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=16, num_layers=1,
                           num_heads=2, hidden_size=16)
    student = gpt2.build(scfg)
    sparams = student_initialization(tparams, "blocks", [0])
    assert sparams["blocks"]["qkv_w"].shape[0] == 1  # 1-layer student
    distilled = init_distillation(student, tparams, alpha=0.7,
                                  temperature=2.0, teacher_apply=teacher.apply_fn)
    batch = {"input_ids": np.random.default_rng(1).integers(
        0, 64, (2, 9)).astype(np.int32)}
    import optax

    tx = optax.adam(5e-3)
    opt = tx.init(sparams)
    losses = []
    fn = jax.jit(jax.value_and_grad(
        lambda p: distilled.loss_fn(p, batch, None, True)))
    for _ in range(20):
        l, g = fn(sparams)
        upd, opt = tx.update(g, opt, sparams)
        sparams = optax.apply_updates(sparams, upd)
        losses.append(float(l))
    assert losses[-1] < losses[0] - 0.05, losses
