"""Tensor-parallel paged serving: the KV pool and paged-attention ops
shard over the mesh ``tp`` axis (KV-head dim) with token-exact parity.

Tier-1 (fast) CPU-sim coverage on the 8-device mesh (conftest):
 - tp=1 vs tp=4 exact-token parity: plain chunked, prefix-heavy,
   speculative (n-gram), and under preemption pressure.
 - per-chip pool placement: ``addressable_shards`` carry ``HKV/tp`` heads
   and the sharding survives a full serve (the compiled programs hand the
   pool back with the same layout they received).
 - compile contract under tp: 2 programs plain, <= 3 speculative.
 - GQA head-divisibility: HKV < tp auto-falls-back to the replicated
   layout (parity intact); ``shard_kv=True`` then raises instead; a
   divisible GQA pool (tp=2, HKV=2) shards.
 - ``stats()`` KV footprint: ``kv_pool_bytes_per_chip`` scales 1/tp.

The scheduler (allocator, prefix trie, block tables) is host-side and
head-sharding-invariant, so admission order and compile counts are
bit-identical across tp degrees — the parity tests exercise exactly that.

Every trace here runs with ``debug_checks=True``: the recompile sentry
enforces the compile budget at trace time and the paged-state invariants
are audited every scheduler iteration (``analysis/``), so each parity
test doubles as a retrace + bookkeeping regression test.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2, llama


def _mk_engine(tp, cfg):
    deepspeed_tpu.comm.reset_topology()
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": tp}})


@pytest.fixture(scope="module")
def tiny_cfg():
    return gpt2.GPT2Config.tiny(max_seq_len=128)


@pytest.fixture(scope="module")
def tp1_engine(tiny_cfg):
    return _mk_engine(1, tiny_cfg)


@pytest.fixture(scope="module")
def tp4_engine(tiny_cfg):
    return _mk_engine(4, tiny_cfg)


def _trace(cfg, n, prefix_len=24, seed=0, tail=(3, 10), max_new=(2, 10)):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len)
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(*tail)))]),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def _serve_pair(e1, e4, cfg, seed, **srv_kw):
    """Serve the same trace at tp=1 and tp=4; return both result dicts and
    the two engines' ServingEngines."""
    kw = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, debug_checks=True)
    kw.update(srv_kw)
    s1 = ServingEngine(e1, **kw)
    s4 = ServingEngine(e4, **kw)
    reqs = _trace(cfg, 6, seed=seed)
    r1 = s1.serve(reqs)
    r4 = s4.serve(_trace(cfg, 6, seed=seed))   # fresh Request objects
    return r1, r4, s1, s4


def test_tp4_parity_prefix_heavy_and_pool_shards(tp1_engine, tp4_engine,
                                                 tiny_cfg):
    """Acceptance: tp=4 serving is token-exact vs tp=1 (and vs sequential
    generate) on a prefix-heavy trace; the pool's per-chip shard is HKV/4
    heads before AND after the serve; compile contract stays 2 programs."""
    r1, r4, s1, s4 = _serve_pair(tp1_engine, tp4_engine, tiny_cfg, seed=0)
    assert s4.kv_sharded and s4.tp_degree == 4
    hkv = tiny_cfg.num_heads
    for leaf in (s4._cache["k"], s4._cache["v"]):
        assert leaf.shape[2] == hkv
        for shard in leaf.addressable_shards:
            assert shard.data.shape[2] == hkv // 4
    for r in _trace(tiny_cfg, 6, seed=0):
        want = tp1_engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(r1[r.uid], want, err_msg=f"tp1 {r.uid}")
        np.testing.assert_array_equal(r4[r.uid], want, err_msg=f"tp4 {r.uid}")
    assert s4.compile_count == 2, s4.compiled_programs
    # scheduler state is head-sharding-invariant: identical counters
    assert s4.prefix_hit_tokens == s1.prefix_hit_tokens
    assert s4.decode_steps == s1.decode_steps


def test_tp4_parity_speculative_and_compile_contract(tp1_engine, tp4_engine,
                                                     tiny_cfg):
    """Speculative (n-gram) serving under tp=4: token-exact vs tp=1 and
    the <= 3-program contract holds unchanged (2 in n-gram mode)."""
    r1, r4, s1, s4 = _serve_pair(tp1_engine, tp4_engine, tiny_cfg, seed=1,
                                 spec_tokens=3)
    for uid in r1:
        np.testing.assert_array_equal(r1[uid], r4[uid], err_msg=f"uid {uid}")
    assert s4.compile_count <= 3, s4.compiled_programs
    assert s4.compile_count == s1.compile_count
    assert s4.spec_rounds == s1.spec_rounds


def test_tp4_parity_under_preemption(tp1_engine, tp4_engine, tiny_cfg):
    """Block pressure (preemption + recompute) resolves identically at any
    tp degree — the allocator never sees head counts."""
    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=32,
              prefill_batch=2, num_blocks=12, debug_checks=True)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, tiny_cfg.vocab_size, 17) for _ in range(5)]
    s1 = ServingEngine(tp1_engine, **kw)
    s4 = ServingEngine(tp4_engine, **kw)
    r1 = s1.serve([Request(uid=i, prompt=p, max_new_tokens=28)
                   for i, p in enumerate(prompts)])
    r4 = s4.serve([Request(uid=i, prompt=p, max_new_tokens=28)
                   for i, p in enumerate(prompts)])
    assert s4.preempted > 0 and s4.preempted == s1.preempted
    for uid in r1:
        np.testing.assert_array_equal(r1[uid], r4[uid], err_msg=f"uid {uid}")


def test_tp4_kv8_parity_and_sharded_scale_table(tp1_engine, tp4_engine,
                                                tiny_cfg):
    """int8 KV (quantize="kv8") composes with the tp head-shard with
    EXACT token parity across degrees: per-token-vector scales are
    head-local, so each chip quantizes its own shard to bit-identical
    codes/scales, and the scale table (``ps`` [L, NB, HKV, bs]) shards
    over the same head dim as the codes — the 8-device CI job's quant
    case."""
    r1, r4, s1, s4 = _serve_pair(tp1_engine, tp4_engine, tiny_cfg, seed=2,
                                 quantize="kv8")
    for uid in r1:
        np.testing.assert_array_equal(r1[uid], r4[uid], err_msg=f"uid {uid}")
    assert s4.kv_sharded
    hkv = tiny_cfg.num_heads
    for rec in (s4._cache["k"], s4._cache["v"]):
        for name, head_dim in (("qp", 2), ("ps", 2)):
            assert rec[name].shape[head_dim] == hkv
            for shard in rec[name].addressable_shards:
                assert shard.data.shape[head_dim] == hkv // 4, name
    st1, st4 = s1.stats(), s4.stats()
    assert st4["kv_dtype"] == "int8" and st4["kv_scale_bytes"] > 0
    assert st4["kv_pool_bytes"] == st1["kv_pool_bytes"]
    assert st4["kv_pool_bytes_per_chip"] == st1["kv_pool_bytes"] // 4
    assert s4.compile_count == 2, s4.compiled_programs


def test_tp4_tiered_kv_parity_per_shard_transfers(tp1_engine, tp4_engine,
                                                  tiny_cfg):
    """Tiered KV (host-DRAM offload) composes with the tp head-shard:
    demotion's ``device_get`` assembles per-addressable-shard and
    promotion's ``device_put`` re-shards the staged buffer, so the swap
    round trip is byte-exact at any degree — tp=4 tokens are BIT-identical
    to the tp=1 tiered run (and swap schedules match: the scheduler never
    sees head counts).  kv8 composes on top with the same exactness."""
    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2, num_blocks=10, host_blocks=64, swap_batch=4,
              debug_checks=True)
    reqs = _trace(tiny_cfg, 6, seed=3, max_new=(20, 28))
    s1 = ServingEngine(tp1_engine, **kw)
    s4 = ServingEngine(tp4_engine, **kw)
    r1 = s1.serve(reqs)
    r4 = s4.serve(_trace(tiny_cfg, 6, seed=3, max_new=(20, 28)))
    st1, st4 = s1.stats(), s4.stats()
    assert s4.kv_sharded
    assert st4["swap_out"] > 0 and st4["swap_in"] > 0
    assert (st4["swap_out"], st4["swap_in"]) == \
        (st1["swap_out"], st1["swap_in"])
    assert s4.compile_count == 4 and s4.compile_budget == 4
    for uid in r1:
        np.testing.assert_array_equal(r1[uid], r4[uid], err_msg=f"uid {uid}")
    sq1 = ServingEngine(tp1_engine, quantize="kv8", **kw)
    sq4 = ServingEngine(tp4_engine, quantize="kv8", **kw)
    q1 = sq1.serve(_trace(tiny_cfg, 6, seed=3, max_new=(20, 28)))
    q4 = sq4.serve(_trace(tiny_cfg, 6, seed=3, max_new=(20, 28)))
    assert sq4.stats()["swap_out"] > 0
    for uid in q1:
        np.testing.assert_array_equal(q1[uid], q4[uid], err_msg=f"uid {uid}")


def test_shard_kv_false_forces_replicated(tp4_engine):
    srv = ServingEngine(tp4_engine, slots=2, max_seq_len=64, block_size=8,
                        shard_kv=False)
    assert not srv.kv_sharded
    leaf = srv._cache["k"]
    for shard in leaf.addressable_shards:
        assert shard.data.shape == leaf.shape      # fully replicated


def test_stats_kv_footprint_scales_with_tp(tp1_engine, tp4_engine):
    kw = dict(slots=2, max_seq_len=64, block_size=8)
    st1 = ServingEngine(tp1_engine, **kw).stats()
    st4 = ServingEngine(tp4_engine, **kw).stats()
    assert st1["tp_degree"] == 1 and not st1["kv_sharded"]
    assert st4["tp_degree"] == 4 and st4["kv_sharded"]
    assert st1["kv_pool_bytes"] == st4["kv_pool_bytes"]
    assert st1["kv_pool_bytes_per_chip"] == st1["kv_pool_bytes"]
    assert st4["kv_pool_bytes_per_chip"] * 4 == st4["kv_pool_bytes"]
    assert tuple(st4["kv_pool_shape"]) == tuple(st1["kv_pool_shape"])


def test_gqa_indivisible_heads_fall_back_or_raise():
    """llama-tiny has HKV=2: tp=4 cannot shard it — auto mode serves
    replicated with parity intact, shard_kv=True raises naming the counts."""
    deepspeed_tpu.comm.reset_topology()
    cfg = llama.LlamaConfig.tiny()
    engine = deepspeed_tpu.init_inference(
        llama.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 4}})
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    assert not srv.kv_sharded and srv.tp_degree == 4
    prompt = np.arange(10) % cfg.vocab_size
    res = srv.serve([Request(uid=0, prompt=prompt, max_new_tokens=5)])
    want = engine.generate(prompt[None, :], max_new_tokens=5)[0]
    np.testing.assert_array_equal(res[0], want)
    with pytest.raises(ValueError, match="KV head count .2. does not divide"):
        ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                      shard_kv=True)


def test_gqa_divisible_heads_shard():
    """tp=2 divides llama-tiny's HKV=2: the GQA pool shards (1 head/chip)
    and decode stays token-exact."""
    deepspeed_tpu.comm.reset_topology()
    cfg = llama.LlamaConfig.tiny()
    engine = deepspeed_tpu.init_inference(
        llama.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}})
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    assert srv.kv_sharded and srv.tp_degree == 2
    assert srv._cache["k"].addressable_shards[0].data.shape[2] == 1
    prompt = np.arange(12) % cfg.vocab_size
    res = srv.serve([Request(uid=0, prompt=prompt, max_new_tokens=6)])
    want = engine.generate(prompt[None, :], max_new_tokens=6)[0]
    np.testing.assert_array_equal(res[0], want)


def test_draft_pool_shards_with_target(tp4_engine, tiny_cfg):
    """A draft model whose HKV divides tp gets a sharded draft pool; the
    fused-prefill + rollout + verify trace stays token-exact vs the tp=1
    n-gram reference and within the 3-program contract."""
    dcfg = gpt2.GPT2Config(vocab_size=tiny_cfg.vocab_size, max_seq_len=128,
                           num_layers=1, num_heads=4, hidden_size=64)
    srv = ServingEngine(tp4_engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2, spec_tokens=3,
                        draft=gpt2.build(dcfg), debug_checks=True)
    assert srv._dcache_sharded
    assert srv._dcache["k"].addressable_shards[0].data.shape[2] == 1
    reqs = _trace(tiny_cfg, 4, seed=2)
    res = srv.serve(reqs)
    assert srv.compile_count <= 3, srv.compiled_programs
    for r in reqs:
        want = tp4_engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want, err_msg=f"{r.uid}")


def test_tiered_mixed_sharding_sharded_target_replicated_draft(tp4_engine,
                                                               tiny_cfg):
    """Tiered KV with a SHARDED target pool and a REPLICATED draft pool
    (GQA draft: 3 heads at tp=4): the staging device_put must apply each
    leaf's OWN sharding — one head-sharded spec over the whole swap tree
    crashed this supported combo.  Parity vs the tp=4 engine's own
    generate under pressure, with swaps in both directions."""
    dcfg = gpt2.GPT2Config(vocab_size=tiny_cfg.vocab_size, max_seq_len=128,
                           num_layers=1, num_heads=3, hidden_size=48)
    srv = ServingEngine(tp4_engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2, num_blocks=10,
                        spec_tokens=3, draft=gpt2.build(dcfg),
                        host_blocks=64, swap_batch=4, debug_checks=True)
    assert srv.kv_sharded and not srv._dcache_sharded
    reqs = _trace(tiny_cfg, 5, seed=4, max_new=(16, 24))
    res = srv.serve(reqs)
    st = srv.stats()
    assert st["swap_out"] > 0 and st["swap_in"] > 0
    assert srv.compile_count <= srv.compile_budget == 5
    for r in reqs:
        want = tp4_engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want, err_msg=f"{r.uid}")


def test_draft_indivisible_heads_raise_with_shard_kv(tp4_engine, tiny_cfg):
    """shard_kv=True + a draft whose HKV does not divide tp fails fast in
    the ctor, naming the draft's head count."""
    dcfg = gpt2.GPT2Config(vocab_size=tiny_cfg.vocab_size, max_seq_len=128,
                           num_layers=1, num_heads=3, hidden_size=48)
    with pytest.raises(ValueError, match="draft model's KV head count"):
        ServingEngine(tp4_engine, slots=2, max_seq_len=128, block_size=8,
                      prefill_chunk=16, spec_tokens=3,
                      draft=gpt2.build(dcfg), shard_kv=True)


def test_init_serving_topology_overrides_config(tiny_cfg):
    """``init_serving(topology=N)`` wins over a conflicting
    ``tensor_parallel`` in a dict config, and never mutates a caller-owned
    config object."""
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(tiny_cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        topology=4, slots=2, max_seq_len=128, block_size=8)
    assert srv.tp_degree == 4 and srv.kv_sharded

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    obj = DeepSpeedInferenceConfig(dtype="fp32")
    deepspeed_tpu.comm.reset_topology()
    deepspeed_tpu.init_serving(gpt2.build(tiny_cfg), config=obj, topology=2,
                               slots=2, max_seq_len=128, block_size=8)
    assert obj.tensor_parallel.tp_size == 1


@pytest.mark.slow  # two engine builds per family
@pytest.mark.parametrize("family", ["opt", "bloom", "mixtral"])
def test_tp_parity_other_families(family):
    """The sharded-cache path holds across the remaining serving families
    (gpt2/llama are tier-1 above): opt's offset learned positions, bloom's
    ALiBi gather path, mixtral's GQA + MoE blocks — tp=2 serving is
    token-exact vs tp=1."""
    if family == "opt":
        from deepspeed_tpu.models import opt as m
        cfg = m.OPTConfig.tiny()
    elif family == "bloom":
        from deepspeed_tpu.models import bloom as m
        cfg = m.BloomConfig.tiny()
    else:
        from deepspeed_tpu.models import mixtral as m
        cfg = m.MixtralConfig.tiny()

    def build(tp):
        deepspeed_tpu.comm.reset_topology()
        return deepspeed_tpu.init_inference(
            m.build(cfg),
            config={"dtype": "fp32", "tensor_parallel": {"tp_size": tp}})

    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, cfg.vocab_size, int(rng.integers(5, 14)))
               for _ in range(4)]
    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2)
    r1 = ServingEngine(build(1), **kw).serve(
        [Request(uid=i, prompt=p, max_new_tokens=6)
         for i, p in enumerate(prompts)])
    s2 = ServingEngine(build(2), **kw)
    r2 = s2.serve([Request(uid=i, prompt=p, max_new_tokens=6)
                   for i, p in enumerate(prompts)])
    assert s2.kv_sharded
    for uid in r1:
        np.testing.assert_array_equal(r1[uid], r2[uid], err_msg=f"uid {uid}")


def test_router_kv_pull_tp4_kv8_composition(tp4_engine, tiny_cfg):
    """PR 11 acceptance: the cross-replica KV pull composes with tp
    sharding AND kv8 — two tp=4 replicas with int8 host tiers migrate a
    session (drain -> pull -> resume) bit-identically to an unmigrated
    tp=4 kv8 engine (per-shard gather/scatter moves codes + scale rows
    as ordinary swap leaves)."""
    from deepspeed_tpu.serving import ReplicaRouter

    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2, host_blocks=32, swap_batch=4,
              quantize="kv8", debug_checks=True)
    rng = np.random.default_rng(21)
    prefixes = [rng.integers(0, tiny_cfg.vocab_size, 24)
                for _ in range(2)]
    reqs = [Request(uid=i,
                    prompt=np.concatenate(
                        [prefixes[i % 2],
                         rng.integers(0, tiny_cfg.vocab_size,
                                      int(rng.integers(3, 8)))]),
                    max_new_tokens=8) for i in range(6)]
    ref = ServingEngine(tp4_engine, **kw)
    ref_outs = ref.serve(reqs)

    deepspeed_tpu.comm.reset_topology()
    peer = deepspeed_tpu.init_inference(
        gpt2.build(tiny_cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 4}},
        params=tp4_engine.params)
    reps = [ServingEngine(tp4_engine, **kw),
            ServingEngine(peer, **kw)]
    assert all(r.kv_sharded and r.tp_degree == 4 for r in reps)
    router = ReplicaRouter(reps, debug_checks=True)
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], ref_outs[r.uid],
                                      err_msg=f"uid {r.uid}")
    p0 = prefixes[0]
    depth = [rep.affinity_probe(np.concatenate([p0, [0]]))
             for rep in reps]
    rid0 = int(np.argmax([d["device_blocks"] + d["host_blocks"]
                          for d in depth]))
    router.drain(rid0)
    cont = Request(uid="tpq",
                   prompt=np.concatenate(
                       [p0, rng.integers(0, tiny_cfg.vocab_size, 4)]),
                   max_new_tokens=6)
    ref_cont = ref.serve([Request(uid="tpq", prompt=cont.prompt,
                                  max_new_tokens=6)])
    out = router.serve([cont])
    np.testing.assert_array_equal(out["tpq"], ref_cont["tpq"])
    st = router.stats()
    assert st["kv_pulls"] >= 1 and st["kv_pull_blocks"] >= 3
    assert all(p["compile_count"] <= p["compile_budget"]
               for p in st["per_replica"])


def test_chaos_crash_rehoming_tp4_parity(tp4_engine, tiny_cfg):
    """PR 15 chaos x tp composition: a seeded FaultPlan kills one of two
    tp=4 replicas mid-decode — every request completes on the survivor
    token-exactly vs the fault-free tp=4 fleet, with clean post-failure
    audits and budgets intact (the 8-device chaos lane of the chaos
    parity gate)."""
    from deepspeed_tpu.serving import FaultPlan, ReplicaRouter

    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2, host_blocks=32, swap_batch=4,
              debug_checks=True)
    rng = np.random.default_rng(31)
    prefixes = [rng.integers(0, tiny_cfg.vocab_size, 24)
                for _ in range(2)]
    reqs = [Request(uid=i,
                    prompt=np.concatenate(
                        [prefixes[i % 2],
                         rng.integers(0, tiny_cfg.vocab_size,
                                      int(rng.integers(3, 8)))]),
                    max_new_tokens=10) for i in range(6)]

    def _fleet():
        deepspeed_tpu.comm.reset_topology()
        peer = deepspeed_tpu.init_inference(
            gpt2.build(tiny_cfg),
            config={"dtype": "fp32", "tensor_parallel": {"tp_size": 4}},
            params=tp4_engine.params)
        reps = [ServingEngine(tp4_engine, **kw),
                ServingEngine(peer, **kw)]
        assert all(r.kv_sharded and r.tp_degree == 4 for r in reps)
        return ReplicaRouter(reps, debug_checks=True)

    free = _fleet()
    outs_free = free.serve(reqs)

    router = _fleet()
    inj = router.arm_faults(FaultPlan(
        seed=0, crashes=[{"replica": 1, "at_step": 4}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    assert inj.report()["crashes_fired"] == [{"replica": 1, "step": 4}]
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)
        np.testing.assert_array_equal(h.result(timeout=0),
                                      outs_free[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = router.stats()
    assert st["failed"] == [1] and st["requests_failed"] == 0
    assert all(p["compile_count"] <= p["compile_budget"]
               for p in st["per_replica"])
    from deepspeed_tpu.analysis.invariants import audit_router
    audit_router(router)


def test_dp_tp_engine_token_identity_vs_router_fronted(tiny_cfg):
    """PR 16 acceptance: the 2-D ``engine_mode="dp_tp"`` engine — ONE
    compiled decode program over a dp-sharded slot batch with the KV
    pool's physical-block dim sharded over ``dp`` and KV heads over
    ``tp`` — is token-identical to the router-fronted replicas-mode
    twin on a mixed trace (8-device CI mesh: dp=4 x tp=2), composes
    with fused ``decode_steps=K``, keeps per-chip KV bytes equal to a
    tp-only replica serving its share of the slots, and demotes the
    router to front-end admission (mixing a dp_tp engine with another
    replica raises)."""
    from deepspeed_tpu.serving import ReplicaRouter

    deepspeed_tpu.comm.reset_topology()
    e2 = deepspeed_tpu.init_inference(
        gpt2.build(tiny_cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}})
    dp = dict(e2.mesh.shape)["dp"]
    assert dp == 4, e2.mesh.shape        # 8 devices / tp=2
    kw = dict(max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, prefix_caching=False, debug_checks=True)
    rng = np.random.default_rng(7)

    def mixed_trace():
        r = np.random.default_rng(7)
        return [Request(uid=i,
                        prompt=r.integers(0, tiny_cfg.vocab_size,
                                          int(r.integers(4, 40))),
                        max_new_tokens=int(r.integers(2, 12)))
                for i in range(10)]

    # replicas-mode twin on the SAME mesh: the token-identity reference
    srv_ref = ServingEngine(e2, slots=8, **kw)
    outs_ref = srv_ref.serve(mixed_trace())

    srv_dp = ServingEngine(e2, slots=8, engine_mode="dp_tp", **kw)
    assert srv_dp.dp_degree == 4 and srv_dp.tp_degree == 2
    router = ReplicaRouter([srv_dp], debug_checks=True)
    handles = [router.submit(r) for r in mixed_trace()]
    while router.step():
        pass
    for r, h in zip(mixed_trace(), handles):
        np.testing.assert_array_equal(h.result(timeout=0), outs_ref[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = srv_dp.stats()
    assert st["engine_mode"] == "dp_tp"
    assert st["compile_count"] == 2      # ONE decode + ONE prefill program
    assert st["retraces_observed"] == 0

    # fused multi-step composes with the 2-D mesh: same tokens again
    srv_dpf = ServingEngine(e2, slots=8, engine_mode="dp_tp",
                            decode_steps=4, **kw)
    outs_f = srv_dpf.serve(mixed_trace())
    for r in mixed_trace():
        np.testing.assert_array_equal(outs_f[r.uid], outs_ref[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert srv_dpf.stats()["host_fence_waits"] > 0

    # per-chip KV bytes: the dp_tp pool (4x blocks over 4x chips) costs
    # each chip exactly what a tp-only replica serving slots/dp costs
    tp_only = ServingEngine(e2, slots=8 // dp, **kw)
    assert srv_dp.stats()["kv_pool_bytes_per_chip"] == \
        tp_only.stats()["kv_pool_bytes_per_chip"]

    # router demotion: a dp_tp engine must be the SOLE replica
    with pytest.raises(ValueError, match="sole"):
        ReplicaRouter([srv_dp, srv_ref])
