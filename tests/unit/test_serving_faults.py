"""Fault-tolerant serving fleet (PR 15): deterministic chaos harness,
crash re-homing with KV salvage, integrity-checked + retried swap
transport, and SLO-aware load shedding.

Tier-1 (fast) coverage:
 - FaultPlan JSON round trip / validation; injector determinism.
 - block checksums: host-store integrity units, import rejection,
   corrupt-arena detection at promote with exact-parity recovery
   (corrupt KV is NEVER served — the corruption acceptance gate).
 - crash re-homing: a seeded SimulatedCrash kills one of two replicas
   mid-decode; every in-flight and pending request completes on the
   survivor with token output EXACTLY matching the fault-free run,
   zero hung handles, clean post-failure audits, per-replica compile
   budgets unchanged (the chaos parity acceptance gate), in fp32 and
   kv8 (bit-exact vs an unfaulted kv8 twin).
 - transport hardening: transient faults retry (counter ticks) with
   parity; permanent faults fall back to local recompute with parity.
 - typed failure: RequestFailedError on re-home exhaustion / empty
   fleet; RequestHandle timeout= raises TimeoutError instead of
   hanging forever.
 - shedding: bounded queue + burn-rate triggers reject batch-class
   work with typed RequestRejected; realtime is never shed.
 - replica state machine: drain/fail/readmit idempotent no-ops.
 - supervisor: hard probe failure (capacity < 0) fails immediately —
   no grace window — and recovery re-admits.
 - audit_router failure-state invariant fault injections.
"""

import json
import time

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_router)
from deepspeed_tpu.inference.paged import (HostBlockStore, TransportError,
                                           block_checksum)
from deepspeed_tpu.inference.serving import (Request, RequestFailedError,
                                             RequestHandle, ServingEngine,
                                             _PendingItem, _PendingQueue)
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import (FaultInjector, FaultPlan, ReplicaRouter,
                                   RequestRejected, RouterSupervisor,
                                   SimulatedCrash)


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    spec = gpt2.build(cfg)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return spec, cfg, engine


_SRV_KW = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
               prefill_batch=2, debug_checks=True)


def _mk_engine(spec, params, **cfg_extra):
    config = {"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}
    config.update(cfg_extra)
    return deepspeed_tpu.init_inference(spec, config=config, params=params)


def _mk_srv(spec, params, **kw):
    merged = dict(_SRV_KW, host_blocks=32, swap_batch=4)
    merged.update(kw)
    return ServingEngine(_mk_engine(spec, params,
                                    **merged.pop("cfg_extra", {})),
                         **merged)


def _session_trace(cfg, n=9, sessions=3, seed=0, prefix_len=24,
                   max_new=10):
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, cfg.vocab_size, prefix_len)
                for _ in range(sessions)]
    return prefixes, [
        Request(uid=i,
                prompt=np.concatenate(
                    [prefixes[i % sessions],
                     rng.integers(0, cfg.vocab_size,
                                  int(rng.integers(3, 8)))]),
                max_new_tokens=max_new)
        for i in range(n)]


def _sequential(engine, reqs):
    return {r.uid: engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            for r in reqs}


# -------------------------------------------------------------- plan units
def test_fault_plan_roundtrip_and_validation(tmp_path):
    plan = FaultPlan(seed=7,
                     crashes=[{"replica": 1, "at_step": 12}],
                     stalls=[{"replica": 0, "at_step": 3, "stall_s": 0.01}],
                     corruption=[{"replica": 0, "at_step": 5,
                                  "entries": 2, "bits": 3}],
                     transport={"ops": ["export", "import"],
                                "transient_rate": 1.0, "max_faults": 2})
    path = plan.save(str(tmp_path / "plan.json"))
    loaded = FaultPlan.load(path)
    assert loaded == plan
    assert FaultPlan.from_json(json.loads(
        json.dumps(plan.to_json()))) == plan
    with pytest.raises(ValueError, match="at_step"):
        FaultPlan(crashes=[{"replica": 0, "at_step": 0}])
    with pytest.raises(ValueError, match="transport op"):
        FaultPlan(transport={"ops": ["teleport"]})


def test_injector_determinism():
    """Same plan, same per-replica call sequence => identical injected
    faults — the property the chaos parity gate rests on."""
    plan = FaultPlan(seed=11, transport={"ops": ["export"],
                                         "transient_rate": 0.5,
                                         "permanent_rate": 0.1,
                                         "max_faults": 100})

    def drive(inj):
        v = inj.bind(0)
        pattern = []
        for _ in range(40):
            try:
                v.on_transport("export")
                pattern.append("ok")
            except TransportError as e:
                pattern.append("t" if e.transient else "p")
        return pattern

    a, b = drive(FaultInjector(plan)), drive(FaultInjector(plan))
    assert a == b
    assert "t" in a and "ok" in a
    # replicas draw from independent streams: binding 1 differs from 0
    inj = FaultInjector(plan)
    inj.bind(0), inj.bind(1)


def test_stall_fires_and_counts():
    plan = FaultPlan(seed=0, stalls=[{"replica": 0, "at_step": 2,
                                      "stall_s": 0.03}])
    inj = FaultInjector(plan)
    v = inj.bind(0)

    class _E:                                 # no host tier needed
        _host = None

    t0 = time.perf_counter()
    v.on_step(_E())                           # step 1: nothing
    fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    v.on_step(_E())                           # step 2: stall
    slow = time.perf_counter() - t0
    assert inj.stalls_fired == 1 and slow > max(fast, 0.02)


# ---------------------------------------------------------- checksum units
def test_block_checksum_and_host_store_integrity():
    store = HostBlockStore(4, [((2, 3), np.float32), ((2,), np.int8)])
    blk = [np.arange(6, dtype=np.float32).reshape(2, 3),
           np.array([1, -2], np.int8)]
    s = block_checksum(blk)
    assert s == block_checksum([b.copy() for b in blk])   # content only
    assert store.put(b"k0", blk) is not None
    assert store.checksum_of(b"k0") == s and store.verify(b"k0")
    # corrupt the arena in place: verify catches it, drop_corrupt frees
    store.arenas[0][store._entries[b"k0"].slot].reshape(-1)[0] += 1.0
    assert not store.verify(b"k0")
    free_before = len(store._free)
    store.drop_corrupt(b"k0")
    assert not store.has(b"k0") and len(store._free) == free_before + 1


def test_import_chain_rejects_corrupt_blocks():
    src = HostBlockStore(4, [((3,), np.float32)])
    for i in range(3):
        src.put(f"k{i}".encode(), [np.full(3, float(i), np.float32)])
    keys = [f"k{i}".encode() for i in range(3)]
    blocks = src.export_chain(keys)
    sums = src.export_checksums(keys)
    # flip a byte of block 1 "in transit"
    blocks[1][0].view(np.uint8)[0] ^= 0xFF
    dst = HostBlockStore(4, [((3,), np.float32)])
    stored = dst.import_chain(keys, blocks, checksums=sums)
    assert stored == 1                        # stops AT the corrupt block
    assert dst.has(keys[0]) and not dst.has(keys[1])
    assert dst.checksum_rejects == 1
    # without checksums the (corrupt) bytes would have been accepted —
    # the wire sums are what makes the transfer end-to-end verified
    dst2 = HostBlockStore(4, [((3,), np.float32)])
    assert dst2.import_chain(keys, blocks) == 3


def test_engine_import_counts_checksum_failures(tiny):
    spec, cfg, engine = tiny
    a = _mk_srv(spec, engine.params)
    b = _mk_srv(spec, engine.params)
    _, reqs = _session_trace(cfg, n=3)
    a.serve(reqs)
    a.drain()                                 # chains demote to a's tier
    keys, blocks, sums = a.host_chain_export(reqs[0].prompt, 0,
                                             len(reqs[0].prompt) - 1)
    assert keys and len(sums) == len(keys)
    blocks[0][0].reshape(-1).view(np.uint8)[3] ^= 0x10
    stored = b.host_chain_import(keys, blocks, checksums=sums)
    assert stored == 0
    assert b.stats()["num_blocks"] and \
        int(b._c_checksum_fail.value) == 1


# ------------------------------------------------ corruption (acceptance)
def test_corruption_detected_100pct_and_never_served(tiny):
    """Acceptance gate: injected bit-flips in host-tier arena bytes are
    detected by checksum on promote in 100% of injected cases and
    recovered via recompute — corrupt KV is never served (exact token
    parity throughout)."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4, max_new=8)
    seq = _sequential(engine, reqs)
    srv = _mk_srv(spec, engine.params)
    outs = srv.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
    srv.drain()                               # host tier = the only copy
    n_host = len(srv._host)
    assert n_host >= 3
    inj = FaultInjector(FaultPlan(
        seed=3, corruption=[{"replica": 0, "at_step": 1,
                             "entries": n_host, "bits": 3}]))
    srv.arm_faults(inj.bind(0))
    # re-serve every session: every corrupted chain is probed, so every
    # injected corruption must be caught at the promote staging gate
    outs2 = srv.serve([Request(uid=f"r{r.uid}", prompt=r.prompt,
                               max_new_tokens=r.max_new_tokens)
                       for r in reqs])
    for r in reqs:
        np.testing.assert_array_equal(outs2[f"r{r.uid}"], seq[r.uid])
    srv.arm_faults(None)
    assert inj.corrupted_entries == n_host
    assert int(srv._c_checksum_fail.value) == inj.corrupted_entries
    names = [e["name"] for e in srv.timeline.events()]
    assert "checksum_fail" in names
    assert srv.compile_count <= srv.compile_budget


def test_patrol_scrub_finds_shadowed_corruption(tiny):
    """A corrupt block shadowed behind an EARLIER corrupt block in its
    chain is never probed by traffic (the run truncates before it);
    scrub_host_tier() is the patrol scrubber that still finds and drops
    it, counted into the same checksum-failure telemetry."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=3, max_new=6)
    srv = _mk_srv(spec, engine.params)
    srv.serve(reqs)
    srv.drain()
    n_host = len(srv._host)
    assert n_host >= 2
    inj = FaultInjector(FaultPlan(
        seed=9, corruption=[{"replica": 0, "at_step": 1,
                             "entries": n_host, "bits": 2}]))
    srv.arm_faults(inj.bind(0))
    srv.serve([Request(uid="probe", prompt=reqs[0].prompt,
                       max_new_tokens=4)])   # may only hit one chain
    srv.arm_faults(None)
    gate_hits = int(srv._c_checksum_fail.value)
    scrubbed = srv.scrub_host_tier()
    assert gate_hits + scrubbed == inj.corrupted_entries
    assert srv.scrub_host_tier() == 0         # idempotent: all clean now
    for key in inj.corrupted_keys:
        assert not srv._host.has(key) or srv._host.verify(key)


# ------------------------------------------------- crash re-homing (gate)
def _chaos_fleet(spec, params, n=2, **router_kw):
    return ReplicaRouter([_mk_srv(spec, params) for _ in range(n)],
                         debug_checks=True, **router_kw)


def test_crash_rehoming_token_exact_midflight(tiny):
    """Acceptance gate: a seeded FaultPlan kills one of two replicas
    mid-decode; every in-flight and pending request completes on the
    survivor with token output EXACTLY matching the fault-free run,
    zero hung handles, clean post-failure audits (debug_checks on every
    step), and per-replica compile budgets unchanged."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=9, max_new=12)
    seq = _sequential(engine, reqs)

    # fault-free twin first (identical fleet construction)
    free = _chaos_fleet(spec, engine.params)
    outs_free = free.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs_free[r.uid], seq[r.uid])

    router = _chaos_fleet(spec, engine.params)
    plan = FaultPlan(seed=0, crashes=[{"replica": 1, "at_step": 4}])
    inj = router.arm_faults(plan)
    handles = [router.submit(r) for r in reqs]
    for _ in range(3):                       # let decode start fleet-wide
        router.step()
    assert any(rep._active for rep in router.replicas)
    while router.step():
        pass
    assert inj.report()["crashes_fired"] == [{"replica": 1, "step": 4}]
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)   # zero hung
        np.testing.assert_array_equal(h.result(timeout=0), seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = router.stats()
    assert st["failed"] == [1] and st["replica_failures"] == 1
    assert st["requests_rehomed"] >= 1 and st["requests_failed"] == 0
    for p in st["per_replica"]:
        assert p["compile_count"] <= p["compile_budget"]
    names = {e["name"] for e in router.timeline.events()}
    assert {"replica_fail", "rehome"} <= names
    audit_router(router)                      # post-failure state green
    # the survivor owns every live uid; the corpse owns zero
    assert not router.replicas[1]._pending and \
        not router.replicas[1]._active


def test_crash_rehoming_kv8_bit_exact(tiny):
    """kv8 composition: the crash-recovered run matches an unfaulted
    kv8 twin bit-exactly (deterministic int8 codes + scales; the kv8
    lane of the chaos gate)."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=6, max_new=8)
    ref = ReplicaRouter([_mk_srv(spec, engine.params, quantize="kv8")
                         for _ in range(2)], debug_checks=True)
    ref_outs = ref.serve(reqs)

    router = ReplicaRouter([_mk_srv(spec, engine.params, quantize="kv8")
                            for _ in range(2)], debug_checks=True)
    router.arm_faults(FaultPlan(seed=0,
                                crashes=[{"replica": 0, "at_step": 3}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    for r, h in zip(reqs, handles):
        assert h.status == "finished"
        np.testing.assert_array_equal(h.result(timeout=0),
                                      ref_outs[r.uid])
    assert router.stats()["replica_failures"] == 1


def test_crash_rehoming_resumes_streams_on_same_handles(tiny):
    """In-flight requests keep streaming on the SAME handle across the
    crash: tokens observed before the kill stand, the resume appends
    the identical continuation (greedy fold-in)."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4, max_new=14)
    seq = _sequential(engine, reqs)
    router = _chaos_fleet(spec, engine.params, n=2)
    router.arm_faults(FaultPlan(seed=0,
                                crashes=[{"replica": 0, "at_step": 6}]))
    handles = {r.uid: router.submit(r) for r in reqs}
    pre_crash: dict = {}
    for _ in range(6):
        router.step()
        for uid, h in handles.items():
            if h.tokens() and uid not in pre_crash:
                pre_crash[uid] = list(h.tokens())
    assert pre_crash                          # someone streamed pre-kill
    while router.step():
        pass
    for r in reqs:
        h = handles[r.uid]
        assert h.status == "finished"
        toks = h.tokens()
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32),
            seq[r.uid][len(r.prompt):len(r.prompt) + len(toks)])
        if r.uid in pre_crash:                # prefix stood untouched
            assert toks[:len(pre_crash[r.uid])] == pre_crash[r.uid]


def test_crash_rehoming_salvages_survivor_kv(tiny):
    """Round-robin splits each session across both replicas, so when one
    dies the survivor already holds session prefixes — the re-homed
    resumes reuse them (prefix hits / pulls) instead of recomputing the
    world."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=8, max_new=10)
    seq = _sequential(engine, reqs)
    router = ReplicaRouter([_mk_srv(spec, engine.params)
                            for _ in range(2)], policy="round_robin",
                           debug_checks=True)
    router.arm_faults(FaultPlan(seed=0,
                                crashes=[{"replica": 0, "at_step": 5}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    for r, h in zip(reqs, handles):
        assert h.status == "finished"
        np.testing.assert_array_equal(h.result(timeout=0), seq[r.uid])
    survivor = router.replicas[1]
    assert survivor.prefix_hit_tokens > 0


def _sampled_reqs(cfg, n=8, max_new=12, seed=2, temperature=0.8):
    """Session trace with every odd request sampled (temperature/top-k/
    top-p + its own seed) and every even one greedy — the mixed stream
    the chaos gate must replay token-exactly."""
    rng = np.random.default_rng([seed, 1009])
    _, base = _session_trace(cfg, n=n, max_new=max_new, seed=seed)
    return [Request(uid=r.uid, prompt=r.prompt,
                    max_new_tokens=r.max_new_tokens,
                    temperature=temperature if i % 2 else 0.0,
                    top_k=20 if i % 2 else 0,
                    top_p=0.95 if i % 2 else 1.0,
                    seed=int(rng.integers(1, 2 ** 31 - 1)) if i % 2 else 0)
            for i, r in enumerate(base)]


def test_crash_rehoming_token_exact_under_sampling(tiny):
    """PR 20 chaos gate: a replica dies mid-decode while serving SAMPLED
    requests; the re-homed resumes reproduce the exact sampled streams
    of a fault-free twin fleet.  Works because the sampler's PRNG is
    counter-based — the key at every emission position is a pure
    function of (request seed, tokens emitted), never of which replica
    or scheduling interleave drew it."""
    spec, cfg, engine = tiny
    reqs = _sampled_reqs(cfg)
    assert any(r.sampled for r in reqs) and any(not r.sampled for r in reqs)

    free = _chaos_fleet(spec, engine.params)
    outs_free = free.serve(reqs)

    router = _chaos_fleet(spec, engine.params)
    inj = router.arm_faults(
        FaultPlan(seed=0, crashes=[{"replica": 1, "at_step": 4}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    assert inj.report()["crashes_fired"] == [{"replica": 1, "step": 4}]
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)
        np.testing.assert_array_equal(h.result(timeout=0),
                                      outs_free[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = router.stats()
    assert st["requests_rehomed"] >= 1 and st["requests_failed"] == 0
    audit_router(router)


def test_crash_rehoming_token_exact_sampled_spec(tiny):
    """Sampled speculative lane under crash: the n-gram proposer plus
    rejection verifier re-homes token-exactly too (the resume backs up
    to re-emit through the verify program's RESIDUAL-salt draws)."""
    spec, cfg, engine = tiny
    reqs = _sampled_reqs(cfg, n=6, max_new=10, seed=5, temperature=0.6)
    mk = lambda: _mk_srv(spec, engine.params, spec_tokens=2)  # noqa: E731
    free = ReplicaRouter([mk() for _ in range(2)], debug_checks=True)
    outs_free = free.serve(reqs)

    router = ReplicaRouter([mk() for _ in range(2)], debug_checks=True)
    router.arm_faults(
        FaultPlan(seed=0, crashes=[{"replica": 0, "at_step": 4}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)
        np.testing.assert_array_equal(h.result(timeout=0),
                                      outs_free[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert router.stats()["replica_failures"] == 1


# ------------------------------------------------------ transport faults
def test_transient_pull_faults_retry_with_parity(tiny):
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg)
    seq = _sequential(engine, reqs)
    router = _chaos_fleet(spec, engine.params, pull_retries=4)
    inj = router.arm_faults(FaultPlan(
        seed=5, transport={"ops": ["export"], "transient_rate": 1.0,
                           "max_faults": 2}))
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
    # force a migration pull (drain the session's home replica)
    p0 = prefixes[0]
    depth = [rep.affinity_probe(np.concatenate([p0, [0]]))
             for rep in router.replicas]
    rid0 = int(np.argmax([d["device_blocks"] + d["host_blocks"]
                          for d in depth]))
    router.drain(rid0)
    rng = np.random.default_rng(7)
    cont = Request(uid="cont", prompt=np.concatenate(
        [p0, rng.integers(0, cfg.vocab_size, 5)]), max_new_tokens=6)
    sc = engine.generate(cont.prompt[None, :], max_new_tokens=6)[0]
    out = router.serve([cont])
    np.testing.assert_array_equal(out["cont"], sc)
    st = router.stats()
    assert st["kv_pull_retries"] >= 1          # transient faults retried
    assert st["kv_pulls"] >= 1                 # ...and the pull landed
    assert inj.report()["transport_faults"]["transient"] >= 1


def test_permanent_pull_fault_falls_back_to_recompute(tiny):
    spec, cfg, engine = tiny
    prefixes, reqs = _session_trace(cfg, n=6)
    seq = _sequential(engine, reqs)
    router = _chaos_fleet(spec, engine.params)
    router.arm_faults(FaultPlan(
        seed=6, transport={"ops": ["export"], "permanent_rate": 1.0,
                           "max_faults": 1000}))
    outs = router.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
    router.drain(0)
    rng = np.random.default_rng(9)
    cont = Request(uid="cont", prompt=np.concatenate(
        [prefixes[0], rng.integers(0, cfg.vocab_size, 4)]),
        max_new_tokens=5)
    sc = engine.generate(cont.prompt[None, :], max_new_tokens=5)[0]
    out = router.serve([cont])                 # recompute, exact anyway
    np.testing.assert_array_equal(out["cont"], sc)
    assert router.stats()["kv_pulls"] == 0


def test_engine_swap_transport_fault_drops_demotion(tiny):
    """Engine-internal transport hardening: a permanent demote fault
    drops the demotion (contents recomputable), a permanent promote
    fault falls back to prefill recompute — parity holds either way."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4, max_new=8)
    seq = _sequential(engine, reqs)
    srv = _mk_srv(spec, engine.params)
    srv.serve(reqs)
    srv.drain()
    assert len(srv._host) > 0
    inj = FaultInjector(FaultPlan(
        seed=1, transport={"ops": ["promote"], "permanent_rate": 1.0,
                           "max_faults": 1000}))
    srv.arm_faults(inj.bind(0))
    outs = srv.serve([Request(uid="p0", prompt=reqs[0].prompt,
                              max_new_tokens=8)])
    np.testing.assert_array_equal(outs["p0"], seq[0])
    assert srv.stats()["swap_in"] == 0         # promotion never ran
    srv.arm_faults(None)


# ------------------------------------------------------- typed failures
def test_request_failed_error_when_no_survivor(tiny):
    """fail() on the only replica: nothing can re-home, so handles
    resolve LOUDLY with RequestFailedError — never a hang."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=3)
    router = ReplicaRouter([_mk_srv(spec, engine.params)],
                           debug_checks=True)
    handles = [router.submit(r) for r in reqs]
    router.step()
    rehomed = router.fail(0)
    assert rehomed == 0
    for h in handles:
        assert h.status == "failed" and h.done
        with pytest.raises(RequestFailedError, match="no live replica"):
            h.result(timeout=0)
        assert h.next_token(timeout=0) is None
    st = router.stats()
    assert st["requests_failed"] == len(reqs)
    assert st["requests_rehomed"] == 0
    audit_router(router)


def test_rehome_budget_exhaustion_fails_typed(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=2)
    router = _chaos_fleet(spec, engine.params, max_rehomes=0)
    handles = [router.submit(r) for r in reqs]
    victims = {rid for rid in range(2)
               if router.replicas[rid]._pending}
    for rid in victims:
        router.fail(rid)
    reasons = []
    for h in handles:
        assert h.status == "failed"
        with pytest.raises(RequestFailedError) as ei:
            h.result(timeout=0)
        reasons.append(ei.value.reason)
    # a zero budget fails typed immediately (the second victim's request
    # may instead see "no live replica" once both replicas are dead)
    assert any("budget exhausted" in r for r in reasons)
    assert router.stats()["requests_failed"] == len(reqs)


def test_handle_timeout_params(tiny):
    """Satellite: result()/next_token() raise TimeoutError on a positive
    expired timeout instead of blocking forever; timeout=0 stays the
    non-blocking poll (None = nothing new)."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=1)
    srv = ServingEngine(engine, **_SRV_KW)
    h = srv.submit(reqs[0])
    with pytest.raises(TimeoutError, match="streamed nothing"):
        h.next_token(timeout=0.02)
    with pytest.raises(TimeoutError, match="still queued"):
        h.result(timeout=0.02)
    assert h.next_token(timeout=0) is None     # poll semantics unchanged
    while srv.step():
        pass
    assert h.status == "finished"
    assert h.result(timeout=0) is not None
    # after completion a positive timeout returns tokens then None
    assert h.next_token(timeout=0.05) is not None


# ------------------------------------------------------------- shedding
def test_shedding_bounded_queue_rejects_batch_not_realtime(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=9, max_new=4)
    router = ReplicaRouter([ServingEngine(_mk_engine(spec, engine.params),
                                          **_SRV_KW) for _ in range(2)],
                           debug_checks=True, max_queue_depth=2)
    handles, shed = [], []
    for i, r in enumerate(reqs):
        cls = "batch" if i % 2 else "realtime"
        try:
            handles.append(router.submit(
                Request(uid=f"s{i}", prompt=r.prompt, max_new_tokens=4),
                slo_class=cls))
        except RequestRejected as e:
            assert e.slo_class == "batch"      # realtime never sheds
            assert "queue depth" in e.reason
            shed.append(e.uid)
    assert shed
    while router.step():
        pass
    assert all(h.status == "finished" for h in handles)
    st = router.stats()
    assert st["requests_shed"] == {"batch": len(shed)}
    assert "batch" not in {h.slo_class for h in handles
                           if h.slo_class == "realtime"}
    names = {e["name"] for e in router.timeline.events()}
    assert "shed" in names
    snap = router.metrics.snapshot()
    fam = snap["serving_requests_shed_total"]
    assert fam["type"] == "counter"
    assert [s["labels"]["slo_class"] for s in fam["series"]] == ["batch"]


def test_shedding_burn_rate_trigger(tiny):
    """An impossible realtime SLO target burns error budget on the first
    finished request; with burn_threshold set, batch-class work is then
    shed while realtime keeps admitting."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=6, max_new=4)
    srv = ServingEngine(
        _mk_engine(spec, engine.params), **_SRV_KW,
        slo_targets={"realtime": {"ttft_s": 1e-9, "tpot_s": 1e-9,
                                  "objective": 0.99}})
    router = ReplicaRouter([srv], debug_checks=True, burn_threshold=5.0)
    h = router.submit(Request(uid="rt", prompt=reqs[0].prompt,
                              max_new_tokens=4), slo_class="realtime")
    while router.step():
        pass
    assert h.status == "finished"              # burned its budget
    with pytest.raises(RequestRejected, match="burn rate"):
        router.submit(Request(uid="b0", prompt=reqs[1].prompt,
                              max_new_tokens=4), slo_class="batch")
    h2 = router.submit(Request(uid="rt2", prompt=reqs[2].prompt,
                               max_new_tokens=4), slo_class="realtime")
    while router.step():
        pass
    assert h2.status == "finished"
    assert router.stats()["requests_shed"] == {"batch": 1}


# ----------------------------------------------- state machine / salvage
def test_replica_state_machine_idempotence(tiny):
    spec, cfg, engine = tiny
    router = ReplicaRouter([ServingEngine(_mk_engine(spec, engine.params),
                                          **_SRV_KW) for _ in range(3)],
                           debug_checks=True)
    assert router.drain(0) == 0               # empty drain fine
    assert router.drain(0) == 0               # drained -> drain: no-op
    assert router.fail(0) == 0                # drained -> fail: marks
    assert router.failed == [0]
    assert router.fail(0) == 0                # failed -> fail: no-op
    assert router.drain(0) == 0               # failed -> drain: no-op
    router.readmit(0)
    assert router.failed == [] and router.drained == []
    router.readmit(0)                         # live -> readmit: no-op
    # fail a LIVE replica directly, then the state table again
    assert router.fail(1) == 0
    assert router.failed == [1]
    assert router.drain(1) == 0
    router.readmit(1)
    assert router.failed == []
    audit_router(router)


def test_salvage_folds_tokens_and_scrubs(tiny):
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=5, max_new=12)
    srv = _mk_srv(spec, engine.params)
    handles = [srv.submit(r) for r in reqs]
    for _ in range(4):
        srv.step()
    active_uids = [st.req.uid for st in srv._active.values()]
    assert active_uids
    streamed = {st.req.uid: len(st.prior) + len(st.out)
                for st in srv._active.values()}
    handles[-1].cancel()                      # a deferred cancel honored
    items = srv.salvage()
    uids = [it.req.uid for it in items]
    assert reqs[-1].uid not in uids           # cancelled, not salvaged
    assert handles[-1].status == "cancelled"
    # actives first, streamed tokens folded into prior
    for it in items:
        if it.req.uid in streamed:
            assert len(it.prior) == streamed[it.req.uid]
            assert it.handle is not None and not it.handle.done
    # the engine is scrubbed and consistent: no live uids, all blocks
    # released from slots, a fresh serve works
    assert not srv._pending and not srv._active and not srv._live_uids
    from deepspeed_tpu.analysis.invariants import audit_serving_engine
    audit_serving_engine(srv, srv._active)
    out = srv.serve([Request(uid="fresh", prompt=reqs[0].prompt,
                             max_new_tokens=4)])
    assert out["fresh"] is not None


# ----------------------------------------------------------- supervisor
class _FakeReplica:
    """Jax-free router stand-in (mirrors test_replica_router's fake)."""

    def __init__(self, block_size=8):
        self.block_size = block_size
        self._host = None
        self._prefix = None
        self._pending = _PendingQueue()
        self._active = {}
        self._alloc = type("A", (), {"blocks_in_use": 0})()
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.admitted = 0
        self.compile_count = 0
        self.compile_budget = 2
        self._c_gen_tokens = type("C", (), {"value": 0.0})()

    def affinity_probe(self, tokens):
        return {"device_blocks": 0, "host_blocks": 0,
                "blocks_in_use": 0,
                "queue_depth": len(self._pending),
                "active": len(self._active)}

    def submit(self, request, priority=0, slo_class=None,
               eos_token_id=None):
        handle = RequestHandle(request, priority=priority,
                               slo_class=slo_class)
        self._pending.push(_PendingItem(req=request, prior=[],
                                        priority=priority,
                                        handle=handle))
        return handle

    def _submit_item(self, item, canceller=None):
        if item.handle is not None and canceller is not None:
            item.handle.set_canceller(canceller)
        self._pending.push(item)

    def step(self):
        if self._pending:
            item = self._pending.popleft()
            if item.handle is not None:
                item.handle._on_finish(np.asarray(item.req.prompt))
        return bool(self._pending)

    def cancel(self, uid):
        item = self._pending.remove(uid)
        if item is not None and item.handle is not None:
            item.handle._on_cancel()
        return item is not None

    def drain(self):
        return self._pending.drain()

    def warm_swap_programs(self):
        pass


def test_supervisor_hard_probe_failure_fails_immediately():
    """Satellite: capacity < 0 (process GONE) skips the grace window
    entirely — fail(rid) re-homing runs on the same tick — while a soft
    miss (capacity 0) still waits out grace_ticks and drains."""
    a, b = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([a, b], kv_pull=False, debug_checks=True)
    handles = [router.submit(Request(uid=i, prompt=[1] * 4))
               for i in range(4)]
    live = {0: 1, 1: 1}
    sup = RouterSupervisor(router, lambda: live, grace_ticks=2)
    assert sup.tick() == {"drained": [], "failed": [], "readmitted": []}
    live = {0: 1, 1: -1}                      # hard death: process gone
    acts = sup.tick()
    assert acts["failed"] == [1] and acts["drained"] == []
    assert router.failed == [1]
    # everything re-homed onto the survivor, nothing dropped
    assert not b._pending
    while router.step():
        pass
    assert all(h.status == "finished" for h in handles)
    assert router.stats()["requests_rehomed"] >= 1
    # recovery (launcher restarted the worker): re-admitted, fault gone
    live = {0: 1, 1: 1}
    assert sup.tick()["readmitted"] == [1]
    assert router.failed == [] and router.drained == []
    # soft miss still drains via grace, never fails
    live = {0: 1, 1: 0}
    assert sup.tick() == {"drained": [], "failed": [], "readmitted": []}
    assert sup.tick() == {"drained": [], "failed": [], "readmitted": []}
    acts = sup.tick()
    assert acts["drained"] == [1] and router.failed == []
    live = {0: 1, 1: 1}
    assert sup.tick()["readmitted"] == [1]
    # an OPERATOR-drained replica that then hard-dies is failed (fault
    # recorded, excluded as pull source) but NOT claimed — recovery does
    # not auto-readmit over the operator's standing drain
    router.drain(1)
    live = {0: 1, 1: -1}
    assert sup.tick()["failed"] == [1]
    live = {0: 1, 1: 1}
    assert sup.tick()["readmitted"] == []
    assert router.failed == [1]               # operator's call to clear
    router.readmit(1)


def test_audit_router_failure_state_fault_injection():
    """Satellite: the failure-state invariant names its violation — a
    failed replica still owning uids, and a live handle mapped to a
    failed replica."""
    a, b = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([a, b], kv_pull=False)
    h = router.submit(Request(uid="x", prompt=[1] * 4))
    rid = router._handles["x"][1]
    audit_router(router)                      # green
    # a crash-failed replica still owning the request
    router._failed.add(rid)
    router._drained.add(rid)
    with pytest.raises(PagedStateError) as ei:
        audit_router(router)
    assert ei.value.invariant == "router-failure-state"
    assert "zero uids" in str(ei.value)
    # request moved off the corpse, but the handle map still points at
    # the failed replica: a live handle on a dead engine
    item = router.replicas[rid]._pending.drain()[0]
    router.replicas[1 - rid]._pending.push(item)
    with pytest.raises(PagedStateError) as ei:
        audit_router(router)
    assert ei.value.invariant == "router-failure-state"
    assert "crash-failed replica" in str(ei.value)
    # fix the map: green again
    router._handles["x"] = (h, 1 - rid)
    audit_router(router)


def test_fail_fallback_salvage_covers_active_requests():
    """Duck-typed replicas without salvage(): fail() must re-home their
    ACTIVE requests too, not just the queue — an active request left on
    the corpse hangs its caller and trips the failure-state audit."""
    bad, good = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([bad, good], policy="round_robin",
                           kv_pull=False, debug_checks=True)
    h_q = router.submit(Request(uid="queued", prompt=[1] * 4))
    h_a = router.submit(Request(uid="activ", prompt=[2] * 4))
    # move one request into the fake's ACTIVE map by hand (slot state
    # duck-type: req/prior/out/priority/handle)
    owner = router._handles["activ"][1]
    rep = router.replicas[owner]
    item = rep._pending.remove("activ")
    rep._active[0] = type("S", (), {
        "req": item.req, "prior": [], "out": [7, 8], "priority": 0,
        "slo_class": None, "eos": None, "handle": item.handle,
        "admit_seq": 0})()
    if owner != 0:                            # fail whichever owns it
        bad, good = good, bad
    router.fail(owner)
    audit_router(router)                      # corpse owns zero uids
    while router.step():
        pass
    assert h_a.status == "finished" and h_q.done
    # the streamed tokens folded into the resume prior
    assert router.stats()["requests_rehomed"] >= 1


def test_fail_survives_salvage_raising():
    """Last-resort crash path: if the crash left even the HOST
    bookkeeping inconsistent and salvage() itself raises, fail() must
    still resolve every handle LOUDLY (RequestFailedError) and leave
    the corpse with zero uids — the no-caller-ever-hangs rule holds
    even when the resume contexts are unrecoverable."""
    class _Unsalvageable(_FakeReplica):
        def salvage(self):
            raise AssertionError("decref on unowned block 7")

    bad, good = _Unsalvageable(), _FakeReplica()
    router = ReplicaRouter([bad, good], policy="round_robin",
                           kv_pull=False, debug_checks=True)
    handles = [router.submit(Request(uid=i, prompt=[1] * 4))
               for i in range(4)]
    on_bad = [h for h in handles if router._handles[h.uid][1] == 0]
    assert on_bad
    router.fail(0)
    for h in on_bad:
        assert h.status == "failed"
        with pytest.raises(RequestFailedError, match="salvage failed"):
            h.result(timeout=0)
    assert not bad._pending and not bad._active   # zero uids on corpse
    audit_router(router)                          # failure-state green
    while router.step():
        pass
    for h in handles:
        assert h.done                             # nobody hangs
    assert router.stats()["requests_failed"] == len(on_bad)


def test_simulated_crash_type():
    e = SimulatedCrash(2, 7)
    assert e.replica == 2 and e.step == 7 and "iteration 7" in str(e)


# ------------------------------------------- PR 17: disaggregated + nvme
def test_prefill_crash_mid_handoff_rehomes_token_exact(tiny):
    """Chaos composition (ISSUE 17): a disaggregated fleet (2 prefill +
    1 decode) loses a prefill worker mid-run — requests parked in its
    handoff buffer and requests still mid-prefill must re-home (salvage
    + host-chain pull on the decode side, re-prefill on the surviving
    prefill worker) with token output exactly matching the sequential
    reference, zero hung handles, and clean post-failure audits."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=9, max_new=12)
    seq = _sequential(engine, reqs)

    roles = ("prefill", "prefill", "decode")
    router = ReplicaRouter(
        [_mk_srv(spec, engine.params, role=r) for r in roles],
        debug_checks=True)
    inj = router.arm_faults(
        FaultPlan(seed=0, crashes=[{"replica": 0, "at_step": 4}]))
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    assert inj.report()["crashes_fired"] == [{"replica": 0, "step": 4}]
    for r, h in zip(reqs, handles):
        assert h.status == "finished", (r.uid, h.status)
        np.testing.assert_array_equal(h.result(timeout=0), seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = router.stats()
    assert st["failed"] == [0] and st["requests_failed"] == 0
    assert st["handoffs"] >= 1          # the disaggregated path ran
    audit_router(router)
    # the decode worker never prefills a PROMPT: every admission arrives
    # as a handoff/re-home whose committed blocks ride the host-chain
    # pull, so its recompute is bounded by the sub-block tail of each
    # prior (< block_size tokens per admission), never the prompt length
    dec = router.replicas[2]
    assert dec.role == "decode"
    ds = dec.stats()
    if ds["admitted"]:
        assert ds["resume_recompute_tokens"] <= \
            ds["admitted"] * dec.block_size


def test_last_decode_worker_lost_fails_handoffs_loudly(tiny):
    """If the fleet loses its LAST decode-capable replica, parked
    handoffs must resolve their handles with RequestFailedError — not
    bounce forever between prefill workers, not hang the caller."""
    spec, cfg, engine = tiny
    _, reqs = _session_trace(cfg, n=4, max_new=8)
    router = ReplicaRouter(
        [_mk_srv(spec, engine.params, role=r)
         for r in ("prefill", "decode")], debug_checks=True)
    handles = [router.submit(r) for r in reqs]
    router.step()                        # prefill admits, maybe hands off
    router.fail(1)                       # the only decode worker dies
    while router.step():
        pass
    for h in handles:
        assert h.done                    # nobody hangs
        if h.status == "failed":
            with pytest.raises(RequestFailedError):
                h.result(timeout=0)
    assert router.stats()["requests_failed"] >= 1
    audit_router(router)


def test_nvme_bit_flip_caught_by_checksum_gate_unit(tmp_path):
    """NvmeBlockStore: a flipped byte in the spill file is caught at the
    NVMe exit — swap_in refuses the bytes, drops exactly that entry, and
    counts the reject."""
    from deepspeed_tpu.inference.paged import NvmeBlockStore

    specs = [((2, 8, 4), np.float32), ((2, 8, 4), np.float32)]
    store = NvmeBlockStore(4, specs, str(tmp_path / "spill.bin"))
    rng = np.random.default_rng(3)
    arrays = [rng.normal(size=s).astype(dt) for s, dt in specs]
    key = b"chain-key-0"
    assert store.swap_out(key, arrays, block_checksum(arrays))
    assert store.swap_in(key) is not None     # clean round trip
    with open(store.path, "r+b") as f:        # flip one payload byte
        f.seek(17)
        b = f.read(1)
        f.seek(17)
        f.write(bytes([b[0] ^ 0x40]))
    assert store.swap_in(key) is None
    assert store.checksum_rejects == 1
    assert not store.has(key)                 # entry dropped, slot freed
    assert store.blocks_in_use == 0
    store.close()


def test_nvme_corruption_recomputes_with_parity(tiny, tmp_path):
    """Engine-level checksum gate: corrupt the WHOLE spill file under a
    live engine, then resume a session whose prefix lives on NVMe — the
    promote path must reject the bytes, truncate the chain, recompute
    from tokens, and still serve token-exact output."""
    spec, cfg, engine = tiny
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, cfg.vocab_size, 32) for _ in range(8)]
    reqs = [Request(uid=i, prompt=p, max_new_tokens=6)
            for i, p in enumerate(prompts)]
    seq = _sequential(engine, reqs)
    srv = _mk_srv(spec, engine.params, slots=2, num_blocks=12,
                  host_blocks=8, swap_batch=2, nvme_blocks=32,
                  nvme_high_watermark=0.5,
                  nvme_path=str(tmp_path / "spill.bin"))
    outs = srv.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(outs[r.uid], seq[r.uid])
    assert srv.stats()["nvme_spills"] > 0
    with open(srv.nvme_path, "r+b") as f:     # scribble over every slot
        size = f.seek(0, 2)
        f.seek(0)
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    resumed = srv.serve([Request(uid="resume", prompt=prompts[0],
                                 max_new_tokens=6)])
    np.testing.assert_array_equal(resumed["resume"], seq[0])
    assert srv._host.nvme_checksum_rejects > 0
    srv.close()
