"""Data-efficiency pipeline tests (reference
``tests/unit/runtime/test_data_efficiency.py`` + Megatron indexed-dataset
round-trips)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 MMapIndexedDataset,
                                                 MMapIndexedDatasetBuilder,
                                                 RandomLTDScheduler,
                                                 token_drop, token_restore)


# ------------------------------------------------------------- curriculum
def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 128, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    assert s.get_difficulty(0) == 8
    assert s.get_difficulty(100) == 128
    assert s.get_difficulty(1000) == 128
    mid = s.get_difficulty(50)
    assert 8 < mid < 128 and mid % 8 == 0
    diffs = [s.get_difficulty(t) for t in range(0, 101, 10)]
    assert diffs == sorted(diffs)


def test_fixed_root_ramp_is_faster_early():
    kw = dict(curriculum_type="seqlen", min_difficulty=8, max_difficulty=128,
              schedule_config={"total_curriculum_step": 100,
                               "difficulty_step": 1})
    lin = CurriculumScheduler(dict(kw, schedule_type="fixed_linear"))
    root = CurriculumScheduler(dict(kw, schedule_type="fixed_root"))
    assert root.get_difficulty(25) > lin.get_difficulty(25)


def test_fixed_discrete_and_custom():
    s = CurriculumScheduler({
        "schedule_type": "fixed_discrete", "min_difficulty": 4,
        "max_difficulty": 64,
        "schedule_config": {"difficulty": [4, 16, 64],
                            "max_step": [10, 20]}})
    assert [s.get_difficulty(t) for t in (0, 9, 10, 19, 20, 99)] == \
        [4, 4, 16, 16, 64, 64]
    c = CurriculumScheduler({
        "schedule_type": "custom", "schedule_fn": lambda t: 7 + t})
    assert c.get_difficulty(3) == 10


def test_engine_curriculum_truncates_seqlen(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "curriculum_learning": {
                "enabled": True, "curriculum_type": "seqlen",
                "min_difficulty": 8, "max_difficulty": 32,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4,
                                    "difficulty_step": 8}},
        })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
    for _ in range(5):
        _, m = engine.train_batch(batch)
        assert np.isfinite(m["loss"])
    # ramped to max by step 4; difficulty tracked on the engine
    assert engine.curriculum_scheduler.current_difficulty == 32


# --------------------------------------------------------- indexed dataset
def test_indexed_dataset_roundtrip(tmp_path):
    prefix = str(tmp_path / "docs")
    rows = [np.arange(n, dtype=np.int32) * 3 for n in (5, 1, 17)]
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for r in rows:
        b.add_item(r)
    b.finalize()

    assert MMapIndexedDataset.exists(prefix)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == 3
    np.testing.assert_array_equal(ds.sizes, [5, 1, 17])
    for r, got in zip(rows, ds[:]):
        np.testing.assert_array_equal(got, r)
    np.testing.assert_array_equal(ds.get(2, offset=4, length=3), rows[2][4:7])


def test_indexed_dataset_bad_magic(tmp_path):
    prefix = str(tmp_path / "x")
    open(prefix + ".idx", "wb").write(b"NOTMAGIC" + b"\x00" * 32)
    open(prefix + ".bin", "wb").write(b"")
    with pytest.raises(ValueError, match="bad magic"):
        MMapIndexedDataset(prefix)


# --------------------------------------------------------------- random-LTD
def test_token_drop_restore():
    import jax

    x = np.arange(2 * 8 * 4, dtype=np.float32).reshape(2, 8, 4)
    kept, idx = token_drop(jax.numpy.asarray(x), jax.random.PRNGKey(0), 5)
    assert kept.shape == (2, 5, 4) and idx.shape == (2, 5)
    idx_np = np.asarray(idx)
    for b in range(2):
        assert sorted(set(idx_np[b])) == list(idx_np[b])  # sorted, unique
        np.testing.assert_array_equal(np.asarray(kept)[b], x[b, idx_np[b]])

    processed = kept * 10.0
    restored = np.asarray(token_restore(jax.numpy.asarray(x), processed, idx))
    for b in range(2):
        for s in range(8):
            if s in idx_np[b]:
                np.testing.assert_allclose(restored[b, s], x[b, s] * 10.0)
            else:
                np.testing.assert_array_equal(restored[b, s], x[b, s])


def test_random_ltd_scheduler():
    s = RandomLTDScheduler({
        "random_ltd_layer_num": 10,
        "min_value": 64, "max_value": 512,
        "total_ltd_step": 100, "difficulty_step": 64})
    assert s.get_keep_count(0, seq_len=512) == 64
    assert s.get_keep_count(100, seq_len=512) == 512
    assert s.get_keep_count(100, seq_len=256) == 256  # capped by seq
    assert not s.applies_to_layer(0, 12)
    assert s.applies_to_layer(5, 12)
    assert not s.applies_to_layer(11, 12)


# --------------------------------------------------------------- data sampler
def test_data_analyzer_and_sampler(tmp_path):
    from deepspeed_tpu.runtime.data_pipeline import (DataAnalyzer,
                                                     DeepSpeedDataSampler)

    rng = np.random.default_rng(0)
    lens = rng.integers(4, 64, size=200)
    data = [{"input_ids": np.zeros(n, np.int32)} for n in lens]
    metrics = DataAnalyzer(data).save(str(tmp_path / "metrics.npz"))
    np.testing.assert_array_equal(metrics["seqlen"], lens)
    loaded = DataAnalyzer.load(str(tmp_path / "metrics.npz"))
    np.testing.assert_array_equal(loaded["seqlen"], lens)

    sched = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8,
        "max_difficulty": 64, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10,
                            "difficulty_step": 8}})
    sampler = DeepSpeedDataSampler(loaded["seqlen"], sched,
                                   global_batch_size=8,
                                   process_rank=0, process_count=2)
    # early steps: only short samples
    idx = sampler.next_batch_indices()
    assert len(idx) == 4  # per-rank share
    assert (lens[idx] <= 8).all()
    # after the ramp: longer samples admitted
    for _ in range(12):
        idx = sampler.next_batch_indices()
    assert (lens[idx] <= 64).all()
    assert lens[idx].max() > 8  # not stuck at the easy set


def test_sampler_rank_sharding_disjoint():
    from deepspeed_tpu.runtime.data_pipeline import DeepSpeedDataSampler

    metric = np.full(64, 1.0)
    a = DeepSpeedDataSampler(metric, None, 8, process_rank=0, process_count=2)
    b = DeepSpeedDataSampler(metric, None, 8, process_rank=1, process_count=2)
    ia, ib = a.next_batch_indices(), b.next_batch_indices()
    assert len(set(ia) & set(ib)) == 0  # same shuffle, disjoint shares


def test_sampler_infeasible_difficulty_raises():
    from deepspeed_tpu.runtime.data_pipeline import DeepSpeedDataSampler

    sched = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 1,
        "max_difficulty": 1, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 5}})
    metric = np.full(16, 100.0)  # nothing is ever eligible
    s = DeepSpeedDataSampler(metric, sched, 4)
    with pytest.raises(RuntimeError, match="admits fewer"):
        s.next_batch_indices()


def test_sampler_state_roundtrip():
    from deepspeed_tpu.runtime.data_pipeline import DeepSpeedDataSampler

    metric = np.full(32, 1.0)
    a = DeepSpeedDataSampler(metric, None, 4, seed=3)
    for _ in range(5):
        a.next_batch_indices()
    sd = a.state_dict()
    b = DeepSpeedDataSampler(metric, None, 4, seed=3)
    b.load_state_dict(sd)
    np.testing.assert_array_equal(a.next_batch_indices(),
                                  b.next_batch_indices())


# ------------------------------------------------- engine wiring (round 3)
def test_random_ltd_engine_wiring():
    """data_efficiency.data_routing.random_ltd drives the model's kept-token
    count through the schedule, retracing at boundaries; loss stays finite
    and the knob provably changes the traced program."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, num_layers=4,
                          num_heads=2, hidden_size=32)
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "data_efficiency": {"data_routing": {"enabled": True, "random_ltd": {
            "enabled": True,
            "random_ltd_schedule": {
                "min_value": 8, "max_value": 32,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4,
                                    "difficulty_step": 8},
            }}}},
    })
    rng = np.random.default_rng(0)
    batch = lambda: {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
    keeps = []
    for _ in range(5):
        _, m = engine.train_batch(batch())
        keeps.append(cfg.random_ltd_keep)
        assert np.isfinite(float(m["loss"]))
    # schedule grew the kept-token count from 8 toward full
    assert keeps[0] == 8
    assert keeps[-1] > keeps[0]
    assert sorted(keeps) == keeps


def test_random_ltd_changes_token_count_in_trace():
    """Behavioral effect at the trace level: with keep=K the middle layers
    see [B, K, D] activations (the reference's gather semantics)."""
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config(vocab_size=64, max_seq_len=16, num_layers=3,
                          num_heads=2, hidden_size=16)
    cfg.random_ltd_keep = 4
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    batch = {"input_ids": np.zeros((2, 9), np.int32)}
    jaxpr = jax.make_jaxpr(
        lambda p: gpt2.loss_from_batch(cfg, p, batch,
                                       rng=jax.random.PRNGKey(1)))(params)
    txt = str(jaxpr)
    assert "(2, 4, 16)" in txt or "2,4,16" in txt  # kept-subset activations
    # dense baseline has no 4-token activations
    cfg.random_ltd_keep = None
    txt_dense = str(jax.make_jaxpr(
        lambda p: gpt2.loss_from_batch(cfg, p, batch,
                                       rng=jax.random.PRNGKey(1)))(params))
    assert "(2, 4, 16)" not in txt_dense


def test_random_ltd_saturation_and_layer_range():
    """Schedule saturation stops retraces (no per-step rebuild churn), and
    the reference layer-range keys narrow which layers drop tokens."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=32, num_layers=4,
                          num_heads=2, hidden_size=32)
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "data_efficiency": {"data_routing": {"enabled": True, "random_ltd": {
            "enabled": True,
            "random_ltd_layer_id_start": 2,
            "random_ltd_layer_num": 1,
            "random_ltd_schedule": {
                "min_value": 8, "max_value": 16,  # = trained seq of 16
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 2,
                                    "difficulty_step": 8},
            }}}},
    })
    assert cfg.random_ltd_layer_start == 2
    assert cfg.random_ltd_layer_num == 1
    rng = np.random.default_rng(0)
    batch = lambda: {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 17)).astype(np.int32)}
    rebuild_steps = []
    orig = engine._build_step_fns

    def spy():
        rebuild_steps.append(engine.global_steps)
        orig()
    engine._build_step_fns = spy
    for _ in range(6):
        engine.train_batch(batch())
    # rebuilds happen only while ramping (8 -> 16), never after the
    # schedule endpoint is reached
    assert engine._ltd_saturated
    assert all(s <= 2 for s in rebuild_steps), rebuild_steps


def test_random_ltd_seq_clamp_does_not_latch():
    """A schedule whose max_value exceeds the trained sequence must NOT
    latch saturated on the clamped value — a later (curriculum-grown)
    longer sequence has to pick the schedule back up."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=64, num_layers=3,
                          num_heads=2, hidden_size=32)
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "data_efficiency": {"data_routing": {"enabled": True, "random_ltd": {
            "enabled": True,
            "random_ltd_schedule": {
                "min_value": 8, "max_value": 32,  # > short seq of 16
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 2,
                                    "difficulty_step": 8},
            }}}},
    })
    rng = np.random.default_rng(0)

    def batch(s):
        return {"input_ids": rng.integers(
            0, cfg.vocab_size,
            size=(engine.train_batch_size(), s + 1)).astype(np.int32)}

    for _ in range(4):
        engine.train_batch(batch(16))   # clamped at 16 < max 32
    assert not engine._ltd_saturated
    assert cfg.random_ltd_keep == 16
    engine.train_batch(batch(48))       # longer seq: schedule resumes
    assert cfg.random_ltd_keep == 32    # full (unclamped) endpoint
    assert engine._ltd_saturated


def test_data_analyzer_map_reduce_multi_worker():
    """Reference map-reduce protocol: 3-worker map + reduce must equal the
    single-worker run; metric_to_sample sorts by difficulty; percentiles
    map curriculum difficulty to thresholds."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import DataAnalyzer

    rng = np.random.default_rng(0)
    data = [{"input_ids": rng.integers(0, 32, size=(rng.integers(4, 20),))}
            for _ in range(23)]
    an = DataAnalyzer(data)
    parts = [an.run_map(w, 3) for w in range(3)]
    red = an.run_reduce(parts)["seqlen"]
    single = an.run()["seqlen"]
    np.testing.assert_array_equal(red["sample_to_metric"], single)
    order = red["metric_to_sample"]
    vals = red["sample_to_metric"][order]
    assert np.all(np.diff(vals) >= 0)  # ascending difficulty index
    pct = red["percentiles"]
    assert pct[0] == vals[0] and pct[-1] == vals[-1]


def test_data_analyzer_accumulate_metric_and_files(tmp_path):
    """accumulate_value_over_samples: vocab-rarity needs GLOBAL counts
    first; worker files + reduce.npz roundtrip (reference writes per-worker
    files then merges)."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
        DataAnalyzer, vocab_rarity_metric)

    rng = np.random.default_rng(1)
    common = rng.integers(0, 4, size=(12,))           # frequent tokens
    rare = np.full(12, 31)                            # one rare-token sample
    data = [{"input_ids": common} for _ in range(7)] + [{"input_ids": rare}]
    an = DataAnalyzer(data, metric_fns={},
                      accumulate_fns={"rarity": vocab_rarity_metric(32)})
    d = str(tmp_path / "ana")
    for w in range(2):
        an.run_map(w, 2, save_dir=d)
    # second SHARDED finalize pass (reference protocol): reduce totals,
    # score shards, then an O(workers) reduce
    totals = an.reduce_totals(an._load_parts(d, "map_"))
    for w in range(2):
        an.run_finalize_map(totals, w, 2, save_dir=d)
    red = an.run_reduce(save_dir=d)
    rarity = red["rarity"]["sample_to_metric"]
    assert rarity[-1] > rarity[0]  # the rare-token sample is hardest
    # serial fallback (no fin_ files) must agree
    serial = an.run_reduce(parts=an._load_parts(d, "map_"))
    np.testing.assert_array_equal(
        serial["rarity"]["sample_to_metric"], rarity)
    # persisted reduce roundtrip + percentile API
    loaded = DataAnalyzer.load_reduced(d)
    np.testing.assert_array_equal(loaded["rarity"]["sample_to_metric"],
                                  rarity)
    pct = an.get_metric_value_percentiles("rarity", save_dir=d)
    assert pct.shape == (101,)


def test_sampler_consumes_analyzer_output(tmp_path):
    """End-to-end: analyzer metric file -> difficulty-gated sampler only
    draws below-threshold samples."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
        DataAnalyzer, DeepSpeedDataSampler)

    rng = np.random.default_rng(2)
    data = [{"input_ids": rng.integers(0, 32, size=(l,))}
            for l in ([4] * 10 + [16] * 10)]
    metrics = DataAnalyzer(data).run()
    sched = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 4,
        "max_difficulty": 16, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 4,
                            "difficulty_step": 4}})
    s = DeepSpeedDataSampler(metrics["seqlen"], sched, global_batch_size=4)
    first = s.next_batch_indices()
    assert all(metrics["seqlen"][i] <= 4 for i in first)


def test_random_ltd_composes_with_curriculum_seqlen():
    """Both schedules active: the curriculum truncates the sequence, the
    LTD keep-count clamps to the truncated length and resumes when the
    curriculum grows it."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config(vocab_size=128, max_seq_len=64, num_layers=3,
                          num_heads=2, hidden_size=32)
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 16, "max_difficulty": 48,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 16}},
        "data_efficiency": {"data_routing": {"enabled": True, "random_ltd": {
            "enabled": True,
            # the LTD ramp OUTRUNS the curriculum (full 48 by step 2 while
            # the sequence is still 32) so the seq clamp actually binds —
            # and must release once the curriculum grows the sequence
            "random_ltd_schedule": {
                "min_value": 8, "max_value": 48,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 2,
                                    "difficulty_step": 8},
            }}}},
    })
    rng = np.random.default_rng(0)
    batch = lambda: {"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(engine.train_batch_size(), 49)).astype(np.int32)}
    keeps, seqs = [], []
    for _ in range(7):
        _, m = engine.train_batch(batch())
        assert np.isfinite(float(m["loss"]))
        keeps.append(cfg.random_ltd_keep)
        seqs.append(engine.curriculum_scheduler.current_difficulty)
    # keep never exceeds the curriculum's (truncated) sequence
    for kp, sq in zip(keeps, seqs):
        assert kp <= sq, (keeps, seqs)
    # the clamp BOUND at least once (schedule outran the sequence)...
    assert any(kp < min(48, sq) or (kp == sq < 48)
               for kp, sq in zip(keeps, seqs)), (keeps, seqs)
    assert max(keeps) == 48 or keeps[-1] == 48, (keeps, seqs)
    # ...and released: both ramps complete, and only then does LTD latch
    assert seqs[-1] == 48 and keeps[-1] == 48
    assert engine._ltd_saturated
