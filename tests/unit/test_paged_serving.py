"""Block-paged KV cache serving: allocator/prefix-trie units, paged-op
correctness, and chunked-prefill scheduler parity.

Tier-1 (fast) CPU-sim coverage for the paged path:
 - BlockAllocator / PrefixCache host-side bookkeeping (alloc/free/refcount/
   OOM, trie lookup/register/evict ordering).
 - paged_cache_update / paged_gather / paged_decode_attention_reference
   against the contiguous reference layout.
 - ServingEngine in chunked-prefill mode: greedy token parity with
   sequential ``generate`` (incl. under preemption pressure), prefix-cache
   hits for shared system prompts, and the O(1) compile contract (1 prefill
   + 1 decode program per trace).

The Pallas paged-decode kernel's interpret-mode twin lives in
``test_decode_attention.py`` (slow lane); the prefix-heavy end-to-end
bench lane is ``test_serving_bench.py`` (slow).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.paged import (SCRATCH_BLOCK, BlockAllocator,
                                           PrefixCache)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.utils.lru import LRUCache


# ------------------------------------------------------------- BlockAllocator
def test_allocator_alloc_free_refcount():
    a = BlockAllocator(5)                      # 1 scratch + 4 usable
    assert a.free_blocks == 4 and a.blocks_in_use == 0
    blocks = [a.alloc() for _ in range(4)]
    assert sorted(blocks) == [1, 2, 3, 4]      # scratch block 0 never issued
    assert SCRATCH_BLOCK not in blocks
    assert a.alloc() is None                   # OOM -> None, not an exception
    a.incref(blocks[0])
    a.decref(blocks[0])
    assert a.free_blocks == 0                  # still held once
    a.decref(blocks[0])
    assert a.free_blocks == 1                  # now free
    b = a.alloc()
    assert b == blocks[0] and a.refcount(b) == 1
    with pytest.raises(ValueError):
        BlockAllocator(1)                      # no usable blocks


def test_allocator_decref_unowned_asserts():
    a = BlockAllocator(3)
    with pytest.raises(AssertionError):
        a.decref(1)
    with pytest.raises(AssertionError):
        a.incref(2)


# ---------------------------------------------------------------- PrefixCache
def test_prefix_cache_lookup_register_roundtrip():
    a = BlockAllocator(10)
    pc = PrefixCache(block_size=4)
    toks = np.arange(12)                       # 3 full blocks
    blocks = [a.alloc() for _ in range(3)]
    pc.register(toks, blocks, a)
    assert len(pc) == 3
    assert all(a.refcount(b) == 2 for b in blocks)  # holder + cache

    # full-prefix hit (capped below the full prompt => only 2 of 3 blocks
    # when max_tokens = len-1)
    assert pc.probe(toks, len(toks)) == 3
    assert pc.probe(toks, len(toks) - 1) == 2
    got = pc.lookup(toks, len(toks), a)
    assert got == blocks
    assert all(a.refcount(b) == 3 for b in blocks)
    for b in got:
        a.decref(b)

    # divergent tail: only the shared leading blocks hit
    other = np.concatenate([toks[:8], [99, 98, 97, 96]])
    assert pc.probe(other, len(other)) == 2
    got = pc.lookup(other, len(other), a)
    assert got == blocks[:2]
    for b in got:
        a.decref(b)

    # probe never touches refcounts
    before = [a.refcount(b) for b in blocks]
    pc.probe(toks, len(toks))
    assert [a.refcount(b) for b in blocks] == before


def test_prefix_cache_eviction_leaf_first_lru():
    a = BlockAllocator(10)
    pc = PrefixCache(block_size=2)
    toks = np.arange(6)                        # chain of 3 blocks
    blocks = [a.alloc() for _ in range(3)]
    pc.register(toks, blocks, a)
    for b in blocks:
        a.decref(b)                            # only the cache holds them
    assert pc.evictable(a) == 3
    assert pc.evict_one(a)
    # leaf-first: the chain tail goes first, parents stay walkable
    assert pc.probe(toks, len(toks)) == 2
    assert pc.evict_one(a) and pc.evict_one(a)
    assert len(pc) == 0 and a.free_blocks == 9
    assert not pc.evict_one(a)                 # empty -> False

    # entries still held by a sequence are not evictable
    blocks = [a.alloc() for _ in range(2)]
    pc.register(np.arange(4), blocks, a)
    assert pc.evictable(a) == 0                # refcount 2 (holder + cache)
    assert not pc.evict_one(a)


def test_prefix_cache_register_keeps_first_writer():
    a = BlockAllocator(10)
    pc = PrefixCache(block_size=2)
    toks = np.arange(4)
    b1 = [a.alloc(), a.alloc()]
    b2 = [a.alloc(), a.alloc()]
    pc.register(toks, b1, a)
    pc.register(toks, b2, a)                   # duplicate content
    assert len(pc) == 2                        # first writer wins
    got = pc.lookup(toks, len(toks), a)
    assert got == b1
    assert a.refcount(b2[0]) == 1              # duplicate not cached


# ------------------------------------------------------------------- LRUCache
def test_lru_cache_hit_refreshes_and_capacity_bounds():
    c = LRUCache(capacity=2)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1                     # refresh "a"
    c.put("c", 3)                              # evicts LRU = "b"
    assert "b" not in c and "a" in c and "c" in c
    built = []
    v = c.get_or_build("a", lambda: 99, on_build=built.append)
    assert v == 1 and built == []              # hit: no build
    v = c.get_or_build("d", lambda: 4, on_build=built.append)
    assert v == 4 and built == [4]


# ----------------------------------------------------------- paged device ops
def test_paged_gather_update_attention_match_contiguous():
    import jax.numpy as jnp

    from deepspeed_tpu.ops.decode_attention import (
        decode_attention_reference, paged_decode_attention_reference)
    from deepspeed_tpu.ops.paged_kv import paged_cache_update, paged_gather

    rng = np.random.default_rng(0)
    b, h, hkv, d, bs, nbper, nb = 3, 4, 2, 16, 8, 4, 13
    s = nbper * bs
    bt = rng.permutation(np.arange(1, nb))[:b * nbper] \
        .reshape(b, nbper).astype(np.int32)
    kc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    vc = rng.standard_normal((b, hkv, s, d)).astype(np.float32)
    kp = np.zeros((nb, hkv, bs, d), np.float32)
    vp = np.zeros((nb, hkv, bs, d), np.float32)
    for row in range(b):
        for i in range(nbper):
            kp[bt[row, i]] = kc[row, :, i * bs:(i + 1) * bs]
            vp[bt[row, i]] = vc[row, :, i * bs:(i + 1) * bs]

    # gather reconstructs the contiguous per-row view
    np.testing.assert_array_equal(
        np.asarray(paged_gather(jnp.asarray(kp), jnp.asarray(bt))), kc)

    # paged attention == contiguous attention (per-row decode positions)
    q = rng.standard_normal((b, h, 1, d)).astype(np.float32)
    pos = np.array([5, 17, 30], np.int32)
    ref = decode_attention_reference(jnp.asarray(q), jnp.asarray(kc),
                                     jnp.asarray(vc), jnp.asarray(pos))
    pag = paged_decode_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
        jnp.asarray(pos))
    np.testing.assert_allclose(np.asarray(pag), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)

    # chunk scatter: per-row bases + valid masking, pads -> scratch block
    t = 8
    kw = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    vw = rng.standard_normal((b, hkv, t, d)).astype(np.float32)
    base = np.array([0, 8, 16], np.int32)
    valid = np.array([8, 5, 1], np.int32)
    kp2, _ = paged_cache_update(
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(kw), jnp.asarray(vw),
        jnp.asarray(base), jnp.asarray(bt), valid=jnp.asarray(valid))
    got = np.asarray(paged_gather(kp2, jnp.asarray(bt)))
    want = kc.copy()
    for row in range(b):
        for i in range(valid[row]):
            want[row, :, base[row] + i] = kw[row, :, i]
    np.testing.assert_array_equal(got, want)


# --------------------------------------------------- chunked-prefill scheduler
@pytest.fixture(scope="module")
def tiny_engine():
    """One shared tiny-gpt2 engine for the scheduler tests: serve() drains
    its slots completely, so ServingEngines stack on it safely, and the
    generate-parity programs stay in its LRU across tests (tier-1 window
    budget)."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _shared_prefix_trace(cfg, n, prefix_len=24, seed=0, tail=(3, 10),
                         max_new=(2, 10)):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len)
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(*tail)))]),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def test_chunked_serving_matches_sequential_generate(tiny_engine):
    """Acceptance: paged chunked-prefill serving (prefix cache on) is
    token-identical to sequential ``generate`` on a shared-prefix trace —
    and the stats() / step_log observability probes fire."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    reqs = _shared_prefix_trace(cfg, 6)
    steps = []
    res = srv.serve(reqs, step_log=steps)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    st = srv.stats()
    assert st["mode"] == "chunked"
    assert st["prefix_cache_hit_rate"] > 0.2, st
    assert st["prefix_hit_tokens"] % srv.block_size == 0
    for key in ("prefix_cache_hit_rate", "blocks_in_use", "compile_count",
                "admitted", "evicted", "decode_steps", "prefill_calls",
                "num_blocks", "free_blocks", "compile_budget",
                "debug_checks", "invariant_checks_run",
                "retraces_observed"):
        assert key in st, key
    # debug_checks=True: every iteration audited, zero retrace drift
    assert st["debug_checks"] and st["invariant_checks_run"] > 0
    assert st["retraces_observed"] == 0
    assert st["admitted"] == len(reqs)
    assert steps and sum(s["admitted"] for s in steps) == len(reqs)
    assert all("blocks_in_use" in s and "evicted" in s for s in steps)


def test_chunked_serving_parity_with_eos(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    reqs = _shared_prefix_trace(cfg, 4, seed=1, max_new=(4, 10))
    probe = engine.generate(reqs[0].prompt[None, :], max_new_tokens=1)
    eos = int(probe[0, len(reqs[0].prompt)])
    res = srv.serve(reqs, eos_token_id=eos)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens,
                               eos_token_id=eos)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


@pytest.mark.slow  # two engine builds — tier-1 covers gpt2 chunked + all
@pytest.mark.parametrize("family", ["llama", "opt"])  # families bucketed
def test_chunked_serving_parity_other_families(family):
    """Chunked paged prefill holds beyond gpt2: per-row rope offsets
    (llama) and offset learned positions (opt) in T>1 windows."""
    deepspeed_tpu.comm.reset_topology()
    if family == "llama":
        from deepspeed_tpu.models import llama as m

        cfg = m.LlamaConfig.tiny()
    else:
        from deepspeed_tpu.models import opt as m

        cfg = m.OPTConfig.tiny()
    engine = deepspeed_tpu.init_inference(
        m.build(cfg), config={"dtype": "fp32",
                              "tensor_parallel": {"tp_size": 1}})
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=16, prefill_batch=2)
    reqs = _shared_prefix_trace(cfg, 5, prefix_len=10, seed=2, tail=(3, 8),
                                max_new=(2, 8))
    res = srv.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_chunked_compile_count_is_two_programs(tiny_engine):
    """Acceptance: the chunked serving loop compiles exactly 1 prefill + 1
    decode program for a whole mixed-shape trace — and stays there for new
    shapes.  Enforced LIVE by the recompile sentry (debug_checks=True
    raises at trace time past the budget of 2), which also replaces the
    old per-fn ``_cache_size`` retrace probe: the sentry counts actual
    Python-body traces, so silent retraces can't hide."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2,
                        debug_checks=True)
    assert srv.compile_budget == 2
    rng = np.random.default_rng(3)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(3, 40))),
                    max_new_tokens=int(rng.integers(1, 12)))
            for i in range(12)]
    srv.serve(reqs)
    assert srv.compile_count == 2, srv.compiled_programs
    reqs2 = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size,
                                                int(rng.integers(40, 80))),
                     max_new_tokens=int(rng.integers(1, 8)))
             for i in range(6)]
    srv.serve(reqs2)                           # new shapes: no new programs
    assert srv.compile_count == 2, srv.compiled_programs
    # sentry ledger: exactly one trace per program, zero beyond budget
    assert srv.sentry.traces == 2, srv.sentry.report()
    assert srv.sentry.retraces_observed == 0


def test_prefix_cache_reuse_across_serve_calls(tiny_engine):
    """A shared system prompt prefilled once is reused by later traffic:
    the second serve call's hit tokens cover the registered prefix."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=128, block_size=8,
                        prefill_chunk=32, prefill_batch=2,
                        debug_checks=True)
    rng = np.random.default_rng(4)
    prefix = rng.integers(0, cfg.vocab_size, 32)      # 4 full blocks

    def mk(uid, seed):
        r = np.random.default_rng(seed)
        return Request(uid=uid, prompt=np.concatenate(
            [prefix, r.integers(0, cfg.vocab_size, 5)]), max_new_tokens=4)

    srv.serve([mk(0, 0)])
    hit0 = srv.prefix_hit_tokens
    res = srv.serve([mk(1, 1), mk(2, 2)])
    # both later requests reuse the full 32-token (4-block) shared prefix
    assert srv.prefix_hit_tokens - hit0 == 2 * 32
    for uid, seed in ((1, 1), (2, 2)):
        want = engine.generate(mk(uid, seed).prompt[None, :],
                               max_new_tokens=4)[0]
        np.testing.assert_array_equal(res[uid], want)


def test_preemption_under_block_pressure_keeps_parity(tiny_engine):
    """Oversubscribed pool: decode growth forces preemption (sequence
    eviction + FIFO re-queue + recompute); greedy outputs stay identical
    and the eviction counters fire."""
    engine, cfg = tiny_engine
    # nbper = 64/8 = 8; 3 slots want up to 6 blocks each (17 prompt + 28
    # new -> 45 tokens) but only 11 usable blocks exist.  debug_checks
    # audits the allocator/trie/table invariants through every eviction +
    # preemption round — the hardest path for refcount conservation.
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=32, prefill_batch=2, num_blocks=12,
                        debug_checks=True)
    rng = np.random.default_rng(5)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28) for i in range(5)]
    log = []
    res = srv.serve(reqs, admission_log=log)
    assert srv.preempted > 0, srv.stats()      # pressure actually happened
    assert set(res) == set(range(5))           # everyone finished
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    # FIRST admissions stay FIFO (re-admissions of evicted uids may repeat)
    first = []
    for uid, _ in log:
        if uid not in first:
            first.append(uid)
    assert first == list(range(5))


@pytest.mark.slow  # engine build + long generations (preemption churn)
def test_bucketed_preemption_resume_outgrows_ladder():
    """Bucketed fallback under block pressure: a preempted request whose
    prompt + generated tokens outgrow the custom ladder re-prefills through
    the full-cache-width fallback program instead of failing mid-trace;
    outputs stay greedy-exact."""
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    # nbper = 8; 3 slots want 6 blocks each (20 prompt + 24 new) but only
    # 11 usable exist -> preemption; resumes reach 20+k > 24 tokens, past
    # the (24,)-ladder
    # bucketed budget = len(buckets) + 2 (ladder + full-cache-width
    # preemption fallback + decode) — the sentry enforces it live
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prompt_buckets=(24,), prefill_batch=2,
                        num_blocks=12, debug_checks=True)
    assert srv.compile_budget == 3
    rng = np.random.default_rng(7)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 20),
                    max_new_tokens=24) for i in range(4)]
    res = srv.serve(reqs)
    assert srv.preempted > 0, srv.stats()
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_paged_serving_rejects_legacy_models():
    deepspeed_tpu.comm.reset_topology()
    from deepspeed_tpu.models import gptj

    legacy = deepspeed_tpu.init_inference(
        gptj.build(gptj.GPTJConfig.tiny()),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    with pytest.raises(ValueError, match="supports_lengths"):
        ServingEngine(legacy)


