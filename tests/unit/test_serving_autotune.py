"""Serving autotuner tests (ISSUE 13 / ROADMAP item 5).

Covers the search core on a fake objective (rung sizes, top-1/eta
survival, budget accounting, determinism, resume-from-exps.json
mid-rung), trace record→replay determinism, constraint pruning counts,
the constraint↔ctor-validation audit (every ``space.py`` predicate has a
loud ``ServingEngine`` twin naming the knob), synthetic-trace fitting
against both a hand-built and a live telemetry snapshot, the
``stats()['config']`` round-trip, and a micro end-to-end
``tune_serving`` run with artifact checks.
"""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning import (ModelGeom, ServingKnobSpace,
                                      ServingTrace, SuccessiveHalving,
                                      TraceRecorder, config_key, fit_trace,
                                      sessions_trace, tune_serving)
from deepspeed_tpu.autotuning.space import (BASE_SERVING_CONFIG,
                                            compile_budget, kv_pool_bytes,
                                            workload_space)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2


# ------------------------------------------------------------ fixtures
@pytest.fixture(scope="module")
def tiny_engine():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=256)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return engine, cfg


def _fake_objective(log=None):
    """Deterministic fake: score = 10*x + budget (ranking by x at every
    budget), even x infeasible."""
    def objective(config, budget):
        if log is not None:
            log.append((config["x"], budget))
        if config["x"] % 2 == 0:
            return {"feasible": False, "error": "even"}
        return {"feasible": True, "throughput": 10.0 * config["x"] + budget}
    return objective


# ------------------------------------------------- successive halving
def test_sh_rung_sizes_survival_and_budget_accounting(tmp_path):
    cands = [{"x": i} for i in range(8)]
    log = []
    sh = SuccessiveHalving(eta=2, min_budget=4, max_budget=16,
                           results_dir=str(tmp_path))
    out = sh.run(cands, _fake_objective(log))
    # rung 0: all 8 at budget 4 -> 4 feasible (odd x); keep ceil(4/2)=2
    # rung 1: 2 at budget 8; keep 1 -> rung 2 would be 1 survivor, but
    # budget doubles to 16 == max and runs, then stops
    assert [r["candidates"] for r in out["rungs"]] == [8, 2, 1]
    assert [r["budget"] for r in out["rungs"]] == [4, 8, 16]
    assert [r["feasible"] for r in out["rungs"]] == [4, 2, 1]
    # survivors of rung 0 are the top-1/eta by score: x = 7, 5
    assert sorted(x for x, b in log if b == 8) == [5, 7]
    assert [x for x, b in log if b == 16] == [7]
    assert out["best"]["config"] == {"x": 7}
    assert out["best"]["budget"] == 16
    assert out["trials_executed"] == 8 + 2 + 1
    assert out["budget_spent"] == 8 * 4 + 2 * 8 + 1 * 16
    # exps.json persisted every record
    exps = json.load(open(tmp_path / "exps.json"))
    assert len(exps) == out["trials_total"] == 11
    assert all("budget" in r and "stage" in r for r in exps)


def test_sh_deterministic():
    cands = [{"x": i} for i in range(6)]
    runs = []
    for _ in range(2):
        out = SuccessiveHalving(eta=2, min_budget=2, max_budget=8).run(
            cands, _fake_objective())
        runs.append([(config_key(r["config"]), r["budget"],
                      r.get("throughput")) for r in out["results"]])
    assert runs[0] == runs[1]


def test_sh_resume_mid_rung(tmp_path):
    cands = [{"x": i} for i in range(8)]
    # interrupted run: budget for 5 executed trials ends mid-rung-0
    log1 = []
    sh1 = SuccessiveHalving(eta=2, min_budget=4, max_budget=16,
                            max_trials=5, results_dir=str(tmp_path))
    out1 = sh1.run(cands, _fake_objective(log1))
    assert out1["exhausted"] and out1["trials_executed"] == 5
    assert len(json.load(open(tmp_path / "exps.json"))) == 5
    # resumed run replays the 5 persisted trials, executes only the rest
    log2 = []
    sh2 = SuccessiveHalving(eta=2, min_budget=4, max_budget=16,
                            results_dir=str(tmp_path))
    out2 = sh2.run(cands, _fake_objective(log2), resume=True)
    assert not out2["exhausted"]
    assert out2["trials_executed"] == 11 - 5
    assert out2["rungs"][0]["resumed"] == 5
    assert [x for x, b in log2 if b == 4] == [5, 6, 7]   # only the tail
    # and the final state matches an uninterrupted run
    clean = SuccessiveHalving(eta=2, min_budget=4, max_budget=16).run(
        cands, _fake_objective())
    strip = lambda rs: [(config_key(r["config"]), r["budget"],
                         r.get("throughput")) for r in rs]
    assert strip(out2["results"]) == strip(clean["results"])
    assert out2["best"]["config"] == clean["best"]["config"]


def test_sh_all_infeasible_returns_none():
    out = SuccessiveHalving(eta=2, min_budget=1, max_budget=2).run(
        [{"x": 0}, {"x": 2}], _fake_objective())
    assert out["best"] is None


# ------------------------------------------------------------- traces
def test_trace_determinism_slice_and_roundtrip(tmp_path):
    t = sessions_trace(12, vocab=512, seed=3, sessions=4, prefix_len=64)
    a = [t.prompt_for(i) for i in range(len(t))]
    b = [t.prompt_for(i) for i in range(len(t))]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    # same session -> same prefix; different sessions differ
    assert np.array_equal(a[0][:64], a[4][:64])
    assert not np.array_equal(a[0][:64], a[1][:64])
    # slice keeps entries and prompts identical
    s = t.slice(5)
    assert len(s) == 5
    assert all(np.array_equal(s.prompt_for(i), a[i]) for i in range(5))
    # JSON round-trip materializes the same tokens
    path = str(tmp_path / "trace.json")
    t.save(path)
    t2 = ServingTrace.load(path)
    assert len(t2) == len(t) and t2.sessions == t.sessions
    assert all(np.array_equal(t2.prompt_for(i), a[i])
               for i in range(len(t)))
    assert t2.working_set_tokens() == t.working_set_tokens()


def test_trace_v2_sampling_roundtrip_and_v1_load(tmp_path):
    from deepspeed_tpu.autotuning.trace import TRACE_VERSION, TraceEntry

    # v2: sampled traces carry per-request params + deterministic seeds
    t = sessions_trace(8, vocab=128, seed=5, temperature=0.8, top_k=20,
                       top_p=0.9)
    assert all(e.temperature == 0.8 and e.top_k == 20 and e.top_p == 0.9
               and e.seed > 0 for e in t.entries)
    # seeds are deterministic functions of the trace seed
    t_again = sessions_trace(8, vocab=128, seed=5, temperature=0.8,
                             top_k=20, top_p=0.9)
    assert [e.seed for e in t.entries] == [e.seed for e in t_again.entries]
    d = t.to_dict()
    assert d["version"] == TRACE_VERSION == 2
    t2 = ServingTrace.from_dict(json.loads(json.dumps(d)))
    for e, e2 in zip(t.entries, t2.entries):
        assert (e.temperature, e.top_k, e.top_p, e.seed) == \
            (e2.temperature, e2.top_k, e2.top_p, e2.seed)
    req = t2.requests()[0][0]
    assert req.temperature == 0.8 and req.top_k == 20 \
        and req.top_p == 0.9 and req.seed == t.entries[0].seed

    # greedy traces serialize WITHOUT the sampling keys — a committed
    # v1 BENCH trace and its v2 re-save are entry-for-entry identical
    g = sessions_trace(4, vocab=128, seed=5)
    for e in g.to_dict()["entries"]:
        assert not ({"temperature", "top_k", "top_p", "seed"} & set(e))

    # old-format (v1) files load and replay as greedy
    v1 = {"version": 1, "vocab": 128, "seed": 5, "prefix_len": 0,
          "meta": {}, "entries": [{"uid": 0, "max_new_tokens": 4,
                                   "prompt_len": 8}]}
    path = str(tmp_path / "v1.json")
    json.dump(v1, open(path, "w"))
    old = ServingTrace.load(path)
    e = old.entries[0]
    assert (e.temperature, e.top_k, e.top_p, e.seed) == (0.0, 0, 1.0, 0)
    assert not old.requests()[0][0].sampled


def test_trace_record_then_replay_same_tokens(tiny_engine):
    engine, cfg = tiny_engine
    trace = sessions_trace(6, vocab=cfg.vocab_size, seed=7, sessions=2,
                           prefix_len=32, tail_range=(8, 16),
                           new_range=(4, 8))
    kw = dict(slots=2, max_seq_len=trace.max_total_len(), block_size=8,
              prefill_chunk=16, debug_checks=True)
    srv = ServingEngine(engine, **kw)
    rec = TraceRecorder(vocab=cfg.vocab_size).attach(srv)
    outs = srv.serve([r for r, _ in trace.requests()], eos_token_id=7)
    rec.detach()
    assert srv._submit_observer is None and len(rec) == 6
    recorded = rec.trace()
    # recorded prompts match what was submitted, arrival order intact,
    # and the submit-time eos rides along (replay stops where the
    # recorded traffic did)
    for i, (req, _) in enumerate(trace.requests()):
        assert recorded.entries[i].uid == req.uid
        assert recorded.entries[i].eos_token_id == 7
        assert np.array_equal(recorded.prompt_for(i), req.prompt)
    # replaying the RECORDED trace on a fresh engine reproduces the
    # exact tokens (same trace -> same tokens), per-entry eos honored
    # through submit_all + the JSON round-trip
    recorded = ServingTrace.from_dict(recorded.to_dict())
    srv2 = ServingEngine(engine, **kw)
    handles = recorded.submit_all(srv2)
    while srv2.step():
        pass
    outs2 = {h.uid: h.result(timeout=0) for h in handles}
    assert set(outs) == set(outs2)
    assert all(np.array_equal(outs[u], outs2[u]) for u in outs)


def test_recorder_refuses_to_clobber_foreign_observer(tiny_engine):
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                        prefill_chunk=16)
    srv._submit_observer = lambda *a, **k: None
    with pytest.raises(RuntimeError, match="observer"):
        TraceRecorder(vocab=cfg.vocab_size).attach(srv)
    with pytest.raises(TypeError, match="_submit_observer"):
        TraceRecorder(vocab=cfg.vocab_size).attach(object())


# ------------------------------------------------------- space pruning
def _geom():
    return ModelGeom(layers=2, kv_heads=4, head_dim=16, dtype_bytes=4)


def test_constraint_pruning_counts():
    geom = _geom()
    base = {"num_blocks": 40}
    # 40-block fp32 pool at block_size 32: 40 * 2*2*4*32*16*4 bytes
    ceiling = 40 * (2 * 2 * 4 * 32 * 16 * 4)
    space = ServingKnobSpace(
        geom, max_seq_len=256, base=base, mem_ceiling_bytes=ceiling,
        domains={"block_size": (32, 64),
                 "spec_tokens": (0, 4, 31),
                 "chunked_prefill": (True, False)})
    cands = space.candidates()
    assert len(cands) == 2 * 3 * 2
    kept, pruned = space.prune(cands)
    # block_size=64 doubles block bytes past the ceiling: 6 candidates
    # pruned by memory.  Of the remaining block_size=32 half:
    # chunked_prefill=False kills spec 4/31 (exclusivity, first match)
    # and spec_tokens=31 kills its chunked variant (window > 16).
    assert pruned["kv_pool_memory"] == 6
    assert pruned["spec_bucketed_exclusive"] == 2
    assert pruned["spec_window"] == 1
    assert len(kept) + sum(pruned.values()) == len(cands)
    # every kept candidate passes every predicate
    assert all(not space.check(c) for c in kept)


def test_mem_sentinel_fills_ceiling_per_block_size():
    geom = _geom()
    ceiling = 20 * (2 * 2 * 4 * 32 * 16 * 4)       # 20 blocks at bs=32
    space = ServingKnobSpace(
        geom, max_seq_len=128, base={"num_blocks": "mem"},
        mem_ceiling_bytes=ceiling, domains={"block_size": (16, 32, 64)})
    by_bs = {c["block_size"]: c["num_blocks"]
             for c in space.candidates()}
    assert by_bs == {16: 40, 32: 20, 64: 10}
    for c in space.candidates():
        assert kv_pool_bytes(c, geom) <= ceiling


def test_compile_budget_mirror(tiny_engine):
    """space.compile_budget must agree with the ctor's sentry budget for
    every mode the space can emit."""
    engine, _ = tiny_engine
    cases = [
        dict(),                                        # chunked
        dict(spec_tokens=4),                           # ngram spec
        dict(host_blocks=16, swap_batch=4),            # tiered
        dict(spec_tokens=4, host_blocks=16, swap_batch=4),
        dict(chunked_prefill=False, prompt_buckets=(32, 64),
             prefix_caching=False),                    # bucketed
    ]
    for kw in cases:
        srv = ServingEngine(engine, slots=2, max_seq_len=64, block_size=8,
                            prefill_chunk=16, **kw)
        cfg = {**BASE_SERVING_CONFIG, **kw}
        assert compile_budget(cfg) == srv.compile_budget, kw


# ----------------------------- constraint <-> ctor validation audit
def test_every_constraint_has_a_loud_ctor_twin(tiny_engine):
    """A tuner-proposed config that slips past pruning must fail the
    ServingEngine ctor with a message naming the offending knob — one
    case per space.py predicate with a ctor-reachable violation."""
    engine, _ = tiny_engine
    base = dict(slots=2, max_seq_len=64, block_size=8, prefill_chunk=16)
    cases = [
        # (space constraint, ctor kwargs, message fragment)
        # chunked_prefill=None = the ctor's auto rule: prompt_buckets
        # selects bucketed mode, which excludes speculation
        ("spec_bucketed_exclusive",
         {**base, "spec_tokens": 3, "prompt_buckets": (64,),
          "chunked_prefill": None},
         "chunked-prefill"),
        ("spec_window", {**base, "spec_tokens": 31}, "spec_tokens"),
        ("tiered_needs_prefix_cache",
         {**base, "host_blocks": 8, "swap_batch": 4,
          "prefix_caching": False}, "prefix_caching"),
        ("swap_batch_bounds",
         {**base, "host_blocks": 4, "swap_batch": 8}, "swap_batch"),
        ("pool_min_blocks", {**base, "num_blocks": 4}, "num_blocks"),
        ("positive_knobs", {**base, "slots": 0}, "slots"),
        ("positive_knobs", {**base, "prefill_batch": 0}, "prefill_batch"),
        ("positive_knobs", {**base, "block_size": 0}, "block_size"),
        # PR 17: disaggregated role + NVMe third tier
        ("role_needs_tiered_kv", {**base, "role": "prefill"},
         "host_blocks"),
        ("role_needs_tiered_kv", {**base, "role": "sideways"}, "role"),
        ("nvme_needs_host_tier", {**base, "nvme_blocks": 8},
         "host tier"),
        ("nvme_watermark_window",
         {**base, "host_blocks": 8, "swap_batch": 4, "nvme_blocks": 8,
          "nvme_high_watermark": 1.5}, "nvme_high_watermark"),
        ("nvme_watermark_window",
         {**base, "host_blocks": 8, "swap_batch": 4, "nvme_blocks": 8,
          "nvme_high_watermark": 0.2}, "watermark budget"),
        # PR 19: long-context lane — sp prefill + resident window
        # (tiny_engine carries no sp mesh axis, so the ctor's loud sp
        # failure is the mesh-shape check; the space predicate prunes
        # the same config on its chunk-divisibility rule)
        ("sp_prefill_exclusive", {**base, "sp": 3}, "sp=3"),
        ("resident_window_span",
         {**base, "resident_window_blocks": 4, "swap_batch": 4},
         "host_blocks"),
        ("resident_window_span",
         {**base, "resident_window_blocks": 2, "host_blocks": 8,
          "swap_batch": 4}, "must be >= 3"),
        ("resident_window_span",
         {**base, "resident_window_blocks": 8, "host_blocks": 8,
          "swap_batch": 4, "spec_tokens": 2}, "speculative"),
        ("pool_min_blocks",
         {**base, "resident_window_blocks": 4, "host_blocks": 8,
          "swap_batch": 4, "num_blocks": 5}, "resident"),
        # PR 20: on-device sampling stack + constrained decoding
        ("spec_sampling_needs_rejection",
         {**base, "spec_tokens": 2, "spec_verifier": "greedy"},
         "rejection verifier"),
        ("spec_sampling_needs_rejection",
         {**base, "spec_verifier": "argmax"}, "spec_verifier"),
        ("logit_masks_excludes_dp_tp",
         {**base, "logit_masks": True, "sampling": False},
         "sampling"),
        ("logit_masks_excludes_dp_tp",
         {**base, "logit_masks": True, "engine_mode": "dp_tp",
          "prefix_caching": False}, "logit_masks"),
    ]
    for name, kwargs, fragment in cases:
        with pytest.raises(ValueError, match=fragment):
            ServingEngine(engine, **kwargs)
        # and the space predicate agrees the config is inadmissible
        space = ServingKnobSpace(_geom(), max_seq_len=64)
        cfg = {**BASE_SERVING_CONFIG, **kwargs}
        cfg.pop("draft", None)
        assert any(n == name for n, _ in space.check(cfg)), name


def test_prefill_ratio_constraint_has_router_twins(tiny_engine):
    """``prefill_decode_ratio`` lives at the FLEET layer, so its loud
    twins are ``plan_roles`` (the launcher/init_router assignment) and
    the ``ReplicaRouter`` ctor (a hand-built all-prefill fleet), not the
    engine ctor."""
    from deepspeed_tpu.serving import ReplicaRouter, plan_roles

    engine, _ = tiny_engine
    space = ServingKnobSpace(_geom(), max_seq_len=64)
    cfg = {**BASE_SERVING_CONFIG, "max_seq_len": 64, "replicas": 2,
           "prefill_workers": 2, "host_blocks": 8}
    assert any(n == "prefill_decode_ratio" for n, _ in space.check(cfg))
    with pytest.raises(ValueError,
                       match="prefill_workers:decode_workers ratio"):
        plan_roles(2, 2)
    # a disaggregated fleet without host_blocks is inadmissible too
    cfg2 = {**BASE_SERVING_CONFIG, "max_seq_len": 64, "replicas": 2,
            "prefill_workers": 1}
    assert any(n == "prefill_decode_ratio" for n, _ in space.check(cfg2))
    # hand-built fleet twins: one-sided roles, and kv_pull=False
    mk = lambda role: ServingEngine(  # noqa: E731
        engine, slots=2, max_seq_len=64, block_size=8, prefill_chunk=16,
        host_blocks=8, swap_batch=4, role=role)
    with pytest.raises(ValueError, match="ratio must keep at least one"):
        ReplicaRouter([mk("prefill"), mk("prefill")])
    with pytest.raises(ValueError, match="kv_pull"):
        ReplicaRouter([mk("prefill"), mk("decode")], kv_pull=False)


# ---------------------------------------------------------- fitting
def test_fit_trace_recovers_handmade_snapshot():
    """Exact-arithmetic fit: 24 requests over 6 sessions of 64-token
    prefixes (block 32), mean prompt 96, mean decode 10."""
    n, sessions, prefix, mean_prompt, mean_new = 24, 6, 64, 96.0, 10.0
    hit = (1 - sessions / n) * prefix / mean_prompt
    snap = {
        "serving_requests_admitted_total": {
            "series": [{"labels": {}, "value": n}]},
        "serving_requests_finished_total": {
            "series": [{"labels": {}, "value": n}]},
        "serving_prompt_tokens_total": {
            "series": [{"labels": {}, "value": n * mean_prompt}]},
        "serving_prefix_hit_tokens_total": {
            "series": [{"labels": {}, "value": hit * n * mean_prompt}]},
        "serving_generated_tokens_total": {
            "series": [{"labels": {}, "value": n * mean_new}]},
        "serving_slo_requests_total": {
            "series": [{"labels": {"slo_class": "interactive"},
                        "value": 2 * n / 3},
                       {"labels": {"slo_class": "batch"},
                        "value": n / 3}]},
    }
    t = fit_trace(snap, vocab=512, n_requests=n, seed=0, block_size=32)
    assert t.meta["fitted_sessions"] == sessions
    assert t.meta["fitted_prefix_len"] == prefix
    assert t.sessions == sessions and t.prefix_len == prefix
    plens = [t.prompt_for(i).size for i in range(n)]
    assert abs(np.mean(plens) - mean_prompt) / mean_prompt < 0.15
    mnews = [e.max_new_tokens for e in t.entries]
    assert abs(np.mean(mnews) - mean_new) / mean_new < 0.15
    classes = [e.slo_class for e in t.entries]
    assert classes.count("interactive") == 16
    assert classes.count("batch") == 8


def test_fit_trace_from_live_snapshot(tiny_engine):
    """Fit against a REAL engine's registry after a known sessions
    trace: the fitted structure lands near the ground truth."""
    engine, cfg = tiny_engine
    truth = sessions_trace(18, vocab=cfg.vocab_size, seed=11, sessions=6,
                           prefix_len=64, tail_range=(8, 24),
                           new_range=(4, 8))
    # unpressured pool: the trie must retain every session chain, or
    # LRU eviction suppresses the hit rate the fit reads (the fitter
    # models the cache-retaining steady state)
    srv = ServingEngine(engine, slots=4,
                        max_seq_len=truth.max_total_len(), block_size=16,
                        num_blocks=160, prefill_chunk=32)
    srv.serve([r for r, _ in truth.requests()])
    fitted = fit_trace(srv.metrics.snapshot(), vocab=cfg.vocab_size,
                       n_requests=18, seed=11, block_size=16)
    assert 0 < fitted.sessions <= 18
    assert abs(fitted.sessions - 6) <= 3
    assert fitted.prefix_len % 16 == 0
    assert 32 <= fitted.prefix_len <= 80
    mean_p = np.mean([fitted.prompt_for(i).size for i in range(18)])
    truth_p = np.mean([truth.prompt_for(i).size for i in range(18)])
    assert abs(mean_p - truth_p) / truth_p < 0.25


def test_fit_trace_empty_snapshot_raises():
    with pytest.raises(ValueError, match="nothing to fit"):
        fit_trace({}, vocab=512)


# --------------------------------------------------- config round-trip
def test_resolved_config_roundtrips_through_init_serving(tiny_engine):
    _, cfg = tiny_engine
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
        spec_tokens=2, host_blocks=12, swap_batch=4)
    rc = srv.stats()["config"]
    assert rc == srv.resolved_config()
    json.dumps(rc)                       # artifact-ready
    deepspeed_tpu.comm.reset_topology()
    srv2 = deepspeed_tpu.init_serving(
        gpt2.build(cfg), config={"dtype": "fp32"}, **rc)
    # rebuilt engine resolves to the identical config (fixpoint)
    assert srv2.resolved_config() == rc


# ------------------------------------------------------ micro e2e tune
def test_tune_serving_micro_end_to_end(tmp_path, tiny_engine):
    engine, cfg = tiny_engine
    trace = sessions_trace(8, vocab=cfg.vocab_size, seed=5, sessions=3,
                           prefix_len=32, tail_range=(8, 16),
                           new_range=(4, 8))
    space = workload_space(
        ModelGeom.from_engine(engine), trace, pool_frac=0.5,
        base={"slots": 3, "block_size": 16, "prefill_chunk": 32},
        domains={"spec_tokens": (0, 2), "host_blocks": (0, "ws")})
    rd = str(tmp_path / "results")
    summary = tune_serving(engine, trace, space=space, min_budget=4,
                           results_dir=rd)
    assert summary["admissible"] == 4
    assert summary["winner"]["measured_tok_s"] > 0
    assert summary["default"]["measured_tok_s"] > 0
    # every feasible trial was parity-gated exact and sentry-clean
    exps = json.load(open(os.path.join(rd, "exps.json")))
    assert all(r.get("token_match") == 1.0
               for r in exps if r.get("feasible"))
    report = open(os.path.join(rd, "report.md")).read()
    assert "| rank |" in report and "tok/s" in report
    assert "Predicted vs measured" in report
    best = json.load(open(os.path.join(rd, "best_config.json")))
    assert best == summary["best_config"]
    # the artifact is ready-to-pass init_serving kwargs
    deepspeed_tpu.comm.reset_topology()
    srv = deepspeed_tpu.init_serving(
        gpt2.build(cfg), config={"dtype": "fp32"}, **best)
    outs = srv.serve([r for r, _ in trace.slice(3).requests()])
    assert len(outs) == 3
