"""ZeRO sharding-plan unit tests (pure spec math + placement checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.zero.sharding import (ZeroShardingPlan,
                                                 shard_over_zero_axes)


@pytest.fixture
def mesh(eight_devices):
    return MeshTopology(dp=4, tp=2).mesh


def test_shard_over_zero_picks_largest_divisible(mesh):
    spec = shard_over_zero_axes((16, 8), None, mesh, ("dp", "ep"))
    assert spec == P(("dp", "ep"), None)  # dim0 is largest and divisible by 4
    spec = shard_over_zero_axes((3, 8), None, mesh, ("dp", "ep"))
    assert spec == P(None, ("dp", "ep"))


def test_shard_over_zero_respects_tp(mesh):
    # dim1 already tp-sharded; residual 16/2=8 divisible by 4 -> stacks axes
    spec = shard_over_zero_axes((4, 16), P(None, "tp"), mesh, ("dp", "ep"))
    assert spec == P(("dp", "ep"), "tp") or spec[1] == ("tp", "dp", "ep")


def test_shard_over_zero_replicates_when_impossible(mesh):
    spec = shard_over_zero_axes((3, 5), None, mesh, ("dp", "ep"))
    assert spec == P(None, None) or spec == P()


def test_stage_rules(mesh):
    shapes = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    for stage, (p_sharded, g_sharded, o_sharded) in {
            0: (False, False, False),
            1: (False, False, True),
            2: (False, True, True),
            3: (True, True, True)}.items():
        plan = ZeroShardingPlan(stage, mesh)
        p = plan.param_shardings(shapes)
        g = plan.grad_shardings(shapes)
        assert (p["w"].spec != P()) == p_sharded
        assert (g["w"].spec != P()) == g_sharded
        o = plan.opt_spec((16, 8), None)
        assert (o != P()) == o_sharded


def test_opt_state_structural_match(mesh):
    import optax

    params = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    plan = ZeroShardingPlan(1, mesh)
    shardings = plan.opt_shardings_like(params, opt_state)
    # moments get sharded specs, count stays replicated
    flat = jax.tree_util.tree_leaves(shardings)
    specs = {str(s.spec) for s in flat}
    assert any("dp" in s for s in specs)
    # placement actually works
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), opt_state, shardings)
    mu_w = sharded[0].mu["w"]
    assert mu_w.addressable_shards[0].data.shape[0] == 16 // 4
