"""ZeRO sharding-plan unit tests (pure spec math + placement checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.zero.sharding import (ZeroShardingPlan,
                                                 shard_over_zero_axes)


@pytest.fixture
def mesh(eight_devices):
    return MeshTopology(dp=4, tp=2).mesh


def test_shard_over_zero_picks_largest_divisible(mesh):
    spec = shard_over_zero_axes((16, 8), None, mesh, ("dp", "ep"))
    assert spec == P(("dp", "ep"), None)  # dim0 is largest and divisible by 4
    spec = shard_over_zero_axes((3, 8), None, mesh, ("dp", "ep"))
    assert spec == P(None, ("dp", "ep"))


def test_shard_over_zero_respects_tp(mesh):
    # dim1 already tp-sharded; residual 16/2=8 divisible by 4 -> stacks axes
    spec = shard_over_zero_axes((4, 16), P(None, "tp"), mesh, ("dp", "ep"))
    assert spec == P(("dp", "ep"), "tp") or spec[1] == ("tp", "dp", "ep")


def test_shard_over_zero_replicates_when_impossible(mesh):
    spec = shard_over_zero_axes((3, 5), None, mesh, ("dp", "ep"))
    assert spec == P(None, None) or spec == P()


def test_stage_rules(mesh):
    shapes = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    for stage, (p_sharded, g_sharded, o_sharded) in {
            0: (False, False, False),
            1: (False, False, True),
            2: (False, True, True),
            3: (True, True, True)}.items():
        plan = ZeroShardingPlan(stage, mesh)
        p = plan.param_shardings(shapes)
        g = plan.grad_shardings(shapes)
        assert (p["w"].spec != P()) == p_sharded
        assert (g["w"].spec != P()) == g_sharded
        o = plan.opt_spec((16, 8), None)
        assert (o != P()) == o_sharded


def test_opt_state_structural_match(mesh):
    import optax

    params = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    plan = ZeroShardingPlan(1, mesh)
    shardings = plan.opt_shardings_like(params, opt_state)
    # moments get sharded specs, count stays replicated
    flat = jax.tree_util.tree_leaves(shardings)
    specs = {str(s.spec) for s in flat}
    assert any("dp" in s for s in specs)
    # placement actually works
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), opt_state, shardings)
    mu_w = sharded[0].mu["w"]
    assert mu_w.addressable_shards[0].data.shape[0] == 16 // 4


# --------------------------------------------------------------------------
# ZeRO-3 liveness knobs (reference zero/config.py:79 stage3_prefetch_bucket_
# size / stage3_max_live_parameters; coordinator fetch_sub_module:239)
# --------------------------------------------------------------------------
def test_stage3_group_size_math():
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.liveness import stage3_group_size

    # prefetch bucket floors the gather size
    zc = DeepSpeedZeroConfig(stage=3, stage3_prefetch_bucket_size=4 * 7_000_000,
                             stage3_max_live_parameters=10**9)
    assert stage3_group_size(zc, 7_000_000, 12) == 4
    # max-live caps it: 2*G*per_layer <= max_live
    zc = DeepSpeedZeroConfig(stage=3, stage3_prefetch_bucket_size=10**9,
                             stage3_max_live_parameters=4 * 7_000_000)
    assert stage3_group_size(zc, 7_000_000, 12) == 2
    # G must divide num_layers
    zc = DeepSpeedZeroConfig(stage=3, stage3_prefetch_bucket_size=5 * 7_000_000,
                             stage3_max_live_parameters=10**9)
    assert stage3_group_size(zc, 7_000_000, 12) == 4
    zc = DeepSpeedZeroConfig(stage=3)
    assert stage3_group_size(zc, 300_000_000, 32) == 1  # 8B-scale: per-layer > bucket


def test_stage3_grouped_scan_loss_parity():
    """Grouping layer gathers must not change the math: a ZeRO-3 engine with
    G=1 and one with G=num_layers produce the same loss trajectory."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    def make(extra):
        deepspeed_tpu.comm.reset_topology()
        cfg = gpt2.GPT2Config.tiny()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=gpt2.build(cfg), config={
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0,
                    **extra},
            })
        return cfg, engine

    batch_of = lambda cfg, e: {"input_ids": np.random.default_rng(7).integers(
        0, cfg.vocab_size, (e.train_batch_size(), 17)).astype(np.int32)}

    cfg1, e1 = make({"stage3_prefetch_bucket_size": 1})   # G=1
    assert getattr(e1.model_spec.model_config, "scan_group_size", 1) == 1
    l1 = [float(e1.train_batch(batch_of(cfg1, e1))[1]["loss"])
          for _ in range(3)]

    cfg2, e2 = make({})   # defaults: bucket 5e7 >> tiny layers -> G=L
    assert getattr(e2.model_spec.model_config, "scan_group_size", 1) == \
        cfg2.num_layers
    l2 = [float(e2.train_batch(batch_of(cfg2, e2))[1]["loss"])
          for _ in range(3)]
    np.testing.assert_allclose(l1, l2, rtol=2e-4)


def test_stage3_group_size_cleared_on_reused_model_config():
    """A model (config) object reused across engines must not inherit the
    previous engine's G: defaults set G=num_layers on a tiny model, and a
    second engine built from the SAME model with stage 0 (liveness knobs
    not applicable) must trace with G=1."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny()
    model = gpt2.build(cfg)

    deepspeed_tpu.comm.reset_topology()
    deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
    })
    assert cfg.scan_group_size == cfg.num_layers

    deepspeed_tpu.comm.reset_topology()
    deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    })
    assert cfg.scan_group_size == 1
