"""Fused multi-step on-device decode (``decode_steps=K``): one
``lax.while_loop`` program runs K decode iterations per host fence with
per-slot eos/budget exits ON-DEVICE, and the host scheduler catches up
in one bookkeeping batch at the fence.

Tier-1 (fast) CPU-sim coverage:
 - exact token parity vs the K=1 per-token loop (and vs sequential
   ``generate``) for chunked + prefix-cache, eos-inside-window, kv8
   (bit-exact between the K=1/K>1 quantized twins), tiered host-DRAM
   KV, and preemption-under-pressure traces — every lane with
   ``debug_checks=True`` so the paged-state invariants are audited at
   each fence and the recompile sentry enforces the budget live.
 - compile contract: the fused program REPLACES the per-token decode
   program (2 programs total, budget unchanged, zero retraces).
 - host-fence accounting: ``host_fence_waits`` ~ ``decode_steps``/K,
   ``fused_iterations`` == device decode iterations, and the new stats
   keys are present.
 - speculative dispatch wins: ``spec_tokens > 0`` makes ``decode_steps``
   inert (no fused program is ever built).
 - ctor validation for the ``engine_mode="dp_tp"`` restrictions (the
   8-device dp×tp parity lane lives in ``test_tp_serving.py``).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2


@pytest.fixture(scope="module")
def tiny_engine():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _trace(cfg, n, prefix_len=24, seed=0, tail=(3, 10), max_new=(2, 12)):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len)
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(*tail)))]),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def _fresh(reqs):
    """New Request objects for a second serve of the same trace."""
    return [Request(uid=r.uid, prompt=r.prompt,
                    max_new_tokens=r.max_new_tokens) for r in reqs]


def _assert_same(res_a, res_b, reqs):
    for r in reqs:
        np.testing.assert_array_equal(res_a[r.uid], res_b[r.uid],
                                      err_msg=f"uid {r.uid}")


def test_fused_parity_chunked_and_fence_accounting(tiny_engine):
    """Acceptance: K=4 fused decode is token-identical to the K=1 loop
    AND to sequential generate on a shared-prefix chunked trace, with
    ~K fewer host fences and an unchanged 2-program compile contract."""
    engine, cfg = tiny_engine
    kw = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, debug_checks=True)
    reqs = _trace(cfg, 6)
    s1 = ServingEngine(engine, **kw)
    r1 = s1.serve(reqs)
    sK = ServingEngine(engine, decode_steps=4, **kw)
    rK = sK.serve(_fresh(reqs))
    _assert_same(r1, rK, reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(rK[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    st1, stK = s1.stats(), sK.stats()
    # fused REPLACES the per-token program: same budget, no extra compile
    assert stK["compile_count"] == 2 == st1["compile_count"]
    assert stK["compile_budget"] == st1["compile_budget"]
    assert stK["retraces_observed"] == 0
    # the new stats keys, live
    assert stK["engine_mode"] == "replicas"
    assert stK["fused_iterations"] == stK["decode_steps"] > 0
    assert st1["fused_iterations"] == 0
    # one fence per <=K-iteration window vs one host sync per iteration
    assert 0 < stK["host_fence_waits"] <= stK["decode_steps"]
    assert stK["host_fence_waits"] <= -(-st1["decode_steps"] // 4) + \
        len(reqs)        # slack: windows clipped by per-slot budgets
    assert stK["generated_tokens"] == st1["generated_tokens"]
    assert sK.resolved_config()["decode_steps"] == 4
    assert s1.resolved_config()["decode_steps"] == 1


def test_fused_parity_eos_inside_window(tiny_engine):
    """An eos fired at iteration i < K must stop THAT slot's emission
    mid-window (device ``active`` mask) without disturbing the others —
    token-exact vs sequential generate with the same eos."""
    engine, cfg = tiny_engine
    kw = dict(slots=3, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, debug_checks=True)
    reqs = _trace(cfg, 4, seed=1, max_new=(6, 12))
    probe = engine.generate(reqs[0].prompt[None, :], max_new_tokens=1)
    eos = int(probe[0, len(reqs[0].prompt)])   # fires on request 0's 1st
    sK = ServingEngine(engine, decode_steps=8, **kw)
    rK = sK.serve(reqs, eos_token_id=eos)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens,
                               eos_token_id=eos)[0]
        np.testing.assert_array_equal(rK[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    # request 0's FIRST generated token is eos — the stop fired at
    # iteration 0 of an 8-wide window (mid-window, not at the fence
    # boundary), and the post-eos fill matches generate's contract
    gen0 = rK[reqs[0].uid][len(reqs[0].prompt):]
    assert gen0[0] == eos and np.all(gen0 == eos)


def test_fused_parity_kv8_bit_exact(tiny_engine):
    """Quantized greedy is a different (equally valid) stream than fp32
    — but between the kv8 twins the fused program must be BIT-exact:
    same int8 codes, same scales, same argmax at every position."""
    engine, cfg = tiny_engine
    kw = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, quantize="kv8", debug_checks=True)
    reqs = _trace(cfg, 6, seed=2)
    r1 = ServingEngine(engine, **kw).serve(reqs)
    rK = ServingEngine(engine, decode_steps=4, **kw).serve(_fresh(reqs))
    _assert_same(r1, rK, reqs)


def test_fused_parity_tiered_host_kv(tiny_engine):
    """Fused decode composes with the host-DRAM KV tier: swaps happen,
    parity holds vs the K=1 tiered twin and sequential generate."""
    engine, cfg = tiny_engine
    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2, num_blocks=10, host_blocks=64,
              swap_batch=4, debug_checks=True)
    reqs = _trace(cfg, 6, seed=5, max_new=(20, 29))
    s1 = ServingEngine(engine, **kw)
    r1 = s1.serve(reqs)
    sK = ServingEngine(engine, decode_steps=4, **kw)
    rK = sK.serve(_fresh(reqs))
    _assert_same(r1, rK, reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(rK[r.uid], want,
                                      err_msg=f"uid {r.uid}")
    st = sK.stats()
    assert st["swap_out"] > 0 and st["swap_in"] > 0
    assert st["compile_count"] == 4       # base 2 + demote + promote


def test_fused_preemption_at_fence_keeps_parity(tiny_engine):
    """Block pressure mid-trace: preemption decisions happen at the
    fence (never mid-window on-device), evicted sequences re-queue and
    recompute, and greedy outputs stay identical to generate."""
    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                        prefill_chunk=32, prefill_batch=2, num_blocks=12,
                        decode_steps=4, debug_checks=True)
    rng = np.random.default_rng(5)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28) for i in range(5)]
    res = srv.serve(reqs)
    assert srv.preempted > 0, srv.stats()  # pressure actually happened
    assert set(res) == set(range(5))
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_spec_dispatch_wins_over_decode_steps(tiny_engine):
    """``spec_tokens > 0`` routes every decode through draft-verify:
    ``decode_steps`` must be inert (no fused program, no fused
    iterations) and parity vs the plain speculative engine holds."""
    engine, cfg = tiny_engine
    kw = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefill_batch=2, spec_tokens=3, debug_checks=True)
    reqs = _trace(cfg, 5, seed=3)
    r_spec = ServingEngine(engine, **kw).serve(reqs)
    s_both = ServingEngine(engine, decode_steps=8, **kw)
    r_both = s_both.serve(_fresh(reqs))
    _assert_same(r_spec, r_both, reqs)
    st = s_both.stats()
    assert st["fused_iterations"] == 0 and st["host_fence_waits"] == 0
    assert st["spec_rounds"] > 0
    assert ("decode", s_both.slots, 8) not in s_both.compiled_programs


def test_decode_steps_validation(tiny_engine):
    engine, _ = tiny_engine
    kw = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16)
    with pytest.raises(ValueError, match="decode_steps"):
        ServingEngine(engine, decode_steps=0, **kw)
    with pytest.raises(ValueError, match="decode_steps"):
        ServingEngine(engine, decode_steps=-3, **kw)


def test_dp_tp_ctor_restrictions(tiny_engine):
    """The v1 dp×tp composition rules fail loudly at the ctor (mirrored
    by ``autotuning/space.py`` ``engine_mode_exclusive``)."""
    engine, _ = tiny_engine
    kw = dict(slots=8, max_seq_len=128, block_size=8, prefill_chunk=16,
              prefix_caching=False)
    with pytest.raises(ValueError, match="engine_mode"):
        ServingEngine(engine, engine_mode="shards", **kw)
    with pytest.raises(ValueError, match="spec"):
        ServingEngine(engine, engine_mode="dp_tp", spec_tokens=3, **kw)
    with pytest.raises(ValueError, match="quantiz"):
        ServingEngine(engine, engine_mode="dp_tp", quantize="kv8", **kw)
    with pytest.raises(ValueError, match="host KV tier"):
        ServingEngine(engine, engine_mode="dp_tp", host_blocks=16, **kw)
    with pytest.raises(ValueError, match="prefix_caching"):
        ServingEngine(engine, engine_mode="dp_tp", slots=8,
                      max_seq_len=128, block_size=8, prefill_chunk=16)
    with pytest.raises(ValueError, match="chunked"):
        ServingEngine(engine, engine_mode="dp_tp", slots=8,
                      max_seq_len=128, block_size=8,
                      prompt_buckets=(64, 128), prefix_caching=False)
