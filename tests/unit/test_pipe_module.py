"""PipelineModule partitioning tests (model: reference tests/unit/pipe/test_pipe_module.py)."""

import pytest

from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               partition_balanced,
                                               partition_uniform)


class FakeLayer:
    def __init__(self, n=10):
        self.n = n

    def num_params(self):
        return self.n


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 2) == [0, 4, 7]
    assert partition_uniform(3, 3) == [0, 1, 2, 3]


def test_partition_balanced():
    parts = partition_balanced([1, 1, 1, 1], 2)
    assert parts == [0, 2, 4]
    parts = partition_balanced([10, 1, 1, 1], 2)
    assert parts[1] == 1  # heavy first layer gets its own stage
    parts = partition_balanced([1, 1, 1, 10], 2)
    assert parts == [0, 3, 4]


def test_pipeline_module_uniform():
    layers = [LayerSpec(FakeLayer) for _ in range(8)]
    pm = PipelineModule(layers, num_stages=4, partition_method="uniform")
    assert pm.num_layers_per_stage() == [2, 2, 2, 2]
    assert list(pm.stage_layer_indices(1)) == [2, 3]


def test_pipeline_module_parameters():
    layers = [LayerSpec(FakeLayer, 100)] + \
             [LayerSpec(FakeLayer, 1) for _ in range(7)]
    pm = PipelineModule(layers, num_stages=2, partition_method="parameters")
    assert pm.parts[1] == 1


def test_pipeline_module_type_regex():
    class TransformerLayer(FakeLayer):
        pass

    class EmbeddingLayer(FakeLayer):
        pass

    layers = [LayerSpec(EmbeddingLayer)] + \
             [LayerSpec(TransformerLayer) for _ in range(4)] + \
             [LayerSpec(EmbeddingLayer)]
    pm = PipelineModule(layers, num_stages=2, partition_method="type:transformer")
    counts = [sum(1 for i in pm.stage_layer_indices(s)
                  if "Transformer" in layers[i].name) for s in range(2)]
    assert counts == [2, 2]


def test_bad_partition_method():
    with pytest.raises(NotImplementedError):
        PipelineModule([LayerSpec(FakeLayer)], num_stages=1,
                       partition_method="bogus")
