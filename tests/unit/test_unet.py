"""UNet2DCondition tests: shapes across the down/mid/up path, cross-attention
conditioning sensitivity, denoising training."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import unet


def _batch(rng, n, cfg):
    s = cfg.sample_size
    return {
        "noisy_latents": rng.normal(size=(n, cfg.in_channels, s, s)).astype(
            np.float32),
        "noise": rng.normal(size=(n, cfg.in_channels, s, s)).astype(
            np.float32),
        "timesteps": rng.integers(0, 1000, size=(n,)).astype(np.int32),
        "encoder_hidden_states": rng.normal(
            size=(n, 7, cfg.cross_attention_dim)).astype(np.float32),
    }


def test_unet_forward_shapes():
    cfg = unet.UNetConfig.tiny()
    params = unet.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    b = _batch(rng, 2, cfg)
    out = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                       jnp.asarray(b["timesteps"]),
                       jnp.asarray(b["encoder_hidden_states"]), train=False)
    assert out.shape == (2, cfg.out_channels, cfg.sample_size,
                         cfg.sample_size)


def test_unet_sd_structure_builds():
    """The full SD 1.x config's param tree has the right top-level shape
    (4 down blocks, attn in the first three, 4 up blocks)."""
    cfg = unet.UNetConfig.sd_unet()
    abstract = jax.eval_shape(
        lambda: unet.init_params(cfg, jax.random.PRNGKey(0)))
    assert len(abstract["down"]) == 4
    assert "attns" in abstract["down"][0]
    assert "attns" not in abstract["down"][3]
    assert len(abstract["up"]) == 4
    assert 8.0e8 < cfg.num_params() < 9.5e8  # SD 1.x UNet is ~860M


def test_unet_conditioning_matters():
    cfg = unet.UNetConfig.tiny()
    params = unet.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    b = _batch(rng, 1, cfg)
    out1 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(b["timesteps"]),
                        jnp.asarray(b["encoder_hidden_states"]), train=False)
    ctx2 = b["encoder_hidden_states"] + 1.0
    out2 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(b["timesteps"]), jnp.asarray(ctx2),
                        train=False)
    assert np.abs(np.asarray(out1) - np.asarray(out2)).max() > 1e-4
    # timestep conditioning too
    t2 = (np.asarray(b["timesteps"]) + 500) % 1000
    out3 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(t2),
                        jnp.asarray(b["encoder_hidden_states"]), train=False)
    assert np.abs(np.asarray(out1) - np.asarray(out3)).max() > 1e-4


def test_unet_denoising_trains():
    deepspeed_tpu.comm.reset_topology()
    cfg = unet.UNetConfig.tiny()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=unet.build(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    batch = _batch(rng, engine.train_batch_size(), cfg)
    losses = []
    for _ in range(6):
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
