"""UNet2DCondition tests: shapes across the down/mid/up path, cross-attention
conditioning sensitivity, denoising training."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import unet


def _batch(rng, n, cfg):
    s = cfg.sample_size
    return {
        "noisy_latents": rng.normal(size=(n, cfg.in_channels, s, s)).astype(
            np.float32),
        "noise": rng.normal(size=(n, cfg.in_channels, s, s)).astype(
            np.float32),
        "timesteps": rng.integers(0, 1000, size=(n,)).astype(np.int32),
        "encoder_hidden_states": rng.normal(
            size=(n, 7, cfg.cross_attention_dim)).astype(np.float32),
    }


def test_unet_forward_shapes():
    cfg = unet.UNetConfig.tiny()
    params = unet.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    b = _batch(rng, 2, cfg)
    out = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                       jnp.asarray(b["timesteps"]),
                       jnp.asarray(b["encoder_hidden_states"]), train=False)
    assert out.shape == (2, cfg.out_channels, cfg.sample_size,
                         cfg.sample_size)


def test_unet_sd_structure_builds():
    """The full SD 1.x config's param tree has the right top-level shape
    (4 down blocks, attn in the first three, 4 up blocks)."""
    cfg = unet.UNetConfig.sd_unet()
    abstract = jax.eval_shape(
        lambda: unet.init_params(cfg, jax.random.PRNGKey(0)))
    assert len(abstract["down"]) == 4
    assert "attns" in abstract["down"][0]
    assert "attns" not in abstract["down"][3]
    assert len(abstract["up"]) == 4
    assert 8.0e8 < cfg.num_params() < 9.5e8  # SD 1.x UNet is ~860M


def test_unet_conditioning_matters():
    cfg = unet.UNetConfig.tiny()
    params = unet.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    b = _batch(rng, 1, cfg)
    out1 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(b["timesteps"]),
                        jnp.asarray(b["encoder_hidden_states"]), train=False)
    ctx2 = b["encoder_hidden_states"] + 1.0
    out2 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(b["timesteps"]), jnp.asarray(ctx2),
                        train=False)
    assert np.abs(np.asarray(out1) - np.asarray(out2)).max() > 1e-4
    # timestep conditioning too
    t2 = (np.asarray(b["timesteps"]) + 500) % 1000
    out3 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                        jnp.asarray(t2),
                        jnp.asarray(b["encoder_hidden_states"]), train=False)
    assert np.abs(np.asarray(out1) - np.asarray(out3)).max() > 1e-4


def test_unet_hf_naming_roundtrip():
    """from_hf_state_dict consumes the published diffusers naming:
    fabricate the dict FROM our params, reload, require identical output."""
    cfg = unet.UNetConfig.tiny()
    params = unet.init_params(cfg, jax.random.PRNGKey(4))

    sd = {}

    def put_conv(name, p):
        sd[name + ".weight"] = np.asarray(p["w"])
        sd[name + ".bias"] = np.asarray(p["b"])

    def put_gn(name, p):
        sd[name + ".weight"] = np.asarray(p["scale"])
        sd[name + ".bias"] = np.asarray(p["bias"])

    def put_dense(name, p):
        sd[name + ".weight"] = np.asarray(p["w"]).T
        if "b" in p:
            sd[name + ".bias"] = np.asarray(p["b"])

    def put_resnet(prefix, p):
        put_gn(prefix + ".norm1", p["norm1"])
        put_conv(prefix + ".conv1", p["conv1"])
        put_dense(prefix + ".time_emb_proj", p["time_emb"])
        put_gn(prefix + ".norm2", p["norm2"])
        put_conv(prefix + ".conv2", p["conv2"])
        if "shortcut" in p:
            put_conv(prefix + ".conv_shortcut", p["shortcut"])

    def put_tx(prefix, p):
        put_gn(prefix + ".norm", p["norm"])
        put_conv(prefix + ".proj_in", p["proj_in"])
        b = prefix + ".transformer_blocks.0"
        blk = p["block"]
        put_gn(b + ".norm1", blk["ln1"])
        put_gn(b + ".norm2", blk["ln2"])
        put_gn(b + ".norm3", blk["ln3"])
        for attn in ("attn1", "attn2"):
            for proj in ("q", "k", "v"):
                put_dense(f"{b}.{attn}.to_{proj}", blk[attn][proj])
            put_dense(f"{b}.{attn}.to_out.0", blk[attn]["out"])
        put_dense(b + ".ff.net.0.proj", blk["geglu"])
        put_dense(b + ".ff.net.2", blk["ff_out"])
        put_conv(prefix + ".proj_out", p["proj_out"])

    put_dense("time_embedding.linear_1", params["time_mlp1"])
    put_dense("time_embedding.linear_2", params["time_mlp2"])
    put_conv("conv_in", params["conv_in"])
    for i, blk in enumerate(params["down"]):
        for j, r in enumerate(blk["resnets"]):
            put_resnet(f"down_blocks.{i}.resnets.{j}", r)
        for j, t in enumerate(blk.get("attns", [])):
            put_tx(f"down_blocks.{i}.attentions.{j}", t)
        if "down" in blk:
            put_conv(f"down_blocks.{i}.downsamplers.0.conv", blk["down"])
    put_resnet("mid_block.resnets.0", params["mid"]["res1"])
    put_tx("mid_block.attentions.0", params["mid"]["attn"])
    put_resnet("mid_block.resnets.1", params["mid"]["res2"])
    for i, blk in enumerate(params["up"]):
        for j, r in enumerate(blk["resnets"]):
            put_resnet(f"up_blocks.{i}.resnets.{j}", r)
        for j, t in enumerate(blk.get("attns", [])):
            put_tx(f"up_blocks.{i}.attentions.{j}", t)
        if "up" in blk:
            put_conv(f"up_blocks.{i}.upsamplers.0.conv", blk["up"])
    put_gn("conv_norm_out", params["norm_out"])
    put_conv("conv_out", params["conv_out"])

    reloaded = unet.from_hf_state_dict(cfg, sd)
    rng = np.random.default_rng(5)
    b = _batch(rng, 1, cfg)
    o1 = unet.forward(cfg, params, jnp.asarray(b["noisy_latents"]),
                      jnp.asarray(b["timesteps"]),
                      jnp.asarray(b["encoder_hidden_states"]), train=False)
    o2 = unet.forward(cfg, reloaded, jnp.asarray(b["noisy_latents"]),
                      jnp.asarray(b["timesteps"]),
                      jnp.asarray(b["encoder_hidden_states"]), train=False)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)


def test_unet_denoising_trains():
    deepspeed_tpu.comm.reset_topology()
    cfg = unet.UNetConfig.tiny()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=unet.build(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    batch = _batch(rng, engine.train_batch_size(), cfg)
    losses = []
    for _ in range(6):
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
