"""graft-race static half (``analysis/concurrency.py``): per-rule fire /
near-miss fixtures, pragma suppression, cross-file edge merging, and the
zero-findings gate over the real package (the same check CI's lint job
runs via ``bin/graft-race``).

The dynamic sanitizer's fault-injection coverage lives in
``tests/unit/test_lock_sanitizer.py``; the threaded end-to-end smoke in
``tests/unit/test_threaded_serving.py``.
"""

import subprocess
import sys
from pathlib import Path

from deepspeed_tpu.analysis import concurrency

REPO = Path(__file__).resolve().parents[2]


def _codes(src, path="fixture.py"):
    return [f.code for f in concurrency.check_source(src, path)]


def _findings(src, path="fixture.py"):
    return concurrency.check_source(src, path)


# ------------------------------------------------------------------ GL009
def test_gl009_opposite_order_pair_fires_both_sites():
    src = """
import threading

class Fleet:
    def __init__(self):
        self._a = threading.RLock()
        self._b = threading.RLock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""
    fs = [f for f in _findings(src) if f.code == "GL009"]
    assert len(fs) == 2, fs
    # each finding names the opposite site
    lines = sorted(f.line for f in fs)
    msgs = " ".join(f.message for f in fs)
    assert "opposite order" in msgs
    assert f"fixture.py:{lines[0]}" in msgs or \
        f"fixture.py:{lines[1]}" in msgs


def test_gl009_declared_order_inversion_fires():
    src = """
import threading

class Router:
    def __init__(self):
        self._fleet_lock = threading.RLock()
        self._locks = [threading.RLock() for _ in range(2)]

    def bad(self, rid):
        with self._locks[rid]:
            with self._fleet_lock:      # replica -> fleet: inverted
                pass
"""
    codes = _codes(src)
    assert "GL009" in codes, codes
    msg = next(f.message for f in _findings(src) if f.code == "GL009")
    assert "declared lock order" in msg


def test_gl009_collection_nesting_fires_and_sorted_loop_near_miss():
    fires = """
import threading

class Router:
    def __init__(self):
        self._locks = [threading.RLock() for _ in range(4)]

    def pull(self, src, dst):
        with self._locks[src], self._locks[dst]:    # unordered pair
            pass
"""
    assert "GL009" in _codes(fires)
    near_miss = """
import threading
from contextlib import ExitStack

class Router:
    def __init__(self):
        self._locks = [threading.RLock() for _ in range(4)]

    def pull(self, src, dst):
        lo, hi = sorted((src, dst))
        with self._locks[lo], self._locks[hi]:      # index-sorted
            pass

    def all_locks(self):
        stack = ExitStack()
        for lock in self._locks:                    # iteration order
            stack.enter_context(lock)
        return stack
"""
    assert "GL009" not in _codes(near_miss)


def test_gl009_literal_ascending_indices_are_clean():
    """Constant-index nesting in ascending order is as deterministic as
    the sorted idiom; descending literals still fire."""
    ok = """
import threading

class Router:
    def __init__(self):
        self._locks = [threading.RLock() for _ in range(2)]

    def fast_path(self):
        with self._locks[0], self._locks[1]:
            pass
"""
    assert "GL009" not in _codes(ok)
    descending = ok.replace("self._locks[0], self._locks[1]",
                            "self._locks[1], self._locks[0]")
    assert "GL009" in _codes(descending)


def test_gl009_consistent_order_is_clean():
    src = """
import threading

class Router:
    def __init__(self):
        self._fleet_lock = threading.RLock()
        self._locks = [threading.RLock() for _ in range(2)]

    def submit(self, rid):
        with self._fleet_lock:
            with self._locks[rid]:
                pass

    def drain(self, rid):
        with self._fleet_lock:
            with self._locks[rid]:
                pass
"""
    assert _codes(src) == []


# ------------------------------------------------------------------ GL010
_GL010_FIRE = """
import threading

class Handle:
    def __init__(self):
        self._lk = threading.Lock()
        self._tokens = []

    def on_tokens(self, toks):
        with self._lk:
            self._tokens.extend(toks)       # guarded mutation

    def reset(self):
        self._tokens = []                   # unguarded mutation
"""


def test_gl010_mixed_guarding_fires_and_names_guarded_site():
    fs = [f for f in _findings(_GL010_FIRE) if f.code == "GL010"]
    assert len(fs) == 1, fs
    assert "_tokens" in fs[0].message
    assert "fixture.py:11" in fs[0].message    # the guarded extend site


def test_gl010_guarded_by_inference_through_private_callee():
    """A private helper only ever called under the lock counts as
    guarded — the call-graph half of the inference."""
    src = """
import threading

class Router:
    def __init__(self):
        self._lk = threading.Lock()
        self._hints = {}

    def submit(self, k, v):
        with self._lk:
            self._note(k, v)

    def drain(self, k, v):
        with self._lk:
            self._note(k, v)

    def _note(self, k, v):
        self._hints[k] = v                  # guarded via every caller
"""
    assert _codes(src) == []


def test_gl010_skips_non_concurrent_classes_and_init():
    src = """
class Plain:                        # no locks, no threads: single-owner
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
"""
    assert _codes(src) == []


def test_gl010_external_store_to_guarded_field_fires():
    src = _GL010_FIRE + """

class Router:
    def __init__(self):
        self._fleet_lock = threading.Lock()

    def rebind(self, handle):
        handle._tokens = []                 # bypasses Handle's lock
"""
    fs = [f for f in _findings(src) if f.code == "GL010"]
    assert any("foreign" in f.message and "Handle" in f.message
               for f in fs), fs


# ------------------------------------------------------------------ GL011
def test_gl011_blocking_calls_under_lock_fire():
    src = """
import threading, time, jax

class Engine:
    def __init__(self):
        self._lk = threading.Lock()

    def bad(self, x, worker):
        with self._lk:
            v = jax.device_get(x)
            worker.join()
            time.sleep(0.1)
        return v
"""
    codes = _codes(src)
    assert codes.count("GL011") == 3, codes


def test_gl011_near_misses_are_clean():
    src = """
import threading, time, jax

class Engine:
    def __init__(self):
        self._lk = threading.Lock()
        self._cond = threading.Condition()

    def bounded(self, worker):
        with self._lk:
            worker.join(timeout=5)          # bounded: fine

    def own_cond(self):
        with self._cond:
            self._cond.wait_for(lambda: True, 1.0)   # releases it

    def unlocked(self, x):
        return jax.device_get(x)            # no lock held

    def demote_batch(self, x):
        with self._lk:
            return jax.device_get(x)        # sanctioned transfer helper
"""
    assert "GL011" not in _codes(src)


def test_gl011_interprocedural_entry_held():
    """A blocking call in a private helper reached only from inside a
    lock region is flagged through the call graph."""
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lk = threading.Lock()

    def step(self, x):
        with self._lk:
            return self._pull(x)

    def _pull(self, x):
        return jax.device_get(x)
"""
    assert "GL011" in _codes(src)


def test_gl011_assignment_form_acquire_persists():
    """'ok = lock.acquire(...)' enters the held-set for the remaining
    block exactly like the bare-expression form."""
    src = """
import threading, jax

class A:
    def __init__(self):
        self._lk = threading.Lock()

    def bad(self, x):
        ok = self._lk.acquire(timeout=5)
        v = jax.device_get(x)
        self._lk.release()
        return v

    def guarded_then_not(self, v):
        got = self._lk.acquire()
        self._n = v
        self._lk.release()

    def unguarded(self, v):
        self._n = v
"""
    codes = _codes(src)
    assert "GL011" in codes, codes
    assert "GL010" in codes, codes


def test_gl011_unbounded_foreign_wait_fires():
    src = """
import threading

class A:
    def __init__(self):
        self._lk = threading.Lock()

    def bad(self, event):
        with self._lk:
            event.wait()                    # unbounded, foreign object
"""
    assert "GL011" in _codes(src)


# ------------------------------------------------------- pragmas / driver
def test_noqa_pragma_suppresses_named_rule_only():
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lk = threading.Lock()

    def commit(self, x):
        with self._lk:
            return jax.device_get(x)  # graft: noqa(GL011) documented commit point
"""
    assert _codes(src) == []
    wrong_code = src.replace("noqa(GL011)", "noqa(GL009)")
    assert "GL011" in _codes(wrong_code)
    bare = src.replace("noqa(GL011)", "noqa")
    assert _codes(bare) == []


def test_cross_file_inversion_detected():
    """Opposite-order acquisitions of the DECLARED lock vocabulary merge
    across files — the fleet order is one contract, not per-module."""
    a = """
import threading

class A:
    def __init__(self):
        self._fleet_lock = threading.Lock()
        self._cond = threading.Condition()

    def fwd(self):
        with self._fleet_lock:
            with self._cond:
                pass
"""
    b = """
import threading

class B:
    def __init__(self):
        self._fleet_lock = threading.Lock()
        self._cond = threading.Condition()

    def rev(self):
        with self._cond:
            with self._fleet_lock:
                pass
"""
    findings = concurrency.analyze_sources([(a, "a.py"), (b, "b.py")])
    gl9 = [f for f in findings if f.code == "GL009"]
    assert any(f.path == "b.py" for f in gl9), findings


def test_package_is_clean_and_cli_exit_codes(tmp_path):
    """The real package gates clean (the CI check), a typo'd path exits
    2, and a finding exits 1 — mirroring graft-lint's driver."""
    findings, nfiles = concurrency.race_paths(
        [str(REPO / "deepspeed_tpu")])
    assert nfiles > 50
    assert findings == [], "\n".join(f.render() for f in findings)

    cli = str(REPO / "bin" / "graft-race")
    ok = subprocess.run([sys.executable, cli,
                         str(REPO / "deepspeed_tpu")],
                        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    missing = subprocess.run([sys.executable, cli,
                              str(tmp_path / "nope")],
                             capture_output=True, text=True)
    assert missing.returncode == 2

    # an explicit .py argument that cannot be read fails loudly too —
    # a since-renamed file in a CI step must not pass forever
    ghost = subprocess.run([sys.executable, cli,
                            str(tmp_path / "renamed_away.py")],
                           capture_output=True, text=True)
    assert ghost.returncode == 1
    assert "GL000" in ghost.stdout

    bad = tmp_path / "bad.py"
    bad.write_text(_GL010_FIRE)
    fires = subprocess.run([sys.executable, cli, str(bad)],
                           capture_output=True, text=True)
    assert fires.returncode == 1
    assert "GL010" in fires.stdout

    rules = subprocess.run([sys.executable, cli, "--list-rules"],
                           capture_output=True, text=True)
    assert rules.returncode == 0
    for code in ("GL009", "GL010", "GL011"):
        assert code in rules.stdout
