"""CLIP tests: HF parity for both towers + the similarity logits, and
contrastive training."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import clip

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_clip():
    text_cfg = transformers.CLIPTextConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, eos_token_id=95)
    vision_cfg = transformers.CLIPVisionConfig(
        hidden_size=48, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=96, image_size=32, patch_size=16)
    cfg = transformers.CLIPConfig.from_text_vision_configs(
        text_cfg, vision_cfg, projection_dim=24)
    with torch.no_grad():
        m = transformers.CLIPModel(cfg)
    m.eval()
    return m


def test_clip_matches_hf():
    hf = _tiny_hf_clip()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    rng = np.random.default_rng(0)
    # eot token (argmax pooling) = highest id, placed mid-sequence
    ids = rng.integers(1, 90, (3, 12)).astype(np.int32)
    ids[:, 7] = 95
    pixels = rng.normal(size=(3, 3, 32, 32)).astype(np.float32)
    ours_img, ours_txt = spec.apply_fn(
        params, {"input_ids": ids, "pixel_values": pixels})
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids.astype(np.int64)),
                 pixel_values=torch.tensor(pixels))
    # logit_scale (e^2.66 ~ 14x) amplifies the towers' f32 rounding
    np.testing.assert_allclose(np.asarray(ours_img),
                               out.logits_per_image.numpy(),
                               atol=5e-2, rtol=5e-3)
    np.testing.assert_allclose(np.asarray(ours_txt),
                               out.logits_per_text.numpy(),
                               atol=5e-2, rtol=5e-3)


def test_clip_legacy_eos2_pools_argmax():
    """OpenAI CLIP configs ship eos_token_id=2 (HF's legacy branch pools at
    argmax(input_ids)); from_hf must map that to our argmax convention."""
    text_cfg = transformers.CLIPTextConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, eos_token_id=2)
    vision_cfg = transformers.CLIPVisionConfig(
        hidden_size=48, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=96, image_size=32, patch_size=16)
    cfg = transformers.CLIPConfig.from_text_vision_configs(
        text_cfg, vision_cfg, projection_dim=24)
    with torch.no_grad():
        hf = transformers.CLIPModel(cfg)
    hf.eval()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    rng = np.random.default_rng(1)
    ids = rng.integers(3, 90, (2, 12)).astype(np.int32)
    ids[:, 5] = 95  # highest id mid-sequence: the argmax pooling position
    from deepspeed_tpu.models.clip import CLIPConfig, encode_text
    ccfg = CLIPConfig.from_hf(hf.config)
    assert ccfg.eos_token_id is None
    ours = np.asarray(encode_text(ccfg, params, ids))
    with torch.no_grad():
        theirs = hf.get_text_features(
            input_ids=torch.tensor(ids.astype(np.int64))).numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=5e-3)


def test_clip_contrastive_training():
    deepspeed_tpu.comm.reset_topology()
    cfg = clip.CLIPConfig.tiny()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=clip.build(cfg),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {}})
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 90, (engine.train_batch_size(), 12)).astype(np.int32)
    pixels = rng.normal(size=(engine.train_batch_size(), 3, 32, 32)).astype(
        np.float32)
    batch = {"input_ids": ids, "pixel_values": pixels}
    losses = []
    for _ in range(8):
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses
