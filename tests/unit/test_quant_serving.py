"""Quantized paged serving: int8 KV pool (per-block scale table) and
w8a8 weights inside the ServingEngine.

Tier-1 (fast) CPU-sim coverage for the PR 7 quantization stack:
 - quantize/dequant round-trip units on the pool ops (``quantize_kv``,
   record scatter/gather vs the float pool, pad routing to scratch).
 - kv8 / w8a8 / w8a8+kv8 end-to-end bounded divergence for all five
   paged families — the shared "close enough" definition lives in
   ``quant_divergence.py`` (token match rate + teacher-forced logit
   RMSE), replacing exact greedy parity on quantized lanes.
 - gpt2 kv8 under speculative decoding and preemption pressure, with
   ``debug_checks=True`` so every iteration runs the paged-state audit
   (including the new ``scale-lockstep`` invariant) and the recompile
   sentry enforces the unchanged ≤2/≤3-program contracts.
 - ``quantize=None`` lanes bit-identical to pre-quantization behavior.
 - scale-ledger fault injection naming the violated invariant.

The Pallas quantized decode/verify kernels' interpret twins live in
``test_decode_attention.py`` (slow lane); the tp=4 × kv8 parity case in
``test_tp_serving.py`` (8-device CI job); the bench lane in
``test_serving_bench.py`` (slow).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import PagedStateError
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops import paged_kv
from quant_divergence import (assert_bounded_divergence, max_logit_rmse,
                              token_match_rate)

#: documented divergence bounds for the tiny fp32 CPU-sim models (random
#: weights — near-uniform logits, the WORST case for argmax stability;
#: measured rates are ~1.0, the bounds leave cascade headroom)
KV8_MIN_MATCH = 0.85
W8A8_MIN_MATCH = 0.70
W8A8_MAX_LOGIT_RMSE = 0.15


# ------------------------------------------------------------ pool-op units
def test_quantize_kv_roundtrip_and_edge_cases():
    import jax.numpy as jnp

    from deepspeed_tpu.ops import quantization as quant

    rng = np.random.default_rng(0)
    x = rng.standard_normal((3, 2, 5, 16)).astype(np.float32) * \
        rng.uniform(0.01, 10.0, (3, 2, 5, 1)).astype(np.float32)
    codes, scale = quant.quantize_kv(jnp.asarray(x))
    assert codes.dtype == jnp.int8 and scale.shape == x.shape[:-1]
    back = np.asarray(quant.dequantize_kv(codes, scale))
    # error bound: half a code of the STORED (bf16-rounded) scale
    bound = np.asarray(scale, np.float32)[..., None] * 0.51 + 1e-7
    assert (np.abs(back - x) <= bound).all()
    # all-zero vectors: scale 1, codes 0, exact zero round-trip
    z_codes, z_scale = quant.quantize_kv(jnp.zeros((2, 4)))
    assert np.asarray(z_scale).tolist() == [1.0, 1.0]
    assert np.asarray(z_codes).sum() == 0


def test_record_pool_scatter_gather_matches_float_pool():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    b, hkv, d, bs, nbper, nb = 3, 2, 16, 8, 4, 13
    bt = rng.permutation(np.arange(1, nb))[:b * nbper] \
        .reshape(b, nbper).astype(np.int32)
    fp = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    qp = paged_kv.quantize_pool(fp)
    assert paged_kv.is_quantized_pool(qp)
    assert qp["qp"].dtype == jnp.int8
    assert qp["ps"].shape == (nb, hkv, bs)
    assert paged_kv.pool_payload(qp).shape == fp.shape

    kw = rng.standard_normal((b, hkv, 8, d)).astype(np.float32)
    vw = rng.standard_normal((b, hkv, 8, d)).astype(np.float32)
    base = np.array([0, 8, 16], np.int32)
    valid = np.array([8, 5, 1], np.int32)
    fk, fv = paged_kv.paged_cache_update(
        fp, fp, jnp.asarray(kw), jnp.asarray(vw), jnp.asarray(base),
        jnp.asarray(bt), valid=jnp.asarray(valid))
    qk, qv = paged_kv.paged_cache_update(
        qp, qp, jnp.asarray(kw), jnp.asarray(vw), jnp.asarray(base),
        jnp.asarray(bt), valid=jnp.asarray(valid))
    gf = np.asarray(paged_kv.paged_gather(fk, jnp.asarray(bt)))
    gq = np.asarray(paged_kv.paged_gather(qk, jnp.asarray(bt)))
    amax = np.abs(gf).max()
    assert np.abs(gf - gq).max() <= amax / 127 * 0.55 + 1e-6
    # invalid tokens routed to scratch: block 0's scale row took writes,
    # but no allocated block picked up the masked tail
    gv = np.asarray(paged_kv.paged_gather(qv, jnp.asarray(bt)))
    assert np.abs(gv[1, :, base[1] + valid[1]:base[1] + 8]).max() == 0.0


def test_quantized_paged_attention_reference_tracks_float():
    import jax.numpy as jnp

    from deepspeed_tpu.ops.decode_attention import (
        paged_decode_attention_reference)

    rng = np.random.default_rng(2)
    b, h, hkv, d, bs, nbper, nb = 3, 4, 2, 16, 8, 4, 13
    bt = rng.permutation(np.arange(1, nb))[:b * nbper] \
        .reshape(b, nbper).astype(np.int32)
    fp = jnp.zeros((nb, hkv, bs, d), jnp.float32)
    kw = rng.standard_normal((b, hkv, 24, d)).astype(np.float32)
    vw = rng.standard_normal((b, hkv, 24, d)).astype(np.float32)
    zero = jnp.zeros(b, jnp.int32)
    fk, fv = paged_kv.paged_cache_update(fp, fp, jnp.asarray(kw),
                                         jnp.asarray(vw), zero,
                                         jnp.asarray(bt))
    qpool = paged_kv.quantize_pool(fp)
    qk, qv = paged_kv.paged_cache_update(qpool, qpool, jnp.asarray(kw),
                                         jnp.asarray(vw), zero,
                                         jnp.asarray(bt))
    q = rng.standard_normal((b, h, 1, d)).astype(np.float32)
    pos = np.array([5, 12, 23], np.int32)
    ref = np.asarray(paged_decode_attention_reference(
        jnp.asarray(q), fk, fv, jnp.asarray(bt), jnp.asarray(pos)))
    got = np.asarray(paged_decode_attention_reference(
        jnp.asarray(q), qk, qv, jnp.asarray(bt), jnp.asarray(pos)))
    np.testing.assert_allclose(got, ref, atol=5e-2)


# --------------------------------------------------------------- scheduling
@pytest.fixture(scope="module")
def tiny_engine():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _trace(cfg, n=6, seed=1, prefix_len=24, tail=(3, 10), max_new=(2, 10)):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len)
    return [Request(uid=i,
                    prompt=np.concatenate(
                        [prefix, rng.integers(0, cfg.vocab_size,
                                              int(rng.integers(*tail)))]),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


def _sequential(engine, reqs):
    return {r.uid: engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            for r in reqs}


def test_kv8_serving_bounded_divergence_and_stats(tiny_engine):
    """kv8 end-to-end on gpt2: bounded token divergence vs sequential
    generate, ≤2-program compile contract live-enforced, quantized memory
    accounting in stats(), and the per-iteration audit (incl.
    scale-lockstep) green throughout."""
    engine, cfg = tiny_engine
    reqs = _trace(cfg)
    want = _sequential(engine, reqs)
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2, quantize="kv8",
                        debug_checks=True)
    assert srv.compile_budget == 2
    res = srv.serve(_trace(cfg))
    rate = assert_bounded_divergence(want, res, KV8_MIN_MATCH, "kv8")
    assert rate > 0  # helper returns the measured rate for logging
    st = srv.stats()
    assert st["quantize"] == "kv8" and st["kv_dtype"] == "int8"
    assert st["weight_quant"] is None
    assert st["kv_scale_bytes"] > 0
    assert st["compile_count"] == 2, srv.compiled_programs
    assert st["retraces_observed"] == 0
    assert st["invariant_checks_run"] > 0
    # quant-adjusted pool accounting: int8 codes + scale table, and the
    # headline — ~2x (>= 1.8x vs a bf16 pool) servable blocks per byte
    plain = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                          prefill_chunk=16, prefill_batch=2)
    bf16_bytes = plain.stats()["kv_pool_bytes"] // 2   # fp32 pool -> bf16
    assert bf16_bytes / st["kv_pool_bytes"] >= 1.8 - 0.11  # hd=16 tiny cfg
    payload = 2 * int(np.prod(st["kv_pool_shape"]))   # k + v leaves, int8
    assert st["kv_pool_bytes"] == payload + st["kv_scale_bytes"]


def test_kv8_speculative_and_preemption_pressure(tiny_engine):
    """kv8 composes with the draft–verify round (n-gram, ≤2 programs) and
    survives eviction + preemption churn with the audit on: rollback
    rewrites the same positions with the same deterministic codes, and
    the scale ledger tracks every free/realloc."""
    engine, cfg = tiny_engine
    reqs = _trace(cfg, seed=3)
    want = _sequential(engine, reqs)
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2, quantize="kv8",
                        spec_tokens=3, debug_checks=True)
    res = srv.serve(_trace(cfg, seed=3))
    assert_bounded_divergence(want, res, KV8_MIN_MATCH, "kv8+spec")
    assert srv.compile_count <= 2, srv.compiled_programs
    assert srv.stats()["acceptance_rate"] >= 0.0

    # oversubscribed pool: preemption + prefix eviction under kv8
    rng = np.random.default_rng(5)
    preqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                     max_new_tokens=28) for i in range(5)]
    pwant = _sequential(engine, preqs)
    srv_p = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                          prefill_chunk=32, prefill_batch=2, num_blocks=12,
                          quantize="kv8", debug_checks=True)
    pres = srv_p.serve(preqs)
    assert srv_p.preempted > 0, srv_p.stats()
    assert_bounded_divergence(pwant, pres, KV8_MIN_MATCH, "kv8+preempt")
    # every free retired its ledger entry; survivors are exactly the
    # still-owned blocks (the audit checked this each iteration too)
    assert all(srv_p._alloc.refcount(b) > 0 for b in srv_p._kv_scale_live)


def test_quantize_none_is_bit_identical(tiny_engine):
    """The guardrail for everything above: an explicit ``quantize=None``
    engine (and the default) traces the exact pre-quantization programs —
    bit-equal tokens, float pool, no scale table."""
    engine, cfg = tiny_engine
    reqs = _trace(cfg, seed=7)
    want = _sequential(engine, reqs)
    srv = ServingEngine(engine, slots=4, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2, quantize=None,
                        debug_checks=True)
    res = srv.serve(_trace(cfg, seed=7))
    for r in reqs:
        np.testing.assert_array_equal(res[r.uid], want[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = srv.stats()
    assert st["quantize"] is None and st["kv_dtype"] == "float32"
    assert st["kv_scale_bytes"] == 0
    assert token_match_rate(want, res) == 1.0


@pytest.mark.parametrize("family", ["gpt2", "llama", "opt", "mixtral",
                                    "bloom"])
def test_quant_serving_all_families(family):
    """kv8 AND w8a8+kv8 end-to-end per paged family: one plain engine
    serves the full-precision reference, the kv8 lane wraps the same
    engine, and the w8a8+kv8 lane rebuilds it with K-grouped int8 records
    through ``init_serving(quantize=...)`` (asserting records actually
    exist, so the lane can't silently serve dense weights)."""
    import jax

    from deepspeed_tpu.ops import quantization as quant

    if family == "gpt2":
        from deepspeed_tpu.models import gpt2 as m
        cfg = m.GPT2Config(vocab_size=512, max_seq_len=64, num_layers=2,
                           num_heads=4, hidden_size=128)
    elif family == "llama":
        from deepspeed_tpu.models import llama as m
        cfg = m.LlamaConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, hidden_size=128,
                            ffn_size=256, rope_theta=10000.0, remat=False)
    elif family == "opt":
        from deepspeed_tpu.models import opt as m
        cfg = m.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                          num_heads=4, hidden_size=128, ffn_size=256)
    elif family == "mixtral":
        from deepspeed_tpu.models import mixtral as m
        cfg = m.MixtralConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                              num_heads=4, num_kv_heads=2, hidden_size=128,
                              ffn_size=128, rope_theta=10000.0,
                              num_experts=4, top_k=2, remat=False)
    else:
        from deepspeed_tpu.models import bloom as m
        cfg = m.BloomConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                            num_heads=4, hidden_size=128)
    params = jax.device_get(m.build(cfg).init_fn(jax.random.PRNGKey(0)))
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        m.build(cfg), params=params,
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    reqs = _trace(cfg, n=4, seed=2, prefix_len=10, tail=(3, 8),
                  max_new=(2, 8))
    want = _sequential(engine, reqs)

    kw = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
              prefill_batch=2, debug_checks=True)
    srv = ServingEngine(engine, quantize="kv8", **kw)
    res = srv.serve(_trace(cfg, n=4, seed=2, prefix_len=10, tail=(3, 8),
                           max_new=(2, 8)))
    assert_bounded_divergence(want, res, KV8_MIN_MATCH, f"{family} kv8")
    assert srv.compile_count <= 2

    deepspeed_tpu.comm.reset_topology()
    srv_w = deepspeed_tpu.init_serving(
        m.build(cfg), params=params, config={"dtype": "fp32"},
        quantize="w8a8+kv8", **kw)
    recs = [x for x in jax.tree_util.tree_leaves(
        srv_w.engine.params, is_leaf=quant.is_k_quantized)
        if quant.is_k_quantized(x)]
    assert recs, f"{family}: w8a8 produced no K-grouped records"
    res_w = srv_w.serve(_trace(cfg, n=4, seed=2, prefix_len=10,
                               tail=(3, 8), max_new=(2, 8)))
    assert_bounded_divergence(want, res_w, W8A8_MIN_MATCH,
                              f"{family} w8a8+kv8")
    st = srv_w.stats()
    assert st["weight_quant"] == "w8a8" and st["kv_dtype"] == "int8"
    assert srv_w.compile_count <= 2
    # teacher-forced logit error stays bounded (no argmax-cascade luck)
    rmse = max_logit_rmse(engine, srv_w.engine,
                          [r.prompt for r in reqs[:2]])
    assert rmse <= W8A8_MAX_LOGIT_RMSE, rmse


def test_scale_lockstep_fault_injection(tiny_engine):
    """The scale ledger is a CHECKED contract: injecting a stale entry
    (freed block still marked live) or dropping a live one (owned block
    missing) raises PagedStateError naming ``scale-lockstep``."""
    from deepspeed_tpu.analysis.invariants import audit_serving_engine

    engine, cfg = tiny_engine
    srv = ServingEngine(engine, slots=2, max_seq_len=128, block_size=8,
                        prefill_chunk=16, prefill_batch=2, quantize="kv8",
                        debug_checks=True)
    srv.serve(_trace(cfg, n=2, seed=9))
    # after the trace the prefix trie still owns blocks: ledger non-empty
    assert srv._kv_scale_live

    # stale scale: a freed block left in the ledger
    free_block = srv._alloc._free[0]
    srv._kv_scale_live.add(free_block)
    with pytest.raises(PagedStateError, match="scale-lockstep") as ei:
        audit_serving_engine(srv, {})
    assert ei.value.invariant == "scale-lockstep"
    srv._kv_scale_live.discard(free_block)
    audit_serving_engine(srv, {})              # green again

    # dropped entry: an owned (trie-held) block missing from the ledger
    owned = next(iter(srv._kv_scale_live))
    srv._kv_scale_live.discard(owned)
    with pytest.raises(PagedStateError, match="scale-lockstep"):
        audit_serving_engine(srv, {})
    srv._kv_scale_live.add(owned)
    audit_serving_engine(srv, {})


def test_quantize_validation_errors(tiny_engine):
    engine, cfg = tiny_engine
    with pytest.raises(ValueError, match="quantize"):
        ServingEngine(engine, quantize="int4")
    # w8a8 requested but the engine carries full-precision weights
    with pytest.raises(ValueError, match="w8a8"):
        ServingEngine(engine, quantize="w8a8")
    with pytest.raises(ValueError, match="w8a8"):
        ServingEngine(engine, quantize="w8a8+kv8")
    # kv8 against a family that never declared the record contract
    hooks = dict(engine.module.decode_hooks)
    hooks.pop("supports_kv_quant")
    spec = engine.module
    orig = spec.decode_hooks
    spec.decode_hooks = hooks
    try:
        with pytest.raises(ValueError, match="supports_kv_quant"):
            ServingEngine(engine, quantize="kv8")
    finally:
        spec.decode_hooks = orig
