"""True multi-process distributed tests (reference DistributedTest,
tests/unit/common.py:266): 2 controller processes x 2 CPU-sim devices run
REAL cross-process collectives through the public engine; loss curves must
match the single-process 4-device run exactly.

This lights up the multi-host branches that are dead code under the
single-process suite: ``_shard_batch``'s
``make_array_from_process_local_data`` path, dataloader process sharding,
``comm.barrier``/``host_all_reduce_sum`` over >1 process.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      os.pardir, "multiproc", "worker_train.py")


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_world(nprocs, steps, tmp_path, timeout=600, save=None, load=None,
               tag="", mode=None):
    port = _free_port()
    outs = [str(tmp_path / f"out_{tag}{nprocs}p_{i}.json")
            for i in range(nprocs)]
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(i), str(nprocs), str(port),
         str(steps), outs[i], save or "-", load or "-", mode or "-"],
        env=env)
        for i in range(nprocs)]
    for p in procs:
        assert p.wait(timeout=timeout) == 0, f"worker failed (rc={p.returncode})"
    return [json.load(open(o)) for o in outs]


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    steps = 3
    # NOTE: worker forces 2 devices/process, so nprocs=2 -> world 4; the
    # single-process reference needs its own 4-device world -> run it as a
    # subprocess too (xla_force_host_platform_device_count must be set
    # before backend init)
    two = _run_world(2, steps, tmp_path)
    assert two[0]["procs"] == 2 and two[0]["world"] == 4

    # single-process 4-dev reference: same global batch, same seeds
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    ref_out = str(tmp_path / "ref.json")
    # nprocs=1 worker: no distributed init; 2-dev flag overridden by env
    rc = subprocess.run(
        [sys.executable, WORKER, "0", "1", "0", str(steps), ref_out],
        env=env, timeout=600).returncode
    assert rc == 0
    ref = json.load(open(ref_out))
    assert ref["world"] == 4 and ref["procs"] == 1

    for d in two:
        np.testing.assert_allclose(d["losses"], ref["losses"],
                                   rtol=2e-5, atol=1e-6)
    # host collective across processes: sum of (1, 2) = 3 everywhere
    for d in two:
        np.testing.assert_allclose(d["host_sum"], [3.0, 3.0, 3.0])


@pytest.mark.slow
def test_checkpoint_saved_on_two_processes_resumes_on_one(tmp_path):
    """DistributedFixture analog (reference tests/unit/common.py:202 and
    the checkpoint resume matrix): a 2-controller run saves; a single
    1-controller run loads the same checkpoint and continues — the loss
    curve after resume must match a 2-process continuation exactly."""
    ck = str(tmp_path / "ck")
    two_a = _run_world(2, 2, tmp_path, save=ck, tag="a")
    # continue 2 more steps in BOTH world shapes from the same checkpoint
    two_b = _run_world(2, 2, tmp_path, load=ck, tag="b")

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    ref_out = str(tmp_path / "ref_resume.json")
    rc = subprocess.run(
        [sys.executable, WORKER, "0", "1", "0", "2", ref_out, "-", ck],
        env=env, timeout=600).returncode
    assert rc == 0
    ref = json.load(open(ref_out))
    np.testing.assert_allclose(two_b[0]["losses"], ref["losses"],
                               rtol=2e-5, atol=1e-6)
    # the resume must actually carry trained state: its first loss sits
    # below the fresh run's first loss (same seed-0 batches)
    assert two_b[0]["losses"][0] < two_a[0]["losses"][0] - 0.05, \
        (two_b[0]["losses"], two_a[0]["losses"])


@pytest.mark.slow
def test_two_process_param_streaming_matches_single_process(tmp_path):
    """ZeRO-Infinity param streaming under 2 controllers: block params are
    host-resident, layer loads/grad pushes flow through io_callbacks pinned
    to the GLOBAL first device, and the host grad combine
    (comm.host_all_reduce_sum in engine._host_apply) must reproduce the
    single-process run exactly.  This validated (and the per-process pin
    bug it caught fixed) the formerly env-gated multi-host leg."""
    steps = 3
    two = _run_world(2, steps, tmp_path, mode="stream", tag="s")
    assert two[0]["procs"] == 2 and two[0]["world"] == 4

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    ref_out = str(tmp_path / "ref_stream.json")
    rc = subprocess.run(
        [sys.executable, WORKER, "0", "1", "0", str(steps), ref_out,
         "-", "-", "stream"],
        env=env, timeout=900).returncode
    assert rc == 0
    ref = json.load(open(ref_out))
    for d in two:
        np.testing.assert_allclose(d["losses"], ref["losses"],
                                   rtol=2e-5, atol=1e-6)
