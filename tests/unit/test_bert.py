"""BERT encoder tests: HF MLM parity, padding mask, training (reference:
BingBertSquad e2e + HFBertLayerPolicy rows of the inference sweep)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import bert

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    with torch.no_grad():
        m = transformers.BertForMaskedLM(cfg)
    m.eval()
    return m


def test_distilbert_matches_hf():
    cfg = transformers.DistilBertConfig(
        vocab_size=96, dim=32, n_layers=2, n_heads=4, hidden_dim=128,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)
    with torch.no_grad():
        hf = transformers.DistilBertForMaskedLM(cfg)
    hf.eval()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(3).integers(2, 96, (2, 12)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_bert_matches_hf_with_padding_mask():
    hf = _tiny_hf_bert()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    rng = np.random.default_rng(0)
    ids = rng.integers(2, 96, (2, 10)).astype(np.int32)
    mask = np.ones((2, 10), np.int32)
    mask[1, 6:] = 0  # padded row
    ours = np.asarray(spec.apply_fn(
        params, {"input_ids": ids, "attention_mask": mask}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids),
                    attention_mask=torch.tensor(mask)).logits.numpy()
    # compare only non-padded positions (HF computes garbage on pads too,
    # but the bias handling can differ there)
    np.testing.assert_allclose(ours[0], theirs[0], atol=3e-4, rtol=2e-3)
    np.testing.assert_allclose(ours[1, :6], theirs[1, :6], atol=3e-4,
                               rtol=2e-3)


def test_bert_mlm_training_overfits():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=bert.build(bert.BertConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, (engine.train_batch_size(), 16)).astype(np.int32)
    labels = np.full_like(ids, -100)
    labels[:, ::4] = ids[:, ::4]          # predict every 4th token
    masked = ids.copy()
    masked[:, ::4] = 3                    # [MASK]
    fixed = {"input_ids": masked, "labels": labels}
    losses = [float(engine.train_batch(fixed)[1]["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0] - 0.1


def test_bert_requires_labels():
    import jax

    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(AssertionError, match="labels"):
        bert.loss_from_batch(cfg, params,
                             {"input_ids": np.zeros((1, 8), np.int32)})


def test_bert_tp_sharded_forward(eight_devices):
    deepspeed_tpu.comm.reset_topology()
    hf = _tiny_hf_bert()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.ones((2, 8), np.int32) * 5
    ref = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    engine = deepspeed_tpu.init_inference(
        model=spec, params=params,
        config={"dtype": "float32", "tensor_parallel": {"tp_size": 2}})
    got = np.asarray(engine.forward({"input_ids": ids}))
    np.testing.assert_allclose(got, ref, atol=1e-4)
