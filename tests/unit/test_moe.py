"""MoE tests (model: reference tests/unit/moe/test_moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.moe import (MoEConfig, init_moe_params, moe_apply,
                               moe_tp_rules, top1gating, top2gating)
from deepspeed_tpu.moe.sharded_moe import _capacity


def test_capacity():
    assert _capacity(num_tokens=64, num_experts=8, capacity_factor=1.0,
                     min_capacity=4) == 8
    assert _capacity(num_tokens=8, num_experts=8, capacity_factor=1.0,
                     min_capacity=4) == 4  # floor


def test_top1_gating_shapes_and_routing():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (2, 16, 4))
    l_aux, combine, dispatch, counts = top1gating(logits, capacity_factor=2.0)
    assert combine.shape == (2, 16, 4, 8)
    assert dispatch.shape == (2, 16, 4, 8)
    # each token goes to at most one (expert, slot)
    per_token = dispatch.sum(axis=(2, 3))
    assert (np.asarray(per_token) <= 1).all()
    # combine weights equal the softmax prob of the chosen expert
    gates = jax.nn.softmax(logits, axis=-1)
    chosen = np.asarray(gates.max(axis=-1))
    got = np.asarray(combine.sum(axis=(2, 3)))
    routed = np.asarray(per_token) > 0
    np.testing.assert_allclose(got[routed], chosen[routed], rtol=1e-5)
    assert float(l_aux) > 0


def test_top1_capacity_drops_tokens():
    # all tokens prefer expert 0; capacity 4 forces drops
    logits = jnp.zeros((1, 16, 4)).at[:, :, 0].set(10.0)
    _, combine, dispatch, counts = top1gating(logits, capacity_factor=1.0,
                                              min_capacity=4)
    assert int(dispatch.sum()) == 4  # only capacity tokens routed
    assert int(counts[0]) == 4


def test_top2_gating():
    rng = jax.random.PRNGKey(1)
    logits = jax.random.normal(rng, (2, 16, 4))
    l_aux, combine, dispatch, counts = top2gating(logits, capacity_factor=2.0)
    per_token = np.asarray(dispatch.sum(axis=(2, 3)))
    assert (per_token <= 2).all()
    assert (per_token >= 1).all()  # ample capacity: everyone routed twice-ish
    # normalized weights sum to ~1 for fully-routed tokens
    w = np.asarray(combine.sum(axis=(2, 3)))
    np.testing.assert_allclose(w[per_token == 2], 1.0, rtol=1e-5)


@pytest.mark.parametrize("k", [1, 2])
def test_moe_apply_forward(k):
    cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=4, k=k,
                    capacity_factor=2.0)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux = moe_apply(cfg, params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert float(aux) > 0


def test_moe_apply_grads_flow():
    cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=4, k=1,
                    capacity_factor=2.0)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))

    def loss(p):
        y, aux = moe_apply(cfg, p, x)
        return jnp.sum(y**2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    gw = np.asarray(jnp.abs(grads["gate_w"]).sum())
    ew = np.asarray(jnp.abs(grads["experts"]["fc_w"]).sum())
    assert gw > 0 and ew > 0


def test_moe_expert_parallel_sharded(eight_devices):
    """Experts shard over ep=4; forward matches the unsharded result."""
    from deepspeed_tpu.parallel.topology import MeshTopology

    cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32, num_experts=4, k=1,
                    capacity_factor=2.0)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    y_ref, aux_ref = moe_apply(cfg, params, x)

    mesh = MeshTopology(ep=4).mesh
    rules = moe_tp_rules(cfg)
    # jax.set_mesh is a recent addition; older jax enters the mesh context
    # through the Mesh object itself (shardings here are explicit anyway)
    with (jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh):
        sharded = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, rules,
            is_leaf=lambda v: isinstance(v, P))
        xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "ep"))))
        y, aux = jax.jit(lambda p, x: moe_apply(cfg, p, x))(sharded, xs)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_mixtral_kv_cache_decode_matches_forward():
    """MoE cached incremental decode equals the full forward (reference
    ``moe_inference.py`` routing-per-token semantics)."""
    import jax

    from deepspeed_tpu.models import mixtral

    cfg = mixtral.MixtralConfig.tiny()
    cfg.use_flash = False
    # exact decode parity needs drop-free eval routing (documented mode)
    cfg.eval_capacity_factor = float(cfg.num_experts)
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 12)).astype(np.int32)
    full = np.asarray(mixtral.forward_with_aux(cfg, params, ids,
                                               train=False)[0])

    from deepspeed_tpu.models import llama as L

    cache = L.init_cache(cfg, 2, 32, dtype=np.float32)
    logits, cache = mixtral.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=2e-4)
    for t in range(8, 12):
        logits, cache = mixtral.forward_cached(cfg, params, ids[:, t:t + 1],
                                               cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=2e-4)


def test_mixtral_generate_kv_path():
    import deepspeed_tpu
    from deepspeed_tpu.models import mixtral

    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        model=mixtral.build(mixtral.MixtralConfig.tiny()),
        config={"dtype": "float32"})
    ids = np.full((1, 4), 7, np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 8)
    out2 = engine.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(out, out2)
