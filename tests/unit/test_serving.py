"""Continuous-batching serving layer (inference/serving.py) + the generate
satellites that ride with it.

Deterministic CPU tests: scheduler admission/free ordering, no starvation,
bucketed compile counts (the O(#buckets) acceptance probe), and per-request
token parity with sequential ``generate`` for greedy decoding.  The ragged
``lengths`` decode-attention contract is covered here on the XLA reference
path; the Pallas-interpret twin lives in test_decode_attention.py (slow).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.engine import _fill_after_eos
from deepspeed_tpu.inference.serving import (Request, ServingEngine,
                                             default_buckets)
from deepspeed_tpu.models import gpt2


def _tiny_engine(max_seq_len=128):
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=max_seq_len)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


def _trace(cfg, n, seed=0, lo=3, hi=30, max_new=(1, 12)):
    rng = np.random.default_rng(seed)
    return [Request(uid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        int(rng.integers(lo, hi))),
                    max_new_tokens=int(rng.integers(*max_new)))
            for i in range(n)]


# --------------------------------------------------------------- _fill_after_eos
def test_fill_after_eos_backfill_semantics():
    """HF back-fill: everything strictly after the first eos in the GENERATED
    region becomes eos; the eos itself, the prompt (even if it contains eos),
    and rows without eos are untouched."""
    eos = 9
    out = np.array([
        [1, 9, 2, 3, 9, 5, 6],    # eos in prompt ignored; first gen eos at 4
        [1, 2, 3, 4, 5, 6, 7],    # no eos: untouched
        [1, 2, 9, 8, 7, 6, 5],    # eos at gen position 0
        [1, 2, 3, 4, 5, 6, 9],    # eos at the last position: nothing after
    ], np.int32)
    got = _fill_after_eos(out.copy(), 2, eos)
    want = np.array([
        [1, 9, 2, 3, 9, 9, 9],
        [1, 2, 3, 4, 5, 6, 7],
        [1, 2, 9, 9, 9, 9, 9],
        [1, 2, 3, 4, 5, 6, 9],
    ], np.int32)
    np.testing.assert_array_equal(got, want)


def test_fill_after_eos_matches_rowwise_loop():
    """Pin the vectorized expression against the per-row np.where original."""
    def rowwise(out, prompt_len, eos):
        for row in range(out.shape[0]):
            hits = np.where(out[row, prompt_len:] == eos)[0]
            if hits.size:
                out[row, prompt_len + hits[0] + 1:] = eos
        return out

    rng = np.random.default_rng(0)
    for _ in range(50):
        out = rng.integers(0, 5, (4, 12)).astype(np.int32)
        np.testing.assert_array_equal(
            _fill_after_eos(out.copy(), 4, 2), rowwise(out.copy(), 4, 2))
    # degenerate: no generated region
    out = rng.integers(0, 5, (2, 6)).astype(np.int32)
    np.testing.assert_array_equal(_fill_after_eos(out.copy(), 6, 2), out)


# -------------------------------------------------------------------- scheduler
def test_serving_matches_sequential_generate_greedy():
    """Acceptance: per-request outputs token-identical to sequential
    ``generate`` (greedy), across mixed prompt lengths and budgets."""
    engine, cfg = _tiny_engine()
    srv = ServingEngine(engine, slots=4, max_seq_len=128,
                        prompt_buckets=(8, 16, 32), prefill_batch=2)
    reqs = _trace(cfg, 10)
    res = srv.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_serving_matches_sequential_generate_with_eos():
    """Same parity when sequences stop early at eos (slot frees early and
    the output is eos back-filled like generate's)."""
    engine, cfg = _tiny_engine()
    srv = ServingEngine(engine, slots=3, max_seq_len=128,
                        prompt_buckets=(8, 16, 32), prefill_batch=2)
    reqs = _trace(cfg, 6, seed=1, max_new=(4, 10))
    # pick an eos that actually occurs: the first generated token of req 0
    probe = engine.generate(reqs[0].prompt[None, :], max_new_tokens=1)
    eos = int(probe[0, len(reqs[0].prompt)])
    res = srv.serve(reqs, eos_token_id=eos)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens,
                               eos_token_id=eos)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


@pytest.mark.parametrize("family", ["llama", "opt"])
def test_serving_parity_other_families(family):
    """The lengths contract holds beyond gpt2: rope offsets (llama) and
    offset learned positions (opt) decode per-slot correctly."""
    deepspeed_tpu.comm.reset_topology()
    if family == "llama":
        from deepspeed_tpu.models import llama as m

        cfg = m.LlamaConfig.tiny()
    else:
        from deepspeed_tpu.models import opt as m

        cfg = m.OPTConfig.tiny()
    engine = deepspeed_tpu.init_inference(
        m.build(cfg), config={"dtype": "fp32",
                              "tensor_parallel": {"tp_size": 1}})
    srv = ServingEngine(engine, slots=3, max_seq_len=64,
                        prompt_buckets=(8, 16), prefill_batch=2)
    reqs = _trace(cfg, 5, seed=2, lo=3, hi=14, max_new=(2, 8))
    res = srv.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res[r.uid], want,
                                      err_msg=f"uid {r.uid}")


def test_compile_count_bucketed():
    """Acceptance: the serving loop compiles O(#buckets) programs for a whole
    mixed-shape trace — and re-serving new shapes in the same buckets
    compiles nothing new."""
    engine, cfg = _tiny_engine()
    srv = ServingEngine(engine, slots=4, max_seq_len=128,
                        prompt_buckets=(8, 16, 32), prefill_batch=2)
    def buckets_of(reqs):
        return {min(b for b in srv.prompt_buckets if len(r.prompt) <= b)
                for r in reqs}

    reqs = _trace(cfg, 12, seed=3)          # ~12 distinct request shapes
    srv.serve(reqs)
    used = buckets_of(reqs)
    assert srv.compile_count == len(used) + 1, srv.compiled_programs
    # distinct new shapes: compiles track BUCKETS, not request shapes
    reqs2 = _trace(cfg, 8, seed=4)
    srv.serve(reqs2)
    used |= buckets_of(reqs2)
    assert srv.compile_count == len(used) + 1, srv.compiled_programs
    # repeat traffic: zero new programs
    srv.serve(_trace(cfg, 12, seed=3))
    assert srv.compile_count == len(used) + 1, srv.compiled_programs
    # the probe counts traced programs, not calls: each jitted fn must have
    # exactly one executable (no silent same-key retraces)
    for fn in list(srv._prefill_fns.values()) + [srv._decode_fn]:
        cache_size = getattr(fn, "_cache_size", None)
        if cache_size is not None:
            assert cache_size() == 1


def test_admission_fifo_and_immediate_slot_reuse():
    """Slots: strict FIFO admission (no starvation), and a freed slot is
    reacquired by the next waiting request."""
    engine, cfg = _tiny_engine()
    srv = ServingEngine(engine, slots=2, max_seq_len=128,
                        prompt_buckets=(8,), prefill_batch=2)
    rng = np.random.default_rng(5)
    # short budgets so slots churn: 6 requests through 2 slots
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 4),
                    max_new_tokens=2 + (i % 3)) for i in range(6)]
    log = []
    res = srv.serve(reqs, admission_log=log)
    assert set(res) == set(range(6))                    # nothing starved
    assert [uid for uid, _ in log] == list(range(6))    # FIFO admission
    slots_seen = {s for _, s in log}
    assert slots_seen == {0, 1}                         # both slots reused
    # with 2 slots and 6 requests, each slot must have served >= 2 requests
    for s in slots_seen:
        assert sum(1 for _, slot in log if slot == s) >= 2


def test_serving_rejects_oversized_and_invalid():
    engine, cfg = _tiny_engine()
    srv = ServingEngine(engine, slots=2, max_seq_len=64,
                        prompt_buckets=(8, 16), prefill_batch=2)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        srv.serve([Request(uid=0, prompt=np.arange(16), max_new_tokens=60)])
    with pytest.raises(ValueError, match="largest bucket"):
        srv.serve([Request(uid=0, prompt=np.arange(20), max_new_tokens=2)])
    with pytest.raises(ValueError, match="duplicate"):
        srv.serve([Request(uid=0, prompt=np.arange(4), max_new_tokens=2),
                   Request(uid=0, prompt=np.arange(4), max_new_tokens=2)])
    with pytest.raises(ValueError, match="empty prompt"):
        Request(uid=1, prompt=np.zeros(0), max_new_tokens=2)
    with pytest.raises(ValueError, match="supports_lengths"):
        from deepspeed_tpu.models import gptj

        deepspeed_tpu.comm.reset_topology()
        legacy = deepspeed_tpu.init_inference(
            gptj.build(gptj.GPTJConfig.tiny()),
            config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
        ServingEngine(legacy)


def test_default_buckets_ladder():
    assert default_buckets(512) == (32, 64, 128, 256, 512)
    assert default_buckets(96) == (32, 64, 96)
    assert default_buckets(32) == (32,)


def test_default_buckets_edge_cases():
    """lo above max_seq_len clamps to one bucket, non-power-of-two tails
    appear exactly once, and degenerate inputs raise instead of looping."""
    assert default_buckets(16) == (16,)                 # lo 32 > max 16
    assert default_buckets(64, lo=100) == (64,)         # explicit lo > max
    assert default_buckets(1) == (1,)
    assert default_buckets(48, lo=48) == (48,)          # lo == max, non-pow2
    assert default_buckets(96, lo=3) == (3, 6, 12, 24, 48, 96)
    for ladder in (default_buckets(96), default_buckets(640, lo=10),
                   default_buckets(100, lo=25)):
        assert len(set(ladder)) == len(ladder), ladder  # no duplicate tail
        assert list(ladder) == sorted(ladder)
    with pytest.raises(ValueError, match="lo"):
        default_buckets(64, lo=0)                       # would loop forever
    with pytest.raises(ValueError, match="max_seq_len"):
        default_buckets(0)


# ------------------------------------------------- generate early-exit satellite
def test_generate_early_exit_matches_full_loop():
    """The eos-keyed while_loop generate == fori_loop generate + back-fill,
    on both the KV-cache and full-recompute paths."""
    engine, cfg = _tiny_engine(max_seq_len=256)
    rng = np.random.default_rng(6)
    ids = rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32)
    base = engine.generate(ids, max_new_tokens=8)           # no-eos fori path
    eos = int(base[0, 9])                                    # occurs mid-run
    want = _fill_after_eos(base.copy(), 7, eos)
    got = engine.generate(ids, max_new_tokens=8, eos_token_id=eos)
    np.testing.assert_array_equal(got, want)

    model = gpt2.build(cfg)
    model.decode_hooks = None                                # recompute path
    deepspeed_tpu.comm.reset_topology()
    engine2 = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        params=engine.params)
    got2 = engine2.generate(ids, max_new_tokens=8, eos_token_id=eos)
    np.testing.assert_array_equal(got2, want)


def test_generate_fns_lru_moves_hit_to_end():
    """Satellite: a cache hit refreshes the entry, so hot shapes survive
    eviction pressure (true LRU, not insertion-order FIFO)."""
    engine, cfg = _tiny_engine()
    ids = np.ones((1, 4), np.int32)
    engine.generate(ids, max_new_tokens=2)      # key A
    engine.generate(ids, max_new_tokens=3)      # key B
    key_a = (1, 4, 2, None, None)
    assert list(engine._generate_fns)[0] == key_a
    engine.generate(ids, max_new_tokens=2)      # hit A: moves to end
    assert list(engine._generate_fns)[-1] == key_a
    fn_a = engine._generate_fns[key_a]
    engine.generate(ids, max_new_tokens=2)
    assert engine._generate_fns[key_a] is fn_a  # hit reused, not rebuilt
