"""Fused int8 dequant-matmul kernel (ops/quantized_matmul) — parity with the
dequantize+matmul reference path, eligibility fallbacks, and the quant-aware
model wiring (reference: DS-Inference int8 GEMMs never materialize an fp16
weight copy; ``module_inject/replace_module.py:152`` GroupQuantizer)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops import quantization as quant
from deepspeed_tpu.ops.quantized_matmul import quantized_matmul


def _mk(k, n, g, rows=1, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(k, n)).astype(np.float32)
    x = rng.normal(size=(rows, k)).astype(np.float32)
    rec = quant.quantize(jnp.asarray(w), group_size=g)
    return jnp.asarray(x, dtype), rec


@pytest.fixture(autouse=True)
def _kernel_on(monkeypatch):
    # the fused kernel is opt-in (it loses to XLA's dequant path end-to-end
    # on this chip — see module docstring); these tests exercise it anyway
    monkeypatch.setenv("DS_QMM", "1")


@pytest.mark.parametrize("rows", [1, 8, 128])
def test_kernel_matches_dequant_matmul(rows):
    x, rec = _mk(512, 1024, 128, rows=rows)
    ref = x @ quant.dequantize(rec, x.dtype)
    out = quantized_matmul(x, rec)
    assert out.shape == (rows, 1024)
    # kernel dequantizes in bf16 (scale rounding ~2^-8, below the int8
    # quantization error itself); reference path computes in f32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=3e-1)


def test_kernel_3d_rows_and_bf16():
    x, rec = _mk(512, 512, 128, rows=6, dtype=jnp.bfloat16)
    x3 = x.reshape(2, 3, 512)
    ref = x3 @ quant.dequantize(rec, x3.dtype)
    out = quantized_matmul(x3, rec)
    assert out.shape == (2, 3, 512) and out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=6e-2, atol=6e-1)


def test_off_lane_group_size_falls_back():
    # reference GroupQuantizer group sizes (64) are honored via fallback
    x, rec = _mk(512, 1024, 64)
    ref = x @ quant.dequantize(rec, x.dtype)
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, rec)),
                               np.asarray(ref), rtol=1e-6)


def test_non_tiling_shapes_fall_back():
    # N=192 has no 128-multiple divisor block: must fall back, still correct
    x, rec = _mk(512, 192, 64)
    ref = x @ quant.dequantize(rec, x.dtype)
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, rec)),
                               np.asarray(ref), rtol=1e-6)


def test_kill_switch_and_row_cap(monkeypatch):
    x, rec = _mk(512, 1024, 128, rows=4)
    ref = x @ quant.dequantize(rec, x.dtype)
    monkeypatch.setenv("DS_QMM", "0")
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, rec)),
                               np.asarray(ref), rtol=1e-6)
    monkeypatch.delenv("DS_QMM")
    xl, _ = _mk(512, 1024, 128, rows=512)  # > max_rows: long-prefill fallback
    np.testing.assert_allclose(
        np.asarray(quantized_matmul(xl, rec)),
        np.asarray(xl @ quant.dequantize(rec, xl.dtype)), rtol=1e-6)


def test_model_decode_parity_kernel_vs_fallback(monkeypatch):
    """An int8-served OPT (tileable dims: hidden 128) must generate the
    same tokens with the fused kernel and with the dequant fallback."""
    import deepspeed_tpu
    from deepspeed_tpu.models import opt

    cfg = opt.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                        num_heads=4, hidden_size=128, ffn_size=512)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 6), dtype=np.int32)

    outs, logits = {}, {}
    for tag, env in (("kernel", "1"), ("fallback", "0")):
        monkeypatch.setenv("DS_QMM", env)
        deepspeed_tpu.comm.reset_topology()
        eng = deepspeed_tpu.init_inference(
            model=opt.build(cfg), params=params,
            config={"dtype": "float32",
                    "quant": {"enabled": True, "group_size": 128}})
        outs[tag] = np.asarray(eng.generate(ids, max_new_tokens=8))
        logits[tag] = np.asarray(eng.forward({"input_ids": ids}))
    # bf16 in-kernel dequant vs f32 fallback: logits agree to bf16-level
    # tolerance and greedy decode stays on the same tokens
    np.testing.assert_allclose(logits["kernel"], logits["fallback"],
                               rtol=5e-2, atol=5e-2)
    agree = (outs["kernel"] == outs["fallback"]).mean()
    assert agree >= 0.9, (agree, outs)


# ------------------------------------------------------------------ W8A8
def test_w8a8_matmul_matches_dequant():
    from deepspeed_tpu.ops.quantized_matmul import w8a8_matmul

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(512, 1024)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(1, 512)), jnp.float32)
    rec = quant.quantize_k_grouped(w, k_group=256)
    ref = np.asarray(x @ quant.dequantize_k(rec, jnp.float32))
    out = np.asarray(w8a8_matmul(x, rec))
    # activation quantization adds ~1% error on top of the weight int8
    np.testing.assert_allclose(out, ref, rtol=5e-2, atol=5e-1)
    # prefill-sized rows fall back to exact dequant+matmul
    xl = jnp.asarray(rng.normal(size=(64, 512)), jnp.float32)
    refl = np.asarray(xl @ quant.dequantize_k(rec, xl.dtype))
    np.testing.assert_allclose(np.asarray(w8a8_matmul(xl, rec)), refl,
                               rtol=1e-5)


def test_w8a8_engine_decode(monkeypatch):
    """Tiny OPT served with quant.type=w8a8: decode runs, logits track the
    bf16 model, greedy tokens mostly agree."""
    import deepspeed_tpu
    from deepspeed_tpu.models import opt

    cfg = opt.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                        num_heads=4, hidden_size=128, ffn_size=512)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 6), dtype=np.int32)

    deepspeed_tpu.comm.reset_topology()
    ref_eng = deepspeed_tpu.init_inference(
        model=opt.build(cfg), params=params, config={"dtype": "float32"})
    ref_tok = np.asarray(ref_eng.generate(ids, max_new_tokens=8))
    ref_logits = np.asarray(ref_eng.forward({"input_ids": ids}))

    deepspeed_tpu.comm.reset_topology()
    eng = deepspeed_tpu.init_inference(
        model=opt.build(cfg), params=params,
        config={"dtype": "float32",
                "quant": {"enabled": True, "type": "w8a8"}})
    from deepspeed_tpu.ops import quantization as q
    recs = [x for x in jax.tree_util.tree_leaves(
        eng.params, is_leaf=q.is_k_quantized) if q.is_k_quantized(x)]
    assert recs, "w8a8 quantization did not produce K-grouped records"
    tok = np.asarray(eng.generate(ids, max_new_tokens=8))
    logits = np.asarray(eng.forward({"input_ids": ids}))
    np.testing.assert_allclose(logits, ref_logits, rtol=2e-1, atol=2e-1)
    assert (tok == ref_tok).mean() >= 0.75, (tok, ref_tok)


def test_w8a8_rejects_non_quant_aware_model():
    # unet's forwards don't dequantize at point of use and carry no
    # stacked-blocks key (mixtral — the previous example here — became
    # quant-aware in PR 7: attention records via the shared mm accessors,
    # experts dequantizing per layer inside moe_apply)
    import deepspeed_tpu
    from deepspeed_tpu.models import unet

    deepspeed_tpu.comm.reset_topology()
    with pytest.raises(ValueError, match="w8a8"):
        deepspeed_tpu.init_inference(
            model=unet.build(unet.UNetConfig.tiny()),
            config={"dtype": "float32",
                    "quant": {"enabled": True, "type": "w8a8"}})


def test_stacked_biases_stay_dense_at_64_layers():
    """[L, 3d] stacked biases pass the 2D weight-matrix shape tests once
    L >= 64 (they are not caught by the name filter either: 'qkv_b' does
    not contain 'bias'); the blocks-subtree quantizers must exclude them
    via min_ndim=3 or the block matmul wrappers crash on a record where
    a bias array is expected."""
    L, d = 64, 128
    blocks = {"qkv_w": jnp.zeros((L, d, 3 * d)),
              "qkv_b": jnp.ones((L, 3 * d)),
              "ln1_scale": jnp.ones((L, d))}
    for fn in (lambda t: quant.quantize_pytree(t, group_size=128,
                                               min_ndim=3),
               lambda t: quant.quantize_pytree_k_grouped(t, k_group=128,
                                                         min_ndim=3)):
        out = fn(blocks)
        assert not isinstance(out["qkv_b"], dict), "bias was quantized"
        assert not isinstance(out["ln1_scale"], dict)
        assert isinstance(out["qkv_w"], dict), "weight was NOT quantized"


def test_engine_serves_64_layer_quant_aware_model(monkeypatch):
    """End-to-end: a 64-layer tiny GPT-2 with quant.enabled must build and
    decode (regression: stacked biases became records and .astype crashed
    at trace time)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config(vocab_size=256, max_seq_len=32, num_layers=64,
                          num_heads=2, hidden_size=128)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = gpt2.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    deepspeed_tpu.comm.reset_topology()
    eng = deepspeed_tpu.init_inference(
        model=gpt2.build(cfg), params=params,
        config={"dtype": "float32", "quant": {"enabled": True}})
    out = eng.generate(np.ones((1, 4), np.int32), max_new_tokens=2)
    assert out.shape == (1, 6)


# ------------------------------------------------------------- w8a8 under TP
# The s8-MXU kernel is opaque to GSPMD, so TP serving routes it through a
# custom_partitioning wrapper (ops/quantized_matmul._w8a8_tp_call): column
# shards (N sharded) each run the kernel on their weight slice with no
# communication; row shards (K sharded) psum a local partial.  The reference
# analog is DS-Inference's INT8 GEMMs running on module_inject-sliced
# weights (replace_module.py:25 ReplaceWithTensorSlicing).


@pytest.fixture
def _w8a8_tp():
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    qmm_mod.configure(kernel_ok=True, w8a8_tp=True)
    yield qmm_mod
    qmm_mod.configure(kernel_ok=True, w8a8_tp=False)


def _mk_k_grouped(k, n, g, rows, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(k, n)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(rows, k)).astype(np.float32))
    rec = quant.quantize_k_grouped(jnp.asarray(w), k_group=g)
    return x, rec


@pytest.mark.parametrize("wspec,kspec", [
    (("tp_n", (None, "tp")), (None, None, "tp")),   # column parallel
    (("tp_k", ("tp", None)), ("tp", None, None)),   # row parallel
])
def test_w8a8_tp_matches_unsharded_kernel(_w8a8_tp, wspec, kspec):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.ops.quantized_matmul import w8a8_matmul

    _, wspec = wspec
    x, rec = _mk_k_grouped(512, 256, 128, rows=4)
    _w8a8_tp.configure(kernel_ok=True, w8a8_tp=False)
    ref = np.asarray(w8a8_matmul(x, rec), np.float32)  # unsharded kernel
    _w8a8_tp.configure(kernel_ok=True, w8a8_tp=True)

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    qk = jax.device_put(rec["qk"], NamedSharding(mesh, P(*wspec)))
    ks = jax.device_put(rec["kscale"], NamedSharding(mesh, P(*kspec)))
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    out = jax.jit(
        lambda a, b, c: w8a8_matmul(a, {"qk": b, "kscale": c}))(xs, qk, ks)
    # column: same per-chunk math and accumulation order -> near-exact;
    # row: one psum reorders the f32 chunk sums
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=1e-5, atol=1e-4)


def test_w8a8_tp_misaligned_shard_still_correct(_w8a8_tp):
    """A K sharding that splits k-groups unevenly (K/G=3 blocks over tp=2)
    must degrade to a gathered-but-correct lowering, not wrong math."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.ops.quantized_matmul import w8a8_matmul

    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    x, rec = _mk_k_grouped(384, 256, 128, rows=2)   # K/G = 3 blocks
    # reference is the UNSHARDED kernel (the replicated lowering runs the
    # same activation-quantizing math on full shapes)
    qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
    ref = qmm_mod.w8a8_matmul(x, rec)
    qmm_mod.configure(kernel_ok=True, w8a8_tp=True)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    qk = jax.device_put(rec["qk"], NamedSharding(mesh, P("tp", None)))
    ks = jax.device_put(rec["kscale"], NamedSharding(mesh, P()))
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    out = jax.jit(
        lambda a, b, c: w8a8_matmul(a, {"qk": b, "kscale": c}))(xs, qk, ks)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-5, atol=1e-4)


def test_w8a8_tp_engine_decode_parity():
    """init_inference(tp=2/4, w8a8) decodes the same tokens as tp=1 w8a8
    on a 128-aligned quant-aware OPT (the driver dryrun asserts the same
    parity for the bf16 auto-TP path; this covers the quantized one).
    ``shard_multiple: 4`` pins the group refinement so every tp degree
    serves bit-identical weight records (hidden K=128 refines to g=32 —
    whole groups on every row-parallel shard)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import opt as opt_model
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    cfg = opt_model.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                              num_heads=2, hidden_size=128, ffn_size=512)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt_model.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 4), np.int32)
    outs = {}
    try:
        for tp in (1, 2, 4):
            deepspeed_tpu.comm.reset_topology()
            eng = deepspeed_tpu.init_inference(
                model=opt_model.build(cfg), params=params,
                config={"dtype": "float32",
                        "tensor_parallel": {"tp_size": tp},
                        "quant": {"enabled": True, "type": "w8a8",
                                  "shard_multiple": 4}})
            outs[tp] = eng.generate(ids, max_new_tokens=4)
    finally:
        # engine init set the module gates (kernel_ok=False at tp=2);
        # restore so later tests exercise the single-device kernel path
        qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
        deepspeed_tpu.comm.reset_topology()
    np.testing.assert_array_equal(outs[1], outs[2])
    np.testing.assert_array_equal(outs[1], outs[4])


def test_w8a8_tp_engine_mixed_gathered_parity():
    """``shard_multiple: 1`` pins g=128 so the hidden-K weights (o_w,
    K=128 -> ONE quant group) cannot be K-sharded at tp=4: the engine's
    kscale divisibility fallback replicates the scale tree and
    _w8a8_partition takes the gathered-but-correct lowering for those
    weights while the column-parallel ones stay sharded — the mixed-path
    parity the refined default no longer exercises."""
    import deepspeed_tpu
    from deepspeed_tpu.models import opt as opt_model
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    cfg = opt_model.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                              num_heads=2, hidden_size=128, ffn_size=512)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt_model.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 4), np.int32)
    outs = {}
    try:
        for tp in (1, 4):
            deepspeed_tpu.comm.reset_topology()
            eng = deepspeed_tpu.init_inference(
                model=opt_model.build(cfg), params=params,
                config={"dtype": "float32",
                        "tensor_parallel": {"tp_size": tp},
                        "quant": {"enabled": True, "type": "w8a8",
                                  "shard_multiple": 1}})
            # unrefined: o_w keeps ONE group (the gathered case at tp=4)
            assert eng.params["blocks"]["o_w"]["kscale"].shape[-3] == 1
            outs[tp] = eng.generate(ids, max_new_tokens=4)
    finally:
        qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
        deepspeed_tpu.comm.reset_topology()
    np.testing.assert_array_equal(outs[1], outs[4])


def test_w8a8_engine_spec_aware_refinement():
    """With shard_multiple DERIVED from tp (the default), only K-sharded
    (row-parallel) weights refine: o_w (K=128, P(None, tp, None)) splits
    into 4 groups of 32 so tp=4 shards hold whole groups; the
    column-parallel qkv_w keeps the g=128 cap (refining it would buy
    nothing and cost scale storage + kernel trip count)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import opt as opt_model
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    cfg = opt_model.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                              num_heads=2, hidden_size=128, ffn_size=512)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt_model.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    try:
        deepspeed_tpu.comm.reset_topology()
        eng = deepspeed_tpu.init_inference(
            model=opt_model.build(cfg), params=params,
            config={"dtype": "float32",
                    "tensor_parallel": {"tp_size": 4},
                    "quant": {"enabled": True, "type": "w8a8"}})
        blocks = eng.params["blocks"]
        assert blocks["o_w"]["kscale"].shape[-3] == 4      # g=32, K-sharded
        assert blocks["proj_w"]["kscale"].shape[-3] == 4   # K=512, g=128 ok
        assert blocks["qkv_w"]["kscale"].shape[-3] == 1    # column: cap
        out = eng.generate(np.ones((1, 4), np.int32), max_new_tokens=4)
        assert out.shape == (1, 8)
    finally:
        qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
        deepspeed_tpu.comm.reset_topology()


def test_pick_k_group_alignment():
    """pick_k_group refines groups so row-parallel shards hold whole
    groups: OPT-2.7B's K=2560 has 20 groups at the g=128 cap (20 % 8 != 0
    -> would gather at tp=8); g=80 gives 32 groups and stays sharded."""
    assert quant.pick_k_group(2560, 128) == 128
    assert quant.pick_k_group(2560, 128, shard_multiple=8) == 80
    # already aligned: keep the cap
    assert quant.pick_k_group(4096, 128, shard_multiple=8) == 128
    # K=384: 3 groups at 128; tp=2 needs an even count -> g=96 (4 groups)
    assert quant.pick_k_group(384, 128, shard_multiple=2) == 96
    # K not divisible by the shard degree: no K sharding is possible
    # anyway, so no refinement constraint applies
    assert quant.pick_k_group(384, 128, shard_multiple=7) == 128
    # nothing admissible (odd K)
    assert quant.pick_k_group(2050, 128) == 0


def test_w8a8_tp_refined_groups_stay_sharded(_w8a8_tp, monkeypatch):
    """A K=384 weight refined to g=96 (shard_multiple=2) runs the ROW-
    PARALLEL sharded lowering — no gathered-fallback warning — and matches
    the unsharded kernel."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.ops import quantized_matmul as qmm_mod
    from deepspeed_tpu.utils import logging as ds_logging

    gathered = []
    monkeypatch.setattr(ds_logging, "warning_once",
                        lambda msg, *a, **k: gathered.append(msg))
    g = quant.pick_k_group(384, 128, shard_multiple=2)
    assert g == 96
    x, rec = _mk_k_grouped(384, 256, g, rows=2)
    qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
    ref = qmm_mod.w8a8_matmul(x, rec)
    qmm_mod.configure(kernel_ok=True, w8a8_tp=True)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    qk = jax.device_put(rec["qk"], NamedSharding(mesh, P("tp", None)))
    ks = jax.device_put(rec["kscale"], NamedSharding(mesh, P("tp", None, None)))
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    out = jax.jit(
        lambda a, b, c: qmm_mod.w8a8_matmul(a, {"qk": b, "kscale": c})
    )(xs, qk, ks)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-5, atol=1e-4)
    assert not [m for m in gathered if "GATHERED" in m], gathered


def test_quantize_k_grouped_host_chunked_matches_jnp(monkeypatch):
    """The chunked numpy path (multi-billion host trees: bounds the
    transient that OOM-killed a 125GB host on OPT-13B) must produce the
    records of the jnp path bit-for-bit, without mutating the input."""
    monkeypatch.setattr(quant, "_HOST_QUANT_CHUNK_BYTES", 1024)
    rng = np.random.default_rng(3)
    w = rng.normal(size=(3, 64, 128)).astype(np.float32)
    w_orig = w.copy()
    rec_np = quant.quantize_k_grouped(w, k_group=32)       # numpy path
    rec_jnp = quant.quantize_k_grouped(jnp.asarray(w), k_group=32)
    assert isinstance(rec_np["qk"], np.ndarray)
    np.testing.assert_array_equal(w, w_orig)
    np.testing.assert_array_equal(rec_np["qk"], np.asarray(rec_jnp["qk"]))
    np.testing.assert_array_equal(rec_np["kscale"],
                                  np.asarray(rec_jnp["kscale"]))
    # bf16 host leaves (the engine casts before quantizing) also go
    # through the numpy path via ml_dtypes
    wb = np.asarray(jax.device_get(jnp.asarray(w, jnp.bfloat16)))
    rec_b = quant.quantize_k_grouped(wb, k_group=32)
    rec_bj = quant.quantize_k_grouped(jnp.asarray(wb), k_group=32)
    np.testing.assert_array_equal(rec_b["qk"], np.asarray(rec_bj["qk"]))


def test_quantize_pytree_k_grouped_shard_multiple():
    """Leaf SELECTION is shard_multiple-independent (every tp degree
    quantizes the same leaves); only the group size refines."""
    tree = {"w": jnp.ones((2560, 128)), "odd": jnp.ones((100, 128))}
    base = quant.quantize_pytree_k_grouped(tree, k_group=128)
    ref8 = quant.quantize_pytree_k_grouped(tree, k_group=128,
                                           shard_multiple=8)
    assert quant.is_k_quantized(base["w"]) and quant.is_k_quantized(ref8["w"])
    assert base["w"]["kscale"].shape[0] == 20    # g=128
    assert ref8["w"]["kscale"].shape[0] == 32    # g=80: 32 % 8 == 0
    # ineligible leaf stays dense under every shard_multiple
    assert not quant.is_k_quantized(base["odd"])
    assert not quant.is_k_quantized(ref8["odd"])


def test_w8a8_stacked_matches_per_layer():
    """The stacked (scalar-prefetch layer index) kernel returns EXACTLY the
    per-layer kernel's result for every layer, including traced indices."""
    from deepspeed_tpu.ops.quantized_matmul import (w8a8_matmul,
                                                    w8a8_matmul_stacked)

    rng = np.random.default_rng(3)
    L, K, N, G = 3, 512, 256, 128
    w = jnp.asarray(rng.standard_normal((L, K, N)), jnp.float32) * 0.05
    rec = quant.quantize_k_grouped(w, k_group=G)
    x = jnp.asarray(rng.standard_normal((1, K)), jnp.bfloat16)
    for l in range(L):
        layer = {"qk": rec["qk"][l], "kscale": rec["kscale"][l]}
        a = np.asarray(w8a8_matmul(x, layer, out_dtype=jnp.float32))
        b = np.asarray(w8a8_matmul_stacked(x, rec, jnp.int32(l),
                                           out_dtype=jnp.float32))
        np.testing.assert_array_equal(a, b)

    def body(l, acc):
        return acc + w8a8_matmul_stacked(x, rec, l, out_dtype=jnp.float32)

    tot = np.asarray(jax.lax.fori_loop(0, L, body,
                                       jnp.zeros((1, N), jnp.float32)))
    want = sum(np.asarray(w8a8_matmul(
        x, {"qk": rec["qk"][l], "kscale": rec["kscale"][l]},
        out_dtype=jnp.float32)) for l in range(L))
    np.testing.assert_allclose(tot, want, rtol=1e-5, atol=1e-5)


def test_w8a8_stacked_ineligible_falls_back():
    """Off-lane N and TP mode route the stacked call to the sliced-layer
    path (same math, no kernel)."""
    from deepspeed_tpu.ops import quantized_matmul as qmm

    rng = np.random.default_rng(4)
    L, K, N = 2, 256, 96          # N % 128 != 0 -> ineligible
    w = jnp.asarray(rng.standard_normal((L, K, N)), jnp.float32)
    rec = quant.quantize_k_grouped(w, k_group=128)
    x = jnp.asarray(rng.standard_normal((1, K)), jnp.float32)
    out = np.asarray(qmm.w8a8_matmul_stacked(x, rec, 1))
    ref = np.asarray(x @ quant.dequantize_k(
        {"qk": rec["qk"][1], "kscale": rec["kscale"][1]}, x.dtype))
    np.testing.assert_allclose(out, ref, rtol=5e-2, atol=5e-1)


def _tiny_model(family):
    if family == "opt":
        from deepspeed_tpu.models import opt as m

        cfg = m.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                          num_heads=4, hidden_size=128, ffn_size=512)
    elif family == "gpt2":
        from deepspeed_tpu.models import gpt2 as m

        cfg = m.GPT2Config(vocab_size=512, max_seq_len=64, num_layers=2,
                           num_heads=4, hidden_size=128, remat=False)
    elif family == "bloom":
        from deepspeed_tpu.models import bloom as m

        cfg = m.BloomConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                            num_heads=4, hidden_size=128)
    elif family == "gptj":
        from deepspeed_tpu.models import gptj as m

        cfg = m.GPTJConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                           num_heads=4, hidden_size=128, rotary_dim=16)
    elif family == "gptneox":
        from deepspeed_tpu.models import gptneox as m

        cfg = m.GPTNeoXConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                              num_heads=4, hidden_size=128)
    elif family == "gptneo":
        from deepspeed_tpu.models import gptneo as m

        cfg = m.GPTNeoConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                             num_heads=4, hidden_size=128, window_size=16)
    else:
        raise ValueError(family)
    return m, cfg


@pytest.mark.parametrize("family", ["opt", "gpt2", "bloom", "gptj",
                                    "gptneox"])
def test_indexed_decode_matches_scan_path(family, monkeypatch):
    """forward_cached's layer-indexed loop (quantized serving) produces the
    same tokens as the scan path (DS_INDEXED_DECODE=0 kill switch) over the
    same quantized records — the dispatch is shared (gpt2.decode_over_layers)
    so every quant-aware family goes through it."""
    import deepspeed_tpu

    m, cfg = _tiny_model(family)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = m.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 6), dtype=np.int32)
    qcfg = {"dtype": "float32", "quant": {"enabled": True, "type": "w8a8"}}

    monkeypatch.setenv("DS_INDEXED_DECODE", "1")  # ambient =0 would make
    deepspeed_tpu.comm.reset_topology()           # this test vacuous
    eng = deepspeed_tpu.init_inference(model=m.build(cfg), params=params,
                                       config=qcfg)
    tok_indexed = np.asarray(eng.generate(ids, max_new_tokens=8))

    monkeypatch.setenv("DS_INDEXED_DECODE", "0")
    deepspeed_tpu.comm.reset_topology()
    eng2 = deepspeed_tpu.init_inference(model=m.build(cfg), params=params,
                                        config=qcfg)
    tok_scan = np.asarray(eng2.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(tok_indexed, tok_scan)


def test_indexed_decode_gate_respects_kernel_state(monkeypatch):
    """use_indexed_decode is False whenever the stacked kernel would fall
    back (TP mode, kernel off, DS_W8A8=0, unquantized blocks) — the indexed
    loop must not run without its benefit."""
    from deepspeed_tpu.models.gpt2 import use_indexed_decode
    from deepspeed_tpu.ops import quantized_matmul as qmm
    from deepspeed_tpu.ops import quantization as quant

    w = jnp.ones((2, 256, 128), jnp.float32)
    blocks = {"qkv_w": quant.quantize_k_grouped(w, k_group=128)}
    monkeypatch.setenv("DS_INDEXED_DECODE", "1")
    monkeypatch.setenv("DS_W8A8", "1")

    try:
        qmm.configure(kernel_ok=True, w8a8_tp=False)
        assert use_indexed_decode(blocks)
        qmm.configure(kernel_ok=True, w8a8_tp=True)    # TP serving
        assert not use_indexed_decode(blocks)
        qmm.configure(kernel_ok=False, w8a8_tp=False)  # kernel unavailable
        assert not use_indexed_decode(blocks)
        qmm.configure(kernel_ok=True, w8a8_tp=False)
        monkeypatch.setenv("DS_W8A8", "0")             # w8a8 disabled
        assert not use_indexed_decode(blocks)
        monkeypatch.setenv("DS_W8A8", "1")
        assert not use_indexed_decode({"qkv_w": w})    # dense blocks
        assert use_indexed_decode(blocks, rows=8)      # batched decode
        assert not use_indexed_decode(blocks, rows=9)  # prefill/big batch
        monkeypatch.setenv("DS_INDEXED_DECODE", "0")   # kill switch
        assert not use_indexed_decode(blocks)
    finally:
        # module-global kernel state: a failed assert must not leak TP
        # mode into later tests
        qmm.configure(kernel_ok=True, w8a8_tp=False)


def test_llama_w8a8_serving(monkeypatch):
    """Llama is quant-aware (round 4): w8a8 serving decodes through the
    stacked-kernel indexed path with token parity vs the scan kill switch,
    and logits track the dense model."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=512, max_seq_len=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, hidden_size=128,
                            ffn_size=256, rope_theta=10000.0, remat=False)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = llama.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 6), dtype=np.int32)

    deepspeed_tpu.comm.reset_topology()
    ref_eng = deepspeed_tpu.init_inference(
        model=llama.build(cfg), params=params, config={"dtype": "float32"})
    ref_tok = np.asarray(ref_eng.generate(ids, max_new_tokens=8))
    ref_logits = np.asarray(ref_eng.forward({"input_ids": ids}))

    qcfg = {"dtype": "float32", "quant": {"enabled": True, "type": "w8a8"}}
    monkeypatch.setenv("DS_INDEXED_DECODE", "1")
    deepspeed_tpu.comm.reset_topology()
    eng = deepspeed_tpu.init_inference(model=llama.build(cfg),
                                       params=params, config=qcfg)
    from deepspeed_tpu.ops import quantization as q
    recs = [x for x in jax.tree_util.tree_leaves(
        eng.params, is_leaf=q.is_k_quantized) if q.is_k_quantized(x)]
    assert recs, "llama w8a8 quantization produced no K-grouped records"
    tok = np.asarray(eng.generate(ids, max_new_tokens=8))
    logits = np.asarray(eng.forward({"input_ids": ids}))
    np.testing.assert_allclose(logits, ref_logits, rtol=2e-1, atol=2e-1)
    assert (tok == ref_tok).mean() >= 0.75, (tok, ref_tok)

    monkeypatch.setenv("DS_INDEXED_DECODE", "0")
    deepspeed_tpu.comm.reset_topology()
    eng2 = deepspeed_tpu.init_inference(model=llama.build(cfg),
                                        params=params, config=qcfg)
    tok_scan = np.asarray(eng2.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(tok, tok_scan)


@pytest.mark.parametrize("family", ["bloom", "gptj", "gptneox", "gptneo"])
def test_w8a8_serving_new_families(family, monkeypatch):
    """Round-4 quant-aware families: w8a8 serving decodes with logits
    tracking the dense model and mostly-agreeing greedy tokens (bloom/
    gptj/gptneox ride the shared indexed dispatch; gptneo's static
    local/global loop uses per-layer records)."""
    import deepspeed_tpu

    m, cfg = _tiny_model(family)
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = m.build(cfg).init_fn(jax.random.PRNGKey(0))
    params = jax.device_get(params)
    ids = np.ones((1, 6), dtype=np.int32)

    deepspeed_tpu.comm.reset_topology()
    ref_eng = deepspeed_tpu.init_inference(
        model=m.build(cfg), params=params, config={"dtype": "float32"})
    ref_tok = np.asarray(ref_eng.generate(ids, max_new_tokens=8))
    ref_logits = np.asarray(ref_eng.forward({"input_ids": ids}))

    monkeypatch.setenv("DS_INDEXED_DECODE", "1")
    deepspeed_tpu.comm.reset_topology()
    eng = deepspeed_tpu.init_inference(
        model=m.build(cfg), params=params,
        config={"dtype": "float32",
                "quant": {"enabled": True, "type": "w8a8"}})
    recs = [x for x in jax.tree_util.tree_leaves(
        eng.params, is_leaf=quant.is_k_quantized)
        if quant.is_k_quantized(x)]
    assert recs, f"{family}: w8a8 produced no K-grouped records"
    tok = np.asarray(eng.generate(ids, max_new_tokens=8))
    logits = np.asarray(eng.forward({"input_ids": ids}))
    np.testing.assert_allclose(logits, ref_logits, rtol=2e-1, atol=2e-1)
    assert (tok == ref_tok).mean() >= 0.75, (tok, ref_tok)
