"""Dynamic loss scaler tests (model: reference test_dynamic_loss_scale.py)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.fp16.loss_scaler import (LossScaleState, has_overflow,
                                                    update_scale)


def test_has_overflow():
    good = {"a": jnp.ones(3), "b": jnp.zeros(2)}
    assert not bool(has_overflow(good))
    bad = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.zeros(2)}
    assert bool(has_overflow(bad))
    inf = {"a": jnp.array([jnp.inf])}
    assert bool(has_overflow(inf))


def test_scale_halves_on_overflow_after_hysteresis():
    s = LossScaleState.create(init_scale=256.0, delayed_shift=2)
    # first overflow burns hysteresis, scale unchanged
    s = update_scale(s, jnp.asarray(True), delayed_shift=2)
    assert float(s.cur_scale) == 256.0
    assert int(s.skipped) == 1
    # second overflow halves
    s = update_scale(s, jnp.asarray(True), delayed_shift=2)
    assert float(s.cur_scale) == 128.0


def test_scale_doubles_after_window():
    s = LossScaleState.create(init_scale=4.0, delayed_shift=1)
    for i in range(10):
        s = update_scale(s, jnp.asarray(False), scale_window=10)
    assert float(s.cur_scale) == 8.0
    assert int(s.good_steps) == 10


def test_min_scale_floor():
    s = LossScaleState.create(init_scale=2.0, delayed_shift=1)
    for _ in range(5):
        s = update_scale(s, jnp.asarray(True), min_scale=1.0, delayed_shift=1)
    assert float(s.cur_scale) == 1.0


def test_static_mode():
    s = LossScaleState.create(init_scale=64.0)
    s2 = update_scale(s, jnp.asarray(True), dynamic=False)
    assert float(s2.cur_scale) == 64.0
    assert int(s2.skipped) == 1
