"""OPT model family + auto-TP + HF weight ingestion tests.

Models the reference's inference sweep (``tests/unit/inference/test_inference.py``
compares injected models against vanilla HF pipeline output) and checkpoint
sharding tests (``test_checkpoint_sharding.py``): here the ground truth is the
HF torch OPT implementation run on CPU with the same randomly-initialized
weights — no downloads needed.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import opt

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_opt(**over):
    kw = dict(vocab_size=96, hidden_size=32, ffn_dim=128,
              num_hidden_layers=2, num_attention_heads=4,
              max_position_embeddings=64, do_layer_norm_before=True,
              word_embed_proj_dim=32, dropout=0.0, pad_token_id=1)
    kw.update(over)
    cfg = transformers.OPTConfig(**kw)
    with torch.no_grad():
        model = transformers.OPTForCausalLM(cfg)
    model.eval()
    return model


def _hf_logits(model, ids):
    with torch.no_grad():
        return model(torch.tensor(ids)).logits.numpy()


@pytest.mark.parametrize("pre_ln", [True, False])
def test_opt_matches_hf(pre_ln):
    """Logit parity with the HF torch implementation (both LN orders)."""
    hf = _tiny_hf_opt(do_layer_norm_before=pre_ln)
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(0).integers(2, 96, (2, 10)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    theirs = _hf_logits(hf, ids)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


def test_opt_350m_style_projection():
    """word_embed_proj_dim != hidden_size exercises project_in/out."""
    hf = _tiny_hf_opt(word_embed_proj_dim=16, do_layer_norm_before=False)
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = (2 + np.arange(8, dtype=np.int32))[None, :] % 96
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    theirs = _hf_logits(hf, ids)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


def test_opt_kv_cache_decode_matches_forward():
    """Cached incremental decode equals full forward at every position."""
    import jax

    cfg = opt.OPTConfig.tiny()
    params = opt.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 12)).astype(np.int32)
    full = np.asarray(opt.forward(cfg, params, ids, train=False))

    cache = opt.init_cache(cfg, 2, 64, dtype=np.float32)
    logits, cache = opt.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=1e-4)
    for t in range(8, 12):
        logits, cache = opt.forward_cached(cfg, params, ids[:, t:t + 1],
                                           cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-4)


def test_opt_trains():
    """OPT works as a training model through the engine (loss decreases)."""
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=opt.build(opt.OPTConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, size=(engine.train_batch_size(), 16)).astype(np.int32)}
    losses = [float(engine.train_batch(batch)[1]["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_init_inference_accepts_hf_model():
    """init_inference ingests a torch HF model directly (auto injection)."""
    deepspeed_tpu.comm.reset_topology()
    hf = _tiny_hf_opt()
    engine = deepspeed_tpu.init_inference(model=hf,
                                          config={"dtype": "float32"})
    ids = np.full((1, 4), 7, np.int32)  # not the pad token: HF masks pads
    out = engine.generate(ids, max_new_tokens=3)
    assert out.shape == (1, 7)
    # greedy continuation matches HF greedy
    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(ids), max_new_tokens=3,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(out, hf_out)


def test_generate_sampling_paths():
    deepspeed_tpu.comm.reset_topology()
    spec = opt.build(opt.OPTConfig.tiny())
    engine = deepspeed_tpu.init_inference(model=spec,
                                          config={"dtype": "float32"})
    ids = np.ones((2, 4), np.int32)
    out = engine.generate(ids, max_new_tokens=4, do_sample=True,
                          temperature=0.8, top_k=50, top_p=0.9, seed=7)
    assert out.shape == (2, 8)
    out2 = engine.generate(ids, max_new_tokens=4, do_sample=True,
                           temperature=0.8, top_k=50, top_p=0.9, seed=7)
    np.testing.assert_array_equal(out, out2)  # same seed -> same draw


def test_opt_tp_sharded_forward_parity(eight_devices):
    """TP=2-sharded OPT produces the same logits as unsharded."""
    deepspeed_tpu.comm.reset_topology()
    hf = _tiny_hf_opt()
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.ones((2, 8), np.int32)
    ref = np.asarray(spec.apply_fn(params, {"input_ids": ids}))

    engine = deepspeed_tpu.init_inference(
        model=spec, params=params,
        config={"dtype": "float32", "tensor_parallel": {"tp_size": 2}})
    got = np.asarray(engine.forward({"input_ids": ids}))
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_auto_tp_agrees_with_handwritten_rules():
    """Generic inference (auto_tp) reproduces the hand-written OPT specs."""
    import jax

    cfg = opt.OPTConfig.tiny()
    params = opt.init_params(cfg, jax.random.PRNGKey(0))
    inferred = deepspeed_tpu.module_inject.infer_tp_specs(params)
    manual = opt.tp_rules(cfg, params)
    flat_i = jax.tree_util.tree_leaves_with_path(inferred,
                                                 is_leaf=lambda x: x is None)
    assert jax.tree_util.tree_structure(inferred) == \
        jax.tree_util.tree_structure(manual)
    for (pi, si), (pm, sm) in zip(
            jax.tree_util.tree_flatten_with_path(inferred)[0],
            jax.tree_util.tree_flatten_with_path(manual)[0]):
        assert si == sm, f"{pi}: auto {si} != manual {sm}"


def test_auto_tp_generic_pytree():
    """auto_tp classifies an unseen (HF-llama-style) pytree sensibly."""
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    params = {
        "model": {
            "embed_tokens": {"weight": jnp.zeros((128, 16))},
            "layers_0": {
                "self_attn": {
                    "q_proj": {"weight": jnp.zeros((16, 16))},
                    "o_proj": {"weight": jnp.zeros((16, 16))},
                },
                "mlp": {
                    "up_proj": {"weight": jnp.zeros((16, 64))},
                    "down_proj": {"weight": jnp.zeros((64, 16))},
                },
                "input_layernorm": {"weight": jnp.zeros((16,))},
            },
        },
    }
    specs = deepspeed_tpu.module_inject.infer_tp_specs(params)
    m = specs["model"]
    assert m["embed_tokens"]["weight"] == P("tp", None)
    assert m["layers_0"]["self_attn"]["q_proj"]["weight"] == P(None, "tp")
    assert m["layers_0"]["self_attn"]["o_proj"]["weight"] == P("tp", None)
    assert m["layers_0"]["mlp"]["up_proj"]["weight"] == P(None, "tp")
    assert m["layers_0"]["mlp"]["down_proj"]["weight"] == P("tp", None)
    assert m["layers_0"]["input_layernorm"]["weight"] == P()


def test_state_dict_factory_loads_hf_dir(tmp_path):
    """load_hf_weights ingests an on-disk HF checkpoint directory."""
    hf = _tiny_hf_opt()
    hf.save_pretrained(tmp_path, safe_serialization=False)
    from deepspeed_tpu.runtime.state_dict_factory import load_hf_weights

    spec, params = load_hf_weights(str(tmp_path))
    ids = np.ones((1, 6), np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    np.testing.assert_allclose(ours, _hf_logits(hf, ids), atol=2e-4,
                               rtol=2e-3)


def test_merge_split_tp_shards():
    from deepspeed_tpu.runtime.state_dict_factory import (
        merge_qkv_shards, merge_tp_shards, split_tp_shard)

    full = np.arange(24, dtype=np.float32).reshape(4, 6)
    shards = split_tp_shard(full, dim=1, ranks=2)
    np.testing.assert_array_equal(merge_tp_shards(shards, dim=1), full)

    # fused qkv: ranks hold [q_r;k_r;v_r] — plain concat would interleave
    q = np.arange(12).reshape(2, 6); k = q + 100; v = q + 200
    fused = np.concatenate([q, k, v], axis=1)  # [2, 18]
    rank_shards = [
        np.concatenate([q[:, :3], k[:, :3], v[:, :3]], axis=1),
        np.concatenate([q[:, 3:], k[:, 3:], v[:, 3:]], axis=1),
    ]
    np.testing.assert_array_equal(merge_qkv_shards(rank_shards, dim=1), fused)
