"""ZeRO-Infinity param-streaming tests (reference posture:
``tests/unit/runtime/zero`` offload matrix — here the ground truth is the
optimizer-offload engine with device-resident params)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


def _cfg(stream: bool, **over):
    zero = {"stage": 0,
            "offload_optimizer": {"device": "cpu"}}
    if stream:
        zero["offload_param"] = {"device": "cpu"}
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zero,
    }
    cfg.update(over)
    return cfg


def _run(config, steps=4, seed=0):
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()), config=config)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(
            0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}
        _, m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    return engine, losses


def test_streamed_grads_match_autodiff():
    """The streamed block vjp (host round-trip) reproduces autodiff grads to
    float rounding — the rigorous correctness check."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.zero.param_stream import StreamedParamStore

    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    spec = gpt2.build(cfg)
    hooks = spec.pipeline_hooks
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 512, (2, 33)).astype(np.int32)}

    ref_grads = jax.grad(lambda p: spec.loss_fn(p, batch, None, True))(params)

    store = StreamedParamStore(params["blocks"], jnp.float32)
    blk = store.streamed_block(lambda layer, x: hooks["block_fn"](layer, x,
                                                                  None))
    resident = dict(params)
    resident["blocks"] = {}

    def loss_fn(p):
        ids = batch["input_ids"]
        inputs, targets = ids[:, :-1], ids[:, 1:]
        x = hooks["embed_fn"](p, inputs)
        x, _ = jax.lax.scan(lambda x, i: (blk(i, x), None), x,
                            jnp.arange(cfg.num_layers))
        return hooks["head_loss_fn"](p, x, targets)

    loss, res_grads = jax.jit(jax.value_and_grad(loss_fn))(resident)
    loss.block_until_ready()
    block_grads = store.pop_grads()
    for gr, gs in zip(jax.tree_util.tree_leaves(ref_grads["blocks"]),
                      block_grads):
        np.testing.assert_allclose(np.asarray(gr), gs, atol=2e-6)
    for k in ("wte", "wpe", "lnf_scale", "lnf_bias"):
        np.testing.assert_allclose(np.asarray(ref_grads[k]),
                                   np.asarray(res_grads[k]), atol=2e-6)


def test_streamed_matches_resident_offload():
    """Loss trajectories agree with the device-resident offload baseline
    (loosely: the two computation graphs differ in op order, and Adam
    amplifies f32 rounding over steps — exact grad parity is asserted by
    test_streamed_grads_match_autodiff)."""
    _, base = _run(_cfg(stream=False))
    engine, stream = _run(_cfg(stream=True))
    np.testing.assert_allclose(stream, base, atol=8e-3)
    # device state holds no blocks — they live in the host store
    assert engine.state["params"]["blocks"] == {}
    assert engine._param_store.num_layers == 2


def test_streamed_with_clipping_matches():
    _, base = _run(_cfg(stream=False, gradient_clipping=0.1))
    _, stream = _run(_cfg(stream=True, gradient_clipping=0.1))
    np.testing.assert_allclose(stream, base, atol=8e-3)


def test_streamed_checkpoint_roundtrip(tmp_path):
    engine, _ = _run(_cfg(stream=True), steps=2)
    engine.save_checkpoint(str(tmp_path / "ck"))
    master_before = [m.copy() for m in engine._param_store.master]

    engine2, _ = _run(_cfg(stream=True), steps=1, seed=9)
    engine2.load_checkpoint(str(tmp_path / "ck"))
    for a, b in zip(master_before, engine2._param_store.master):
        np.testing.assert_allclose(a, b, rtol=1e-7)
    # training continues from the restored masters
    rng = np.random.default_rng(3)
    batch = {"input_ids": rng.integers(
        0, 512, size=(engine2.train_batch_size(), 33)).astype(np.int32)}
    _, m = engine2.train_batch(batch)
    assert np.isfinite(m["loss"])


def test_streamed_requires_offload_optimizer():
    deepspeed_tpu.comm.reset_topology()
    with pytest.raises(ValueError, match="offload_param requires"):
        deepspeed_tpu.initialize(
            model=gpt2.build(gpt2.GPT2Config.tiny()),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 0, "offload_param": {"device": "cpu"}},
            })
