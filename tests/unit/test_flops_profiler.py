"""Flops profiler tests (reference ``tests/unit/profiling/flops_profiler``)."""

import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    get_model_profile)


def _engine(extra=None):
    deepspeed_tpu.comm.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gpt2.build(gpt2.GPT2Config.tiny()), config=cfg)
    return engine


def _batch(engine):
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(
        0, 512, size=(engine.train_batch_size(), 33)).astype(np.int32)}


def test_engine_profile_step(tmp_path):
    out = tmp_path / "profile.txt"
    engine = _engine({"flops_profiler": {
        "enabled": True, "profile_step": 2, "output_file": str(out)}})
    for _ in range(2):
        engine.train_batch(_batch(engine))
    prof = engine.flops_profiler.profile
    assert prof["params"] > 0.1e6
    assert prof["step_flops"] > 1e6  # tiny model, but real flops
    assert prof["step_latency_s"] > 0
    mods = prof["modules"]
    assert mods["transformer_block"]["count"] == 2
    assert mods["transformer_block"]["flops"] > 0
    assert mods["head_loss"]["flops"] > 0
    text = out.read_text()
    assert "Flops Profiler" in text and "transformer_block" in text


def test_profile_counts_scale_with_depth():
    """4 layers ~2x the block flops of 2 layers; total step flops grow."""
    def step_flops(layers):
        deepspeed_tpu.comm.reset_topology()
        cfg = gpt2.GPT2Config.tiny()
        cfg.num_layers = layers
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=gpt2.build(cfg),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        prof = FlopsProfiler(engine=engine)
        return prof.profile_engine_step(_batch(engine))

    p2, p4 = step_flops(2), step_flops(4)
    b2, b4 = p2["modules"]["transformer_block"], \
        p4["modules"]["transformer_block"]
    assert b4["count"] == 4 and b2["count"] == 2
    # per-block flops identical; totals scale with depth
    np.testing.assert_allclose(b4["flops"], b2["flops"], rtol=1e-6)
    assert p4["step_flops"] > p2["step_flops"]


def test_get_model_profile_standalone():
    spec = gpt2.build(gpt2.GPT2Config.tiny())
    batch = {"input_ids": np.zeros((2, 17), np.int32)}
    prof = get_model_profile(spec, batch)
    assert prof["params"] > 0.1e6
    assert prof["flops"] > 0
    assert prof["macs"] == prof["flops"] / 2
