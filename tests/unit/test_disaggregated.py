"""Disaggregated prefill/decode serving + NVMe third KV tier (ISSUE 17).

Covers: ``plan_roles`` fleet planning, role-aware routing with token
parity against the colocated twin, the ``role="both"`` +
``nvme_blocks=0`` bit-identity guarantee, the ``serve()`` guard on
dedicated roles, NVMe spill/promote with zero-prefix-recompute session
resume, spill-file lifecycle (tempfile mint/cleanup vs operator-owned
path), the three-tier residency audit (green on live spilled state,
loud on crafted violations), and the new telemetry surface (handoff /
nvme timeline events, tier-labeled swap counters).
"""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.invariants import (PagedStateError,
                                               audit_host_store,
                                               audit_router)
from deepspeed_tpu.inference.paged import (HostBlockStore, NvmeBlockStore,
                                           block_checksum)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.serving import ReplicaRouter, plan_roles


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny():
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    spec = gpt2.build(cfg)
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    return spec, cfg, engine


_SRV_KW = dict(slots=3, max_seq_len=64, block_size=8, prefill_chunk=16,
               prefill_batch=2, debug_checks=True)


def _mk_srv(spec, params, **kw):
    merged = dict(_SRV_KW, host_blocks=32, swap_batch=4)
    merged.update(kw)
    engine = deepspeed_tpu.init_inference(
        spec, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}},
        params=params)
    return ServingEngine(engine, **merged)


def _trace(cfg, n=8, seed=0, prompt_len=24, max_new=8):
    rng = np.random.default_rng(seed)
    return [Request(uid=i,
                    prompt=rng.integers(0, cfg.vocab_size, prompt_len),
                    max_new_tokens=max_new) for i in range(n)]


def _sequential(engine, reqs):
    return {r.uid: engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            for r in reqs}


def _run(router, reqs):
    handles = [router.submit(r) for r in reqs]
    while router.step():
        pass
    return {r.uid: np.asarray(h.result(timeout=0))
            for r, h in zip(reqs, handles)}


# -------------------------------------------------------------- plan_roles
def test_plan_roles_assignment_and_validation():
    assert plan_roles(3) == ["both"] * 3
    assert plan_roles(3, 0) == ["both"] * 3
    assert plan_roles(3, 1) == ["prefill", "decode", "decode"]
    assert plan_roles(4, 3) == ["prefill"] * 3 + ["decode"]
    with pytest.raises(ValueError,
                       match="prefill_workers:decode_workers ratio"):
        plan_roles(2, 2)
    with pytest.raises(ValueError, match="ratio"):
        plan_roles(1, 1)
    with pytest.raises(ValueError, match="prefill_workers"):
        plan_roles(2, -1)
    with pytest.raises(ValueError, match="replicas"):
        plan_roles(0)


def test_prefill_first_keeps_decode_ids_stable():
    """Growing the prefill pool must not re-role existing decode ids'
    tail positions: decode workers (long-lived session KV) stay decode."""
    assert plan_roles(4, 1)[-2:] == ["decode", "decode"]
    assert plan_roles(4, 2)[-2:] == ["decode", "decode"]


# -------------------------------------------------- role-aware scheduling
def test_disaggregated_token_parity_and_handoffs(tiny):
    """The tentpole acceptance path: a 1 prefill + 1 decode fleet serves
    a trace token-identically to the colocated 2x"both" twin; every
    request crosses exactly one handoff; both sides' timelines record
    it; the audit stays green throughout (debug_checks on)."""
    spec, cfg, engine = tiny
    reqs = _trace(cfg, n=8)
    seq = _sequential(engine, reqs)

    colo = ReplicaRouter([_mk_srv(spec, engine.params) for _ in range(2)],
                         debug_checks=True)
    ref = _run(colo, reqs)
    for r in reqs:
        np.testing.assert_array_equal(ref[r.uid], seq[r.uid])

    dis = ReplicaRouter(
        [_mk_srv(spec, engine.params, role=r)
         for r in ("prefill", "decode")], debug_checks=True)
    out = _run(dis, reqs)
    for r in reqs:
        np.testing.assert_array_equal(out[r.uid], seq[r.uid],
                                      err_msg=f"uid {r.uid}")
    st = dis.stats()
    assert st["handoffs"] == len(reqs)
    assert [p["role"] for p in st["per_replica"]] == ["prefill", "decode"]
    assert st["requests_failed"] == 0
    # timeline: the router and the prefill engine both record handoffs
    assert any(e["name"] == "handoff" for e in dis.timeline.events())
    assert any(e["name"] == "handoff"
               for e in dis.replicas[0].timeline.events())
    # the prefill engine's own counter agrees
    assert dis.replicas[0].stats()["handoffs"] == len(reqs)
    audit_router(dis)


def test_decode_worker_never_prefills_prompts(tiny):
    """TPOT isolation, structurally: the decode worker's recompute is
    bounded by each handoff's sub-block tail — it never re-runs a
    prompt's prefill (the prefill worker's prompt_tokens carries the
    whole trace; the decode worker's recompute stays < block_size per
    admission)."""
    spec, cfg, engine = tiny
    reqs = _trace(cfg, n=6, prompt_len=31)
    dis = ReplicaRouter(
        [_mk_srv(spec, engine.params, role=r)
         for r in ("prefill", "decode")], debug_checks=True)
    _run(dis, reqs)
    pre, dec = dis.replicas
    assert pre.stats()["prompt_tokens"] == sum(len(r.prompt) for r in reqs)
    ds = dec.stats()
    assert ds["admitted"] == len(reqs)
    assert ds["resume_recompute_tokens"] <= ds["admitted"] * dec.block_size
    assert ds["prefix_hit_tokens"] > 0     # the chain pull did the work


def test_role_both_and_nvme_off_bit_identical(tiny):
    """Acceptance gate: explicit ``role="both"``, ``nvme_blocks=0``
    serves bit-identically to an engine built without the PR 17 knobs —
    same tokens, same swap counters, same compile budget — and the new
    stats keys idle at their zeros."""
    spec, cfg, engine = tiny
    reqs = _trace(cfg, n=6)
    base = _mk_srv(spec, engine.params)
    new = _mk_srv(spec, engine.params, role="both", nvme_blocks=0,
                  nvme_high_watermark=0.9, nvme_path=None)
    out_b, out_n = base.serve(reqs), new.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(out_b[r.uid], out_n[r.uid])
    sb, sn = base.stats(), new.stats()
    for k in ("swap_out", "swap_in", "swap_bytes", "compile_budget",
              "iterations", "generated_tokens", "prefix_hit_tokens"):
        assert sb[k] == sn[k], k
    assert sn["role"] == "both" and sn["handoffs"] == 0
    assert sn["nvme_blocks"] == 0 and sn["nvme_blocks_in_use"] == 0
    assert sn["nvme_spills"] == 0 and sn["nvme_loads"] == 0
    assert new.nvme_path is None


def test_serve_refuses_dedicated_roles(tiny):
    spec, cfg, engine = tiny
    srv = _mk_srv(spec, engine.params, role="prefill")
    with pytest.raises(RuntimeError, match="ReplicaRouter"):
        srv.serve(_trace(cfg, n=1))


def test_role_validation_is_loud(tiny):
    spec, cfg, engine = tiny
    with pytest.raises(ValueError, match="role"):
        _mk_srv(spec, engine.params, role="sideways")
    with pytest.raises(ValueError, match="host_blocks"):
        _mk_srv(spec, engine.params, role="decode", host_blocks=0)


# --------------------------------------------------------- nvme third tier
_NVME_KW = dict(slots=2, num_blocks=12, host_blocks=8, swap_batch=2,
                nvme_blocks=32, nvme_high_watermark=0.5)


def test_nvme_session_resume_zero_prefix_recompute(tiny):
    """A session whose prefix spilled all the way to NVMe resumes with
    the prefix riding promotion (loads > 0), recompute bounded by the
    unfinished tail, and token output exactly matching the fault-free
    sequential run."""
    spec, cfg, engine = tiny
    reqs = _trace(cfg, n=8, prompt_len=32, max_new=6)
    seq = _sequential(engine, reqs)
    srv = _mk_srv(spec, engine.params, **_NVME_KW)
    out = srv.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(out[r.uid], seq[r.uid])
    st = srv.stats()
    assert st["nvme_spills"] > 0
    assert st["nvme_blocks_in_use"] > 0

    # resume session 0: its 32-token prompt is 4 committed blocks — all
    # spilled by now.  The resume must promote (nvme_loads grows), not
    # recompute: the recompute delta stays under one block.
    rec0 = srv.stats()["resume_recompute_tokens"]
    resumed = srv.serve([Request(uid="resume", prompt=reqs[0].prompt,
                                 max_new_tokens=6)])
    np.testing.assert_array_equal(resumed["resume"], seq[0])
    st2 = srv.stats()
    assert st2["nvme_loads"] > 0
    assert st2["resume_recompute_tokens"] - rec0 < srv.block_size
    # tier-labeled swap metrics: host and nvme directions both moved
    prom = srv.metrics.prometheus_text()
    assert 'serving_kv_swaps_total{direction="out",tier="nvme"}' in prom
    assert 'serving_kv_swaps_total{direction="in",tier="nvme"}' in prom
    assert 'tier="host"' in prom
    assert "serving_nvme_blocks_in_use" in prom
    names = {e["name"] for e in srv.timeline.events()}
    assert {"nvme_spill", "nvme_load"} <= names
    srv.close()


def test_nvme_spill_file_lifecycle(tiny, tmp_path):
    """An auto-minted spill tempfile dies with the engine; an
    operator-named path survives close() (their file, their lifecycle)."""
    spec, cfg, engine = tiny
    auto = _mk_srv(spec, engine.params, **_NVME_KW)
    path = auto.nvme_path
    assert os.path.exists(path)
    auto.close()
    assert not os.path.exists(path)

    mine = str(tmp_path / "operator.bin")
    owned = _mk_srv(spec, engine.params, **{**_NVME_KW,
                                            "nvme_path": mine})
    owned.serve(_trace(cfg, n=6, prompt_len=32))
    assert owned.stats()["nvme_spills"] > 0
    owned.close()
    assert os.path.exists(mine)            # operator-owned file retained


def test_nvme_knob_validation_is_loud(tiny):
    spec, cfg, engine = tiny
    with pytest.raises(ValueError, match="host tier"):
        _mk_srv(spec, engine.params, host_blocks=0, nvme_blocks=8)
    with pytest.raises(ValueError, match="nvme_high_watermark"):
        _mk_srv(spec, engine.params, nvme_blocks=8,
                nvme_high_watermark=1.5)
    with pytest.raises(ValueError, match="watermark budget"):
        _mk_srv(spec, engine.params, host_blocks=8, swap_batch=4,
                nvme_blocks=8, nvme_high_watermark=0.2)


# ------------------------------------------------------- residency audit
_SPECS = [((4,), np.float32), ((4,), np.float32)]


def _blk(seed):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=s).astype(dt) for s, dt in _SPECS]


def _spilled_store(tmp_path, n_put=6):
    nvme = NvmeBlockStore(8, _SPECS, str(tmp_path / "s.bin"))
    store = HostBlockStore(4, _SPECS, nvme=nvme, nvme_watermark=0.5)
    for i in range(n_put):
        store.put(f"k{i}".encode(), _blk(i))
    return store, nvme


def test_residency_audit_green_on_live_spilled_state(tmp_path):
    store, nvme = _spilled_store(tmp_path)
    assert store.nvme_blocks_in_use > 0        # the watermark spilled
    audit_host_store(store, ())
    # promotion back up the ladder keeps it green too
    spilled = [k for k, _ in nvme.nvme_snapshot()[1].items()]
    store.promote_spilled(spilled[:1])
    audit_host_store(store, ())
    nvme.close()


def test_residency_audit_catches_dual_tier_residency(tmp_path):
    store, nvme = _spilled_store(tmp_path)
    resident = next(iter(store.snapshot()[1]))
    nvme.swap_out(resident, _blk(99), block_checksum(_blk(99)))
    with pytest.raises(PagedStateError, match="BOTH"):
        audit_host_store(store, ())
    nvme.close()


def test_residency_audit_catches_nvme_slot_leaks(tmp_path):
    store, nvme = _spilled_store(tmp_path)
    # leaked slot: neither free nor owned
    spilled_key = next(iter(nvme.nvme_snapshot()[1]))
    del nvme._entries[spilled_key]             # drop without freeing
    with pytest.raises(PagedStateError, match="neither free nor owned"):
        audit_host_store(store, ())
    nvme.close()


def test_residency_audit_catches_double_owned_file_slot(tmp_path):
    store, nvme = _spilled_store(tmp_path)
    snap = nvme.nvme_snapshot()[1]
    keys = list(snap)
    assert len(keys) >= 2
    nvme._entries[keys[1]].slot = nvme._entries[keys[0]].slot
    with pytest.raises(PagedStateError, match="residency-conservation"):
        audit_host_store(store, ())
    nvme.close()
