"""Inference engine tests (model: reference tests/unit/inference/test_inference.py)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gpt2


@pytest.fixture
def tiny():
    return gpt2.build(gpt2.GPT2Config.tiny())


def test_init_inference_forward(tiny, eight_devices):
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(
        model=tiny, config={"dtype": "float32", "tensor_parallel": {"tp_size": 2}})
    ids = np.zeros((2, 8), np.int32)
    logits = engine.forward({"input_ids": ids})
    assert logits.shape == (2, 8, 512)


def test_generate_greedy(tiny):
    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(model=tiny, config={"dtype": "float32"})
    ids = np.ones((1, 4), np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 8)
    # prompt preserved
    np.testing.assert_array_equal(out[:, :4], ids)
    # generation is deterministic
    out2 = engine.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(out, out2)


def test_generate_matches_stepwise_argmax(tiny):
    """Greedy loop output equals manually argmaxing the forward pass."""
    import jax

    deepspeed_tpu.comm.reset_topology()
    engine = deepspeed_tpu.init_inference(model=tiny, config={"dtype": "float32"})
    ids = np.ones((1, 4), np.int32)
    out = engine.generate(ids, max_new_tokens=2)
    logits = np.asarray(engine.forward({"input_ids": out[:, :4]}))
    expected_next = logits[:, 3, :].argmax(-1)
    np.testing.assert_array_equal(out[:, 4], expected_next)
