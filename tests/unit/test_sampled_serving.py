"""Per-slot sampling through the serving engine (PR 20 tentpole).

End-to-end contracts on a tiny gpt2:
 - sampled streams are DETERMINISTIC: two fresh engines replay the same
   requests (same per-request seeds) token-identically — the sampler's
   PRNG is counter-based, keyed only by (request seed, emission index);
 - ``temperature=0`` requests through a sampling engine are bit-identical
   to a ``sampling=False`` engine AND to sequential ``generate`` (greedy
   is the zero row of the same program, not a separate lane);
 - the compile contract is unchanged: mixed greedy+sampled+constrained
   traces compile the same <= 2 / <= 3 programs (chunked / draft-spec),
   sentry-strict — sampling params ride as fixed-shape operands;
 - fused multi-step decode (``decode_steps=K``) composes: same tokens as
   the one-step path;
 - speculative decoding composes through the rejection verifier for both
   proposers (n-gram: 2 programs, draft model: 3), temp-0 rows staying
   exactly greedy;
 - constrained decoding (``logit_masks=True`` + ``JsonMaskBuilder``)
   emits valid JSON for EVERY request;
 - preemption/resume replays sampled streams token-exactly (the chaos
   crash lane is ``test_serving_faults.py``);
 - loud validation at the ctor and at ``submit``.
"""

import json

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.constrain import (JsonMaskBuilder,
                                               ascii_token_strings)
from deepspeed_tpu.inference.serving import Request, ServingEngine
from deepspeed_tpu.models import gpt2


@pytest.fixture(scope="module")
def tiny_engine():
    deepspeed_tpu.comm.reset_topology()
    cfg = gpt2.GPT2Config.tiny(max_seq_len=128)
    return deepspeed_tpu.init_inference(
        gpt2.build(cfg),
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}}), cfg


_KW = dict(slots=4, max_seq_len=128, block_size=8, prefill_chunk=16,
           prefill_batch=2, debug_checks=True)


def _sampled_trace(cfg, n, seed=0, temperature=0.8, top_k=20, top_p=0.95,
                   plen=(5, 30), max_new=(6, 20), greedy_every=0):
    """n requests, all sampled unless ``greedy_every`` interleaves greedy
    rows (uid % greedy_every == 0)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        greedy = greedy_every and i % greedy_every == 0
        out.append(Request(
            uid=i,
            prompt=rng.integers(0, cfg.vocab_size, int(rng.integers(*plen))),
            max_new_tokens=int(rng.integers(*max_new)),
            temperature=0.0 if greedy else temperature,
            top_k=0 if greedy else top_k,
            top_p=1.0 if greedy else top_p,
            seed=0 if greedy else int(rng.integers(1, 2 ** 31 - 1))))
    return out


# ------------------------------------------------------------ determinism
def test_sampled_streams_deterministic_and_two_programs(tiny_engine):
    engine, cfg = tiny_engine
    reqs = _sampled_trace(cfg, 6)
    a = ServingEngine(engine, **_KW)
    b = ServingEngine(engine, **_KW)
    res_a, res_b = a.serve(reqs), b.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(res_a[r.uid], res_b[r.uid],
                                      err_msg=f"uid {r.uid}")
        # sampled != greedy almost surely on at least one request
    want_greedy = {r.uid: engine.generate(
        r.prompt[None, :], max_new_tokens=r.max_new_tokens)[0]
        for r in reqs}
    assert any(not np.array_equal(res_a[r.uid], want_greedy[r.uid])
               for r in reqs), "sampling never deviated from greedy"
    assert a.compile_count == 2, a.compiled_programs
    assert a.sentry.retraces_observed == 0
    st = a.stats()
    assert st["sampling"] is True and st["spec_verifier"] == "rejection"
    assert st["sampled_requests"] == len(reqs)


def test_temp0_rows_bit_identical_to_greedy_engine(tiny_engine):
    engine, cfg = tiny_engine
    reqs = _sampled_trace(cfg, 5, seed=1, greedy_every=1)   # all greedy
    assert all(not r.sampled for r in reqs)
    on = ServingEngine(engine, **_KW)
    off = ServingEngine(engine, sampling=False, **_KW)
    res_on, res_off = on.serve(reqs), off.serve(reqs)
    for r in reqs:
        want = engine.generate(r.prompt[None, :],
                               max_new_tokens=r.max_new_tokens)[0]
        np.testing.assert_array_equal(res_on[r.uid], want,
                                      err_msg=f"on uid {r.uid}")
        np.testing.assert_array_equal(res_off[r.uid], want,
                                      err_msg=f"off uid {r.uid}")
    assert on.stats()["sampled_requests"] == 0


def test_fused_decode_composes_token_identical(tiny_engine):
    engine, cfg = tiny_engine
    reqs = _sampled_trace(cfg, 5, seed=2, greedy_every=3)
    plain = ServingEngine(engine, **_KW)
    fused = ServingEngine(engine, decode_steps=4, **_KW)
    res_p, res_f = plain.serve(reqs), fused.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(res_p[r.uid], res_f[r.uid],
                                      err_msg=f"uid {r.uid}")
    assert fused.stats()["fused_iterations"] > 0
    assert fused.compile_count == 2, fused.compiled_programs


# ----------------------------------------------------------- speculative
def test_spec_ngram_sampled_deterministic_two_programs(tiny_engine):
    engine, cfg = tiny_engine
    reqs = _sampled_trace(cfg, 6, seed=3, temperature=0.5, greedy_every=3)
    mk = lambda: ServingEngine(engine, spec_tokens=3, **_KW)  # noqa: E731
    a, b = mk(), mk()
    res_a, res_b = a.serve(reqs), b.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(res_a[r.uid], res_b[r.uid],
                                      err_msg=f"uid {r.uid}")
        if not r.sampled:                   # temp-0 rows stay greedy
            want = engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            np.testing.assert_array_equal(res_a[r.uid], want)
    assert a.compile_count == 2, a.compiled_programs
    st = a.stats()
    assert st["spec_rounds"] > 0 and 0.0 <= st["acceptance_rate"] <= 1.0
    assert st["spec_draft_rejected"] >= 0
    assert st["spec_draft_rejected"] == \
        st["drafted_tokens"] - st["accepted_tokens"]


def test_spec_draft_sampled_three_programs_and_temp0_parity(tiny_engine):
    engine, cfg = tiny_engine
    dcfg = gpt2.GPT2Config(vocab_size=cfg.vocab_size, max_seq_len=128,
                           num_layers=1, num_heads=2, hidden_size=32)
    mk = lambda: ServingEngine(engine, spec_tokens=3,  # noqa: E731
                               draft=gpt2.build(dcfg), **_KW)
    reqs = _sampled_trace(cfg, 5, seed=4, temperature=0.6, greedy_every=2)
    a, b = mk(), mk()
    res_a, res_b = a.serve(reqs), b.serve(reqs)
    for r in reqs:
        np.testing.assert_array_equal(res_a[r.uid], res_b[r.uid],
                                      err_msg=f"uid {r.uid}")
        if not r.sampled:
            want = engine.generate(r.prompt[None, :],
                                   max_new_tokens=r.max_new_tokens)[0]
            np.testing.assert_array_equal(res_a[r.uid], want)
    assert a.compile_count == 3, a.compiled_programs
    assert sorted(p[0] for p in a.compiled_programs) == \
        ["draft", "prefill", "verify"]


def test_greedy_verifier_refused_on_sampling_spec_engine(tiny_engine):
    engine, cfg = tiny_engine
    with pytest.raises(ValueError, match="rejection verifier"):
        ServingEngine(engine, spec_tokens=3, spec_verifier="greedy", **_KW)
    # legacy combination still constructs: greedy verify, sampling off
    srv = ServingEngine(engine, spec_tokens=3, spec_verifier="greedy",
                        sampling=False, **_KW)
    assert srv.stats()["spec_verifier"] == "greedy"


# ------------------------------------------------------------ constrained
def _constrained_reqs(cfg, n, seed=0, temperature=0.7, max_new=24):
    rng = np.random.default_rng(seed)
    toks = ascii_token_strings(cfg.vocab_size)
    return toks, [Request(
        uid=i, prompt=rng.integers(0, cfg.vocab_size, 12),
        max_new_tokens=max_new,
        temperature=temperature, top_k=0, top_p=1.0,
        seed=int(rng.integers(1, 2 ** 31 - 1)),
        mask_builder=JsonMaskBuilder(toks, eos_token_id=0))
        for i in range(n)]


def _decode_json(toks, out, plen, eos=0):
    gen = [int(t) for t in out[plen:]]
    if eos in gen:
        gen = gen[: gen.index(eos)]
    return json.loads("".join(toks[t] for t in gen))


def test_constrained_lane_emits_valid_json_every_request(tiny_engine):
    engine, cfg = tiny_engine
    toks, reqs = _constrained_reqs(cfg, 4, seed=5)
    srv = ServingEngine(engine, logit_masks=True, **_KW)
    res = srv.serve(reqs, eos_token_id=0)
    for r in reqs:
        _decode_json(toks, res[r.uid], len(r.prompt))   # raises if invalid
    assert srv.compile_count == 2, srv.compiled_programs
    assert srv.stats()["logit_masks"] is True


def test_json_mask_bans_leading_zero_numbers():
    """JSON forbids leading zeros: ``0`` / ``-0`` are COMPLETE integers
    (``json.loads("01")`` raises), so after one the mask must offer the
    terminators/eos and never another digit — regression for the bench
    lane emitting ``019...`` at full scale."""
    toks = ascii_token_strings(128)
    tid = {s: i for i, s in enumerate(toks) if s}
    digits = [tid[d] for d in "0123456789"]

    m = JsonMaskBuilder(toks, eos_token_id=0).allowed([tid["0"]], 8)
    assert not m[digits].any() and m[0] and m.sum() == 1  # eos only

    m = JsonMaskBuilder(toks, eos_token_id=0).allowed(
        [tid["-"], tid["0"]], 8)
    assert not m[digits].any() and m[0]

    m = JsonMaskBuilder(toks, eos_token_id=0).allowed(
        [tid["["], tid["0"]], 8)
    assert not m[digits].any() and m[tid[","]] and m[tid["]"]]

    m = JsonMaskBuilder(toks, eos_token_id=0).allowed([tid["1"]], 8)
    assert m[digits].all()                 # non-zero lead still extends

    bad = JsonMaskBuilder(toks, eos_token_id=0)
    with pytest.raises(ValueError):        # a violating stream is loud
        bad.allowed([tid["0"], tid["1"]], 8)


def test_mixed_trace_keeps_compile_contract_sentry_strict(tiny_engine):
    """The zero-recompile acceptance gate: ONE engine serving greedy,
    sampled, and constrained requests in the same trace compiles the
    same 2 programs as a greedy-only trace — strict sentry, no silent
    retraces.  Same check on a speculative engine (still 2: prefill +
    verify)."""
    engine, cfg = tiny_engine
    toks, constrained = _constrained_reqs(cfg, 2, seed=6)
    mixed = _sampled_trace(cfg, 4, seed=7, greedy_every=2)
    for r in constrained:                    # disjoint uids
        r.uid += 100
    srv = ServingEngine(engine, logit_masks=True, **_KW)
    res = srv.serve(mixed + constrained, eos_token_id=0)
    for r in constrained:
        _decode_json(toks, res[r.uid], len(r.prompt))
    assert srv.compile_count == 2, srv.compiled_programs
    assert srv.sentry.retraces_observed == 0
    st = srv.stats()
    assert st["sampled_requests"] == len(mixed) - 2 + len(constrained)

    toks, constrained = _constrained_reqs(cfg, 2, seed=8)
    for r in constrained:
        r.uid += 100
    spec = ServingEngine(engine, spec_tokens=3, logit_masks=True, **_KW)
    res = spec.serve(mixed + constrained, eos_token_id=0)
    for r in constrained:
        _decode_json(toks, res[r.uid], len(r.prompt))
    assert spec.compile_count == 2, spec.compiled_programs
    assert spec.sentry.retraces_observed == 0


# ------------------------------------------------------- preempt / resume
def test_preemption_replays_sampled_streams_token_exact(tiny_engine):
    """A tight pool forces preempt -> resume mid-stream; the resumed
    sampled continuation must re-derive the exact keys from (seed,
    emitted count) and match an unpressured run token-for-token."""
    engine, cfg = tiny_engine
    rng = np.random.default_rng(9)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28, temperature=0.8, top_k=30,
                    top_p=0.9, seed=int(rng.integers(1, 2 ** 31 - 1)))
            for i in range(5)]
    roomy = ServingEngine(engine, **_KW)
    want = roomy.serve(reqs)
    tight = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                          prefill_chunk=32, prefill_batch=2, num_blocks=12,
                          debug_checks=True)
    got = tight.serve(reqs)
    assert tight.preempted > 0, tight.stats()
    for r in reqs:
        np.testing.assert_array_equal(got[r.uid], want[r.uid],
                                      err_msg=f"uid {r.uid}")


def test_preemption_replays_sampled_spec_token_exact(tiny_engine):
    engine, cfg = tiny_engine
    rng = np.random.default_rng(10)
    reqs = [Request(uid=i, prompt=rng.integers(0, cfg.vocab_size, 17),
                    max_new_tokens=28, temperature=0.6,
                    seed=int(rng.integers(1, 2 ** 31 - 1)))
            for i in range(5)]
    roomy = ServingEngine(engine, spec_tokens=3, **_KW)
    want = roomy.serve(reqs)
    tight = ServingEngine(engine, slots=3, max_seq_len=64, block_size=8,
                          prefill_chunk=32, prefill_batch=2, num_blocks=12,
                          spec_tokens=3, debug_checks=True)
    got = tight.serve(reqs)
    assert tight.preempted > 0, tight.stats()
    for r in reqs:
        np.testing.assert_array_equal(got[r.uid], want[r.uid],
                                      err_msg=f"uid {r.uid}")


# -------------------------------------------------------------- validation
def test_request_and_engine_validation(tiny_engine):
    engine, cfg = tiny_engine
    prompt = np.arange(5)
    with pytest.raises(ValueError, match="temperature"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, top_p=1.5)
    # seed lands in a np.uint32 slot array at admission: out-of-range
    # values must be refused at construction, not crash step() later
    with pytest.raises(ValueError, match="seed"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, seed=-1)
    with pytest.raises(ValueError, match="seed"):
        Request(uid=0, prompt=prompt, max_new_tokens=4, seed=2 ** 32)
    Request(uid=0, prompt=prompt, max_new_tokens=4, seed=2 ** 32 - 1)

    with pytest.raises(ValueError, match="sampling"):
        ServingEngine(engine, logit_masks=True, sampling=False, **_KW)
    with pytest.raises(ValueError, match="spec_verifier"):
        ServingEngine(engine, spec_verifier="argmax", **_KW)

    off = ServingEngine(engine, sampling=False, **_KW)
    with pytest.raises(ValueError, match="sampling=False"):
        off.submit(Request(uid=1, prompt=prompt, max_new_tokens=4,
                           temperature=0.7, seed=3))
    masked = Request(uid=2, prompt=prompt, max_new_tokens=4,
                     mask_builder=JsonMaskBuilder(
                         ascii_token_strings(cfg.vocab_size), 0))
    unmasked_engine = ServingEngine(engine, **_KW)
    with pytest.raises(ValueError, match="logit_masks"):
        unmasked_engine.submit(masked)
