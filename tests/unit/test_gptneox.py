"""GPT-NeoX tests: HF parity (partial rotary, parallel residual,
interleaved qkv), decode, training."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import gptneox

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_neox(**over):
    kw = dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=128,
              max_position_embeddings=64, rotary_pct=0.5,
              use_parallel_residual=True, hidden_act="gelu",
              attention_dropout=0.0, hidden_dropout=0.0)
    kw.update(over)
    cfg = transformers.GPTNeoXConfig(**kw)
    with torch.no_grad():
        m = transformers.GPTNeoXForCausalLM(cfg)
    m.eval()
    return m


@pytest.mark.parametrize("parallel", [True, False])
def test_neox_matches_hf(parallel):
    hf = _tiny_hf_neox(use_parallel_residual=parallel)
    spec, params = deepspeed_tpu.module_inject.replace_module(hf_model=hf)
    ids = np.random.default_rng(0).integers(2, 96, (2, 12)).astype(np.int32)
    ours = np.asarray(spec.apply_fn(params, {"input_ids": ids}))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=2e-3)


def test_neox_kv_cache_decode_matches_forward():
    import jax

    cfg = gptneox.GPTNeoXConfig.tiny()
    params = gptneox.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, 512, (2, 12)).astype(np.int32)
    full = np.asarray(gptneox.forward(cfg, params, ids, train=False))

    cache = gptneox.init_cache(cfg, 2, 32, dtype=np.float32)
    logits, cache = gptneox.forward_cached(cfg, params, ids[:, :8], cache, 0)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=1e-4)
    for t in range(8, 12):
        logits, cache = gptneox.forward_cached(cfg, params, ids[:, t:t + 1],
                                               cache, t)
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-4)


def test_neox_trains_and_generates():
    deepspeed_tpu.comm.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=gptneox.build(gptneox.GPTNeoXConfig.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    fixed = {"input_ids": rng.integers(
        0, 512, (engine.train_batch_size(), 17)).astype(np.int32)}
    losses = [float(engine.train_batch(fixed)[1]["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]

    deepspeed_tpu.comm.reset_topology()
    hf = _tiny_hf_neox()
    ie = deepspeed_tpu.init_inference(model=hf, config={"dtype": "float32"})
    ids = np.full((1, 4), 7, np.int32)
    out = ie.generate(ids, max_new_tokens=3)
    with torch.no_grad():
        hf_out = hf.generate(torch.tensor(ids), max_new_tokens=3,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(out, hf_out)
