"""ZeRO-Inference streamed serving (inference/zero_inference.py).

Reference parity: ZeRO-Inference — zero stage-3 ``offload_param: cpu``
driving inference-only forwards (the OPT-30B-on-one-GPU configuration of
BASELINE.md).  The TPU analog keeps stacked blocks host-resident and
streams one layer at a time through the jitted KV-cache decode step;
these tests pin token-level parity against the resident engine, which is
the whole correctness contract of the streamed path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import opt as opt_model


def _tiny_cfg():
    return opt_model.OPTConfig(vocab_size=512, max_seq_len=64, num_layers=3,
                               num_heads=2, hidden_size=128, ffn_size=256)


@pytest.fixture
def _params():
    cfg = _tiny_cfg()
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        params = opt_model.build(cfg).init_fn(jax.random.PRNGKey(0))
    yield cfg, jax.device_get(params)
    deepspeed_tpu.comm.reset_topology()


def _engine(cfg, params, **zi):
    deepspeed_tpu.comm.reset_topology()
    config = {"dtype": "float32"}
    if zi:
        config["zero_inference"] = zi
    return deepspeed_tpu.init_inference(
        model=opt_model.build(cfg), params=params, config=config)


def test_streamed_matches_resident_greedy(_params):
    cfg, params = _params
    ids = np.arange(2 * 5, dtype=np.int32).reshape(2, 5) % 512
    ref = _engine(cfg, params).generate(ids, max_new_tokens=6)
    out = _engine(cfg, params, enabled=True, prefetch=2).generate(
        ids, max_new_tokens=6)
    np.testing.assert_array_equal(ref, out)


def test_streamed_matches_resident_sampling_and_eos(_params):
    cfg, params = _params
    ids = np.ones((1, 4), np.int32)
    kw = dict(max_new_tokens=5, do_sample=True, temperature=0.7, top_k=7,
              top_p=0.9, seed=123, eos_token_id=3)
    ref = _engine(cfg, params).generate(ids, **kw)
    out = _engine(cfg, params, enabled=True).generate(ids, **kw)
    np.testing.assert_array_equal(ref, out)


def test_streamed_pinned_layers_parity(_params):
    """pin_layers keeps a device-resident prefix; tokens must not change."""
    cfg, params = _params
    ids = np.ones((1, 4), np.int32)
    ref = _engine(cfg, params).generate(ids, max_new_tokens=4)
    eng = _engine(cfg, params, enabled=True, pin_layers=2, sync_every=2)
    assert eng._streamed.pin_layers == 2
    out = eng.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(ref, out)


def test_streamed_w8a8_parity(_params):
    """Streaming int8 records (the 1 byte/param wire format) must decode
    the tokens of the RESIDENT w8a8 engine — same records, same kernels,
    different residency."""
    cfg, params = _params
    ids = np.ones((1, 4), np.int32)
    q = {"enabled": True, "type": "w8a8"}
    deepspeed_tpu.comm.reset_topology()
    ref = deepspeed_tpu.init_inference(
        model=opt_model.build(cfg), params=params,
        config={"dtype": "float32", "quant": q}).generate(
            ids, max_new_tokens=4)
    deepspeed_tpu.comm.reset_topology()
    eng = deepspeed_tpu.init_inference(
        model=opt_model.build(cfg), params=params,
        config={"dtype": "float32", "quant": q,
                "zero_inference": {"enabled": True}})
    # the streamed layers really are int8 records on the host
    from deepspeed_tpu.ops import quantization as quant
    layer0 = eng._streamed.host_layers[0]
    assert quant.is_k_quantized(layer0["qkv_w"])
    assert isinstance(layer0["qkv_w"]["qk"], np.ndarray)
    out = eng.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(ref, out)
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod
    qmm_mod.configure(kernel_ok=True, w8a8_tp=False)


def test_engine_accepts_prequantized_params(_params):
    """A tree that already carries K-grouped records (quantized checkpoint
    / 30B-scale bench init) is served as-is: no re-quantization, scales
    stay f32 through the dtype cast, tokens match the engine-quantized
    path; a record-kind/config mismatch raises."""
    cfg, params = _params
    from deepspeed_tpu.ops import quantization as quant
    from deepspeed_tpu.ops import quantized_matmul as qmm_mod

    ids = np.ones((1, 4), np.int32)
    q = {"enabled": True, "type": "w8a8"}
    try:
        deepspeed_tpu.comm.reset_topology()
        ref = deepspeed_tpu.init_inference(
            model=opt_model.build(cfg), params=params,
            config={"dtype": "bfloat16", "quant": q}).generate(
                ids, max_new_tokens=4)
        cast = jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(
                jnp.asarray(a, jnp.bfloat16)))
            if a.dtype == np.float32 else a, params)
        pre = dict(cast)
        pre["blocks"] = quant.quantize_pytree_k_grouped(
            cast["blocks"], k_group=128, min_ndim=3)
        assert pre["blocks"]["qkv_w"]["kscale"].dtype == np.float32
        deepspeed_tpu.comm.reset_topology()
        eng = deepspeed_tpu.init_inference(
            model=opt_model.build(cfg), params=pre,
            config={"dtype": "bfloat16", "quant": q})
        # scales survived the cast in f32
        assert eng.params["blocks"]["qkv_w"]["kscale"].dtype == jnp.float32
        out = eng.generate(ids, max_new_tokens=4)
        np.testing.assert_array_equal(ref, out)
        with pytest.raises(ValueError):
            deepspeed_tpu.comm.reset_topology()
            deepspeed_tpu.init_inference(
                model=opt_model.build(cfg), params=pre,
                config={"dtype": "bfloat16",
                        "quant": {"enabled": True, "type": "weight"}})
    finally:
        qmm_mod.configure(kernel_ok=True, w8a8_tp=False)
        deepspeed_tpu.comm.reset_topology()


def test_streamed_rejects_unsupported(_params):
    cfg, params = _params
    eng = _engine(cfg, params, enabled=True)
    with pytest.raises(NotImplementedError):
        eng.forward({"input_ids": np.ones((1, 4), np.int32)})
    # over-length requests fail loudly, same as the resident path
    with pytest.raises(ValueError, match="context length"):
        eng.generate(np.ones((1, 60), np.int32), max_new_tokens=10)
    with pytest.raises(ValueError):
        deepspeed_tpu.comm.reset_topology()
        deepspeed_tpu.init_inference(
            model=opt_model.build(cfg), params=params,
            config={"dtype": "float32",
                    "tensor_parallel": {"tp_size": 2},
                    "zero_inference": {"enabled": True}})
